"""Time-to-fresh-model: incremental warm-start retrain vs full retrain
at a 5% daily delta on the GLMix bench shape (ISSUE 14 acceptance:
``freshness_speedup`` >= 10x on TPU).

Measures, on the bench_game GLMix shape (FE sparse shard + per-user RE
shard):

  1. one FULL fit over the combined data (yesterday ∪ today's delta) —
     the Spark-cadence baseline that re-solves every entity, and
  2. the INCREMENTAL path: warm-start from yesterday's checkpoint,
     delta-scan the touched 5% of users, re-solve only their RE lanes
     (untouched lanes bit-identical, zero-touched buckets skipped) while
     the FE refreshes over the combined stream,

and reports ``freshness_speedup`` = full_s / incremental_s. The detail
block carries the STRUCTURAL evidence the tier-1 gate rides on
(lanes solved vs skipped, bucket solves vs skips, touched fraction) and
a ``quality_gap``: |validation AUC(incremental) − AUC(from-scratch)|,
asserted < 0.02 — speed that costs model quality is not freshness.

On non-TPU backends the problem shrinks and the line carries
``"simulated": true`` — wall-clock ratios are only meaningful on TPU;
the structural lane accounting is platform-independent.

Budget: ``PHOTON_BENCH_BUDGET_S`` honored; skipped phases emit valid
``"truncated": true`` lines.
"""

from __future__ import annotations

import json
import shutil
import tempfile
import time

import numpy as np

FRESHNESS_METRICS = (
    "freshness_speedup",
    "event_to_served_staleness_p99_s",
)

DELTA_FRACTION = 0.05
QUALITY_TOL = 0.02


def _glmix_data(rng, n_rows, n_users, fe_features, fe_nnz, re_features):
    nnz = n_rows * fe_nnz
    fe_rows = np.repeat(np.arange(n_rows, dtype=np.int64), fe_nnz)
    fe_cols = rng.integers(0, fe_features, size=nnz)
    fe_vals = rng.normal(size=nnz)
    users = rng.integers(0, n_users, size=n_rows)
    Xu = rng.normal(size=(n_rows, re_features))
    return fe_vals, fe_rows, fe_cols, users, Xu


def _build_dataset(fe_vals, fe_rows, fe_cols, users, Xu, y,
                   fe_features):
    from photon_ml_tpu.game import build_game_dataset
    from photon_ml_tpu.ops.sparse import SparseBatch

    n = len(y)
    fe_batch = SparseBatch.from_coo(
        values=fe_vals, rows=fe_rows, cols=fe_cols, labels=y,
        num_features=fe_features,
    )
    ru_rows, ru_cols = np.nonzero(Xu)
    re_batch = SparseBatch.from_coo(
        values=Xu[ru_rows, ru_cols], rows=ru_rows, cols=ru_cols,
        labels=y, num_features=Xu.shape[1],
    )
    return build_game_dataset(
        response=y,
        feature_shards={"global": fe_batch, "user": re_batch},
        id_columns={"userId": users},
    )


def run_freshness(deadline=None) -> dict[str, float | None]:
    from bench_suite import truncated_line

    def truncated(done=None):
        done = dict(done or {})
        for metric in FRESHNESS_METRICS:
            if metric not in done:
                print(truncated_line(metric), flush=True)
                done[metric] = None
        return done

    if deadline is not None and time.monotonic() > deadline:
        return truncated()

    import dataclasses

    import jax

    from photon_ml_tpu import incremental, telemetry
    from photon_ml_tpu.game import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
        RandomEffectConfig,
    )
    from photon_ml_tpu.game.checkpoint import CheckpointSpec
    from photon_ml_tpu.game.coordinate_descent import (
        ValidationSpec,
        _evaluate,
    )
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )

    telemetry.configure_from_env()
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # the bench_game GLMix shape, split 95/5 base/delta
        n_rows, n_users, fe_features, fe_nnz, re_f = (
            1_000_000, 100_000, 10_000, 20, 10
        )
    else:
        n_rows, n_users, fe_features, fe_nnz, re_f = (
            40_000, 2_000, 1_000, 10, 6
        )

    rng = np.random.default_rng(0)
    fe_vals, fe_rows, fe_cols, users, Xu = _glmix_data(
        rng, n_rows, n_users, fe_features, fe_nnz, re_f
    )
    w_true = rng.normal(size=fe_features) * 0.5
    wu_true = rng.normal(size=(n_users, re_f)) * 0.5
    margins = np.zeros(n_rows)
    np.add.at(margins, fe_rows, fe_vals * w_true[fe_cols])
    margins += np.einsum("ij,ij->i", Xu, wu_true[users])
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-margins))).astype(
        np.float64
    )

    # the delta: the LAST rows, restricted to 5% of the users — today's
    # events touch a small entity subset, the production cadence shape
    touched_users = rng.choice(
        n_users, size=max(int(n_users * DELTA_FRACTION), 1), replace=False
    )
    n_delta = n_rows // 20
    delta_lo = n_rows - n_delta
    users = users.copy()
    users[delta_lo:] = touched_users[
        rng.integers(0, len(touched_users), n_delta)
    ]

    def slice_data(lo, hi):
        keep = (fe_rows >= lo) & (fe_rows < hi)
        return _build_dataset(
            fe_vals[keep], fe_rows[keep] - lo, fe_cols[keep],
            users[lo:hi], Xu[lo:hi], y[lo:hi], fe_features,
        )

    base_data = slice_data(0, delta_lo)
    comb_data = slice_data(0, n_rows)
    delta_data = slice_data(delta_lo, n_rows)
    # validation holdout drawn from the same generator
    nv = max(n_rows // 20, 1000)
    Xv_fe_rows = np.repeat(np.arange(nv, dtype=np.int64), fe_nnz)
    Xv_fe_cols = rng.integers(0, fe_features, size=nv * fe_nnz)
    Xv_fe_vals = rng.normal(size=nv * fe_nnz)
    uv = rng.integers(0, n_users, nv)
    Xv_u = rng.normal(size=(nv, re_f))
    mv = np.zeros(nv)
    np.add.at(mv, Xv_fe_rows, Xv_fe_vals * w_true[Xv_fe_cols])
    mv += np.einsum("ij,ij->i", Xv_u, wu_true[uv])
    yv = (rng.random(nv) < 1.0 / (1.0 + np.exp(-mv))).astype(np.float64)
    val_data = _build_dataset(
        Xv_fe_vals, Xv_fe_rows, Xv_fe_cols, uv, Xv_u, yv, fe_features
    )

    opt = OptimizerConfig(
        max_iterations=20,
        tolerance=1e-7,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    re_opt = dataclasses.replace(opt, optimizer_type=OptimizerType.NEWTON)
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="global", optimizer=opt),
            "perUser": RandomEffectConfig(
                shard_name="user", id_name="userId", optimizer=re_opt
            ),
        },
        num_iterations=2,
        evaluators=["auc"],
    )

    workdir = tempfile.mkdtemp(prefix="bench_freshness_")
    try:
        # --- yesterday's fit -> checkpoint (untimed: it already ran) ---
        ckpt = f"{workdir}/base-ckpt"
        GameEstimator(config).fit(
            base_data,
            checkpoint_spec=CheckpointSpec(directory=ckpt, resume=False),
        )
        if deadline is not None and time.monotonic() > deadline:
            return truncated()

        # --- the full-retrain baseline over the combined data ---
        # (fresh estimator: no warm coordinate caches; one prior fit has
        # already compiled the solver family, so this times solves)
        t0 = time.perf_counter()
        ref = GameEstimator(config).fit(comb_data)
        full_s = time.perf_counter() - t0
        if deadline is not None and time.monotonic() > deadline:
            return truncated()

        # --- the incremental path ---
        counters0 = dict(telemetry.snapshot()["counters"])
        t0 = time.perf_counter()
        ws = incremental.load_warm_start(ckpt)
        scan = incremental.scan_delta(
            delta_data, {"userId": ws.model.models["perUser"].vocab}
        )
        res = GameEstimator(config).fit_incremental(
            comb_data, ws, delta=scan
        )
        inc_s = time.perf_counter() - t0
        speedup = full_s / max(inc_s, 1e-9)

        spec = ValidationSpec(data=val_data, evaluators=["auc"])
        auc_inc = _evaluate(res.model, spec)["auc"]
        auc_ref = _evaluate(ref.model, spec)["auc"]
        quality_gap = abs(auc_inc - auc_ref)
        assert quality_gap < QUALITY_TOL, (
            f"incremental model lost quality: AUC {auc_inc:.4f} vs "
            f"from-scratch {auc_ref:.4f} (gap {quality_gap:.4f} >= "
            f"{QUALITY_TOL})"
        )
        # structural speedup: the re-solved lane share must match the
        # delta, platform-independently
        lane_share = res.lanes_solved / max(
            res.lanes_solved + res.lanes_skipped, 1
        )
        counters1 = telemetry.snapshot()["counters"]
        print(
            json.dumps(
                {
                    "metric": "freshness_speedup",
                    "value": round(speedup, 3),
                    "unit": "x",
                    "vs_baseline": None,
                    "detail": {
                        "full_retrain_s": round(full_s, 3),
                        "time_to_fresh_s": round(inc_s, 3),
                        "rows": n_rows,
                        "users": n_users,
                        "delta_fraction": DELTA_FRACTION,
                        "touched_fraction": round(
                            max(
                                c.touched_fraction
                                for c in scan.coordinates.values()
                            ),
                            4,
                        ),
                        "lanes_solved": res.lanes_solved,
                        "lanes_skipped": res.lanes_skipped,
                        "lane_solve_share": round(lane_share, 4),
                        "bucket_solves": res.bucket_solves,
                        "buckets_skipped": res.buckets_skipped,
                        "new_entities": res.new_entities,
                        "quality_gap_auc": round(quality_gap, 5),
                        "incremental_auc": round(float(auc_inc), 4),
                        "from_scratch_auc": round(float(auc_ref), 4),
                        "warm_restores": int(
                            counters1.get("incremental.warm_restores", 0)
                            - counters0.get("incremental.warm_restores", 0)
                        ),
                        "platform": jax.devices()[0].platform,
                        "simulated": not on_tpu,
                    },
                }
            ),
            flush=True,
        )
        out = {"freshness_speedup": round(speedup, 3)}

        # --- event→served staleness p99 (the conductor's gated SLO) ---
        # One sample = the measured incremental fit plus a real registry
        # publish + ModelRegistry hot-swap leg — the `cli pipeline`
        # cycle's serving composition, without re-fitting per sample.
        if deadline is not None and time.monotonic() > deadline:
            return truncated(out)
        from photon_ml_tpu.serving.registry import ModelRegistry

        registry_dir = f"{workdir}/registry"
        index_maps = {
            "global": [f"g{i}" for i in range(fe_features)],
            "user": [f"u{i}" for i in range(re_f)],
        }
        registry = None
        samples = []
        for _ in range(3):
            t_pub = time.perf_counter()
            incremental.publish_incremental(
                registry_dir, res.model, index_maps, res.lineage,
                delta=scan,
            )
            if registry is None:
                registry = ModelRegistry(registry_dir, warm=False)
            swapped = registry.refresh()
            assert swapped, "registry did not hot-swap a published version"
            samples.append(inc_s + (time.perf_counter() - t_pub))
        registry.stop()
        p99 = float(np.percentile(np.asarray(samples), 99.0))
        print(
            json.dumps(
                {
                    "metric": "event_to_served_staleness_p99_s",
                    "value": round(p99, 3),
                    "unit": "s",
                    "vs_baseline": None,
                    "detail": {
                        "samples_s": [round(s, 3) for s in samples],
                        "time_to_fresh_s": round(inc_s, 3),
                        "publishes": len(samples),
                        "composition": "incremental fit + registry "
                        "publish + ModelRegistry hot-swap per sample",
                        "platform": jax.devices()[0].platform,
                        "simulated": not on_tpu,
                    },
                }
            ),
            flush=True,
        )
        out["event_to_served_staleness_p99_s"] = round(p99, 3)
        return out
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def main():
    from bench_suite import budget_deadline

    run_freshness(deadline=budget_deadline())


if __name__ == "__main__":
    main()
