"""Host->device streaming overlap measurement, wired into the bench.py /
bench_suite.py driver chain (``bench_suite --overlap``) so the streaming-
overlap number gets a per-round trajectory instead of living only in
PERF_NOTES.md.

Streams HOST numpy chunks through StreamingRandomEffectTrainer twice:
through the ingest pipeline's bounded double buffer (prefetch=True: a
background feeder thread runs decode + the H2D ``device_put`` of chunk
i+1 while chunk i's solve runs — ``photon_ml_tpu.ingest.double_buffered``,
the same facility the out-of-core ChunkStream uploader uses) and fully
synchronous (prefetch=False: a scalar fetch between chunks serializes
feed and solve). Reports both wall-clocks and the overlap factor as the
``overlap_factor`` metric — a factor > 1 proves the solve overlapped
decode+upload instead of serializing behind them.

Budget: ``PHOTON_BENCH_BUDGET_S`` is honored — a run starting past the
deadline emits a valid ``{"metric": "overlap_factor", "truncated": true}``
line instead of silence.

Caveat (PERF_NOTES "Round 4: 1B"): on this rig the TPU sits behind a
~4 MB/s tunnel, so transfer dominates absurdly and the overlap factor is
bounded by max(transfer, compute)/(transfer + compute) with transfer >>
compute; on PCIe-attached hardware the two are comparable and the factor
approaches 2x. The mechanics (enqueue ordering, donation, result
correctness) are identical either way, and both arms must produce the
SAME table.
"""

from __future__ import annotations

import json
import time

import numpy as np

OVERLAP_METRICS = ("overlap_factor",)


def run_overlap(deadline=None) -> dict[str, float | None]:
    """Measure the prefetch-vs-sync overlap factor; emits one JSON line.
    Returns ``{metric: value-or-None}`` for the ``--gate`` flow."""
    from bench_suite import truncated_line

    if deadline is not None and time.monotonic() > deadline:
        print(truncated_line("overlap_factor"), flush=True)
        return {"overlap_factor": None}

    from photon_ml_tpu.game.streaming import (
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )

    import jax

    n_ent, rows, k, n_chunks = 16_384, 32, 64, 8
    per = n_ent // n_chunks
    rng = np.random.default_rng(0)
    W = rng.normal(size=(n_ent, k)).astype(np.float32)
    cfg = OptimizerConfig(
        optimizer_type=OptimizerType.LBFGS,
        max_iterations=15,
        tolerance=1e-7,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )

    def chunk(lo, hi):
        X = rng.normal(size=(hi - lo, rows, k)).astype(np.float32)
        z = np.einsum("erk,ek->er", X, W[lo:hi])
        y = (rng.random((hi - lo, rows)) < 1 / (1 + np.exp(-z))).astype(
            np.float32
        )
        return DenseBatch(
            x=X,
            labels=y,
            offsets=np.zeros((hi - lo, rows), np.float32),
            weights=np.ones((hi - lo, rows), np.float32),
        )

    chunks = [
        (i * per, chunk(i * per, (i + 1) * per)) for i in range(n_chunks)
    ]
    chunk_mb = sum(
        leaf.nbytes for leaf in jax.tree.leaves(chunks[0][1])
    ) / 2**20

    results = {}
    tables = {}
    for mode in (True, False):
        trainer = StreamingRandomEffectTrainer(
            "logistic", cfg, prefetch=mode, prefetch_depth=2
        )
        table = ShardedCoefficientTable(n_ent, k)
        trainer.train(table, chunks[:1])  # compile warm-up
        table = ShardedCoefficientTable(n_ent, k)
        t0 = time.perf_counter()
        trainer.train(table, chunks)
        jax.block_until_ready(table.coefficients)
        results["prefetch" if mode else "sync"] = time.perf_counter() - t0
        tables[mode] = table.to_numpy()

    np.testing.assert_allclose(tables[True], tables[False], atol=1e-6)
    factor = results["sync"] / results["prefetch"]
    print(
        json.dumps(
            {
                "metric": "overlap_factor",
                "value": round(factor, 3),
                "unit": "x",
                "vs_baseline": None,
                "detail": {
                    "prefetch_s": round(results["prefetch"], 3),
                    "sync_s": round(results["sync"], 3),
                    "via": "ingest.double_buffered",
                    "prefetch_depth": 2,
                    "chunks": n_chunks,
                    "chunk_mb": round(chunk_mb, 1),
                    "entities": n_ent,
                    "dim": k,
                    "arms_identical": True,
                    "platform": jax.devices()[0].platform,
                    # CPU backend: "device" compute and the feeder thread
                    # share the same cores AND device_put is a memcpy, so
                    # no overlap win is physically available — the run
                    # proves mechanics (ordering, bounded queue, identical
                    # tables), not the speedup
                    "simulated": jax.devices()[0].platform == "cpu",
                },
            }
        ),
        flush=True,
    )
    return {"overlap_factor": round(factor, 3)}


def main():
    from bench_suite import budget_deadline

    run_overlap(deadline=budget_deadline())


if __name__ == "__main__":
    main()
