"""Benchmark suite: the remaining BASELINE.md configs beyond bench.py (#1)
and bench_game.py (#4). Prints ONE JSON line PER config.

  #2: linear regression + TRON, sparse 1M x 10K (elastic-net is L1-bearing
      and TRON rejects L1 per OptimizerFactory parity, so TRON runs the L2
      member of the elastic family; an OWLQN elastic-net line is measured
      alongside for the L1 half).
  #3: Poisson regression with offset training + per-coefficient box
      constraints.

Timing recipe per PERF_NOTES.md: warm up with different arg values (the
tunnel TPU result-caches identical calls), sync via scalar fetch.

Budget: ``PHOTON_BENCH_BUDGET_S`` caps this process's wall clock. When the
budget runs out mid-suite, the remaining configs are SKIPPED but still
emit valid JSON — ``{"metric": ..., "value": null, "truncated": true}`` —
so harness consumers see every expected metric instead of an rc=124 with
partial output (the BENCH_r05 failure mode).

Gate: ``--gate baseline.json`` compares this run's rows/s values against a
baseline (a ``{metric: value}`` dict keyed by THESE suite metric names,
or an earlier run's bench JSON lines) and exits 3 when any metric
regressed more than ``--gate-threshold`` (default 20%) — the CI perf
gate. A gate that compared nothing exits 2 — whether the baseline shares
no metric names with the suite or the budget truncated every gateable
metric — so a mis-wired or starved gate can never pass silently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

GATE_EXIT_CODE = 3

SUITE_METRICS = (
    "linreg_tron_1Mx10K_rows_per_sec_per_chip",
    "linreg_owlqn_elasticnet_1Mx10K_rows_per_sec_per_chip",
    "poisson_offsets_box_1Mx10K_rows_per_sec_per_chip",
    # per-kernel utilization (telemetry.profile, every dispatch sampled):
    # achieved MFU of the profiled GLM value+grad solve and the fraction
    # of the timed window spent inside it — both HIGHER is better, so
    # they ride the default gate direction, not LOWER_IS_BETTER_METRICS
    "glm_value_grad_mfu",
    "hot_dispatch_fraction",
)

#: The solver configs (#2, #3 + the elastic-net half) — the leading
#: SUITE_METRICS entries, one timed step each; the utilization pair is
#: derived from its own profiled step after them.
_SOLVER_METRICS = SUITE_METRICS[:3]
_UTILIZATION_METRICS = SUITE_METRICS[3:]

#: Gate metrics where a RISE is the regression (wall-time ratios and
#: latency/flatness SLOs); all other gated metrics are rates where a
#: drop regresses.
LOWER_IS_BETTER_METRICS = frozenset({
    "sweep_over_single_ratio",
    "serving_slo_p99_ms",
    "serving_slo_p99_swap_ratio",
    "serving_slo_p99_nearline_ratio",
    "serving_nearline_apply_ms",
    # serving fleet (bench_serving run_serving_fleet_bench): resize-window
    # p99 flatness and hard-kill recovery both regress upward
    "serving_fleet_p99_resize_ratio",
    "serving_fleet_kill_recovery_s",
    # request-scoped tracing (bench_serving run_trace_overhead): traced
    # over untraced wall clock — the tracer's ring+tail-sampling cost
    # per request regresses upward; the acceptance line is <= 1.05
    "serving_trace_overhead_ratio",
    # fleet observability (bench_multichip): time lost waiting at
    # collectives and per-member MFU imbalance both regress upward
    "fleet_collective_wait_fraction",
    "fleet_mfu_spread",
    # freshness conductor (bench_freshness staleness section): seconds
    # from delta-event mtime to registry hot-swap confirmed — the
    # pipeline tier's headline SLO regresses upward
    "event_to_served_staleness_p99_s",
    # quality diagnostics (bench_diagnostics): B=64 bootstrap wall clock
    # as a multiple of one fit — the lane-vectorization claim (<= 2.0 on
    # TPU) regresses upward
    "bootstrap_overhead_ratio",
})


#: Safety margin reserved BEFORE the PHOTON_BENCH_BUDGET_S wall so the
#: process can kill a running sub-benchmark, flush truncated placeholder
#: lines, and write the run report while the harness's outer `timeout -k`
#: has not yet fired. BENCH_r05 lost its whole run to rc=124 because the
#: old deadline ran right up to the wall: the budget check passed, the
#: sub-benchmark was capped AT the remaining budget, and the cleanup after
#: the cap landed past it. Override with PHOTON_BENCH_MARGIN_S.
DEFAULT_BUDGET_MARGIN_S = 30.0


def budget_margin() -> float:
    raw = os.environ.get("PHOTON_BENCH_MARGIN_S")
    if not raw:
        return DEFAULT_BUDGET_MARGIN_S
    try:
        return float(raw)
    except ValueError:
        # a malformed margin must not kill the bench before any metric
        # prints — that would be worse than the rc=124 it guards against
        print(
            f"ignoring malformed PHOTON_BENCH_MARGIN_S={raw!r}; "
            f"using {DEFAULT_BUDGET_MARGIN_S}",
            file=sys.stderr,
        )
        return DEFAULT_BUDGET_MARGIN_S


def budget_deadline(now: float | None = None):
    """Monotonic flush-by deadline from PHOTON_BENCH_BUDGET_S (the budget
    minus the flush margin), or None (no cap). Work must STOP at this
    deadline; the reserved margin pays for truncated-line flushes and the
    run report so the process exits 0 before the outer kill."""
    budget = os.environ.get("PHOTON_BENCH_BUDGET_S")
    if not budget:
        return None
    try:
        budget_s = float(budget)
    except ValueError:
        print(
            f"ignoring malformed PHOTON_BENCH_BUDGET_S={budget!r}; "
            "running uncapped",
            file=sys.stderr,
        )
        return None
    margin = budget_margin()
    # a budget at or below the margin must not silently skip ALL work:
    # keep at least half the budget for benchmarking, and say so
    usable = max(budget_s - margin, budget_s * 0.5)
    if budget_s <= margin:
        print(
            f"PHOTON_BENCH_BUDGET_S={budget_s:g} <= flush margin "
            f"{margin:g}s; keeping {usable:g}s for work — expect "
            "heavy truncation",
            file=sys.stderr,
        )
    return (time.monotonic() if now is None else now) + usable


def truncated_line(metric: str) -> str:
    """The valid-JSON placeholder for a budget-skipped metric."""
    return json.dumps(
        {
            "metric": metric,
            "value": None,
            "unit": None,
            "vs_baseline": None,
            "truncated": True,
        }
    )


def _sparse_problem(rng, n_rows, n_features, nnz_per_row, kind):
    nnz = n_rows * nnz_per_row
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, n_features, size=nnz)
    values = rng.normal(size=nnz)
    w_true = rng.normal(size=n_features) * 0.5
    margins = np.zeros(n_rows)
    np.add.at(margins, rows, values * w_true[cols])
    if kind == "linear":
        y = margins + 0.1 * rng.normal(size=n_rows)
        offsets = None
    elif kind == "poisson":
        offsets = rng.normal(size=n_rows) * 0.3  # exposure offsets
        y = rng.poisson(np.exp(np.clip(0.2 * margins + offsets, -4, 4)))
        y = y.astype(np.float64)
    else:
        raise ValueError(kind)
    return values, rows, cols, y, offsets


def _run(solver, batch, w0, n_rows):
    import jax

    res = solver(w0, batch)
    float(res.value)  # warm-up sync
    t0 = time.perf_counter()
    res = solver(w0 + 1e-6, batch)  # fresh args defeat result caching
    final = float(res.value)
    elapsed = time.perf_counter() - t0
    iters = int(res.iterations)
    # rows/s counts EVERY full pass over the data the solver made —
    # including TRON's truncated-CG Hessian-vector passes
    # (SolveResult.data_passes) — so all optimizer lines are comparable
    passes = int(res.data_passes)
    return {
        "elapsed_s": round(elapsed, 3),
        "iterations": iters,
        "data_passes": passes,
        "final_loss": final,
        "rows_per_sec": round(n_rows * passes / elapsed, 1),
        "platform": jax.devices()[0].platform,
    }


def run_suite(deadline=None) -> dict[str, float | None]:
    """Run the configs in order, emitting one JSON line each; configs past
    the budget deadline emit truncated placeholders instead. Returns
    {metric: rows_per_sec or None}."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.ops.tiled import TiledBatch
    from photon_ml_tpu.optim import (
        BoxConstraints,
        LBFGSConfig,
        TRONConfig,
        glm_adapter,
        lbfgs_solve,
        owlqn_solve,
        tron_solve,
    )

    rng = np.random.default_rng(0)
    n_rows, n_features, nnz_per_row = 1_000_000, 10_000, 20
    w0 = jnp.zeros((n_features,), jnp.float32)
    results: dict[str, float | None] = {}
    cache: dict[str, object] = {}

    def linear_batch():
        if "linear" not in cache:
            values, rows, cols, y, _ = _sparse_problem(
                rng, n_rows, n_features, nnz_per_row, "linear"
            )
            cache["linear"] = TiledBatch.from_coo(
                values=values, rows=rows, cols=cols, labels=y,
                num_features=n_features,
            )
        return cache["linear"]

    # --- config #2: linear + TRON (L2) -----------------------------------
    def run_tron():
        obj = make_objective("squared", l2_weight=1.0)
        tron_cfg = TRONConfig(max_iterations=10, tolerance=0.0)

        def tron_run(w0, b):
            return tron_solve(glm_adapter(obj, b), w0, tron_cfg)

        return _run(jax.jit(tron_run), linear_batch(), w0, n_rows)

    # elastic-net half: OWLQN with l1=0.5, l2=0.5
    def run_owlqn():
        obj_en = make_objective("squared", l2_weight=0.5)
        lcfg = LBFGSConfig(max_iterations=20, tolerance=0.0)

        def owlqn_run(w0, b):
            return owlqn_solve(
                glm_adapter(obj_en, b), w0, jnp.float32(0.5), lcfg
            )

        return _run(jax.jit(owlqn_run), linear_batch(), w0, n_rows)

    # --- config #3: Poisson + offsets + box constraints ------------------
    def run_poisson():
        values, rows, cols, y, offsets = _sparse_problem(
            rng, n_rows, n_features, nnz_per_row, "poisson"
        )
        batch = TiledBatch.from_coo(
            values=values, rows=rows, cols=cols, labels=y,
            offsets=offsets, num_features=n_features,
        )
        obj_p = make_objective("poisson", l2_weight=1.0)
        constraints = BoxConstraints(
            lower=jnp.asarray(np.full(n_features, -0.5), jnp.float32),
            upper=jnp.asarray(np.full(n_features, 0.5), jnp.float32),
        )

        def poisson_run(w0, b):
            return lbfgs_solve(
                glm_adapter(obj_p, b), w0,
                LBFGSConfig(max_iterations=20, tolerance=0.0),
                constraints=constraints,
            )

        return _run(jax.jit(poisson_run), batch, w0, n_rows)

    steps = zip(_SOLVER_METRICS, (run_tron, run_owlqn, run_poisson))
    truncated = False
    for metric, step in steps:
        if truncated or (
            deadline is not None and time.monotonic() > deadline
        ):
            truncated = True  # budget spent: skip everything remaining
            print(truncated_line(metric), flush=True)
            results[metric] = None
            continue
        d = step()
        results[metric] = d["rows_per_sec"]
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": d["rows_per_sec"],
                    "unit": "rows/s",
                    "vs_baseline": None,
                    "detail": d,
                }
            ),
            flush=True,
        )

    # --- per-kernel utilization (telemetry.profile) ----------------------
    # One profiled GLM value+grad solve over the cached linear batch:
    # instrumented_jit + the dispatch sampler at every=1 give an honest
    # (fetch-synchronized) per-dispatch time, from which achieved MFU and
    # the hot-dispatch fraction of the timed window follow. Unknowable
    # values (no cost analysis / unknown device peak) are SKIPPED with a
    # note, never gated as zero.
    if truncated or (
        deadline is not None and time.monotonic() > deadline
    ):
        for metric in _UTILIZATION_METRICS:
            print(truncated_line(metric), flush=True)
            results[metric] = None
        return results
    from photon_ml_tpu import telemetry

    telemetry.profile.set_sample_every(1)
    obj_glm = make_objective("squared", l2_weight=1.0)
    glm_cfg = LBFGSConfig(max_iterations=20, tolerance=0.0)

    def glm_value_grad(w, b):
        return lbfgs_solve(glm_adapter(obj_glm, b), w, glm_cfg)

    solver = telemetry.instrumented_jit(
        glm_value_grad, name="suite_glm_value_grad"
    )
    batch = linear_batch()
    # warm up with different args (tunnel result-caching, PERF_NOTES.md)
    float(telemetry.sync_fetch(solver(w0, batch).value, label="warmup"))
    # hot fraction = exclusive profiled seconds accrued DURING the timed
    # window / wall elapsed; the warmup dispatch (compile wait) lands
    # before the snapshot so it can't inflate the fraction
    excl0 = telemetry.profile.exclusive_seconds_by_name().get(
        "suite_glm_value_grad", 0.0
    )
    t0 = time.perf_counter()
    res = solver(w0 + 1e-6, batch)
    float(telemetry.sync_fetch(res.value, label="loss"))
    util_elapsed = time.perf_counter() - t0
    excl1 = telemetry.profile.exclusive_seconds_by_name().get(
        "suite_glm_value_grad", 0.0
    )
    prof = telemetry.profile.merged_profiles(
        names=("suite_glm_value_grad",)
    ).get("suite_glm_value_grad")
    mfu = None if prof is None else prof.get("mfu")
    hot_fraction = None
    if excl1 > excl0 and util_elapsed > 0:
        hot_fraction = round(
            min((excl1 - excl0) / util_elapsed, 1.0), 6
        )
    for metric, value in zip(
        _UTILIZATION_METRICS, (mfu, hot_fraction)
    ):
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": value,
                    "unit": "fraction",
                    "vs_baseline": None,
                    "detail": {
                        "executable": "suite_glm_value_grad",
                        "profile": prof,
                    },
                }
            ),
            flush=True,
        )
        if value is not None:
            results[metric] = value
        else:
            print(
                f"gate: {metric}: unavailable on this backend (no "
                "cost analysis or unknown device peak) — skipped",
                file=sys.stderr,
            )
    return results


def load_gate_baseline(path: str) -> dict[str, float]:
    """Baseline formats accepted: a bare ``{metric: value}`` dict, JSONL
    of earlier bench output lines (``{"metric": ..., "value": ...}``), or
    — for generality — any report-shaped JSON with ``key_metrics``
    (run_gate errors if its names don't overlap the suite's)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        if "key_metrics" in doc:
            doc = doc["key_metrics"]
        return {
            k: float(v)
            for k, v in doc.items()
            if isinstance(v, (int, float))
        }
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if (
            isinstance(rec, dict)
            and rec.get("metric")
            and isinstance(rec.get("value"), (int, float))
        ):
            out[rec["metric"]] = float(rec["value"])
    return out


def run_gate(
    results: dict[str, float | None], baseline: dict[str, float],
    threshold: float,
) -> int:
    """Compare measured rows/s against the baseline (higher is better);
    returns the process exit code. Truncated (None) metrics are not
    gateable and are reported as skipped."""
    from photon_ml_tpu.telemetry.report import compare_metrics

    current = {k: v for k, v in results.items() if v is not None}
    # rows/s-style metrics regress when they DROP; ratio-of-walltime
    # metrics (the sweep bench) regress when they RISE
    directions = {
        name: (-1 if name in LOWER_IS_BETTER_METRICS else +1)
        for name in set(current) | set(baseline)
    }
    deltas = compare_metrics(
        current, baseline, threshold=threshold, directions=directions
    )
    # metrics this run measured that the baseline has never seen (e.g. the
    # multichip_* lines against a pre-multichip BENCH_r05 baseline) are
    # SKIPPED WITH A NOTE — a baseline that predates a metric must never
    # fail the gate (nor crash it); the next baseline refresh picks it up
    for name in sorted(set(current) - set(baseline)):
        print(
            f"gate: {name}: new metric, not in baseline — skipped "
            "(refresh the baseline to start gating it)",
            file=sys.stderr,
        )
    for d in deltas:
        status = "REGRESSED" if d.regressed else "ok"
        print(
            f"gate: {d.metric}: {d.current:.1f} vs baseline "
            f"{d.baseline:.1f} ({d.change:+.1%}) {status}",
            file=sys.stderr,
        )
    truncated_overlap = False
    for name, value in results.items():
        if value is None:
            truncated_overlap = truncated_overlap or name in baseline
            print(f"gate: {name}: truncated, not gated", file=sys.stderr)
    if not deltas:
        # a gate that compared NOTHING must not pass: neither a
        # mismatched baseline (wrong metric names — a permanent false
        # pass) nor a run whose every gateable metric was budget-
        # truncated (a real regression would stay green)
        reason = (
            "every overlapping metric was budget-truncated; nothing "
            "was compared"
            if truncated_overlap
            else "no comparable metrics between this run "
            f"({sorted(results)}) and the baseline ({sorted(baseline)})"
        )
        print(f"gate: ERROR — {reason}", file=sys.stderr)
        return 2
    if any(d.regressed for d in deltas):
        return GATE_EXIT_CODE
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--gate",
        metavar="baseline.json",
        help="compare rows/s against this baseline and exit nonzero on "
        "a regression beyond --gate-threshold",
    )
    parser.add_argument(
        "--gate-threshold",
        type=float,
        default=0.2,
        help="fractional regression threshold for --gate (default 0.2)",
    )
    parser.add_argument(
        "--multichip",
        action="store_true",
        help="also run bench_multichip.py (1-vs-8-device scaling "
        "efficiency) and include its metrics in the gate; baselines that "
        "predate the multichip_* metrics skip them with a note",
    )
    parser.add_argument(
        "--sweep",
        action="store_true",
        help="also run bench_sweep.py (16-config λ-sweep wall time as a "
        "multiple of single-fit wall time) and include "
        "sweep_over_single_ratio in the gate; baselines that predate it "
        "skip with a note",
    )
    parser.add_argument(
        "--overlap",
        action="store_true",
        help="also run bench_overlap.py (streaming prefetch overlap "
        "factor) and include overlap_factor in the gate; baselines that "
        "predate it skip with a note",
    )
    parser.add_argument(
        "--ingest",
        action="store_true",
        help="also run bench_ingest.py (one-shot reader + ingest "
        "pipeline rows/s) and include both metrics in the gate; "
        "baselines that predate ingest_pipeline_rows_per_sec skip it "
        "with a note",
    )
    parser.add_argument(
        "--freshness",
        action="store_true",
        help="also run bench_freshness.py (incremental warm-start retrain "
        "vs full retrain at a 5%% delta — time-to-fresh-model speedup "
        "with a quality-parity assertion) and include freshness_speedup "
        "in the gate; baselines that predate it skip with a note",
    )
    parser.add_argument(
        "--diagnostics",
        action="store_true",
        help="also run bench_diagnostics.py (B=64 GLMix bootstrap wall "
        "time as a multiple of one fit — the vmapped resample-lane "
        "claim, <= 2.0 on TPU) and include bootstrap_overhead_ratio in "
        "the gate (lower is better); baselines that predate it skip "
        "with a note",
    )
    parser.add_argument(
        "--serving",
        action="store_true",
        help="also run bench_serving.py's sustained-load SLO sweep "
        "(offered-load grid, p99-across-hot-swap and across-nearline "
        "flatness, time-to-applied-update) plus the request-tracing "
        "overhead A/B and include the serving_slo_* and "
        "serving_trace_overhead_ratio metrics in the gate; baselines "
        "that predate them skip with a note",
    )
    args = parser.parse_args(argv)
    from photon_ml_tpu import faults

    if faults.warn_if_armed():
        if args.gate:
            # gated runs are the CI perf contract: numbers produced under
            # injection are not comparable to any baseline — refuse
            print(
                "bench_suite: refusing --gate with PHOTON_FAULT_PLAN "
                "armed (injected faults corrupt gated metrics)",
                file=sys.stderr,
            )
            return 2
    deadline = budget_deadline()
    results = run_suite(deadline=deadline)
    if args.multichip:
        from bench_multichip import run_multichip

        results.update(run_multichip(deadline=deadline))
    if args.sweep:
        from bench_sweep import run_sweep_bench

        results.update(run_sweep_bench(deadline=deadline))
    if args.overlap:
        from bench_overlap import run_overlap

        results.update(run_overlap(deadline=deadline))
    if args.ingest:
        from bench_ingest import run_ingest

        results.update(run_ingest(deadline=deadline))
    if args.freshness:
        from bench_freshness import run_freshness

        results.update(run_freshness(deadline=deadline))
    if args.diagnostics:
        from bench_diagnostics import run_diagnostics

        results.update(run_diagnostics(deadline=deadline))
    if args.serving:
        from bench_serving import run_serving_slo, run_trace_overhead

        results.update(run_serving_slo(deadline=deadline))
        results.update(run_trace_overhead(deadline=deadline))
    if args.gate:
        return run_gate(
            results, load_gate_baseline(args.gate), args.gate_threshold
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
