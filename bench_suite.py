"""Benchmark suite: the remaining BASELINE.md configs beyond bench.py (#1)
and bench_game.py (#4). Prints ONE JSON line PER config.

  #2: linear regression + TRON, sparse 1M x 10K (elastic-net is L1-bearing
      and TRON rejects L1 per OptimizerFactory parity, so TRON runs the L2
      member of the elastic family; an OWLQN elastic-net line is measured
      alongside for the L1 half).
  #3: Poisson regression with offset training + per-coefficient box
      constraints.

Timing recipe per PERF_NOTES.md: warm up with different arg values (the
tunnel TPU result-caches identical calls), sync via scalar fetch.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _sparse_problem(rng, n_rows, n_features, nnz_per_row, kind):
    nnz = n_rows * nnz_per_row
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, n_features, size=nnz)
    values = rng.normal(size=nnz)
    w_true = rng.normal(size=n_features) * 0.5
    margins = np.zeros(n_rows)
    np.add.at(margins, rows, values * w_true[cols])
    if kind == "linear":
        y = margins + 0.1 * rng.normal(size=n_rows)
        offsets = None
    elif kind == "poisson":
        offsets = rng.normal(size=n_rows) * 0.3  # exposure offsets
        y = rng.poisson(np.exp(np.clip(0.2 * margins + offsets, -4, 4)))
        y = y.astype(np.float64)
    else:
        raise ValueError(kind)
    return values, rows, cols, y, offsets


def _run(solver, batch, w0, n_rows):
    import jax

    res = solver(w0, batch)
    float(res.value)  # warm-up sync
    t0 = time.perf_counter()
    res = solver(w0 + 1e-6, batch)  # fresh args defeat result caching
    final = float(res.value)
    elapsed = time.perf_counter() - t0
    iters = int(res.iterations)
    # rows/s counts EVERY full pass over the data the solver made —
    # including TRON's truncated-CG Hessian-vector passes
    # (SolveResult.data_passes) — so all optimizer lines are comparable
    passes = int(res.data_passes)
    return {
        "elapsed_s": round(elapsed, 3),
        "iterations": iters,
        "data_passes": passes,
        "final_loss": final,
        "rows_per_sec": round(n_rows * passes / elapsed, 1),
        "platform": jax.devices()[0].platform,
    }


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.ops.tiled import TiledBatch
    from photon_ml_tpu.optim import (
        BoxConstraints,
        LBFGSConfig,
        TRONConfig,
        glm_adapter,
        owlqn_solve,
        tron_solve,
    )

    rng = np.random.default_rng(0)
    n_rows, n_features, nnz_per_row = 1_000_000, 10_000, 20

    # --- config #2: linear + TRON (L2), + OWLQN elastic-net companion ----
    values, rows, cols, y, _ = _sparse_problem(
        rng, n_rows, n_features, nnz_per_row, "linear"
    )
    batch = TiledBatch.from_coo(
        values=values, rows=rows, cols=cols, labels=y, num_features=n_features
    )
    obj = make_objective("squared", l2_weight=1.0)
    tron_cfg = TRONConfig(max_iterations=10, tolerance=0.0)

    def tron_run(w0, b):
        return tron_solve(glm_adapter(obj, b), w0, tron_cfg)

    w0 = jnp.zeros((n_features,), jnp.float32)
    d = _run(jax.jit(tron_run), batch, w0, n_rows)
    print(json.dumps({
        "metric": "linreg_tron_1Mx10K_rows_per_sec_per_chip",
        "value": d["rows_per_sec"], "unit": "rows/s", "vs_baseline": None,
        "detail": d,
    }))

    # elastic-net half: OWLQN with l1=0.5, l2=0.5
    obj_en = make_objective("squared", l2_weight=0.5)
    lcfg = LBFGSConfig(max_iterations=20, tolerance=0.0)

    def owlqn_run(w0, b):
        return owlqn_solve(glm_adapter(obj_en, b), w0, jnp.float32(0.5), lcfg)

    d = _run(jax.jit(owlqn_run), batch, w0, n_rows)
    print(json.dumps({
        "metric": "linreg_owlqn_elasticnet_1Mx10K_rows_per_sec_per_chip",
        "value": d["rows_per_sec"], "unit": "rows/s", "vs_baseline": None,
        "detail": d,
    }))

    # --- config #3: Poisson + offsets + box constraints ------------------
    values, rows, cols, y, offsets = _sparse_problem(
        rng, n_rows, n_features, nnz_per_row, "poisson"
    )
    batch = TiledBatch.from_coo(
        values=values, rows=rows, cols=cols, labels=y,
        offsets=offsets, num_features=n_features,
    )
    obj_p = make_objective("poisson", l2_weight=1.0)
    lower = np.full(n_features, -0.5)
    upper = np.full(n_features, 0.5)
    constraints = BoxConstraints(
        lower=jnp.asarray(lower, jnp.float32),
        upper=jnp.asarray(upper, jnp.float32),
    )

    from photon_ml_tpu.optim import lbfgs_solve

    def poisson_run(w0, b):
        return lbfgs_solve(
            glm_adapter(obj_p, b), w0,
            LBFGSConfig(max_iterations=20, tolerance=0.0),
            constraints=constraints,
        )

    d = _run(jax.jit(poisson_run), batch, w0, n_rows)
    print(json.dumps({
        "metric": "poisson_offsets_box_1Mx10K_rows_per_sec_per_chip",
        "value": d["rows_per_sec"], "unit": "rows/s", "vs_baseline": None,
        "detail": d,
    }))


if __name__ == "__main__":
    main()
