"""Online serving subsystem: engine parity with the batch scorer,
micro-batching + admission control, registry hot-swap/fallback, HTTP and
stdio front ends, and the steady-state no-recompile guarantee."""

import io
import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.model_store import ModelLoadError, save_game_model
from photon_ml_tpu.game.dataset import build_game_dataset
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectBucketModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    BadRequest,
    MicroBatcher,
    ModelRegistry,
    Overloaded,
    ScoringEngine,
    ScoringServer,
    ScoringService,
    publish_version,
    serve_stdio,
)
from photon_ml_tpu.testing import generate_game_dataset


def _make_model(truth, scale=1.0, n_buckets=2, task="logistic"):
    """FE + per-user RE GameModel straight from planted coefficients."""
    w_users = truth["w_users"] * scale
    n_users, local_k = w_users.shape
    fe = FixedEffectModel(
        coefficients=jnp.asarray(truth["w_global"] * scale, jnp.float32),
        shard_name="global",
    )
    entity_bucket = (np.arange(n_users) % n_buckets).astype(np.int64)
    entity_pos = np.zeros(n_users, np.int64)
    buckets = []
    for b in range(n_buckets):
        codes_b = np.nonzero(entity_bucket == b)[0]
        entity_pos[codes_b] = np.arange(len(codes_b))
        proj = np.tile(np.arange(local_k, dtype=np.int32), (len(codes_b), 1))
        buckets.append(
            RandomEffectBucketModel(
                coefficients=jnp.asarray(w_users[codes_b], jnp.float32),
                projection=jnp.asarray(proj),
                entity_codes=jnp.asarray(codes_b, jnp.int32),
            )
        )
    re = RandomEffectModel(
        id_name="userId",
        shard_name="user",
        buckets=tuple(buckets),
        entity_bucket=entity_bucket,
        entity_pos=entity_pos,
        vocab=np.arange(n_users),
    )
    return GameModel(task=task, models={"fixed": fe, "perUser": re})


def _request_rows(truth, data, indices):
    """The dataset's rows re-expressed in the serving request schema."""
    Xg, Xu, users = truth["Xg"], truth["Xu"], truth["users"]
    rows = []
    for i in indices:
        rows.append(
            {
                "features": {
                    "global": [
                        [j, float(Xg[i, j])]
                        for j in range(Xg.shape[1])
                        if Xg[i, j] != 0
                    ],
                    "user": [
                        [j, float(Xu[i, j])]
                        for j in range(Xu.shape[1])
                        if Xu[i, j] != 0
                    ],
                },
                "ids": {"userId": int(users[i])},
                "offset": float(data.offset[i]),
            }
        )
    return rows


@pytest.fixture(scope="module")
def game_world():
    data, truth = generate_game_dataset(
        n_users=12, rows_per_user=10, fe_dim=6, re_dim=4, seed=3
    )
    # non-zero offsets so the offset plumbing is actually exercised
    rng = np.random.default_rng(17)
    data = build_game_dataset(
        response=data.response,
        feature_shards=data.feature_shards,
        id_columns=data.id_columns,
        offset=rng.normal(size=data.num_rows) * 0.3,
    )
    return data, truth


_INDEX_MAPS = {
    "global": [f"g{j}" for j in range(6)],
    "user": [f"u{j}" for j in range(4)],
}


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def test_engine_matches_predict_mean(game_world):
    data, truth = game_world
    model = _make_model(truth)
    expected = np.asarray(model.predict_mean(data))[: data.num_rows]
    rows = _request_rows(truth, data, range(data.num_rows))
    # max_batch below num_rows: internal chunking + several buckets
    engine = ScoringEngine(model, max_batch=32, version="t").warmup()
    got = engine.score_rows(rows)
    np.testing.assert_allclose(got, expected, atol=1e-6)
    assert engine.warm


def test_engine_squared_task_is_raw_scores(game_world):
    data, truth = game_world
    model = _make_model(truth, task="squared")
    expected = np.asarray(model.predict_mean(data))[: data.num_rows]
    engine = ScoringEngine(model, max_batch=16)
    got = engine.score_rows(_request_rows(truth, data, range(data.num_rows)))
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_engine_unseen_entity_falls_back_to_fixed_effect(game_world):
    data, truth = game_world
    model = _make_model(truth)
    fe_only = GameModel(
        task="logistic", models={"fixed": model.models["fixed"]}
    )
    expected = np.asarray(fe_only.predict_mean(data))[:3]
    rows = _request_rows(truth, data, range(3))
    for r in rows:
        r["ids"] = {"userId": 424242}  # never in the training vocab
    engine = ScoringEngine(model, max_batch=8)
    np.testing.assert_allclose(engine.score_rows(rows), expected, atol=1e-6)
    assert (
        telemetry.snapshot()["counters"]["serving.unseen_entities"] == 3
    )
    # a row with no id at all gets the same fallback
    del rows[0]["ids"]
    np.testing.assert_allclose(
        engine.score_rows(rows[:1]), expected[:1], atol=1e-6
    )


def test_engine_named_features_resolve_through_index_maps(game_world):
    data, truth = game_world
    model = _make_model(truth)
    engine = ScoringEngine(model, index_maps={
        "global": {f"g{j}": j for j in range(6)},
        "user": {f"u{j}": j for j in range(4)},
    }, max_batch=8)
    indexed = _request_rows(truth, data, [0, 1])
    named = []
    for row in indexed:
        named.append(
            {
                "features": {
                    "global": [
                        ["g%d" % c, "", v]
                        for c, v in row["features"]["global"]
                    ],
                    "user": [
                        {"name": "u%d" % c, "value": v}
                        for c, v in row["features"]["user"]
                    ],
                },
                "ids": row["ids"],
                "offset": row["offset"],
            }
        )
    np.testing.assert_allclose(
        engine.score_rows(named), engine.score_rows(indexed), atol=1e-7
    )
    # unknown names score as absent features (index-map default), counted
    named[0]["features"]["global"].append(["no_such_feature", "", 1.0])
    engine.score_rows(named)
    assert telemetry.snapshot()["counters"]["serving.unknown_features"] == 1


def test_engine_bad_requests_are_typed(game_world):
    data, truth = game_world
    engine = ScoringEngine(_make_model(truth), max_batch=4, max_row_nnz=4)
    with pytest.raises(BadRequest, match="max_row_nnz"):
        engine.score_rows(
            [{"features": {"global": [[j, 1.0] for j in range(5)]}}]
        )
    with pytest.raises(BadRequest, match="must be an object"):
        engine.score_rows(["not-a-row"])
    with pytest.raises(BadRequest, match="no feature index"):
        engine.score_rows(
            [{"features": {"global": [["named", "", 1.0]]}}]
        )
    # a typo'd shard name must not silently drop features (the
    # silent-wrong-scores hazard)
    with pytest.raises(BadRequest, match="unknown feature shard"):
        engine.score_rows([{"features": {"globl": [[0, 1.0]]}}])
    # ...nor an out-of-range feature id (clamped gathers drop it silently)
    with pytest.raises(BadRequest, match="outside shard"):
        engine.score_rows([{"features": {"global": [[100, 1.0]]}}])
    with pytest.raises(BadRequest, match="outside shard"):
        engine.score_rows([{"features": {"global": [[-1, 1.0]]}}])
    # non-numeric payloads are 400-class, never internal errors
    with pytest.raises(BadRequest, match="offset"):
        engine.score_rows([{"offset": "x"}])
    with pytest.raises(BadRequest, match="must be numbers"):
        engine.score_rows([{"features": {"global": [[0, "not-a-number"]]}}])


def test_micro_batcher_isolates_bad_unit_from_co_batched(game_world):
    """One malformed request coalesced into a batch must fail ALONE —
    the valid co-riders still get their scores."""
    data, truth = game_world
    engine = ScoringEngine(_make_model(truth), max_batch=8)
    batcher = MicroBatcher(
        lambda rows: (engine.score_rows(rows), engine.version),
        max_batch=8, max_delay_ms=50.0, queue_depth=100,
    ).start()
    try:
        good_rows = _request_rows(truth, data, [0, 1])
        good = batcher.submit(good_rows)
        bad = batcher.submit([{"features": {"globl": [[0, 1.0]]}}])
        result = good.result(timeout=10)
        expected = np.asarray(
            _make_model(truth).predict_mean(data)
        )[:2]
        np.testing.assert_allclose(result["scores"], expected, atol=1e-6)
        with pytest.raises(BadRequest, match="unknown feature shard"):
            bad.result(timeout=10)
    finally:
        batcher.stop()


def test_engine_load_requires_feature_indexes(tmp_path, game_world):
    _, truth = game_world
    model_dir = str(tmp_path / "model")
    save_game_model(_make_model(truth), model_dir)
    with pytest.raises(ModelLoadError, match="feature-indexes"):
        ScoringEngine.load(model_dir)
    engine = ScoringEngine.load(model_dir, require_feature_indexes=False)
    assert engine.version == "model"


def test_engine_rejects_unservable_coordinates(game_world):
    _, truth = game_world
    model = _make_model(truth)
    bad = model.with_model("weird", object())
    with pytest.raises(TypeError, match="online serving supports"):
        ScoringEngine(bad)


def test_steady_state_never_recompiles(game_world):
    data, truth = game_world
    engine = ScoringEngine(_make_model(truth), max_batch=16).warmup()
    rows = _request_rows(truth, data, range(9))
    engine.score_rows(rows)  # one post-warmup call settles caches
    before = telemetry.snapshot()["counters"].get("jit_compiles", 0)
    for size in (1, 3, 9, 16, 5):  # every bucket was warmed
        engine.score_rows(_request_rows(truth, data, range(size)))
    after = telemetry.snapshot()["counters"].get("jit_compiles", 0)
    assert after == before


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------


def test_micro_batcher_coalesces_under_deadline():
    dispatched = []

    def scorer(rows):
        dispatched.append(len(rows))
        time.sleep(0.01)  # let submissions pile up behind the first batch
        return np.arange(len(rows), dtype=np.float32), "v9"

    b = MicroBatcher(
        scorer, max_batch=8, max_delay_ms=25.0, queue_depth=1000
    ).start()
    try:
        futures = [b.submit([{"k": i}, {"k": i}]) for i in range(8)]
        results = [f.result(timeout=10) for f in futures]
    finally:
        b.stop()
    assert all(len(r["scores"]) == 2 for r in results)
    assert all(r["model_version"] == "v9" for r in results)
    assert max(dispatched) > 2  # units rode together, not one-by-one
    assert sum(dispatched) == 16
    snap = telemetry.snapshot()
    assert snap["counters"]["serving.requests"] == 8
    assert snap["histograms"]["serving.batch_size"]["count"] == len(dispatched)


def test_micro_batcher_sheds_on_overload():
    release = threading.Event()

    def scorer(rows):
        release.wait(timeout=10)
        return np.zeros(len(rows), np.float32), "v"

    b = MicroBatcher(
        scorer, max_batch=4, max_delay_ms=1.0, queue_depth=4
    ).start()
    try:
        first = b.submit([{}] * 4)
        time.sleep(0.1)  # dispatcher grabs the first batch, blocks in scorer
        second = b.submit([{}] * 4)  # refills the queue to capacity
        with pytest.raises(Overloaded, match="queue at capacity"):
            b.submit([{}])
        assert telemetry.snapshot()["counters"]["serving.shed"] == 1
        release.set()
        assert len(first.result(timeout=10)["scores"]) == 4
        assert len(second.result(timeout=10)["scores"]) == 4
    finally:
        release.set()
        b.stop()


def test_micro_batcher_rejects_unservable_giant_request():
    """A unit larger than queue_depth can never be admitted — it must be
    a typed 400-class error, not a retryable-looking Overloaded."""
    b = MicroBatcher(
        lambda rows: (np.zeros(len(rows), np.float32), "v"),
        max_batch=4, queue_depth=8,
    ).start()
    try:
        with pytest.raises(BadRequest, match="queue depth"):
            b.submit([{}] * 9)
        assert len(b.submit([{}] * 8).result(timeout=10)["scores"]) == 8
    finally:
        b.stop()


def test_micro_batcher_drops_cancelled_units():
    """A caller that timed out cancels its future; the dispatcher must
    not burn device time scoring work nobody will read."""
    calls = []
    gate = threading.Event()

    def scorer(rows):
        calls.append(len(rows))
        gate.wait(timeout=10)
        return np.zeros(len(rows), np.float32), "v"

    b = MicroBatcher(scorer, max_batch=4, max_delay_ms=1.0).start()
    try:
        first = b.submit([{}])
        time.sleep(0.1)  # dispatcher is blocked in scorer on `first`
        doomed = b.submit([{}])
        assert doomed.cancel()
        gate.set()
        assert len(first.result(timeout=10)["scores"]) == 1
    finally:
        b.stop()  # drains: the cancelled unit is collected and dropped
    assert calls == [1]


def test_micro_batcher_propagates_scorer_errors():
    def scorer(rows):
        raise RuntimeError("device fell over")

    b = MicroBatcher(scorer, max_batch=4, max_delay_ms=1.0).start()
    try:
        fut = b.submit([{}])
        with pytest.raises(RuntimeError, match="device fell over"):
            fut.result(timeout=10)
    finally:
        b.stop()
    with pytest.raises(RuntimeError, match="not running"):
        b.submit([{}])


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_skips_corrupt_and_index_less_versions(tmp_path, game_world):
    _, truth = game_world
    registry_dir = str(tmp_path)
    publish_version(registry_dir, _make_model(truth), _INDEX_MAPS)
    # v2: loadable model but NO feature-indexes -> refused outright
    v2 = os.path.join(registry_dir, "v-00000002")
    save_game_model(_make_model(truth, scale=2.0), v2)
    # v3: partial write (no model-metadata.json)
    v3 = os.path.join(registry_dir, "v-00000003")
    os.makedirs(v3)
    with open(os.path.join(v3, "garbage"), "w") as f:
        f.write("x")
    registry = ModelRegistry(registry_dir, max_batch=4, warm=False,
                             poll_interval=60)
    registry.start()
    try:
        assert registry.engine.version == "v-00000001"
        skipped = telemetry.snapshot()["counters"]["serving.skipped_versions"]
        assert skipped >= 2
        # unchanged bad versions are remembered, not re-read every poll
        registry.refresh()
        assert (
            telemetry.snapshot()["counters"]["serving.skipped_versions"]
            == skipped
        )
    finally:
        registry.stop()


def test_registry_with_no_valid_version_raises(tmp_path):
    registry = ModelRegistry(str(tmp_path), warm=False, poll_interval=60)
    with pytest.raises(RuntimeError, match="no valid model version"):
        registry.start()


def test_publish_version_requires_index_maps(tmp_path, game_world):
    _, truth = game_world
    with pytest.raises(ValueError, match="index_maps is required"):
        publish_version(str(tmp_path), _make_model(truth), {})


def test_registry_retries_transient_io_and_does_not_pin_the_version(
    tmp_path, game_world
):
    """One flaky read must not mark a good version skipped-by-mtime
    forever: a transient OSError on the load is retried with backoff
    (``serving.version_retries``) and the version still comes up — and
    nothing lands in the mtime-pinned skip set."""
    from photon_ml_tpu import faults

    _, truth = game_world
    registry_dir = str(tmp_path)
    publish_version(registry_dir, _make_model(truth), _INDEX_MAPS)
    telemetry.reset()
    try:
        # the first load attempt fails with an injected OSError; the
        # bounded retry's second attempt succeeds
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.registry.load", action="io", nth=1),
        ]))
        registry = ModelRegistry(registry_dir, max_batch=4, warm=False,
                                 poll_interval=60, retry_backoff_s=0.01)
        registry.start()
        try:
            assert registry.engine.version == "v-00000001"
            counters = telemetry.snapshot()["counters"]
            assert counters["serving.version_retries"] == 1
            assert counters.get("serving.skipped_versions") is None
            assert registry._skipped == {}
        finally:
            registry.stop()
    finally:
        faults.clear_plan()
        telemetry.reset()


def test_registry_transient_exhaustion_skips_refresh_not_forever(
    tmp_path, game_world
):
    """When EVERY retry of a load fails transiently, the version is
    skipped for that refresh only — the next poll retries from scratch
    (no mtime pin) and succeeds once the flake clears. Deterministic
    validation failures keep the mtime pin (existing behavior, asserted
    by test_registry_skips_corrupt_and_index_less_versions)."""
    from photon_ml_tpu import faults

    _, truth = game_world
    registry_dir = str(tmp_path)
    publish_version(registry_dir, _make_model(truth), _INDEX_MAPS)
    telemetry.reset()
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.registry.load", action="io",
                             probability=1.0),
        ]))
        registry = ModelRegistry(registry_dir, max_batch=4, warm=False,
                                 poll_interval=60, load_retries=1,
                                 retry_backoff_s=0.01)
        assert registry.refresh() is False  # both attempts flaked
        counters = telemetry.snapshot()["counters"]
        assert counters["serving.version_retries"] == 1
        assert counters["serving.skipped_versions"] == 1
        assert registry._skipped == {}  # NOT pinned: next poll retries
        faults.clear_plan()
        assert registry.refresh() is True  # the flake cleared
        assert registry.engine.version == "v-00000001"
    finally:
        faults.clear_plan()
        telemetry.reset()


# ---------------------------------------------------------------------------
# front ends
# ---------------------------------------------------------------------------


def test_stdio_jsonl_mode(game_world):
    data, truth = game_world
    model = _make_model(truth)
    engine = ScoringEngine(model, max_batch=8, version="v-test")
    rows = _request_rows(truth, data, range(3))
    expected = np.asarray(model.predict_mean(data))[:3]
    inp = io.StringIO(
        json.dumps({"rows": rows})
        + "\n"
        + json.dumps({"op": "health"})
        + "\nnot json\n"
        + json.dumps({"op": "metrics"})
        + "\n"
    )
    out = io.StringIO()
    assert serve_stdio(engine, inp, out) == 0
    lines = [json.loads(ln) for ln in out.getvalue().strip().splitlines()]
    np.testing.assert_allclose(lines[0]["scores"], expected, atol=1e-6)
    assert lines[0]["model_version"] == "v-test"
    assert lines[1]["status"] == "serving"
    assert "error" in lines[2]
    assert "counters" in lines[3]


def _post(port, body, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(port, path, timeout=15):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return json.loads(resp.read())


def test_http_error_codes(game_world):
    _, truth = game_world
    engine = ScoringEngine(_make_model(truth), max_batch=4, max_row_nnz=4)
    service = ScoringService(engine, max_batch=4, max_delay_ms=1.0)
    server = ScoringServer(service, port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, {"not_rows": []})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, {"rows": [
                {"features": {"global": [[j, 1.0] for j in range(9)]}}
            ]})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(server.port, "/nope")
        assert ei.value.code == 404
    finally:
        server.stop()


def test_serving_e2e_http_hot_swap(tmp_path, game_world):
    """The acceptance path: concurrent HTTP scoring matches
    predict_mean, a mid-run registry publish swaps versions with zero
    failed requests, and warmed steady state never recompiles."""
    data, truth = game_world
    m1 = _make_model(truth)
    m2 = _make_model(truth, scale=0.5)
    expected = {
        "v-00000001": np.asarray(m1.predict_mean(data))[: data.num_rows],
        "v-00000002": np.asarray(m2.predict_mean(data))[: data.num_rows],
    }
    registry_dir = str(tmp_path / "registry")
    publish_version(registry_dir, m1, _INDEX_MAPS)
    registry = ModelRegistry(registry_dir, max_batch=16, poll_interval=0.2)
    registry.start()
    service = ScoringService(
        registry, max_batch=16, max_delay_ms=2.0, queue_depth=10_000
    )
    server = ScoringServer(service, port=0).start()
    port = server.port
    try:
        health = _get(port, "/healthz")
        assert health["status"] == "serving"
        assert health["model_version"] == "v-00000001"
        assert health["warm"]

        indices = list(range(8))
        rows = _request_rows(truth, data, indices)

        def check(result):
            exp = expected[result["model_version"]][indices]
            np.testing.assert_allclose(result["scores"], exp, atol=1e-6)

        # steady state: the compile counter must be FLAT across >= 3
        # post-warmup batches
        check(_post(port, {"rows": rows}))
        compiles_before = telemetry.snapshot()["counters"].get(
            "jit_compiles", 0
        )
        for _ in range(3):
            check(_post(port, {"rows": rows}))
        assert (
            telemetry.snapshot()["counters"].get("jit_compiles", 0)
            == compiles_before
        )

        # concurrent clients hammer while v2 lands mid-run
        failures, seen_versions = [], set()
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    result = _post(port, {"rows": rows})
                    check(result)
                    seen_versions.add(result["model_version"])
                except Exception as e:  # noqa: BLE001 — recorded, asserted 0
                    failures.append(repr(e))

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        publish_version(registry_dir, m2, _INDEX_MAPS)
        deadline = time.monotonic() + 30
        while (
            "v-00000002" not in seen_versions
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not failures, failures[:3]
        assert seen_versions == {"v-00000001", "v-00000002"}
        assert _get(port, "/healthz")["model_version"] == "v-00000002"
        metrics = _get(port, "/metricsz")
        assert metrics["counters"]["serving.model_swaps"] == 2
        assert metrics["counters"]["serving.requests"] >= 4
        assert metrics["histograms"]["serving.queue_ms"]["count"] >= 4
    finally:
        server.stop()
        registry.stop()


def test_cli_serve_stdio_rejects_ignored_flags(tmp_path, game_world):
    """--stdio is a bare engine loop: combining it with flags that only
    affect the HTTP stack (--nearline, --frontend asyncio, --batcher)
    must fail loudly instead of silently ignoring them."""
    from photon_ml_tpu.cli import serve as serve_cli

    data, truth = game_world
    model = _make_model(truth)
    registry_dir = str(tmp_path / "registry")
    publish_version(registry_dir, model, _INDEX_MAPS)
    with pytest.raises(SystemExit, match="--nearline, --frontend"):
        serve_cli.main([
            "--registry-dir", registry_dir, "--stdio", "--max-batch", "8",
            "--nearline", "userId", "--frontend", "asyncio",
        ])


def test_cli_serve_stdio_subprocess(tmp_path, game_world):
    """`cli serve --registry-dir ... --stdio` drives the full stack (load,
    warmup, request schema) from a clean process without sockets."""
    import subprocess
    import sys

    data, truth = game_world
    model = _make_model(truth)
    registry_dir = str(tmp_path / "registry")
    publish_version(registry_dir, model, _INDEX_MAPS)
    rows = _request_rows(truth, data, range(4))
    stdin = (
        json.dumps({"rows": rows}) + "\n" + json.dumps({"op": "health"}) + "\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli", "serve",
         "--registry-dir", registry_dir, "--stdio", "--max-batch", "8"],
        input=stdin, capture_output=True, text=True, timeout=600,
        cwd=repo, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    expected = np.asarray(model.predict_mean(data))[:4]
    np.testing.assert_allclose(lines[0]["scores"], expected, atol=1e-6)
    assert lines[0]["model_version"] == "v-00000001"
    health = lines[1]
    # warm state carries per-batch-bucket compile accounting (ISSUE 5):
    # one executable-registry entry per padded bucket, with compile wall
    # time always present and cost fields null-or-numeric ("unknown" on
    # backends without cost analysis, never a crash)
    compile_state = health.pop("compile")
    assert set(compile_state) == {"1", "2", "4", "8"}
    for entry in compile_state.values():
        assert entry["compile_seconds"] >= 0
        assert entry["calls"] >= 1
        assert "flops" in entry and "bytes_accessed" in entry
    assert health == {
        "status": "serving", "model_version": "v-00000001",
        "warm": True, "buckets": [1, 2, 4, 8],
    }


# ---------------------------------------------------------------------------
# cli score guard (satellite: silent-wrong-scores hazard)
# ---------------------------------------------------------------------------


def test_score_cli_requires_feature_indexes(tmp_path, game_world):
    from photon_ml_tpu.cli.score import run

    _, truth = game_world
    model_dir = str(tmp_path / "model")
    save_game_model(_make_model(truth), model_dir)
    with pytest.raises(ModelLoadError, match="feature-indexes"):
        run(model_dir, {"format": "avro", "paths": []})
    # --allow-index-rebuild gets past the guard (and then fails on the
    # empty input spec, NOT on the index maps)
    with pytest.raises(Exception) as ei:
        run(
            model_dir,
            {"format": "avro", "paths": []},
            allow_index_rebuild=True,
        )
    assert "feature-indexes" not in str(ei.value)
