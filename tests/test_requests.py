"""Request-scoped tracing (ISSUE 18): trace-context propagation
(``X-Photon-Trace`` mint/parse roundtrip), the per-process request RING
(overflow evicts oldest, drop-counted), TAIL SAMPLING (persist only
slow / degraded / errored / explicitly-sampled requests as ``request:*``
spans), the crash-safe FLIGHT RECORDER (atomic dump, the
``telemetry.flight_dump`` fault seam, torn-tail harvest for hard-killed
members), the fleet-report join (one user request reads as one trace
spanning router + member streams, with "last words" for lost members),
and the report/CLI surfaces (``requests_summary``, ``--requests``,
merged fleet Chrome export)."""

import json
import os

import pytest

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.cli import report as cli_report
from photon_ml_tpu.telemetry import fleet_report, trace
from photon_ml_tpu.telemetry import requests as rq
from photon_ml_tpu.telemetry.report import RunReport


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    faults.clear_plan()


def _counter(name: str) -> int:
    return int(telemetry.snapshot()["counters"].get(name, 0))


# ---------------------------------------------------------------------------
# context propagation
# ---------------------------------------------------------------------------


def test_context_header_roundtrip():
    ctx = rq.make_context()
    assert ";s=1" not in ctx.to_header()
    back = rq.parse_header(ctx.to_header())
    assert (back.trace_id, back.request_id) == (ctx.trace_id, ctx.request_id)
    assert back.sampled is False

    sampled = rq.make_context(sampled=True)
    assert sampled.to_header().endswith(";s=1")
    back = rq.parse_header(sampled.to_header())
    assert back.sampled is True
    # ids are process-unique and monotone per mint
    assert sampled.trace_id != ctx.trace_id
    assert sampled.request_id != ctx.request_id


@pytest.mark.parametrize(
    "value",
    [None, "", "abc", "a/b/c", "/b", "a/", "//", ";s=1", 123, b"a/b"],
)
def test_parse_header_malformed_is_none_never_raises(value):
    # a bad header must never fail the request it rode in on
    assert rq.parse_header(value) is None


def test_parse_header_tolerates_whitespace_and_unknown_flags():
    ctx = rq.parse_header("  tid/rid;x=9;s=1  ")
    assert (ctx.trace_id, ctx.request_id, ctx.sampled) == ("tid", "rid", True)
    assert rq.parse_header("tid/rid;x=9").sampled is False


# ---------------------------------------------------------------------------
# ring overflow + drop accounting
# ---------------------------------------------------------------------------


def test_request_ring_overflow_evicts_oldest_and_counts_drops():
    rq.configure(ring_limit=4)
    for i in range(7):
        rq.finish(rq.begin(f"r{i}"))
    recs = rq.records()
    assert [r["name"] for r in recs] == ["r3", "r4", "r5", "r6"]
    assert rq.REQUESTS.dropped == 3
    assert _counter("telemetry.trace_dropped") == 3
    assert _counter("request.records") == 7
    # reset restores the default cap and clears drop accounting
    rq.reset()
    assert rq.REQUESTS.dropped == 0
    assert rq.REQUESTS._ring_limit == rq.DEFAULT_RING_LIMIT


def test_tracer_buffer_overflow_evicts_oldest_and_counts_drops():
    telemetry.configure(buffer_limit=4)
    now = trace.TRACER.now()
    for i in range(10):
        trace.TRACER.emit(f"s{i}", ts=now, dur=0.001)
    kept = [s.name for s in trace.finished_spans()]
    assert kept == ["s6", "s7", "s8", "s9"]
    assert trace.TRACER.dropped_spans == 6
    assert _counter("trace.dropped_spans") == 6


def test_disabled_tracer_records_nothing():
    rq.configure(enabled=False)
    assert rq.begin("x") is None
    assert rq.finish(None) is None
    assert rq.records() == []
    rq.configure(enabled=True)
    assert rq.begin("x") is not None


# ---------------------------------------------------------------------------
# tail sampling
# ---------------------------------------------------------------------------


def _persisted(name="x"):
    return trace.finished_spans(f"request:{name}")


def test_tail_sampling_persists_error_degraded_sampled_slow():
    # fast + unsampled + threshold still filling: ring-only, no spans
    rq.finish(rq.begin("x"))
    assert _persisted() == []

    rq.finish(rq.begin("x"), status="error", error="boom")
    (err,) = _persisted()
    assert err.attrs["sampled_reason"] == "error"
    assert err.attrs["error"] == "boom"

    rq.finish(rq.begin("x", degraded=True))
    assert [s.attrs["sampled_reason"] for s in _persisted()] == [
        "error", "degraded",
    ]

    rec = rq.begin("x", ctx=rq.make_context(sampled=True))
    rq.finish(rec)
    assert _persisted()[-1].attrs["sampled_reason"] == "sampled"

    # a pinned slow threshold of 0 makes everything "slow"
    rq.configure(slow_threshold_ms=0.0)
    rq.finish(rq.begin("x"))
    assert _persisted()[-1].attrs["sampled_reason"] == "slow"
    # ...and None restores the rolling p99 (still unfilled -> not slow)
    rq.configure(slow_threshold_ms=None)
    before = len(_persisted())
    rq.finish(rq.begin("x"))
    assert len(_persisted()) == before
    assert _counter("request.persisted") == before


def test_error_outranks_sampled_and_root_carries_phase_children():
    rec = rq.begin(
        "score", ctx=rq.make_context(sampled=True), role="member",
        version="v3", fleet_size=4,
    )
    rec.phase("batcher_wait", 2.0)
    rec.phase("device_dispatch", 1.0)
    rq.finish(rec, status="error", error="shed")
    (root,) = trace.finished_spans("request:score")
    assert root.attrs["sampled_reason"] == "error"  # error > sampled
    assert root.attrs["trace_id"] == rec.ctx.trace_id
    assert root.attrs["version"] == "v3"
    assert root.attrs["fleet_size"] == 4
    assert root.attrs["phases"] == {
        "batcher_wait": 2.0, "device_dispatch": 1.0,
    }
    children = trace.finished_spans("request:score:batcher_wait")
    assert children and children[0].parent_id == root.span_id
    assert children[0].attrs["trace_id"] == rec.ctx.trace_id


def test_rolling_p99_threshold_engages_after_min_samples():
    assert rq.REQUESTS.slow_threshold_ms is None
    for _ in range(128):
        rq.finish(rq.begin("x"))
    # 128 finishes > _MIN_SAMPLES with recompute every 32: engaged
    assert rq.REQUESTS.slow_threshold_ms is not None


# ---------------------------------------------------------------------------
# flight recorder: dump, read, fault seam, harvest
# ---------------------------------------------------------------------------


def test_flight_path_naming_contract(monkeypatch):
    assert rq.flight_path("/x", 3) == "/x/flight-proc-3.json"
    monkeypatch.setenv("PHOTON_PROC_ID", "2")
    assert rq.flight_path("/x").endswith("flight-proc-2.json")
    assert fleet_report._FLIGHT_RE.match("flight-proc-3.json")
    # the atomic-write shadow must never look adoptable
    assert not fleet_report._FLIGHT_RE.match("flight-proc-3.json.tmp")


def test_flight_dump_read_roundtrip(tmp_path):
    for i in range(5):
        rq.finish(rq.begin(f"r{i}"))
    path = str(tmp_path / "flight-proc-0.json")
    assert rq.flight_dump(path) == 5
    doc = rq.read_flight(path)
    assert doc["type"] == "flight_record"
    assert [r["name"] for r in doc["records"]] == [f"r{i}" for i in range(5)]
    assert doc["window_s"] == 30.0
    assert doc["dropped"] == 0
    assert "anchor_unix_s" in doc and "monotonic_anchor" in doc
    # the window filter: nothing just-finished survives last_s=0
    assert rq.flight_dump(path, last_s=0.0) == 0

    # read_flight: absent / torn / not-a-flight-record -> None
    assert rq.read_flight(str(tmp_path / "missing.json")) is None
    (tmp_path / "torn.json").write_text('{"type": "flight_record", "rec')
    assert rq.read_flight(str(tmp_path / "torn.json")) is None
    (tmp_path / "other.json").write_text('{"type": "metrics"}')
    assert rq.read_flight(str(tmp_path / "other.json")) is None


def test_flight_dump_fault_seam_fails_soft(tmp_path):
    rq.finish(rq.begin("x"))
    faults.install_plan(
        faults.FaultPlan(
            [faults.FaultRule("telemetry.flight_dump", action="io", nth=1)]
        )
    )
    path = str(tmp_path / "flight-proc-0.json")
    # the drain path must survive a failed dump: None, counted, no file
    assert rq.flight_dump(path) is None
    assert _counter("telemetry.flight_dump_failures") == 1
    assert not os.path.exists(path)
    assert not os.path.exists(path + ".tmp")
    # seam disarmed: the retry lands atomically
    faults.clear_plan()
    assert rq.flight_dump(path) == 1
    assert rq.read_flight(path)["records"][0]["name"] == "x"


def test_tail_records_drops_torn_first_and_last_lines(tmp_path):
    path = tmp_path / "trace.proc-0.jsonl"
    header = {"type": "trace_header", "anchor_unix_s": 1.0,
              "monotonic_anchor": 0.0, "hostname": "h"}
    lines = [json.dumps(header)]
    for i in range(50):
        lines.append(json.dumps(
            {"type": "span", "name": f"s{i}", "ts": float(i), "dur": 0.001,
             "attrs": {"pad": "x" * 64}}
        ))
    path.write_text("\n".join(lines) + "\n" + '{"type": "span", "na')
    # full read: torn LAST line (hard kill mid-write) skipped silently
    hdr, recs = rq.tail_records(str(path))
    assert hdr["type"] == "trace_header"
    assert len(recs) == 51  # header line parses as a record too
    assert recs[-1]["name"] == "s49"
    # bounded read: the seek lands mid-line, the torn FIRST line drops,
    # the header still comes from the file's real first line
    hdr, recs = rq.tail_records(str(path), max_tail_bytes=400)
    assert hdr["type"] == "trace_header"
    assert 0 < len(recs) < 10
    assert all(isinstance(r, dict) for r in recs)


def test_harvest_flight_windows_and_anchors(tmp_path):
    path = tmp_path / "trace.proc-1.jsonl"
    header = {"type": "trace_header", "anchor_unix_s": 123.0,
              "monotonic_anchor": 5.0, "hostname": "h", "process_index": 1}
    spans = [
        {"type": "span", "name": "request:old", "ts": 0.0, "dur": 0.001},
        {"type": "span", "name": "request:new", "ts": 100.0, "dur": 0.002},
    ]
    path.write_text(
        "\n".join(json.dumps(r) for r in [header] + spans)
        + "\n" + '{"torn'
    )
    out = str(tmp_path / "flight-proc-1.json")
    assert rq.harvest_flight(str(path), out, last_s=10.0) == 1
    doc = rq.read_flight(out)
    assert doc["harvested"] is True
    assert doc["process_index"] == 1
    assert doc["anchor_unix_s"] == 123.0
    assert [r["name"] for r in doc["records"]] == ["request:new"]
    # a missing or span-free stream harvests to None, writes nothing
    missing_out = str(tmp_path / "flight-proc-2.json")
    assert rq.harvest_flight(str(tmp_path / "nope.jsonl"), missing_out) is None
    assert not os.path.exists(missing_out)


# ---------------------------------------------------------------------------
# the fleet join: one request across router + members (+ flight records)
# ---------------------------------------------------------------------------


def _build_fleet_dir(tmp_path, monkeypatch):
    """A synthetic 2-member fleet dir carrying ONE fanned-out request:
    router stream + member streams share a trace_id; member 1 "dies"
    (no metrics snapshot, torn trace tail) and gets a harvested flight
    record."""
    d = tmp_path / "fleet"
    d.mkdir(exist_ok=True)
    monkeypatch.delenv("PHOTON_PROC_ID", raising=False)
    monkeypatch.setenv("PHOTON_PROC_COUNT", "2")

    telemetry.configure(trace_out=str(d / "trace.router.jsonl"))
    ctx = rq.make_context(sampled=True)
    rec = rq.begin("route", ctx=ctx, role="router", fleet_size=2)
    rec.phase("fanout", 2.0)
    rq.finish(rec)

    monkeypatch.setenv("PHOTON_PROC_ID", "0")
    telemetry.configure(
        trace_out=telemetry.member_artifact_path(str(d / "trace.jsonl"))
    )
    rec = rq.begin("margins", ctx=ctx, role="member", version="v1",
                   fleet_size=2)
    rec.phase("engine_dispatch", 1.5)
    rq.finish(rec)
    (d / "telemetry.proc-0.jsonl").write_text(
        json.dumps({"type": "metrics", "snapshot": {"counters": {}}}) + "\n"
    )

    monkeypatch.setenv("PHOTON_PROC_ID", "1")
    m1 = telemetry.member_artifact_path(str(d / "trace.jsonl"))
    telemetry.configure(trace_out=m1)
    rec = rq.begin("margins", ctx=ctx, role="member", version="v1",
                   fleet_size=2)
    rec.phase("engine_dispatch", 1.1)
    rq.finish(rec)
    telemetry.configure(trace_out=str(tmp_path / "scratch.jsonl"))
    with open(m1, "a", encoding="utf-8") as fh:
        fh.write('{"type": "span", "torn')  # hard kill mid-write
    assert rq.harvest_flight(m1, rq.flight_path(str(d), 1)) is not None

    monkeypatch.delenv("PHOTON_PROC_ID", raising=False)
    return d, ctx


def test_fleet_report_joins_one_request_across_processes(
    tmp_path, monkeypatch
):
    d, ctx = _build_fleet_dir(tmp_path, monkeypatch)
    fr = fleet_report.FleetReport.load(str(d))
    assert [m.process_index for m in fr.members] == [0, 1]
    assert fr.router is not None and fr.router.process_index == -1
    assert fr.router_trace_path.endswith("trace.router.jsonl")

    traces = fr.request_traces()
    (t,) = [t for t in traces if t["trace_id"] == ctx.trace_id]
    # one user request spans the router and BOTH members
    assert t["sources"] == ["proc-0", "proc-1", "router"]
    assert t["status"] == "ok"
    by_source = {h["source"]: h for h in t["hops"]}
    assert by_source["router"]["phases"] == {"fanout": 2.0}
    for proc in ("proc-0", "proc-1"):
        hop = by_source[proc]
        assert hop["phases"]  # non-empty phase decomposition
        assert hop["attrs"]["version"] == "v1"
        assert hop["attrs"]["fleet_size"] == 2
    # the harvested flight re-read the same stream member 1 persisted
    # to: still exactly one hop per process
    assert len(t["hops"]) == 3


def test_fleet_report_last_words_for_lost_member(tmp_path, monkeypatch):
    d, _ctx = _build_fleet_dir(tmp_path, monkeypatch)
    fr = fleet_report.FleetReport.load(str(d))
    assert fr.lost_members() == [1]
    m1 = fr.members[1]
    assert m1.flight is not None and m1.flight.get("harvested")
    assert m1.flight_path.endswith("flight-proc-1.json")
    md = fr.to_markdown()
    assert "## Flight recorder" in md
    assert "Last words — member 1" in md
    assert "## Requests" in md
    assert "router" in md
    doc = fr.to_json()
    assert doc["request_traces"]
    assert doc["router_trace"] == fr.router_trace_path


def test_fleet_chrome_export_merges_member_tracks(tmp_path, monkeypatch):
    d, _ctx = _build_fleet_dir(tmp_path, monkeypatch)
    tc = telemetry.to_chrome_trace(str(d))
    names = {
        e["args"]["name"]: e["pid"]
        for e in tc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert any(k.startswith("proc-0") for k in names)
    assert any(k.startswith("proc-1") for k in names)
    assert len({pid for pid in names.values()}) == 2
    assert any(
        e.get("ph") == "X" and e["name"].startswith("request:")
        for e in tc["traceEvents"]
    )
    out = str(tmp_path / "fleet.perfetto.json")
    telemetry.export_chrome_trace(str(d), out)
    with open(out, encoding="utf-8") as fh:
        assert json.load(fh)["traceEvents"]


# ---------------------------------------------------------------------------
# RunReport + CLI surfaces
# ---------------------------------------------------------------------------


def _build_run_artifacts(tmp_path):
    tpath = str(tmp_path / "run.trace.jsonl")
    mpath = str(tmp_path / "run.metrics.jsonl")
    telemetry.configure(trace_out=tpath)
    rec = rq.begin("score", ctx=rq.make_context(sampled=True))
    rec.phase("batcher_wait", 3.0)
    rq.finish(rec)
    rq.finish(rq.begin("score"), status="error", error="boom")
    rq.finish(rq.begin("score"))  # ring-only
    telemetry.flush_metrics(mpath)
    return tpath, mpath


def test_run_report_requests_summary_and_slowest(tmp_path):
    tpath, mpath = _build_run_artifacts(tmp_path)
    run = RunReport.load(trace=tpath, telemetry=mpath)
    rs = run.requests_summary()
    assert rs["records"] == 3
    assert rs["persisted"] == 2
    assert rs["dropped"] == 0
    assert rs["p99_ms"] is not None
    assert rs["phases"]["batcher_wait"]["count"] == 1
    slow = run.slowest_requests()
    assert len(slow) == 2
    assert {r["sampled_reason"] for r in slow} == {"sampled", "error"}
    assert all(r["trace_id"] for r in slow)
    md = run.to_markdown()
    assert "## Requests" in md
    assert "persisted by tail sampling" in md
    assert run.to_json()["requests"]["records"] == 3


def test_run_report_without_requests_has_no_section():
    run = RunReport(spans=[], snapshot={"counters": {"x": 1}})
    assert run.requests_summary() is None
    assert "## Requests" not in run.to_markdown()


def test_cli_report_requests_flag(tmp_path, capsys):
    tpath, mpath = _build_run_artifacts(tmp_path)
    assert cli_report.main(
        ["--trace", tpath, "--telemetry", mpath, "--requests", "5"]
    ) == 0
    out = capsys.readouterr().out
    assert "## Requests" in out
    assert "Slowest persisted traces" in out

    # a run with no request records says so instead of an empty report
    empty = str(tmp_path / "empty.trace.jsonl")
    telemetry.reset()
    telemetry.configure(trace_out=empty)
    telemetry.configure(trace_out=str(tmp_path / "scratch2.jsonl"))
    assert cli_report.main(["--trace", empty, "--requests"]) == 0
    assert "No request traces" in capsys.readouterr().out
