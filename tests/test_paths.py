"""Date-range input path expansion (IOUtils/DateRange analog)."""

import datetime
import os

import pytest

from photon_ml_tpu.data.paths import (
    daily_paths,
    expand_input_paths,
    parse_date_range,
    parse_days_ago,
)


def test_parse_date_range():
    s, e = parse_date_range("20160101-20160131")
    assert s == datetime.date(2016, 1, 1)
    assert e == datetime.date(2016, 1, 31)
    with pytest.raises(ValueError, match="start"):
        parse_date_range("20160131-20160101")
    with pytest.raises(ValueError, match="yyyymmdd"):
        parse_date_range("2016-01-01")


def test_parse_days_ago():
    today = datetime.date(2016, 2, 1)
    s, e = parse_days_ago("31-1", today=today)
    assert s == datetime.date(2016, 1, 1)
    assert e == datetime.date(2016, 1, 31)
    with pytest.raises(ValueError, match="starts after"):
        parse_days_ago("1-31", today=today)


def test_daily_paths_and_expand(tmp_path):
    root = tmp_path / "daily"
    for d in (1, 2, 4):  # day 3 missing
        os.makedirs(root / "2016" / "01" / f"{d:02d}")
    got = daily_paths(str(root), datetime.date(2016, 1, 1),
                      datetime.date(2016, 1, 4))
    assert [p[-10:] for p in got] == ["2016/01/01", "2016/01/02", "2016/01/04"]
    with pytest.raises(FileNotFoundError):
        daily_paths(str(root), datetime.date(2016, 1, 1),
                    datetime.date(2016, 1, 4), error_on_missing=True)
    got2 = expand_input_paths([str(root)], date_range="20160101-20160104")
    assert got2 == got
    # passthrough without a range
    assert expand_input_paths(["a", "b"]) == ["a", "b"]
    with pytest.raises(FileNotFoundError, match="no daily"):
        expand_input_paths([str(root)], date_range="20200101-20200102")


def test_read_input_with_date_range(tmp_path, rng):
    """End-to-end: avro daily dirs selected by date range."""
    from photon_ml_tpu.cli.train import read_input
    from photon_ml_tpu.data.avro import TRAINING_EXAMPLE_AVRO, write_avro

    def rec(i):
        return {
            "uid": str(i), "label": float(i % 2),
            "features": [{"name": "f", "term": "", "value": 1.0 + i}],
            "metadataMap": None, "weight": None, "offset": None,
        }

    root = tmp_path / "daily"
    for day, lo in ((1, 0), (2, 10)):
        d = root / "2016" / "01" / f"{day:02d}"
        os.makedirs(d)
        write_avro(str(d / "part.avro"), TRAINING_EXAMPLE_AVRO,
                   [rec(lo + j) for j in range(5)])

    data, _ = read_input({
        "format": "avro",
        "paths": [str(root)],
        "date_range": "20160101-20160101",
    })
    assert data.num_rows == 5
    data2, _ = read_input({
        "format": "avro",
        "paths": [str(root)],
        "date_range": "20160101-20160102",
    })
    assert data2.num_rows == 10
