"""ISSUE 8: on-device hyperparameter sweeps — vmapped multi-λ training,
warm-started regularization paths, best-model selection, and registry
export.

Acceptance paths covered here:
- per-config loss PARITY of the batched sweep vs independent single fits
  at the same λs (rtol 1e-6), and the selected model's validation metric
  >= the best of those independent fits;
- the sweep winner exported through publish_version is hot-swapped by a
  live ModelRegistry and serves scores matching predict_mean to 1e-6;
- xla.recompiles stays flat across the warmed sweep executable.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.evaluation.evaluators import EVALUATORS, better_than
from photon_ml_tpu.game.dataset import build_game_dataset
from photon_ml_tpu.game.estimator import (
    FactoredRandomEffectConfig,
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    RandomEffectConfig,
)
from photon_ml_tpu.optim.factory import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
    solve,
    split_reg_weights,
)
from photon_ml_tpu.sweep import (
    SweepGrid,
    SweepSelectionError,
    SweepSpecError,
    SweepUnsupportedError,
    parse_sweep_spec,
    run_selection,
    select_best,
    sweep_game,
    sweep_glm,
)
from photon_ml_tpu.sweep.runner import path_warm_start
from photon_ml_tpu.testing import generate_game_dataset, generate_glm_problem

L2 = RegularizationContext(RegularizationType.L2)


# ---------------------------------------------------------------------------
# grid grammar
# ---------------------------------------------------------------------------


class TestGrid:
    def test_log_range_descending(self):
        grid = parse_sweep_spec("lambda=1e-4:1e2:log16")
        assert grid.size == 16
        lams = grid.default
        assert lams[0] == pytest.approx(100.0)
        assert lams[-1] == pytest.approx(1e-4)
        assert all(a > b for a, b in zip(lams, lams[1:]))

    def test_lin_range_and_explicit_list(self):
        assert parse_sweep_spec("lambda=0:2:lin3").default == (2.0, 1.0, 0.0)
        assert parse_sweep_spec("lambda=0.1,10,1").default == (10.0, 1.0, 0.1)

    def test_per_coordinate_override_and_broadcast(self):
        grid = parse_sweep_spec(
            ["lambda=1:100:log3", "lambda.perUser=5"]
        )
        assert grid.size == 3
        assert grid.for_coordinate("fixed") == grid.default
        assert grid.for_coordinate("perUser") == (5.0, 5.0, 5.0)

    def test_duplicates_removed(self):
        assert parse_sweep_spec("lambda=1,1,2").default == (2.0, 1.0)

    @pytest.mark.parametrize(
        "spec, match",
        [
            ("lambda=", "empty grid"),
            ("lambda", "expected"),
            ("lambda=10:1:log4", "inverted range"),
            ("lambda=1:10:log0", "zero/negative point count"),
            ("lambda=1:10:lin-2", "zero/negative point count"),
            ("lambda=-1,2", "negative regularization"),
            ("lambda=1:10:geo4", "must be 'logN' or 'linN'"),
            ("lambda=a,b", "not a number"),
            ("lambda=0:10:log4", "log spacing needs lo > 0"),
            ("gamma=1,2", "unknown key"),
            ("lambda=1:10", "ranges are"),
            ("lambda=nan", "not finite"),
            ("lambda=inf", "not finite"),
        ],
    )
    def test_malformed_specs_are_typed_and_name_the_token(self, spec, match):
        with pytest.raises(SweepSpecError, match=match) as err:
            parse_sweep_spec(spec)
        # the offending token is in the message for log-grepping
        assert spec.split("=")[0] in str(err.value)

    def test_mismatched_lengths_rejected(self):
        with pytest.raises(SweepSpecError, match="one config-axis length"):
            parse_sweep_spec(["lambda=1,2,3", "lambda.fixed=1,2"])

    def test_missing_default_for_coordinate(self):
        grid = parse_sweep_spec("lambda.fixed=1,2")
        with pytest.raises(SweepSpecError, match="no default"):
            grid.for_coordinate("perUser")


# ---------------------------------------------------------------------------
# GLM sweep: parity + warm start + recompile discipline
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def glm_problem():
    return generate_glm_problem("logistic", n=400, d=10, seed=11)


class TestGlmSweep:
    def test_16_lane_parity_with_independent_fits(self, glm_problem):
        """ACCEPTANCE: per-config losses of the batched 16-λ sweep match
        16 independent single fits at the same λs to 1e-6 (relative)."""
        cfg = OptimizerConfig(
            max_iterations=60, tolerance=1e-8, regularization=L2
        )
        lams = parse_sweep_spec("lambda=1e-3:1e2:log16").default
        res = sweep_glm(
            glm_problem.batch.device(), "logistic", lams, cfg,
            warm_start=False,
        )
        sweep_vals = np.asarray(res.values)
        single_vals = []
        for g, lam in enumerate(res.lambdas):
            ind = solve(
                "logistic", glm_problem.batch,
                dataclasses.replace(cfg, regularization_weight=lam),
                jnp.zeros((10,), jnp.float32),
            )
            single_vals.append(float(ind.value))
        np.testing.assert_allclose(
            sweep_vals, single_vals, rtol=1e-6,
            err_msg="batched sweep lanes diverge from independent fits",
        )

    def test_warm_start_refinement_never_worse(self, glm_problem):
        cfg = OptimizerConfig(
            max_iterations=25, tolerance=1e-9, regularization=L2
        )
        lams = parse_sweep_spec("lambda=1e-3:10:log8").default
        cold = sweep_glm(
            glm_problem.batch.device(), "logistic", lams, cfg,
            warm_start=False,
        )
        warm = sweep_glm(
            glm_problem.batch.device(), "logistic", lams, cfg,
            warm_start=True,
        )
        assert warm.rounds == 2
        # the warm refinement round can only improve (or tie) each lane
        assert np.all(
            np.asarray(warm.values) <= np.asarray(cold.values) + 1e-5
        )

    def test_lambdas_sorted_descending_whatever_the_input_order(
        self, glm_problem
    ):
        cfg = OptimizerConfig(max_iterations=5, regularization=L2)
        res = sweep_glm(
            glm_problem.batch.device(), "logistic", (0.1, 10.0, 1.0), cfg,
        )
        assert res.lambdas == (10.0, 1.0, 0.1)
        assert res.size == 3
        assert len(res.reason_names()) == 3

    def test_path_warm_start_masks_converged_lanes(self):
        w = jnp.asarray([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        # lane 1 converged (reason 3) keeps its own w; lanes 0/2 (reason 1
        # = MaxIterations) take their more-regularized neighbor
        reasons = jnp.asarray([1, 3, 1], jnp.int32)
        out = np.asarray(path_warm_start(w, reasons))
        np.testing.assert_allclose(out[0], [1.0, 1.0])  # lane 0: itself
        np.testing.assert_allclose(out[1], [2.0, 2.0])  # converged: kept
        np.testing.assert_allclose(out[2], [2.0, 2.0])  # from lane 1

    def test_recompiles_flat_across_warmed_sweep(self, glm_problem):
        """ACCEPTANCE: the G-config executable is multi_shape by design —
        re-running the warmed sweep must not grow xla.recompiles."""
        from photon_ml_tpu.telemetry import metrics

        cfg = OptimizerConfig(max_iterations=8, regularization=L2)
        lams = parse_sweep_spec("lambda=0.1:10:log4").default
        batch = glm_problem.batch.device()
        sweep_glm(batch, "logistic", lams, cfg, warm_start=False)  # warmup
        before = metrics.peek_counter("xla.recompiles") or 0
        sweep_glm(batch, "logistic", lams, cfg, warm_start=False)
        after = metrics.peek_counter("xla.recompiles") or 0
        assert after == before

    def test_mesh_shards_config_axis_with_parity(self, glm_problem):
        """A model-axis mesh partitions the config lanes across devices
        (pad lanes included: G=3 on 8 devices) with results matching the
        meshless sweep."""
        import jax

        if jax.device_count() < 2:
            pytest.skip("needs a multi-device (virtual CPU) platform")
        from photon_ml_tpu.parallel import make_mesh

        cfg = OptimizerConfig(
            max_iterations=20, tolerance=1e-8, regularization=L2
        )
        lams = (10.0, 1.0, 0.1)
        batch = glm_problem.batch.device()
        plain = sweep_glm(batch, "logistic", lams, cfg, warm_start=False)
        mesh = make_mesh({"model": jax.device_count()})
        sharded = sweep_glm(
            batch, "logistic", lams, cfg, warm_start=False, mesh=mesh
        )
        assert sharded.size == 3
        np.testing.assert_allclose(
            np.asarray(sharded.values), np.asarray(plain.values), rtol=1e-5
        )
        # coefficients agree to convergence tolerance (sharded reductions
        # reorder float sums, so trajectories differ at the last ulps)
        np.testing.assert_allclose(
            np.asarray(sharded.w), np.asarray(plain.w), atol=1e-3
        )

    def test_empty_grid_rejected(self, glm_problem):
        cfg = OptimizerConfig(max_iterations=5)
        with pytest.raises(ValueError, match="non-empty"):
            sweep_glm(glm_problem.batch.device(), "logistic", (), cfg)

    def test_split_reg_weights_shapes(self):
        l2s, l1s = split_reg_weights(L2, (1.0, 0.5))
        np.testing.assert_allclose(np.asarray(l2s), [1.0, 0.5])
        np.testing.assert_allclose(np.asarray(l1s), [0.0, 0.0])
        none = RegularizationContext(RegularizationType.NONE)
        l2s, l1s = split_reg_weights(none, (1.0, 0.5, 2.0))
        assert l2s.shape == l1s.shape == (3,)
        np.testing.assert_allclose(np.asarray(l2s), 0.0)


# ---------------------------------------------------------------------------
# GAME sweep
# ---------------------------------------------------------------------------


def _split_game_dataset(n_users=10, rows_per_user=16, fe_dim=6, re_dim=4,
                        seed=5):
    """One planted GLMix world split into interleaved train/validation
    GameDatasets (every user appears in both)."""
    data, truth = generate_game_dataset(
        n_users=n_users, rows_per_user=rows_per_user, fe_dim=fe_dim,
        re_dim=re_dim, seed=seed,
    )
    n = data.num_rows
    val_mask = np.arange(n) % 4 == 3

    def subset(mask):
        from photon_ml_tpu.ops.sparse import SparseBatch

        idx = np.nonzero(mask)[0]
        return build_game_dataset(
            response=data.response[idx],
            feature_shards={
                "global": SparseBatch.from_dense(
                    truth["Xg"][idx], data.response[idx]
                ),
                "user": SparseBatch.from_dense(
                    truth["Xu"][idx], data.response[idx]
                ),
            },
            id_columns={"userId": truth["users"][idx]},
        )

    return subset(~val_mask), subset(val_mask), truth


@pytest.fixture(scope="module")
def game_split():
    return _split_game_dataset()


def _game_config(num_iterations=2, max_iterations=30):
    return GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="global",
                optimizer=OptimizerConfig(
                    max_iterations=max_iterations, regularization=L2
                ),
            ),
            "perUser": RandomEffectConfig(
                shard_name="user",
                id_name="userId",
                optimizer=OptimizerConfig(
                    max_iterations=max_iterations, regularization=L2
                ),
            ),
        },
        num_iterations=num_iterations,
        evaluators=("auc",),
    )


class TestGameSweep:
    def test_selected_beats_independent_single_fits(self, game_split):
        """ACCEPTANCE: the sweep's selected validation metric is >= the
        best of independent single fits run at the same λ lanes."""
        from photon_ml_tpu.game.coordinate_descent import (
            padded_validation_arrays,
        )

        train, val, _ = game_split
        config = _game_config()
        grid = parse_sweep_spec("lambda=0.03:30:log4")
        # warm_start off: the acceptance compares the BATCHED executable
        # against independent fits, so lanes must run the exact same
        # cold-start CD schedule the single fits run
        result = sweep_game(config, train, grid, warm_start=False)
        selection = run_selection(result, val)
        assert selection.metric == "auc"

        best_single = None
        for lam in grid.default:
            cfg1 = GameConfig(
                task="logistic",
                coordinates={
                    name: dataclasses.replace(
                        c,
                        optimizer=dataclasses.replace(
                            c.optimizer, regularization_weight=lam
                        ),
                    )
                    for name, c in config.coordinates.items()
                },
                num_iterations=config.num_iterations,
            )
            fit = GameEstimator(cfg1).fit(train)
            # final-model metric through the same evaluator inputs the
            # sweep selector uses (apples to apples)
            scores = fit.model.score(val)
            labels, weights, offsets = padded_validation_arrays(
                val, scores.shape[0]
            )
            value = float(EVALUATORS["auc"](scores + offsets, labels, weights))
            if best_single is None or better_than("auc", value, best_single):
                best_single = value
        assert selection.best_value >= best_single - 1e-6

    def test_per_coordinate_lambdas_and_convergence(self, game_split):
        train, _val, _ = game_split
        grid = parse_sweep_spec(
            ["lambda=0.1:10:log3", "lambda.perUser=1"]
        )
        result = sweep_game(_game_config(num_iterations=1), train, grid)
        assert result.size == 3
        assert result.lambdas["fixed"] == grid.default
        assert result.lambdas["perUser"] == (1.0, 1.0, 1.0)
        conv = result.convergence()
        for name in ("fixed", "perUser"):
            assert conv[name]["iterations"].shape == (3,)
            assert np.all(conv[name]["values"] > 0)
        assert [h["coordinate"] for h in result.history] == [
            "fixed", "perUser",
        ]

    def test_winning_lane_matches_estimator_fit(self, game_split):
        """A sweep lane's model is the same model a plain estimator fit
        produces at that λ (same CD schedule, warm start excluded)."""
        train, _val, _ = game_split
        lam = 1.0
        grid = SweepGrid(default=(lam,))
        result = sweep_game(
            _game_config(num_iterations=2), train, grid, warm_start=False
        )
        model = result.model_for(0)
        cfg1 = GameConfig(
            task="logistic",
            coordinates={
                name: dataclasses.replace(
                    c,
                    optimizer=dataclasses.replace(
                        c.optimizer, regularization_weight=lam
                    ),
                )
                for name, c in _game_config(2).coordinates.items()
            },
            num_iterations=2,
        )
        fit = GameEstimator(cfg1).fit(train)
        # both ran 2 CD iterations to convergence tolerance; they agree up
        # to that tolerance (bitwise lane parity is covered by the GLM
        # parity test above — CD residual paths add tolerance-level noise)
        np.testing.assert_allclose(
            np.asarray(model.models["fixed"].coefficients),
            np.asarray(fit.model.models["fixed"].coefficients),
            atol=5e-3,
        )
        scores_sweep = np.asarray(model.score(train))
        scores_fit = np.asarray(fit.model.score(train))
        np.testing.assert_allclose(scores_sweep, scores_fit, atol=5e-3)

    def test_validation_scores_match_per_lane_model_score(self, game_split):
        """The on-device [G, n] validation scorer must agree with the
        host model.score path for every lane — it feeds selection."""
        train, val, _ = game_split
        grid = parse_sweep_spec("lambda=0.1,1,10")
        result = sweep_game(_game_config(num_iterations=1), train, grid)
        all_scores = np.asarray(result.validation_scores(val))
        for g in range(result.size):
            model = result.model_for(g)
            expected = np.asarray(model.score(val))
            np.testing.assert_allclose(
                all_scores[g], expected, atol=1e-5,
                err_msg=f"lane {g} on-device validation scores diverge",
            )

    def test_unsupported_coordinates_are_typed(self, game_split):
        train, _val, _ = game_split
        config = GameConfig(
            task="squared",
            coordinates={
                "mf": FactoredRandomEffectConfig(
                    shard_name="user", id_name="userId", latent_dim=2
                ),
            },
        )
        with pytest.raises(SweepUnsupportedError, match="mf"):
            sweep_game(config, train, SweepGrid(default=(1.0,)))

    def test_down_sampling_rejected(self, game_split):
        train, _val, _ = game_split
        config = GameConfig(
            task="logistic",
            coordinates={
                "fixed": FixedEffectConfig(
                    shard_name="global",
                    optimizer=OptimizerConfig(down_sampling_rate=0.5),
                ),
            },
        )
        with pytest.raises(SweepUnsupportedError, match="down-sampling"):
            sweep_game(config, train, SweepGrid(default=(1.0,)))


# ---------------------------------------------------------------------------
# selection policies + degenerate metrics
# ---------------------------------------------------------------------------


class TestSelection:
    def test_best_policy_prefers_more_regularized_on_tie(self):
        metrics = np.asarray([0.7, 0.7, 0.6])
        assert select_best(metrics, "auc") == 0

    def test_minimizing_metrics_select_min(self):
        metrics = np.asarray([3.0, 1.0, 2.0])
        assert select_best(metrics, "rmse") == 1

    def test_nan_lanes_excluded_with_counter(self):
        from photon_ml_tpu.telemetry import metrics as tmetrics

        before = tmetrics.peek_counter("sweep.nan_configs") or 0
        values = np.asarray([np.nan, 0.8, 0.9])
        assert select_best(values, "auc") == 2
        assert (tmetrics.peek_counter("sweep.nan_configs") or 0) == before + 1

    def test_all_nan_is_typed_error_not_silent_argmax(self):
        with pytest.raises(SweepSelectionError, match="non-finite"):
            select_best(np.asarray([np.nan, np.nan]), "auc")

    def test_parsimonious_policy(self):
        metrics = np.asarray([0.897, 0.899, 0.9])
        # within 1% of the best -> the most regularized lane wins
        assert select_best(metrics, "auc", policy="parsimonious") == 0
        assert select_best(
            metrics, "auc", policy="parsimonious", rel_tol=1e-5
        ) == 2

    def test_unknown_policy_typed(self):
        with pytest.raises(SweepSelectionError, match="unknown selection"):
            select_best(np.asarray([0.5]), "auc", policy="magic")

    def test_sharded_metric_spec_rejected(self, game_split):
        train, val, _ = game_split
        result = sweep_game(
            _game_config(num_iterations=1), train,
            SweepGrid(default=(1.0,)),
        )
        with pytest.raises(SweepSelectionError, match="auc:queryid"):
            run_selection(result, val, metric="auc:queryid")

    def test_single_class_validation_degrades_to_half_auc(self, game_split):
        """A single-class validation split must yield the evaluators'
        documented 0.5 AUC fallback for every lane — selectable, never
        NaN (the sweep then just picks lane 0 deterministically)."""
        train, val, _ = game_split
        one_class = build_game_dataset(
            response=np.ones(val.num_rows),
            feature_shards=dict(val.feature_shards),
            id_columns=dict(val.id_columns),
        )
        result = sweep_game(
            _game_config(num_iterations=1), train,
            SweepGrid(default=(0.5, 5.0)),
        )
        selection = run_selection(result, one_class)
        np.testing.assert_allclose(selection.metrics, 0.5, atol=1e-6)
        assert selection.index == 0


# ---------------------------------------------------------------------------
# serving export e2e
# ---------------------------------------------------------------------------


class TestServingExport:
    def test_winner_published_and_hot_swapped_by_live_registry(
        self, game_split, tmp_path
    ):
        """ACCEPTANCE: sweep -> publish_version -> a LIVE ModelRegistry
        hot-swaps to the winner and serves scores matching the winner's
        predict_mean to 1e-6."""
        from photon_ml_tpu.serving import ModelRegistry, publish_version
        from photon_ml_tpu.sweep.select import export_winner

        train, val, truth = game_split
        index_maps = {
            "global": [f"g{j}" for j in range(6)],
            "user": [f"u{j}" for j in range(4)],
        }
        registry_dir = str(tmp_path / "registry")

        result = sweep_game(
            _game_config(num_iterations=2), train,
            parse_sweep_spec("lambda=0.1:10:log3"),
        )
        selection = run_selection(result, val)
        # v1: a deliberately-worse baseline model (a non-selected lane)
        other = (selection.index + 1) % result.size
        publish_version(
            registry_dir, result.model_for(other), index_maps
        )
        registry = ModelRegistry(
            registry_dir, max_batch=16, poll_interval=3600
        ).start()
        try:
            assert registry.current_version == "v-00000001"
            winner = result.model_for(selection.index)
            path = export_winner(
                winner, index_maps, registry_dir, selection=selection
            )
            assert path.endswith("v-00000002")
            assert registry.refresh()  # the live watcher's poll step
            assert registry.current_version == "v-00000002"

            # served scores == winner.predict_mean on real rows
            rows = []
            take = np.arange(val.num_rows)[:24]
            Xg, Xu = truth["Xg"], truth["Xu"]
            val_idx = np.arange(len(truth["users"]))[
                np.arange(len(truth["users"])) % 4 == 3
            ]
            for i in take:
                src = val_idx[i]
                rows.append(
                    {
                        "features": {
                            "global": [
                                [j, float(Xg[src, j])] for j in range(6)
                            ],
                            "user": [
                                [j, float(Xu[src, j])] for j in range(4)
                            ],
                        },
                        "ids": {"userId": int(truth["users"][src])},
                    }
                )
            got = registry.engine.score_rows(rows)
            expected = np.asarray(winner.predict_mean(val))[take]
            np.testing.assert_allclose(got, expected, atol=1e-6)

            # published metadata round-trips the selection record
            from photon_ml_tpu.data.model_store import (
                load_game_model_metadata,
            )

            meta = load_game_model_metadata(path)
            sel = meta["extra"]["sweep_selection"]
            assert sel["index"] == selection.index
            assert sel["metric"] == "auc"
        finally:
            registry.stop()


# ---------------------------------------------------------------------------
# estimator surface
# ---------------------------------------------------------------------------


class TestFitSweep:
    def test_fit_sweep_saves_best_and_publishes(self, game_split, tmp_path):
        train, val, _ = game_split
        est = GameEstimator(_game_config(num_iterations=1))
        out = est.fit_sweep(
            train,
            val,
            parse_sweep_spec("lambda=0.1,1"),
            output_dir=str(tmp_path / "model"),
            registry_dir=str(tmp_path / "registry"),
            index_maps={
                "global": [f"g{j}" for j in range(6)],
                "user": [f"u{j}" for j in range(4)],
            },
        )
        import os

        from photon_ml_tpu.data.model_store import load_game_model

        assert out.published_version is not None
        best_dir = tmp_path / "model" / "best"
        assert (best_dir / "model-metadata.json").exists()
        loaded = load_game_model(str(best_dir))
        np.testing.assert_allclose(
            np.asarray(loaded.models["fixed"].coefficients),
            np.asarray(out.model.models["fixed"].coefficients),
            atol=1e-6,
        )
        assert os.path.isdir(
            os.path.join(out.published_version, "feature-indexes", "global")
        )

    def test_fit_sweep_registry_requires_index_maps(self, game_split,
                                                    tmp_path):
        train, val, _ = game_split
        est = GameEstimator(_game_config(num_iterations=1))
        with pytest.raises(ValueError, match="index_maps"):
            est.fit_sweep(
                train, val, SweepGrid(default=(1.0,)),
                registry_dir=str(tmp_path / "r"),
            )


# ---------------------------------------------------------------------------
# evaluator sanity for the vmapped scorer
# ---------------------------------------------------------------------------


def test_vmapped_evaluators_match_scalar_path():
    rng = np.random.default_rng(0)
    scores = jnp.asarray(rng.normal(size=(3, 50)), jnp.float32)
    labels = jnp.asarray((rng.random(50) > 0.5).astype(np.float32))
    weights = jnp.ones((50,), jnp.float32)
    from photon_ml_tpu.sweep.select import _sweep_evaluator

    for metric in ("auc", "rmse", "logistic_loss"):
        batched = np.asarray(_sweep_evaluator(metric)(scores, labels, weights))
        for g in range(3):
            single = float(EVALUATORS[metric](scores[g], labels, weights))
            assert batched[g] == pytest.approx(single, rel=1e-6)


def test_fit_sweep_threads_rel_tol_to_parsimonious_policy(game_split):
    """rel_tol reaches selection: an enormous tolerance makes the
    parsimonious policy pick the most regularized lane outright."""
    train, val, _ = game_split
    est = GameEstimator(_game_config(num_iterations=1))
    out = est.fit_sweep(
        train, val, parse_sweep_spec("lambda=0.01,0.1,1,10"),
        policy="parsimonious", rel_tol=10.0,
    )
    assert out.selection.index == 0
    assert out.selection.policy == "parsimonious"


def test_convergence_is_fetched_once_and_cached(game_split):
    train, _val, _ = game_split
    result = sweep_game(
        _game_config(num_iterations=1), train, SweepGrid(default=(1.0,))
    )
    first = result.convergence()
    assert result.convergence() is first  # no second device fetch
