"""ISSUE 16 (executable-level roofline profiler): the dispatch sampler's
honest timing, sampling determinism, dispatch-key merging, exclusive-time
nesting, the <2% overhead budget, bound-class attribution, the
timing-honesty self-check, HBM high-watermarks, and the xprof capture
window."""

import logging
import types

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import memory, metrics, profile, trace, xla


def _rec(name, signature=("f32[4]",), flops=None, bytes_accessed=None):
    """A minimal ExecutableRecord stand-in for driving profile_dispatch
    directly (the sampler only reads these four fields)."""
    return types.SimpleNamespace(
        name=name,
        signature=signature,
        flops=flops,
        bytes_accessed=bytes_accessed,
    )


# -- sampling determinism -----------------------------------------------------


def test_sampling_is_deterministic_every_nth_and_first():
    profile.set_sample_every(4)
    f = telemetry.instrumented_jit(lambda x: x + 1.0, name="det")
    x = np.zeros((4,), np.float32)
    for _ in range(10):
        f(x)
    (entry,) = profile.PROFILE_REGISTRY.entries("det")
    assert entry.dispatches == 10
    # dispatches 1, 5, 9: the FIRST dispatch is always sampled, then
    # every 4th — a deterministic per-entry counter, not a coin flip
    assert entry.sampled == 3
    assert metrics.counter("profile.sampled").value == 3
    # each sample synchronized through the sanctioned crossing
    fetch_events = [
        e
        for s in trace.finished_spans()
        for e in s.events
        if e.get("name") == "device_fetch"
        and str(e.get("label", "")).startswith("profile:det")
    ]
    # events attach to an open span only when one exists; the counter is
    # the ground truth either way
    assert metrics.counter("device_fetches").value >= 3


def test_single_dispatch_still_profiles():
    # default sampling is 1/64, but a run with ONE dispatch must still
    # produce a profile (the first dispatch of every entry is sampled)
    f = telemetry.instrumented_jit(lambda x: x * 2.0, name="once")
    f(np.ones((4,), np.float32))
    (entry,) = profile.PROFILE_REGISTRY.entries("once")
    assert entry.dispatches == 1
    assert entry.sampled == 1
    assert entry.sampled_seconds > 0


def test_sample_every_env_override(monkeypatch):
    monkeypatch.setenv("PHOTON_PROFILE_SAMPLE_EVERY", "2")
    profile.reset()  # clear the env cache so the override is read
    f = telemetry.instrumented_jit(lambda x: x + 1.0, name="env")
    x = np.zeros((2,), np.float32)
    for _ in range(4):
        f(x)
    (entry,) = profile.PROFILE_REGISTRY.entries("env")
    assert entry.sampled == 2  # dispatches 1 and 3


# -- dispatch-key merging -----------------------------------------------------


def test_distinct_signatures_merge_per_name():
    # distinct dispatch keys (shape change = new signature, the same
    # mechanism that separates shardings) stay distinct entries and merge
    # per NAME in the report view
    profile.set_sample_every(1)
    f = telemetry.instrumented_jit(lambda x: x + 1.0, name="shapes")
    for _ in range(3):
        f(np.zeros((4,), np.float32))
    for _ in range(2):
        f(np.zeros((8,), np.float32))
    entries = profile.PROFILE_REGISTRY.entries("shapes")
    assert len(entries) == 2
    assert {e.dispatches for e in entries} == {3, 2}
    merged = profile.merged_profiles()["shapes"]
    assert merged["dispatches"] == 5
    assert merged["sampled"] == 5


def test_merged_cost_is_sample_weighted():
    # two shardings of one name with different cost analyses: the merged
    # per-dispatch cost weights by sample count, so the rarely-run
    # sharding does not skew intensity
    reg = profile.PROFILE_REGISTRY
    reg.count_dispatch("w", ("f32[8]@x",), 1)
    reg.record_sample("w", ("f32[8]@x",), 1.0, 1.0, 0.0, 100.0, 10.0)
    for _ in range(3):
        reg.count_dispatch("w", ("f32[8]@y",), 1)
        reg.record_sample("w", ("f32[8]@y",), 1.0, 1.0, 0.0, 500.0, 50.0)
    merged = profile.merged_profiles()["w"]
    assert merged["flops_per_dispatch"] == pytest.approx(400.0)
    assert merged["bytes_per_dispatch"] == pytest.approx(40.0)
    assert merged["intensity"] == pytest.approx(10.0)


# -- exclusive time under nesting (forged clock) ------------------------------


def test_exclusive_time_subtracts_nested_sampled_dispatches():
    profile.set_sample_every(1)
    now = [0.0]
    profile.set_clock(lambda: now[0])

    inner_rec = _rec("inner")
    outer_rec = _rec("outer")

    def inner_target(*a, **k):
        now[0] += 2.0  # 2 forged seconds of inner device work
        return 7  # array-free output: no fetch, timing stands as-is

    def outer_target(*a, **k):
        profile.profile_dispatch(inner_rec, inner_target, (), {})
        now[0] += 3.0  # 3 forged seconds of the outer's OWN work
        return 7

    profile.profile_dispatch(outer_rec, outer_target, (), {})

    (inner,) = profile.PROFILE_REGISTRY.entries("inner")
    (outer,) = profile.PROFILE_REGISTRY.entries("outer")
    assert inner.sampled_seconds == pytest.approx(2.0)
    assert inner.sampled_exclusive_seconds == pytest.approx(2.0)
    # inclusive 5s, minus the 2s nested sampled dispatch
    assert outer.sampled_seconds == pytest.approx(5.0)
    assert outer.sampled_exclusive_seconds == pytest.approx(3.0)
    excl = profile.exclusive_seconds_by_name()
    assert excl["outer"] == pytest.approx(3.0)
    assert excl["inner"] == pytest.approx(2.0)


def test_target_exception_propagates_without_a_sample():
    profile.set_sample_every(1)

    def boom(*a, **k):
        raise ValueError("no result, no sample")

    with pytest.raises(ValueError):
        profile.profile_dispatch(_rec("boom"), boom, (), {})
    (entry,) = profile.PROFILE_REGISTRY.entries("boom")
    assert entry.dispatches == 1
    assert entry.sampled == 0
    # the measurement stack unwound: a later dispatch still works
    profile.profile_dispatch(_rec("ok"), lambda: 1, (), {})
    (ok,) = profile.PROFILE_REGISTRY.entries("ok")
    assert ok.sampled == 1


# -- overhead budget ----------------------------------------------------------


def test_steady_state_overhead_under_two_percent():
    import time

    import jax.numpy as jnp

    profile.set_sample_every(64)  # pin the default cadence explicitly
    f = telemetry.instrumented_jit(lambda x: x @ x + 1.0, name="overhead")
    x = jnp.ones((64, 64), jnp.float32)
    host = np.ones((256, 256), np.float32)
    np.asarray(f(x))  # compile + first-dispatch sample, outside window
    # steady-state training-loop shape: host-side step work between
    # dispatches; the overhead counter is read as a DELTA over the timed
    # window so the warmup sample's compile-wait fetch is excluded
    overhead0 = metrics.counter("profile.overhead_seconds").value
    sampled0 = metrics.counter("profile.sampled").value
    t0 = time.perf_counter()
    for _ in range(320):
        float(np.sin(host).sum())
        f(x)
    np.asarray(f(x))  # close the async tail before stopping the clock
    elapsed = time.perf_counter() - t0
    overhead = metrics.counter("profile.overhead_seconds").value - overhead0
    assert metrics.counter("profile.sampled").value - sampled0 >= 4
    assert overhead / elapsed < 0.02, (
        f"profiler overhead {overhead:.4f}s of {elapsed:.4f}s "
        f"({overhead / elapsed:.1%}) blows the 2% budget"
    )


# -- bound classes ------------------------------------------------------------


def test_bound_class_attribution():
    peak_flops, peak_bw = 1e12, 1e11  # balance point: 10 FLOPs/byte
    # memory leg binds: intensity 2 < 10
    assert (
        profile.bound_class(1.0, 2e11, 1e11, peak_flops, peak_bw, 0.2)
        == profile.BOUND_HBM
    )
    # compute leg binds at healthy MFU
    assert (
        profile.bound_class(1.0, 9e11, 1e9, peak_flops, peak_bw, 0.9)
        == profile.BOUND_MXU
    )
    # compute-side but the MXU is idle -> VPU-bound
    assert (
        profile.bound_class(0.5, 4e11, 1e9, peak_flops, peak_bw, 0.04)
        == profile.BOUND_VPU
    )
    # roofline-predicted time far below measured -> dispatch-bound
    assert (
        profile.bound_class(1.0, 1e9, 1e6, peak_flops, peak_bw, 0.001)
        == profile.BOUND_DISPATCH
    )
    # missing evidence is never a class
    assert (
        profile.bound_class(1.0, None, 1e9, peak_flops, peak_bw, None)
        == profile.BOUND_UNKNOWN
    )
    assert (
        profile.bound_class(1.0, 1e9, 1e6, None, None, None)
        == profile.BOUND_UNKNOWN
    )
    assert profile.bound_class_name(profile.BOUND_HBM) == "HBM-bound"
    assert profile.bound_class_name(None) == "unknown"
    assert profile.bound_class_name(99) == "unknown"


# -- timing honesty self-check ------------------------------------------------


def test_timing_suspect_flags_rates_above_device_peak(caplog):
    xla.set_peaks(1e12, 1e11)
    reg = profile.PROFILE_REGISTRY
    reg.count_dispatch("liar", ("f32[4]",), 1)
    # forged clock limit: 1e9 FLOPs "measured" in a nanosecond is
    # 1e18 FLOP/s against a 1e12 peak — physically impossible
    reg.record_sample("liar", ("f32[4]",), 1e-9, 1e-9, 0.0, 1e9, 1e6)
    merged = profile.merged_profiles()["liar"]
    assert merged["timing_suspect"] is True
    with caplog.at_level(
        logging.WARNING, logger="photon_ml_tpu.telemetry.profile"
    ):
        profile.publish_metrics()
        profile.publish_metrics()
    snap = telemetry.snapshot()
    assert snap["gauges"]["profile.exec.liar.timing_suspect"] == 1
    assert snap["counters"]["profile.timing_suspect_total"] >= 1
    # warn-once latch: two publishes, one warning
    warnings = [
        r for r in caplog.records if "timing suspect" in r.getMessage()
    ]
    assert len(warnings) == 1
    assert "liar" in warnings[0].getMessage()


def test_honest_rate_is_not_suspect():
    xla.set_peaks(1e12, 1e11)
    reg = profile.PROFILE_REGISTRY
    reg.count_dispatch("honest", ("f32[4]",), 1)
    reg.record_sample("honest", ("f32[4]",), 1.0, 1.0, 0.0, 1e9, 1e6)
    merged = profile.merged_profiles()["honest"]
    assert merged["timing_suspect"] is False
    assert merged["mfu"] == pytest.approx(1e-3)
    profile.publish_metrics()
    gauges = telemetry.snapshot()["gauges"]
    assert "profile.exec.honest.timing_suspect" not in gauges


def test_unknown_peaks_mean_unknown_not_suspect():
    # no resolved peaks: mfu/bound stay unknown and the self-check cannot
    # fire (absence of evidence is not dishonesty)
    reg = profile.PROFILE_REGISTRY
    reg.count_dispatch("nopeaks", ("f32[4]",), 1)
    reg.record_sample("nopeaks", ("f32[4]",), 1e-9, 1e-9, 0.0, 1e9, 1e6)
    merged = profile.merged_profiles()["nopeaks"]
    if xla.device_peaks() == (None, None):
        assert merged["timing_suspect"] is False
        assert merged["mfu"] is None
        assert merged["bound_code"] == profile.BOUND_UNKNOWN


# -- publish / metrics round trip ---------------------------------------------


def test_publish_metrics_gauges_round_trip(tmp_path):
    import json

    xla.set_peaks(1e12, 1e11)
    reg = profile.PROFILE_REGISTRY
    for _ in range(4):
        reg.count_dispatch("hot", ("f32[8]",), 1)
        reg.record_sample("hot", ("f32[8]",), 0.5, 0.4, 0.01, 1e10, 8e9)
    path = str(tmp_path / "telemetry.jsonl")
    telemetry.flush_metrics(path)  # publishes derived gauges first
    with open(path, encoding="utf-8") as fh:
        snap = json.loads(fh.readline())["snapshot"]
    g = snap["gauges"]
    assert g["profile.exec.hot.dispatches"] == 4
    assert g["profile.exec.hot.sampled"] == 4
    assert g["profile.exec.hot.est_exclusive_seconds"] == pytest.approx(
        1.6
    )
    assert g["profile.exec.hot.mean_dispatch_seconds"] == pytest.approx(
        0.5
    )
    assert g["profile.exec.hot.mfu"] == pytest.approx(0.02)
    assert g["profile.exec.hot.intensity"] == pytest.approx(1.25)
    assert g["profile.exec.hot.bound_code"] == profile.BOUND_HBM


def test_exclusive_seconds_by_name_registers_nothing():
    before = set(telemetry.snapshot()["gauges"])
    assert profile.exclusive_seconds_by_name() == {}
    assert set(telemetry.snapshot()["gauges"]) == before


# -- HBM high-watermarks ------------------------------------------------------


class _FakeDevice:
    def __init__(self, did, in_use, limit=16 * 2**30):
        self.id = did
        self._stats = {"bytes_in_use": in_use, "bytes_limit": limit}

    def memory_stats(self):
        return self._stats


def test_watermarks_max_track_per_device_and_phase():
    d0, d1 = _FakeDevice(0, 100), _FakeDevice(1, 700)
    memory.record_device_watermarks([d0, d1], phase="fit")
    d0._stats["bytes_in_use"] = 500
    d1._stats["bytes_in_use"] = 300  # dips: the peak must NOT follow
    memory.record_device_watermarks([d0, d1], phase="fit")
    g = telemetry.snapshot()["gauges"]
    assert g["memory.device.0.peak_bytes"] == 500
    assert g["memory.device.1.peak_bytes"] == 700
    assert g["memory.phase.fit.device.0.peak_bytes"] == 500
    assert g["memory.phase.fit.device.1.peak_bytes"] == 700
    # the last-sample gauges still track the dip
    assert g["memory.device.1.bytes_in_use"] == 300


def test_watermarks_absent_on_statless_backends():
    class _Statless:
        id = 0

        def memory_stats(self):
            return None

    assert memory.record_device_watermarks([_Statless()]) == {}
    assert not any(
        ".peak_bytes" in name
        for name in telemetry.snapshot()["gauges"]
    )


def test_sampler_records_watermarks_under_open_span():
    profile.set_sample_every(1)
    provider_stats = {"bytes_in_use": 4096, "bytes_limit": 2**30}
    d = _FakeDevice(3, 4096)
    with trace.span("fit"):
        # the sampler probes real devices (statless on CPU); drive the
        # watermark recorder directly with a fake device to prove the
        # phase attribution path the sampler uses
        span = trace.current_span()
        memory.record_device_watermarks([d], phase=span.name)
    g = telemetry.snapshot()["gauges"]
    assert g["memory.phase.fit.device.3.peak_bytes"] == 4096


# -- xprof capture window -----------------------------------------------------


def test_xprof_window_arms_and_stops_via_hooks():
    calls = []
    profile.set_xprof_hooks(
        lambda d: calls.append(("start", d)),
        lambda: calls.append(("stop",)),
    )
    assert profile.configure_xprof("/tmp/xp", arm_at=3, capture=2,
                                   force=True)
    f = telemetry.instrumented_jit(lambda x: x + 1.0, name="xp")
    x = np.zeros((2,), np.float32)
    for _ in range(6):
        f(x)
    assert ("start", "/tmp/xp") in calls
    assert ("stop",) in calls
    assert calls.index(("start", "/tmp/xp")) < calls.index(("stop",))
    assert telemetry.snapshot()["gauges"]["profile.xprof_armed"] == 1


def test_xprof_refuses_cpu_backend_without_force():
    assert profile.configure_xprof("/tmp/xp") is False


def test_xprof_reset_closes_open_window():
    calls = []
    profile.set_xprof_hooks(
        lambda d: calls.append("start"), lambda: calls.append("stop")
    )
    profile.configure_xprof("/tmp/xp", arm_at=0, capture=100, force=True)
    f = telemetry.instrumented_jit(lambda x: x + 1.0, name="xpreset")
    f(np.zeros((2,), np.float32))
    assert "start" in calls and "stop" not in calls
    profile.reset()  # run teardown: the window must not stay open
    assert "stop" in calls


def test_xprof_start_failure_disarms_without_killing_dispatch():
    def broken(d):
        raise RuntimeError("capture machinery wedged")

    profile.set_xprof_hooks(broken, lambda: None)
    profile.configure_xprof("/tmp/xp", arm_at=0, capture=2, force=True)
    f = telemetry.instrumented_jit(lambda x: x * 3.0, name="xpfail")
    out = f(np.ones((2,), np.float32))  # must not raise
    np.testing.assert_allclose(np.asarray(out), 3.0)


# -- lifecycle ----------------------------------------------------------------


def test_reset_rearms_the_sampler():
    telemetry.reset()  # the test-isolation path
    f = telemetry.instrumented_jit(lambda x: x + 1.0, name="rearmed")
    f(np.zeros((2,), np.float32))
    (entry,) = profile.PROFILE_REGISTRY.entries("rearmed")
    assert entry.sampled == 1
