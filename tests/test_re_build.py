"""Random-effect dataset builder: correctness vs a per-entity reference
reconstruction, and ingest-rate at scale (VERDICT r2 item 5 — the build must
be bulk-numpy, not per-entity Python)."""

import time

import numpy as np
import pytest

from photon_ml_tpu.game import build_game_dataset, build_random_effect_dataset
from photon_ml_tpu.ops.sparse import SparseBatch


def _dataset(rng, n, n_entities, n_features, density=0.4):
    X = rng.normal(size=(n, n_features)) * (rng.random((n, n_features)) < density)
    y = (rng.random(n) > 0.5).astype(float)
    ids = rng.integers(0, n_entities, size=n)
    offs = rng.normal(size=n)
    wgts = rng.random(n) + 0.5
    gds = build_game_dataset(
        response=y,
        feature_shards={"s": SparseBatch.from_dense(X, y)},
        id_columns={"eid": ids},
        offset=offs,
        weight=wgts,
    )
    return gds, X, ids


def test_buckets_match_per_entity_reference(rng):
    """Every entity's padded bucket problem must equal the direct per-entity
    extraction: dense features in LOCAL space, labels/offsets/weights in
    member-row order, projection = sorted observed global cols."""
    gds, X, ids = _dataset(rng, n=200, n_entities=23, n_features=12)
    red = build_random_effect_dataset(gds, "eid", "s")
    codes = gds.id_columns["eid"].codes

    seen = 0
    for code in np.unique(codes):
        b_idx = red.entity_bucket[code]
        pos = red.entity_pos[code]
        assert b_idx >= 0
        b = red.buckets[b_idx]
        members = np.sort(np.where(codes == code)[0])

        # labels/offsets/weights/row_index in member order
        R = b.rows_per_entity
        np.testing.assert_array_equal(
            np.asarray(b.row_index)[pos, : len(members)], members)
        np.testing.assert_allclose(
            np.asarray(b.labels)[pos, : len(members)],
            gds.response[members], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(b.offsets)[pos, : len(members)],
            gds.offset[members], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(b.weights)[pos, : len(members)],
            gds.weight[members], rtol=1e-6)
        assert np.all(np.asarray(b.weights)[pos, len(members):] == 0)

        # projection = sorted unique observed global cols
        obs = np.unique(np.nonzero(X[members])[1])
        proj = np.asarray(b.projection)[pos]
        np.testing.assert_array_equal(proj[: len(obs)], obs)
        assert np.all(proj[len(obs):] == red.num_global_features)

        # dense reconstruction in local space
        dense_local = np.zeros((R, b.num_local_features))
        v = np.asarray(b.values)[pos]
        r = np.asarray(b.rows)[pos]
        c = np.asarray(b.cols)[pos]
        np.add.at(dense_local, (r, c), v)
        expected = np.zeros((R, b.num_local_features))
        expected[: len(members), : len(obs)] = X[members][:, obs]
        np.testing.assert_allclose(dense_local, expected, rtol=1e-5, atol=1e-6)

        # nnz sorted by local row within the entity (segment_sum contract)
        live = v != 0
        assert np.all(np.diff(r[live]) >= 0)
        seen += 1
    assert seen == 23


def test_cap_and_min_rows_vectorized(rng):
    gds, X, ids = _dataset(rng, n=500, n_entities=10, n_features=8)
    red = build_random_effect_dataset(
        gds, "eid", "s", active_rows_per_entity=20, min_rows_per_entity=5)
    codes = gds.id_columns["eid"].codes
    n_active = 0
    for code in np.unique(codes):
        members = np.where(codes == code)[0]
        b_idx = red.entity_bucket[code]
        if len(members) < 5:
            assert b_idx == -1
            continue
        b = red.buckets[b_idx]
        pos = red.entity_pos[code]
        kept = np.asarray(b.row_index)[pos]
        kept = kept[kept >= 0]
        n_kept = min(len(members), 20)
        assert len(kept) == n_kept
        assert set(kept).issubset(set(members))
        # weight rescale on capped entities: kept weights *= count/cap
        if len(members) > 20:
            np.testing.assert_allclose(
                np.asarray(b.weights)[pos, : n_kept],
                gds.weight[kept] * (len(members) / 20), rtol=1e-5)
        n_active += len(kept)
    assert n_active + len(red.passive_rows) == 500


@pytest.mark.slow
def test_build_rate_100k_entities_1m_rows(rng):
    """Ingest rate: 100K entities / 1M rows / ~10M nnz must build in bulk
    numpy time (seconds), not per-entity Python time (minutes)."""
    n, n_entities, nnz_per_row = 1_000_000, 100_000, 10
    n_features = 50
    nnz = n * nnz_per_row
    rows = np.repeat(np.arange(n, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, n_features, size=nnz)
    values = rng.normal(size=nnz)
    # ensure one nnz per (row, col) pair: dedupe by unique key
    key = rows * n_features + cols
    _, first = np.unique(key, return_index=True)
    rows, cols, values = rows[first], cols[first], values[first]
    y = (rng.random(n) > 0.5).astype(float)
    ids = rng.integers(0, n_entities, size=n)
    batch = SparseBatch.from_coo(values, rows, cols, y, num_features=n_features)
    gds = build_game_dataset(
        response=y, feature_shards={"s": batch}, id_columns={"eid": ids})

    t0 = time.perf_counter()
    red = build_random_effect_dataset(gds, "eid", "s")
    elapsed = time.perf_counter() - t0
    total_active = sum(
        int((np.asarray(b.row_index) >= 0).sum()) for b in red.buckets)
    assert total_active == n
    # generous bound: catches any regression to per-entity looping, which
    # takes minutes at this size
    assert elapsed < 120, f"RE build took {elapsed:.1f}s"
    print(f"RE build: {n} rows / {n_entities} entities in {elapsed:.2f}s")
