"""Distributed (8-virtual-device mesh) tests: sharded solves match
single-device solves bit-for-tolerance; collectives actually ride the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    solve,
)
from photon_ml_tpu.parallel import (
    distributed_solve,
    distributed_value_and_grad,
    make_mesh,
    put_sharded,
    shard_rows,
)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh({"data": 8})


def _problem(rng, n=400, d=20, loss="logistic"):
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.3)
    if loss == "logistic":
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ rng.normal(size=d))))).astype(float)
    else:
        y = X @ rng.normal(size=d) + 0.1 * rng.normal(size=n)
    wt = rng.random(n) + 0.5
    return SparseBatch.from_dense(X, y, weights=wt)


def test_sharded_value_and_grad_matches_local(rng, mesh):
    batch = _problem(rng)
    stacked = put_sharded(shard_rows(batch, 8), mesh)
    obj = make_objective("logistic", l2_weight=0.7)
    w = jnp.asarray(rng.normal(size=batch.num_features) * 0.2, jnp.float32)
    v_local, g_local = obj.value_and_grad(w, batch)
    v_dist, g_dist = distributed_value_and_grad(obj, w, stacked, mesh)
    np.testing.assert_allclose(v_dist, v_local, rtol=1e-5)
    np.testing.assert_allclose(g_dist, g_local, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize(
    "opt,reg",
    [
        (OptimizerType.LBFGS, RegularizationType.L2),
        (OptimizerType.TRON, RegularizationType.L2),
        (OptimizerType.LBFGS, RegularizationType.L1),
    ],
)
def test_distributed_solve_matches_single_device(rng, mesh, opt, reg):
    batch = _problem(rng)
    stacked = put_sharded(shard_rows(batch, 8), mesh)
    cfg = OptimizerConfig(
        optimizer_type=opt,
        regularization=RegularizationContext(reg),
        regularization_weight=1.0,
        max_iterations=50,
    )
    w0 = jnp.zeros(batch.num_features, jnp.float32)
    res_single = solve("logistic", batch, cfg, w0)
    res_dist = distributed_solve("logistic", stacked, cfg, w0, mesh)
    np.testing.assert_allclose(res_dist.value, res_single.value, rtol=1e-4)
    np.testing.assert_allclose(res_dist.w, res_single.w, rtol=5e-3, atol=5e-3)


def test_uneven_rows_sharding(rng, mesh):
    # 403 rows over 8 shards: padding rows must stay inert
    batch = _problem(rng, n=403)
    stacked = put_sharded(shard_rows(batch, 8), mesh)
    obj = make_objective("logistic", l2_weight=0.5)
    w = jnp.asarray(rng.normal(size=batch.num_features) * 0.1, jnp.float32)
    v_local, g_local = obj.value_and_grad(w, batch)
    v_dist, g_dist = distributed_value_and_grad(obj, w, stacked, mesh)
    np.testing.assert_allclose(v_dist, v_local, rtol=1e-5)
    np.testing.assert_allclose(g_dist, g_local, rtol=1e-4, atol=1e-4)


def test_result_is_replicated(rng, mesh):
    batch = _problem(rng, n=160)
    stacked = put_sharded(shard_rows(batch, 8), mesh)
    cfg = OptimizerConfig(max_iterations=10, regularization_weight=1.0,
                          regularization=RegularizationContext(RegularizationType.L2))
    res = distributed_solve("logistic", stacked, cfg,
                            jnp.zeros(batch.num_features, jnp.float32), mesh)
    # replicated output: every device holds the full coefficient vector
    assert res.w.sharding.is_fully_replicated
