"""Estimator surface: config-grid sweeps, box constraints in configs,
event bus, and the to_summary_string protocol."""

import numpy as np
import pytest

from photon_ml_tpu.game import (
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    RandomEffectConfig,
    build_game_dataset,
)
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.utils.events import (
    OptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)


def _data(rng, n=400, d=8, n_users=5):
    X = rng.normal(size=(n, d))
    users = rng.integers(0, n_users, n)
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(float)
    return build_game_dataset(
        response=y,
        feature_shards={"f": SparseBatch.from_dense(X, y)},
        id_columns={"u": users},
    ), X, y, w


def _l2(lam):
    return OptimizerConfig(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=lam,
    )


@pytest.mark.slow
def test_fit_grid_sweeps_cartesian_product(rng):
    data, X, y, w = _data(rng)
    val, *_ = _data(rng, n=200)
    cfg = GameConfig(
        task="logistic",
        evaluators=["auc"],
        coordinates={
            "fixed": FixedEffectConfig(shard_name="f"),
            "perUser": RandomEffectConfig(shard_name="f", id_name="u"),
        },
    )
    est = GameEstimator(cfg)
    grid = {
        "fixed": [_l2(0.01), _l2(100.0)],
        "perUser": [_l2(1.0), _l2(10.0)],
    }
    entries = est.fit_grid(data, val, grid)
    assert len(entries) == 4
    combos = {
        (
            e.optimizer_configs["fixed"].regularization_weight,
            e.optimizer_configs["perUser"].regularization_weight,
        )
        for e in entries
    }
    assert combos == {(0.01, 1.0), (0.01, 10.0), (100.0, 1.0), (100.0, 10.0)}
    # sorted best-first by the primary (maximizing) evaluator
    metrics = [e.result.best_metric for e in entries]
    assert metrics == sorted(metrics, reverse=True)
    # RE dataset built once across all 4 combos
    assert len(est._re_datasets) == 1


def test_fit_grid_validations(rng):
    data, *_ = _data(rng, n=100)
    cfg = GameConfig(
        task="logistic",
        coordinates={"fixed": FixedEffectConfig(shard_name="f")},
    )
    with pytest.raises(ValueError, match="evaluators"):
        GameEstimator(cfg).fit_grid(data, data, {"fixed": [_l2(1.0)]})
    cfg2 = GameConfig(
        task="logistic",
        evaluators=["auc"],
        coordinates={"fixed": FixedEffectConfig(shard_name="f")},
    )
    with pytest.raises(ValueError, match="unknown coordinates"):
        GameEstimator(cfg2).fit_grid(data, data, {"nope": [_l2(1.0)]})


def test_box_constraints_in_fixed_effect_config(rng):
    data, X, y, w = _data(rng)
    # clamp coefficient 2 to [0, 0] (force zero) and 3 to [-0.05, 0.05]
    opt = OptimizerConfig(
        box_constraints=((2, 0.0, 0.0), (3, -0.05, 0.05)),
    )
    cfg = GameConfig(
        task="logistic",
        coordinates={"fixed": FixedEffectConfig(shard_name="f", optimizer=opt)},
    )
    model = GameEstimator(cfg).fit(data).model.models["fixed"]
    coefs = np.asarray(model.coefficients)
    assert coefs[2] == pytest.approx(0.0, abs=1e-7)
    assert -0.0501 <= coefs[3] <= 0.0501
    # unconstrained coefficients move freely
    assert np.abs(coefs).max() > 0.1


def test_box_constraints_accepted_for_random_effect(rng):
    """Global-space boxes now thread into per-entity solves through the
    index-map projection (SingleNodeOptimizationProblem.scala:124-139);
    detailed parity lives in test_game.py."""
    data, *_ = _data(rng, n=100)
    opt = OptimizerConfig(box_constraints=((0, -0.2, 0.2),), max_iterations=20)
    cfg = GameConfig(
        task="logistic",
        coordinates={
            "perUser": RandomEffectConfig(
                shard_name="f", id_name="u", optimizer=opt
            )
        },
    )
    model = GameEstimator(cfg).fit(data).model.models["perUser"]
    for bm in model.buckets:
        proj = np.asarray(bm.projection)
        w = np.asarray(bm.coefficients)
        assert np.all(w[proj == 0] >= -0.2 - 1e-6)
        assert np.all(w[proj == 0] <= 0.2 + 1e-6)


def test_box_constraints_validation():
    with pytest.raises(ValueError, match="out of range"):
        OptimizerConfig(box_constraints=((99, 0.0, 1.0),)).build_box_constraints(5)
    with pytest.raises(ValueError, match="empty"):
        OptimizerConfig(box_constraints=((1, 2.0, 1.0),)).build_box_constraints(5)


def test_box_constraints_in_train_glm(rng):
    from photon_ml_tpu.training import train_glm

    data, X, y, w = _data(rng)
    opt = OptimizerConfig(box_constraints=((0, 0.0, 0.0),))
    e = train_glm(data.batch_for("f"), "logistic", [0.1], opt)[0]
    assert float(e.model.coefficients.means[0]) == pytest.approx(0.0, abs=1e-7)


def test_box_constraints_config_json_round_trip():
    from photon_ml_tpu.config import parse_optimizer_config
    from photon_ml_tpu.game.estimator import _config_metadata

    opt = parse_optimizer_config(
        {"box_constraints": [[1, -1.0, 1.0], [4, None, 0.0]]}
    )
    assert opt.box_constraints == ((1, -1.0, 1.0), (4, float("-inf"), 0.0))
    cfg = GameConfig(
        task="logistic",
        coordinates={"fixed": FixedEffectConfig(shard_name="f", optimizer=opt)},
    )
    meta = _config_metadata(cfg)
    assert meta["coordinates"]["fixed"]["optimizer"]["box_constraints"] == [
        [1, -1.0, 1.0],
        [4, None, 0.0],
    ]
    from photon_ml_tpu.config import parse_game_config

    assert parse_game_config(meta).coordinates["fixed"].optimizer == opt


@pytest.mark.slow
def test_event_bus_lifecycle(rng):
    data, *_ = _data(rng, n=150)
    val, *_ = _data(rng, n=100)
    cfg = GameConfig(
        task="logistic",
        num_iterations=2,
        evaluators=["auc"],
        coordinates={
            "fixed": FixedEffectConfig(shard_name="f"),
            "perUser": RandomEffectConfig(shard_name="f", id_name="u"),
        },
    )
    est = GameEstimator(cfg)
    seen = []
    est.events.register(seen.append)
    # a broken listener must not break training
    def broken(_):
        raise RuntimeError("boom")
    est.events.register(broken)
    est.fit(data, validation_data=val)

    kinds = [type(e).__name__ for e in seen]
    assert kinds[0] == "SetupEvent"
    assert "TrainingStartEvent" in kinds
    assert kinds[-1] == "TrainingFinishEvent"
    logs = [e for e in seen if isinstance(e, OptimizationLogEvent)]
    assert len(logs) == 4  # 2 iterations x 2 coordinates
    assert {(l.iteration, l.coordinate) for l in logs} == {
        (0, "fixed"), (0, "perUser"), (1, "fixed"), (1, "perUser"),
    }
    assert all(l.metrics and "auc" in l.metrics for l in logs)
    finish = seen[-1]
    assert isinstance(finish, TrainingFinishEvent)
    assert finish.best_metric is not None and finish.seconds > 0


def test_to_summary_string_protocol(rng):
    data, *_ = _data(rng, n=150)
    cfg = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="f"),
            "perUser": RandomEffectConfig(shard_name="f", id_name="u"),
        },
    )
    result = GameEstimator(cfg).fit(data)
    s = result.model.to_summary_string()
    assert "GameModel(task=logistic" in s
    assert "FixedEffectModel(shard=f" in s
    assert "RandomEffectModel(id=u" in s
    from photon_ml_tpu.game import build_random_effect_dataset

    red = build_random_effect_dataset(data, "u", "f")
    rs = red.to_summary_string()
    assert "RandomEffectDataset(id=u" in rs and "bucket 0" in rs


def test_box_constraints_transformed_under_normalization(rng):
    """Original-space bounds must hold after back-transform when training
    with scale normalization (bounds rescaled into solving space)."""
    data, X, y, w = _data(rng)
    Xs = X.copy()
    Xs[:, 3] *= 10.0  # factor ~ 1/10 for this column
    data2 = build_game_dataset(
        response=np.asarray(data.response),
        feature_shards={"f": SparseBatch.from_dense(Xs, np.asarray(data.response))},
    )
    opt = OptimizerConfig(box_constraints=((3, -0.01, 0.01),))
    cfg = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="f", optimizer=opt,
                normalization="scale_with_standard_deviation",
            )
        },
    )
    m = GameEstimator(cfg).fit(data2).model.models["fixed"]
    assert -0.0101 <= float(m.coefficients[3]) <= 0.0101
    # intercept bound + shift normalization is rejected
    opt_i = OptimizerConfig(box_constraints=((0, -1.0, 1.0),))
    cfg_i = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="f", optimizer=opt_i,
                normalization="standardization", intercept_index=0,
            )
        },
    )
    with pytest.raises(ValueError, match="intercept"):
        GameEstimator(cfg_i).fit(data2)


def test_fit_grid_emits_events_and_reuses_coordinates(rng):
    data, *_ = _data(rng, n=150)
    val, *_ = _data(rng, n=100)
    cfg = GameConfig(
        task="logistic",
        evaluators=["auc"],
        coordinates={
            "fixed": FixedEffectConfig(shard_name="f"),
            "perUser": RandomEffectConfig(shard_name="f", id_name="u"),
        },
    )
    est = GameEstimator(cfg)
    seen = []
    est.events.register(seen.append)
    entries = est.fit_grid(data, val, {"perUser": [_l2(0.1), _l2(10.0)]})
    assert len(entries) == 2
    starts = [e for e in seen if isinstance(e, TrainingStartEvent)]
    finishes = [e for e in seen if isinstance(e, TrainingFinishEvent)]
    assert len(starts) == 2 and len(finishes) == 2
    logs = [e for e in seen if isinstance(e, OptimizationLogEvent)]
    assert len(logs) == 4  # 2 combos x 2 coordinates x 1 iteration
    # the fixed coordinate (not swept) is the same object across combos
    # via the per-sweep coordinate cache: both fits share ONE FE solve
    # structure, asserted indirectly through identical fixed-coef models
    m0 = np.asarray(entries[0].result.model.models["fixed"].coefficients)
    m1 = np.asarray(entries[1].result.model.models["fixed"].coefficients)
    assert m0.shape == m1.shape


def test_optimization_trackers(rng):
    """Per-update solve telemetry (Fixed/RandomEffectOptimizationTracker
    analog): convergence-reason counts, iteration stats, CD history."""
    data, *_ = _data(rng, n=200)
    cfg = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="f", optimizer=_l2(0.1)),
            "perUser": RandomEffectConfig(
                shard_name="f", id_name="u", optimizer=_l2(1.0)
            ),
        },
    )
    result = GameEstimator(cfg).fit(data)
    entries = {e["coordinate"]: e for e in result.history}
    assert "iterations=" in entries["fixed"]["tracker"]
    assert "reason=" in entries["fixed"]["tracker"]
    re_summary = entries["perUser"]["tracker"]
    assert "entities=5" in re_summary
    assert "convergence {" in re_summary

    from photon_ml_tpu.optim.trackers import RandomEffectOptimizationTracker
    import numpy as np_

    t = RandomEffectOptimizationTracker(
        iterations=np_.asarray([3, 5, 5, 7]),
        reasons=np_.asarray([3, 3, 4, 1]),
    )
    assert t.count_convergence_reasons() == {
        "FunctionValuesConverged": 2, "GradientConverged": 1,
        "MaxIterations": 1,
    }
    s = t.iteration_stats()
    assert s["count"] == 4 and s["mean"] == 5.0 and s["max"] == 7.0


def test_repeated_fit_reproducible_with_down_sampling(rng):
    """Regression: the coordinate cache must reset per-fit state so two
    fits of the same estimator draw the SAME seeded down-sampling sequence
    and return identical models."""
    data, *_ = _data(rng, n=300)
    cfg = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="f",
                optimizer=OptimizerConfig(down_sampling_rate=0.5),
                down_sampling_seed=7,
            )
        },
    )
    est = GameEstimator(cfg)
    m1 = est.fit(data).model.models["fixed"]
    m2 = est.fit(data).model.models["fixed"]
    np.testing.assert_array_equal(
        np.asarray(m1.coefficients), np.asarray(m2.coefficients)
    )
