"""ISSUE 3 (observability interpretation layer): HBM accounting, the
progress heartbeat, run reports, the `cli report` perf gate, and the
end-to-end acceptance path (fit -> report -> compare)."""

import json
import time

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import memory
from photon_ml_tpu.telemetry.progress import Heartbeat
from photon_ml_tpu.telemetry.report import (
    MetricDelta,
    RunReport,
    build_phase_tree,
    compare_metrics,
    report_path,
)


@pytest.fixture
def fake_hbm():
    """Deterministic 16 GB device with 10 GB in use (CPU has no stats)."""
    memory.set_stats_provider(
        lambda: {"bytes_in_use": 10 * 2**30, "bytes_limit": 16 * 2**30}
    )
    yield
    memory.set_stats_provider(None)


# -- memory accounting --------------------------------------------------------


def test_hbm_stats_none_on_statless_backend():
    # the CPU test mesh publishes no memory stats: probes return None and
    # the headroom check reports "unknown", never a false warning
    assert memory.hbm_stats() is None
    assert memory.check_headroom(2**40, label="huge") is None
    assert memory.record_phase_memory("fit") is None
    assert (
        "memory.headroom_warnings"
        not in telemetry.snapshot()["counters"]
    )


def test_check_headroom_warns_before_predicted_oom(fake_hbm, caplog):
    import logging

    # 16*0.92 - 10 = ~4.7 GB free
    assert memory.check_headroom(2**30, label="small") is True
    with caplog.at_level(
        logging.WARNING, logger="photon_ml_tpu.telemetry.memory"
    ):
        assert memory.check_headroom(8 * 2**30, label="re chunk") is False
    assert any("re chunk" in r.message for r in caplog.records)
    snap = telemetry.snapshot()
    assert snap["counters"]["memory.headroom_warnings"] == 1
    assert snap["gauges"]["memory.free_bytes"] > 0


def test_record_phase_memory_tracks_peaks(fake_hbm):
    in_use = memory.record_phase_memory("coordinate:fixed")
    assert in_use == 10 * 2**30
    memory.set_stats_provider(
        lambda: {"bytes_in_use": 12 * 2**30, "bytes_limit": 16 * 2**30}
    )
    memory.record_phase_memory("coordinate:fixed")
    memory.set_stats_provider(
        lambda: {"bytes_in_use": 6 * 2**30, "bytes_limit": 16 * 2**30}
    )
    memory.record_phase_memory("coordinate:fixed")
    g = telemetry.snapshot()["gauges"]
    # last sample wins the in_use gauge; the peak holds the max
    assert g["memory.phase.coordinate:fixed.bytes_in_use"] == 6 * 2**30
    assert g["memory.phase.coordinate:fixed.peak_bytes"] == 12 * 2**30


def test_estimate_table_and_batch_bytes():
    assert memory.estimate_table_bytes(1000, 50) == 1000 * 50 * 4
    assert memory.estimate_table_bytes(10, 3, itemsize=8) == 240
    from photon_ml_tpu.ops.dense import DenseBatch

    b = DenseBatch(
        x=np.zeros((4, 3), np.float32),
        labels=np.zeros(4, np.float32),
        offsets=np.zeros(4, np.float32),
        weights=np.zeros(4, np.float32),
    )
    assert memory.estimate_batch_bytes(b) == (4 * 3 + 3 * 4) * 4


# -- heartbeat ----------------------------------------------------------------


def test_heartbeat_beat_contents(fake_hbm, tmp_path):
    out = tmp_path / "hb.jsonl"
    hb = Heartbeat(interval=60, jsonl_path=str(out))
    telemetry.counter("progress.rows").inc(5000)
    telemetry.counter("progress.coeffs").inc(300)
    telemetry.gauge("checkpoint.last_save_ts").set(
        telemetry.trace.TRACER.now()
    )
    telemetry.gauge("checkpoint.last_step").set(7)
    with telemetry.span("fit"):
        with telemetry.span("coordinate:perUser"):
            line = hb.beat()
    assert line["type"] == "heartbeat"
    assert line["span"] == "fit > coordinate:perUser"
    assert line["rows_per_s"] > 0 and line["coeffs_per_s"] > 0
    assert line["rows_total"] == 5000
    assert line["hbm_bytes_in_use"] == 10 * 2**30
    assert line["checkpoint_age_s"] >= 0
    assert line["checkpoint_last_step"] == 7
    # rates persist as gauges for the final snapshot / run report
    g = telemetry.snapshot()["gauges"]
    assert g["progress.rows_per_sec"] > 0
    # the sink got the same line; deltas reset so a second beat reads 0
    (rec,) = [json.loads(x) for x in out.read_text().splitlines()]
    assert rec["seq"] == 1
    line2 = hb.beat()
    assert line2["rows_per_s"] == 0.0 and line2["seq"] == 2


def test_device_spread_from_gauges_and_heartbeat(fake_hbm):
    """Per-device HBM spread (max-min): computed from the published
    memory.device.* gauges (make_mesh publishes them; CPU probes are
    statless) and surfaced on heartbeat lines + its own gauge."""
    from photon_ml_tpu.telemetry import memory as tmem

    telemetry.gauge("memory.device.0.bytes_in_use").set(10 * 2**20)
    telemetry.gauge("memory.device.1.bytes_in_use").set(4 * 2**20)
    assert tmem.device_spread_bytes() == 6 * 2**20
    assert (
        telemetry.snapshot()["gauges"]["memory.device_spread_bytes"]
        == 6 * 2**20
    )
    line = Heartbeat(interval=60).beat()
    assert line["hbm_device_spread_bytes"] == 6 * 2**20


def test_device_spread_unknown_with_one_device():
    from photon_ml_tpu.telemetry import memory as tmem

    telemetry.gauge("memory.device.0.bytes_in_use").set(10 * 2**20)
    assert tmem.device_spread_bytes() is None
    line = Heartbeat(interval=60).beat()
    assert "hbm_device_spread_bytes" not in line


def test_report_renders_device_spread():
    from photon_ml_tpu.telemetry.report import RunReport

    telemetry.gauge("memory.device.0.bytes_in_use").set(3 * 2**30)
    telemetry.gauge("memory.device.1.bytes_in_use").set(1 * 2**30)
    md = RunReport.from_live().to_markdown()
    assert "spread" in md
    assert "2 devices" in md


def test_heartbeat_daemon_thread_emits_and_stops(tmp_path):
    out = tmp_path / "hb.jsonl"
    hb = Heartbeat(interval=0.02, jsonl_path=str(out))
    with hb:
        deadline = time.monotonic() + 5.0
        while not out.exists() and time.monotonic() < deadline:
            time.sleep(0.005)
    assert out.exists(), "daemon thread never beat"
    n_at_stop = len(out.read_text().splitlines())
    assert n_at_stop >= 1
    time.sleep(0.1)  # stopped: no further beats
    assert len(out.read_text().splitlines()) == n_at_stop
    assert hb._thread is None


def test_heartbeat_rejects_bad_interval():
    with pytest.raises(ValueError, match="interval"):
        Heartbeat(interval=0)


# -- report building ----------------------------------------------------------


def _span(id, parent, name, ts, dur, thread="MainThread"):
    return {
        "type": "span", "id": id, "parent": parent, "name": name,
        "ts": ts, "dur": dur, "thread": thread, "attrs": {}, "events": [],
    }


SPANS = [
    _span(1, None, "fit", 0.0, 10.0),
    _span(2, 1, "cd_iteration", 0.5, 4.0),
    _span(3, 2, "coordinate:fixed", 0.5, 2.5),
    _span(4, 2, "coordinate:perUser", 3.0, 1.5),
    _span(5, 1, "cd_iteration", 5.0, 4.5),
    _span(6, 5, "coordinate:fixed", 5.0, 2.0),
    _span(7, 5, "coordinate:perUser", 7.0, 2.5),
]


def test_build_phase_tree_aggregates_by_path():
    root = build_phase_tree(SPANS)
    fit = root.children["fit"]
    assert fit.count == 1 and fit.total_s == 10.0
    cd = fit.children["cd_iteration"]
    assert cd.count == 2 and cd.total_s == pytest.approx(8.5)
    assert cd.children["coordinate:fixed"].total_s == pytest.approx(4.5)
    assert cd.children["coordinate:perUser"].total_s == pytest.approx(4.0)
    # self time subtracts children at each level
    assert fit.self_s == pytest.approx(1.5)
    assert cd.self_s == pytest.approx(0.0)


def test_build_phase_tree_orphan_parent_roots_at_survivor():
    # span 9's parent 8 was dropped from a bounded buffer
    spans = SPANS + [_span(9, 8, "leaked", 9.0, 0.5)]
    root = build_phase_tree(spans)
    assert root.children["leaked"].count == 1  # rooted, not lost


def test_compare_metrics_directions_and_threshold():
    deltas = compare_metrics(
        {"rows_per_sec": 80.0, "jit_compiles": 30.0, "fit_seconds": 95.0},
        {"rows_per_sec": 100.0, "jit_compiles": 20.0, "fit_seconds": 100.0},
        threshold=0.2,
    )
    by = {d.metric: d for d in deltas}
    # -20% rows/s is AT the threshold, not beyond: ok
    assert not by["rows_per_sec"].regressed
    # +50% compiles (lower-is-better): regression
    assert by["jit_compiles"].regressed
    assert not by["fit_seconds"].regressed  # 5% faster = improvement
    # zero baselines and unknown metrics are skipped
    assert compare_metrics({"x": 1.0}, {"x": 0.0}) == []
    assert compare_metrics({"mystery": 1.0}, {"mystery": 2.0}) == []


def test_run_report_load_merge_and_markdown(tmp_path):
    trace = tmp_path / "run.trace.jsonl"
    with open(trace, "w") as fh:
        fh.write(json.dumps({"type": "trace_header"}) + "\n")
        for s in SPANS:
            fh.write(json.dumps(s) + "\n")
        fh.write("{truncated last line")
    tele = tmp_path / "run.metrics.jsonl"
    snapshot = {
        "counters": {
            "jit_compiles": 12,
            "jit_compile_seconds": 3.5,
            "device_fetches": 40,
            "device_fetch_seconds": 4.2,
            "trace.dropped_spans": 2,
            "memory.headroom_warnings": 1,
        },
        "gauges": {
            "progress.rows_per_sec": 5e5,
            "progress.coeffs_per_sec": 1e4,
            "memory.bytes_in_use": 10 * 2**30,
            "memory.bytes_limit": 16 * 2**30,
            "memory.phase.coordinate:fixed.peak_bytes": 11 * 2**30,
        },
        "histograms": {
            "device_fetch_seconds": {"count": 40, "p50": 0.1, "p95": 0.2}
        },
    }
    with open(tele, "w") as fh:
        fh.write(
            json.dumps({"type": "heartbeat", "seq": 1, "uptime_s": 30.0,
                        "span": "fit", "rows_per_s": 4e5}) + "\n"
        )
        fh.write(
            json.dumps({"type": "metrics", "snapshot": snapshot}) + "\n"
        )
    ckpt = tmp_path / "ckpt" / "step-00000003"
    ckpt.mkdir(parents=True)
    (ckpt / "manifest.json").write_text(json.dumps({
        "format_version": 1, "step": 3, "best_metric": 0.71,
        "frozen": ["perUser"],
        "consecutive_rollbacks": {"perUser": 2},
        "history": [
            {"iteration": 0, "coordinate": "fixed", "seconds": 2.5,
             "metrics": {"auc": 0.7}},
            {"iteration": 0, "coordinate": "perUser", "seconds": 1.5,
             "solve_retries": 2, "rolled_back": True},
            {"iteration": 1, "coordinate": "fixed", "seconds": 2.0,
             "metrics": {"auc": 0.71}},
        ],
    }))

    report = RunReport.load(
        trace=str(trace), telemetry=str(tele),
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    km = report.key_metrics()
    assert km["fit_seconds"] == 10.0
    assert km["rows_per_sec"] == 5e5
    assert km["jit_compiles"] == 12
    assert km["dropped_spans"] == 2

    coords = report.coordinate_summary()
    by = {c["coordinate"]: c for c in coords}
    assert by["fixed"]["steps"] == 2
    assert by["fixed"]["last_metrics"] == {"auc": 0.71}
    assert by["perUser"]["rollbacks"] == 1
    assert by["perUser"]["solve_retries"] == 2
    assert by["perUser"]["frozen"] is True

    md = report.to_markdown()
    # the full phase-time tree, nested
    assert "- `fit` — n=1" in md
    assert "  - `cd_iteration` — n=2" in md
    assert "    - `coordinate:fixed` — n=2" in md
    assert "    - `coordinate:perUser` — n=2" in md
    # accounting, memory, coordinates, heartbeats, drop warning
    assert "`jit_compiles` | 12" in md
    assert "headroom warning" in md
    assert "`coordinate:fixed` | 11.0 GiB" in md
    assert "1 beat(s)" in md
    assert "2 span(s) were dropped" in md

    # round-trip: the saved JSON is a usable compare baseline
    doc = report.save_json(str(tmp_path / "report.json"))
    assert doc["key_metrics"] == km
    deltas = report.compare(
        json.load(open(tmp_path / "report.json")), threshold=0.2
    )
    assert deltas and not any(d.regressed for d in deltas)
    # doctored baseline (2x the rows/s): current run has regressed
    doctored = dict(doc, key_metrics=dict(km, rows_per_sec=km["rows_per_sec"] * 2))
    regressed = [d for d in report.compare(doctored) if d.regressed]
    assert [d.metric for d in regressed] == ["rows_per_sec"]
    md2 = report.to_markdown(deltas=report.compare(doctored))
    assert "**REGRESSED**" in md2


def test_report_path_sibling():
    assert report_path("x/run.trace.jsonl") == "x/run.trace.report.md"
    assert report_path("run") == "run.report.md"


def test_metric_delta_is_json_safe():
    d = MetricDelta("m", 1.0, 2.0, -0.5, True)
    json.dumps(d.to_dict())


# -- bench budget / gate ------------------------------------------------------


def test_bench_suite_budget_emits_truncated_lines(capsys, monkeypatch):
    import bench_suite

    monkeypatch.setenv("PHOTON_BENCH_BUDGET_S", "0")
    deadline = bench_suite.budget_deadline()
    assert deadline is not None
    # budget already spent: EVERY metric line still appears, truncated
    results = bench_suite.run_suite(deadline=time.monotonic() - 1.0)
    lines = [
        json.loads(x)
        for x in capsys.readouterr().out.splitlines()
        if x.startswith("{")
    ]
    assert [x["metric"] for x in lines] == list(bench_suite.SUITE_METRICS)
    assert all(x["truncated"] is True and x["value"] is None for x in lines)
    assert all(v is None for v in results.values())
    monkeypatch.delenv("PHOTON_BENCH_BUDGET_S")
    assert bench_suite.budget_deadline() is None


def test_bench_suite_gate(tmp_path, capsys):
    import bench_suite

    results = {
        "linreg_tron_1Mx10K_rows_per_sec_per_chip": 50_000.0,
        "poisson_offsets_box_1Mx10K_rows_per_sec_per_chip": None,  # truncated
    }
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps(
        {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 100_000.0}
    ))
    rc = bench_suite.run_gate(
        results, bench_suite.load_gate_baseline(str(baseline)), 0.2
    )
    assert rc == bench_suite.GATE_EXIT_CODE
    err = capsys.readouterr().err
    assert "REGRESSED" in err and "truncated, not gated" in err
    # within threshold: passes
    rc = bench_suite.run_gate(
        results, {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 55_000.0}, 0.2
    )
    assert rc == 0
    # a baseline sharing NO metric names (e.g. a run-report key_metrics
    # doc) must ERROR, not silently pass the gate
    rc = bench_suite.run_gate(
        results, {"rows_per_sec": 1.0, "fit_seconds": 2.0}, 0.2
    )
    assert rc == 2
    assert "no comparable metrics" in capsys.readouterr().err
    # an all-truncated run compared NOTHING: the gate must not pass —
    # a starved budget would otherwise keep a real regression green
    rc = bench_suite.run_gate(
        {"linreg_tron_1Mx10K_rows_per_sec_per_chip": None},
        {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0},
        0.2,
    )
    assert rc == 2
    assert "budget-truncated" in capsys.readouterr().err


def test_bench_suite_gate_baseline_formats(tmp_path):
    import bench_suite

    # JSONL of bench output lines
    p = tmp_path / "lines.jsonl"
    p.write_text(
        json.dumps({"metric": "a", "value": 2.0, "unit": "rows/s"}) + "\n"
        + json.dumps({"metric": "bad", "value": None, "truncated": True})
        + "\nnot json\n"
    )
    assert bench_suite.load_gate_baseline(str(p)) == {"a": 2.0}
    # run-report JSON with key_metrics
    p2 = tmp_path / "report.json"
    p2.write_text(json.dumps({"key_metrics": {"b": 3.0, "note": "x"}}))
    assert bench_suite.load_gate_baseline(str(p2)) == {"b": 3.0}


def test_bench_budget_skips_all_sub_benchmarks(capsys):
    import bench

    # deadline in the past: every sub-benchmark is skipped WITHOUT
    # launching a subprocess, yet every expected metric line appears
    bench.run_sub_benchmarks(deadline=time.monotonic() - 1.0)
    lines = [
        json.loads(x)
        for x in capsys.readouterr().out.splitlines()
        if x.startswith("{")
    ]
    expected = [
        m for ms in bench._SCRIPT_METRICS.values() for m in ms
    ]
    assert [x["metric"] for x in lines] == expected
    assert all(x["truncated"] is True for x in lines)


# -- train CLI wiring ---------------------------------------------------------


def test_train_parse_heartbeat_variants():
    from photon_ml_tpu.cli.train import _parse_heartbeat

    hb = _parse_heartbeat({}, None)  # on by default
    assert hb is not None and hb.interval == 30.0 and hb.jsonl_path is None
    # every documented "off" spelling disables without crashing
    assert _parse_heartbeat({"heartbeat": False}, None) is None
    assert _parse_heartbeat({"heartbeat": 0}, None) is None
    assert _parse_heartbeat({"heartbeat": None}, None) is None
    # {} means enabled with defaults; a bare number is the interval
    assert _parse_heartbeat({"heartbeat": {}}, None).interval == 30.0
    assert _parse_heartbeat({"heartbeat": 10}, None).interval == 10.0
    hb = _parse_heartbeat(
        {"heartbeat": {"every": 5, "out": "hb.jsonl"}}, "m.jsonl"
    )
    assert hb.interval == 5.0 and hb.jsonl_path == "hb.jsonl"
    # sink defaults to telemetry_out so the report finds the beats
    hb = _parse_heartbeat({"heartbeat": {"every": 5}}, "m.jsonl")
    assert hb.jsonl_path == "m.jsonl"
    assert _parse_heartbeat({"heartbeat": {"every": 0}}, None) is None
    with pytest.raises(ValueError, match="unknown heartbeat"):
        _parse_heartbeat({"heartbeat": {"interval": 5}}, None)


def test_train_maybe_write_report_from_live(tmp_path):
    from photon_ml_tpu.cli.train import _maybe_write_report

    summary = {}
    _maybe_write_report({}, summary, None, None)  # no report_out: no-op
    assert summary == {}
    with telemetry.span("fit"):
        pass
    report_out = tmp_path / "run.report.md"
    _maybe_write_report(
        {"report_out": str(report_out)}, summary, None, None
    )
    assert summary["report"] == str(report_out)
    assert "- `fit`" in report_out.read_text()
    doc = json.loads((tmp_path / "run.report.json").read_text())
    assert doc["type"] == "run_report"


# -- e2e acceptance -----------------------------------------------------------


def test_e2e_fit_report_compare(tmp_path):
    """ISSUE 3 acceptance: a small GameEstimator.fit with trace+telemetry
    sinks -> `cli report` produces a markdown report with the full
    phase-time tree; heartbeat lines were emitted; `cli report --compare
    --fail-on-regress` exits nonzero against a doctored baseline showing
    a >20% rows/s regression and 0 against the undoctored one."""
    from photon_ml_tpu.cli.report import main as report_main
    from photon_ml_tpu.game.checkpoint import CheckpointSpec
    from photon_ml_tpu.game.estimator import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
        RandomEffectConfig,
    )
    from photon_ml_tpu.optim.factory import OptimizerConfig
    from photon_ml_tpu.testing import generate_game_dataset

    data, _ = generate_game_dataset(
        task="logistic", n_users=6, rows_per_user=10, fe_dim=4, re_dim=2
    )
    trace_out = tmp_path / "run.trace.jsonl"
    tele_out = tmp_path / "run.metrics.jsonl"
    ckpt_dir = tmp_path / "ckpt"
    telemetry.reset()
    telemetry.configure(trace_out=str(trace_out))
    opt = OptimizerConfig(max_iterations=5)
    estimator = GameEstimator(GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="global", optimizer=opt),
            "perUser": RandomEffectConfig(
                shard_name="user", id_name="userId", optimizer=opt
            ),
        },
        num_iterations=2,
    ))
    # a sub-second-interval heartbeat so even this tiny fit beats
    with Heartbeat(interval=0.05, jsonl_path=str(tele_out)):
        estimator.fit(
            data,
            checkpoint_spec=CheckpointSpec(directory=str(ckpt_dir)),
        )
    telemetry.flush_metrics(str(tele_out))

    # heartbeat lines WERE emitted during the fit
    hb_lines = [
        json.loads(x)
        for x in tele_out.read_text().splitlines()
        if json.loads(x).get("type") == "heartbeat"
    ]
    assert hb_lines, "no heartbeat lines during the fit"
    assert any(x["rows_total"] > 0 for x in hb_lines)

    # the snapshot carries the report's rate + progress metrics
    snap = telemetry.snapshot()
    assert snap["gauges"]["progress.rows_per_sec"] > 0
    assert snap["counters"]["progress.rows"] == 6 * 10 * 2 * 2  # rows*coords*iters
    telemetry.reset()

    md_path = tmp_path / "report.md"
    json_path = tmp_path / "report.json"
    rc = report_main([
        "--trace", str(trace_out),
        "--telemetry", str(tele_out),
        "--checkpoint-dir", str(ckpt_dir),
        "--out", str(md_path),
        "--json", str(json_path),
    ])
    assert rc == 0
    md = md_path.read_text()
    # the full phase-time tree
    assert "- `fit` — n=1" in md
    assert "  - `cd_iteration` — n=2" in md
    assert "    - `coordinate:fixed` — n=2" in md
    assert "    - `coordinate:perUser` — n=2" in md
    assert "`build_coordinates`" in md
    # convergence history from the checkpoint manifests
    assert "## Coordinates" in md and "`perUser` | 2" in md
    assert "## Heartbeats" in md

    # undoctored baseline: exit 0
    rc = report_main([
        "--trace", str(trace_out), "--telemetry", str(tele_out),
        "--out", str(tmp_path / "cmp.md"),
        "--compare", str(json_path), "--fail-on-regress",
    ])
    assert rc == 0
    # doctored baseline: rows/s 2x better than measured -> >20% regression
    doc = json.loads(json_path.read_text())
    assert doc["key_metrics"]["rows_per_sec"] > 0
    doc["key_metrics"]["rows_per_sec"] *= 2.0
    doctored = tmp_path / "doctored.json"
    doctored.write_text(json.dumps(doc))
    rc = report_main([
        "--trace", str(trace_out), "--telemetry", str(tele_out),
        "--out", str(tmp_path / "cmp2.md"),
        "--compare", str(doctored), "--fail-on-regress",
    ])
    assert rc == 3
    assert "**REGRESSED**" in (tmp_path / "cmp2.md").read_text()


def test_cli_report_requires_a_source():
    from photon_ml_tpu.cli.report import main as report_main

    with pytest.raises(SystemExit) as exc:
        report_main([])
    assert exc.value.code == 2


def test_cli_report_bad_baseline(tmp_path):
    from photon_ml_tpu.cli.report import main as report_main

    trace = tmp_path / "t.jsonl"
    trace.write_text("")
    rc = report_main(
        ["--trace", str(trace), "--compare", str(tmp_path / "missing.json")]
    )
    assert rc == 1


# ---------------------------------------------------------------------------
# ISSUE 8: sweep awareness — heartbeat fields + per-config report table
# ---------------------------------------------------------------------------


def test_heartbeat_sweep_progress_fields():
    """sweep_configs_done/total ride the heartbeat line while a sweep is
    running, and are absent otherwise."""
    line = Heartbeat(interval=60).beat()
    assert "sweep_configs_total" not in line
    telemetry.gauge("sweep.configs_total").set(16)
    telemetry.gauge("sweep.configs_done").set(5)
    line = Heartbeat(interval=60).beat()
    assert line["sweep_configs_total"] == 16
    assert line["sweep_configs_done"] == 5


def test_report_sweep_table_round_trip(tmp_path):
    """The sweep runner's sweep_config spans + sweep.* gauges render as a
    per-config convergence table, round-tripping through the on-disk
    trace/telemetry JSONL (the satellite acceptance)."""
    trace_path = str(tmp_path / "sweep.trace.jsonl")
    tele_path = str(tmp_path / "sweep.metrics.jsonl")
    telemetry.configure(trace_out=trace_path)
    telemetry.gauge("sweep.configs_total").set(3)
    telemetry.gauge("sweep.configs_done").set(3)
    telemetry.gauge("sweep.selected_index").set(1)
    telemetry.gauge("sweep.selected_metric").set(0.81)
    telemetry.counter("sweep.solves").inc(6)
    for g, (lam, iters, reason, metric) in enumerate(
        [(10.0, 12, "FunctionValuesConverged", 0.74),
         (1.0, 20, "MaxIterations", 0.81),
         (0.1, 18, "GradientConverged", None)]
    ):
        with telemetry.span(
            "sweep_config", index=g, **{"lambda": lam},
            iterations=iters, reason=reason, final_loss=100.0 + g,
            metric=metric, metric_name="auc",
        ):
            pass
    telemetry.flush_metrics(tele_path)

    # live view
    live = RunReport.from_live()
    sweep = live.sweep_summary()
    assert sweep["configs_total"] == 3
    assert sweep["selected_index"] == 1
    assert [c["index"] for c in sweep["configs"]] == [0, 1, 2]
    assert sweep["configs"][1]["reason"] == "MaxIterations"
    assert sweep["configs"][2]["metric"] is None
    assert sweep["solves"] == 6

    # disk round trip
    telemetry.reset()  # close the sink; report reads files only
    report = RunReport.load(trace=trace_path, telemetry=tele_path)
    sweep2 = report.sweep_summary()
    assert sweep2["configs"] == sweep["configs"]
    assert report.key_metrics()["sweep_selected_metric"] == 0.81
    md = report.to_markdown()
    assert "## Hyperparameter sweep" in md
    assert "selected config **#1**" in md
    assert "| 0 | 10 | 12 | FunctionValuesConverged |" in md
    doc = report.save_json(str(tmp_path / "r.json"))
    assert doc["sweep"]["selected_index"] == 1


def test_report_without_sweep_has_no_section():
    report = RunReport.from_live()
    assert report.sweep_summary() is None
    assert "Hyperparameter sweep" not in report.to_markdown()


def test_gate_sweep_ratio_is_lower_is_better(capsys):
    """sweep_over_single_ratio regresses when it RISES (wall-time ratio),
    unlike the rows/s metrics; and old baselines skip it with a note."""
    import bench_suite

    # ratio rose 2.0 -> 3.0: regression
    rc = bench_suite.run_gate(
        {"sweep_over_single_ratio": 3.0},
        {"sweep_over_single_ratio": 2.0},
        0.2,
    )
    assert rc == bench_suite.GATE_EXIT_CODE
    assert "REGRESSED" in capsys.readouterr().err
    # ratio dropped (sweep got faster): fine
    rc = bench_suite.run_gate(
        {"sweep_over_single_ratio": 1.5},
        {"sweep_over_single_ratio": 2.0},
        0.2,
    )
    assert rc == 0
    # pre-sweep baseline: skip-with-note, gate still compares the rest
    rc = bench_suite.run_gate(
        {"sweep_over_single_ratio": 2.5,
         "linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0},
        {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 95.0},
        0.2,
    )
    err = capsys.readouterr().err
    assert rc == 0
    assert "sweep_over_single_ratio: new metric" in err

    # overlap_factor likewise skips on baselines that predate it
    rc = bench_suite.run_gate(
        {"overlap_factor": 1.2,
         "linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0},
        {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 95.0},
        0.2,
    )
    err = capsys.readouterr().err
    assert rc == 0
    assert "overlap_factor: new metric" in err


def test_report_ingestion_section_round_trip():
    """The RunReport "Ingestion" section answers the one operational
    question: did the solve ever wait on data?"""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry.report import RunReport

    telemetry.metrics.counter("ingest.rows").inc(120_000)
    telemetry.metrics.counter("ingest.chunks").inc(12)
    telemetry.metrics.gauge("ingest.rows_per_sec").set(1.2e6)
    telemetry.metrics.gauge("ingest.staging_bytes").set(64 * 2**20)
    live = RunReport.from_live()
    ing = live.ingestion_summary()
    assert ing["rows"] == 120_000
    assert ing["chunks"] == 12
    assert ing["solve_waits"] == 0
    md = live.to_markdown()
    assert "## Ingestion" in md
    assert "never waited on data" in md
    assert live.key_metrics()["ingest_rows_per_sec"] == 1.2e6
    assert live.to_json()["ingestion"]["rows"] == 120_000

    # now the ingest-bound variant
    telemetry.metrics.counter("ingest.solve_waits").inc(5)
    telemetry.metrics.histogram("ingest.solve_wait_s").observe_many(
        [0.1] * 5
    )
    md2 = RunReport.from_live().to_markdown()
    assert "waited on data 5 time(s)" in md2


def test_report_without_ingest_has_no_section():
    from photon_ml_tpu.telemetry.report import RunReport

    live = RunReport.from_live()
    assert live.ingestion_summary() is None
    assert "## Ingestion" not in live.to_markdown()
    assert "ingest_rows_per_sec" not in live.key_metrics()


def test_report_recovery_section_round_trip():
    """The "Recovery" section makes "the run recovered" auditable:
    sharded saves with the max single-shard fetch (the no-host-gather
    proof), elastic resumes, corrupt-skip fallbacks, absorbed
    transient-IO retries, and — loudly — deliberate injections."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry.report import RunReport

    telemetry.metrics.counter("checkpoint.saves").inc(3)
    telemetry.metrics.counter("checkpoint.shard_saves").inc(24)
    telemetry.metrics.gauge("checkpoint.max_shard_fetch_bytes").set(
        5 * 2**20
    )
    telemetry.metrics.counter("checkpoint.restores").inc(1)
    telemetry.metrics.counter("checkpoint.corrupt").inc(1)
    telemetry.metrics.counter("recovery.elastic_resumes").inc(1)
    telemetry.metrics.counter("ingest.read_retries").inc(2)
    telemetry.metrics.counter("serving.version_retries").inc(1)
    telemetry.metrics.counter("faults.injected").inc(4)
    telemetry.metrics.counter(
        "faults.injected.checkpoint.save.before_rename"
    ).inc(4)
    live = RunReport.from_live()
    rec = live.recovery_summary()
    assert rec["checkpoint_saves"] == 3
    assert rec["checkpoint_shard_saves"] == 24
    assert rec["max_shard_fetch_bytes"] == 5 * 2**20
    assert rec["recovery_elastic_resumes"] == 1
    assert rec["faults_injected_by_point"] == {
        "checkpoint.save.before_rename": 4
    }
    md = live.to_markdown()
    assert "## Recovery" in md
    assert "never the full table" in md
    assert "1 elastic" in md
    assert "corrupt/partial checkpoint(s) skipped" in md
    assert "2 transient-IO retry(ies) absorbed on ingest chunk reads" in md
    assert "deliberately injected" in md
    assert "checkpoint.save.before_rename" in md
    assert live.to_json()["recovery"]["checkpoint_restores"] == 1


def test_report_recovery_fleet_rows_round_trip():
    """Fleet-recovery accounting (supervised multi-process fits): member
    deaths + survivor relaunches, coordinated-checkpoint quorum
    outcomes, and absorbed distributed-init retries each get their own
    Recovery row — and any one of them alone is enough to materialize
    the section."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry.report import RunReport

    telemetry.metrics.counter("recovery.fleet_member_deaths").inc(1)
    telemetry.metrics.counter("recovery.fleet_relaunches").inc(1)
    telemetry.metrics.counter("checkpoint.peer_manifests").inc(6)
    telemetry.metrics.counter("checkpoint.quorum_timeouts").inc(2)
    telemetry.metrics.counter("multihost.init_retries").inc(3)
    live = RunReport.from_live()
    rec = live.recovery_summary()
    assert rec["recovery_fleet_member_deaths"] == 1
    assert rec["recovery_fleet_relaunches"] == 1
    assert rec["checkpoint_peer_manifests"] == 6
    assert rec["checkpoint_quorum_timeouts"] == 2
    assert rec["multihost_init_retries"] == 3
    md = live.to_markdown()
    assert "## Recovery" in md
    assert "fleet: 1 member death(s), 1 survivor relaunch(es)" in md
    assert "6 per-process manifest(s) written, 2 quorum timeout(s)" in md
    assert "3 distributed-init retry(ies) absorbed" in md
    assert (
        live.to_json()["recovery"]["recovery_fleet_relaunches"] == 1
    )


def test_report_without_recovery_activity_has_no_section():
    from photon_ml_tpu.telemetry.report import RunReport

    live = RunReport.from_live()
    assert live.recovery_summary() is None
    assert "## Recovery" not in live.to_markdown()


def test_heartbeat_ingest_fields():
    """Heartbeats surface live ingest throughput — and only when an
    ingest pipeline actually ran (absence stays unknown, never zero)."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry.progress import Heartbeat

    hb = Heartbeat(interval=60)
    line = hb.beat()
    assert "ingest_rows_per_s" not in line  # no pipeline: no field
    telemetry.metrics.counter("ingest.rows").inc(50_000)
    telemetry.metrics.gauge("ingest.queue_depth").set(2)
    line = hb.beat()
    assert line["ingest_rows_per_s"] > 0
    assert line["ingest_queue_depth"] == 2
    assert "ingest_stalls" not in line  # zero stalls: field omitted
    telemetry.metrics.counter("ingest.stalls").inc()
    line = hb.beat()
    assert line["ingest_stalls"] == 1


# ---------------------------------------------------------------------------
# ISSUE 16: executable-level roofline profiler in heartbeats + reports
# ---------------------------------------------------------------------------


def _record_profile(name, seconds, exclusive, flops, nbytes, n=1):
    """Drive the profile registry directly: n sampled dispatches of
    ``name`` at the given per-dispatch honest timing / cost."""
    from photon_ml_tpu.telemetry import profile

    for _ in range(n):
        profile.PROFILE_REGISTRY.count_dispatch(name, ("f32[8]",), 1)
        profile.PROFILE_REGISTRY.record_sample(
            name, ("f32[8]",), seconds, exclusive, 0.0, flops, nbytes
        )


def test_heartbeat_hot_exec_round_trip(tmp_path):
    """The heartbeat's hot_exec field names the executable with the top
    exclusive-time DELTA over the last interval, rides the JSONL sink
    through tail_heartbeat_fields, and stays absent (unknown) when no
    dispatch was profiled — never a stale winner."""
    from photon_ml_tpu.telemetry.progress import tail_heartbeat_fields

    out = tmp_path / "hb.jsonl"
    hb = Heartbeat(interval=60, jsonl_path=str(out))
    line = hb.beat()
    assert "hot_exec" not in line  # nothing profiled yet: unknown

    _record_profile("alpha", 3.0, 3.0, None, None)
    _record_profile("beta", 1.0, 1.0, None, None)
    line = hb.beat()
    assert line["hot_exec"] == "alpha"
    rec = tail_heartbeat_fields(str(out))
    assert rec is not None and rec["hot_exec"] == "alpha"

    # next interval: only beta advances -> the DELTA winner flips
    _record_profile("beta", 2.0, 2.0, None, None)
    assert hb.beat()["hot_exec"] == "beta"
    # idle interval: no new samples, no winner, field omitted
    assert "hot_exec" not in hb.beat()


def test_report_hot_executables_round_trip(tmp_path):
    """Hot-executables table: built from the profile.exec.* gauges at
    report time, ranked by exclusive seconds, carrying MFU / intensity /
    bound class and the xla.exec.* compile split; survives the JSON
    baseline and a metrics-JSONL reload."""
    from photon_ml_tpu.telemetry import xla

    xla.set_peaks(1e12, 1e11)
    # 4 dispatches, 0.5 s each, intensity 1.25 (< balance 10): HBM-bound
    _record_profile("glm_value_grad", 0.5, 0.4, 1e10, 8e9, n=4)
    _record_profile("tiny", 0.01, 0.01, None, None)
    telemetry.metrics.counter(
        "xla.exec.glm_value_grad.recompiles"
    ).inc(2)
    telemetry.metrics.counter(
        "xla.exec.glm_value_grad.compile_seconds"
    ).inc(1.5)

    report = RunReport.from_live()
    hot = report.hot_executables()
    assert [e["name"] for e in hot] == ["glm_value_grad", "tiny"]
    top = hot[0]
    assert top["est_exclusive_seconds"] == pytest.approx(1.6)
    assert top["dispatches"] == 4
    assert top["mfu"] == pytest.approx(0.02)
    assert top["bound_class"] == "HBM-bound"
    assert top["recompiles"] == 2
    assert top["compile_seconds"] == pytest.approx(1.5)
    assert top["timing_suspect"] is False

    km = report.key_metrics()
    assert km["exec.glm_value_grad.mfu"] == pytest.approx(0.02)

    md = report.to_markdown()
    assert "## Hot executables" in md
    assert "`glm_value_grad`" in md
    assert "HBM-bound" in md
    assert "| MFU |" in md

    # JSON baseline round trip
    doc = report.save_json(str(tmp_path / "r.json"))
    loaded = json.loads((tmp_path / "r.json").read_text())
    assert loaded["hot_executables"][0]["name"] == "glm_value_grad"
    assert doc["key_metrics"]["exec.glm_value_grad.mfu"] == km[
        "exec.glm_value_grad.mfu"
    ]

    # metrics-JSONL reload reconstructs the same table
    tele = tmp_path / "run.metrics.jsonl"
    telemetry.flush_metrics(str(tele))
    reloaded = RunReport.load(telemetry=str(tele))
    rehot = reloaded.hot_executables()
    assert rehot[0]["name"] == "glm_value_grad"
    assert rehot[0]["bound_class"] == "HBM-bound"


def test_report_without_profiles_has_no_hot_section():
    live = RunReport.from_live()
    assert live.hot_executables() == []
    assert "## Hot executables" not in live.to_markdown()


def test_report_renders_timing_suspect_warning():
    from photon_ml_tpu.telemetry import xla

    xla.set_peaks(1e12, 1e11)
    # forged-clock rate: 1e9 FLOPs in a nanosecond >> device peak
    _record_profile("liar", 1e-9, 1e-9, 1e9, 1e6)
    md = RunReport.from_live().to_markdown()
    assert "`liar ⚠`" in md
    assert "timing suspect" in md
    assert "physically impossible" in md


def test_cli_report_hot_flag(tmp_path):
    """`cli report --hot` renders ONLY the hot-executables table."""
    from photon_ml_tpu.cli.report import main as report_main

    _record_profile("solve", 2.0, 2.0, None, None)
    tele = tmp_path / "run.metrics.jsonl"
    telemetry.flush_metrics(str(tele))
    telemetry.reset()

    out = tmp_path / "hot.md"
    rc = report_main(
        ["--telemetry", str(tele), "--hot", "--out", str(out)]
    )
    assert rc == 0
    md = out.read_text()
    assert "## Hot executables" in md
    assert "`solve`" in md
    assert "# Run report" not in md  # the full report is suppressed

    # no profiled dispatches: an explanatory line, not an empty file
    empty_tele = tmp_path / "empty.metrics.jsonl"
    empty_tele.write_text(
        json.dumps({"type": "metrics", "snapshot": {
            "counters": {}, "gauges": {}, "histograms": {},
        }}) + "\n"
    )
    rc = report_main(
        ["--telemetry", str(empty_tele), "--hot", "3",
         "--out", str(tmp_path / "none.md")]
    )
    assert rc == 0
    assert "No profiled executables" in (tmp_path / "none.md").read_text()


def test_cli_report_compare_notes_and_skips_exec_metrics(
    tmp_path, capsys
):
    """Per-executable rows in --compare: renamed/new executables are
    note-and-skipped on stderr; a regression on a SHARED executable's
    MFU still flags."""
    from photon_ml_tpu.cli.report import main as report_main
    from photon_ml_tpu.telemetry import xla

    xla.set_peaks(1e12, 1e11)
    # shared: mfu 0.02; new_kernel: only in the current run
    _record_profile("shared", 0.5, 0.5, 1e10, 8e9, n=2)
    _record_profile("new_kernel", 0.2, 0.2, 2e10, 1e9)
    tele = tmp_path / "run.metrics.jsonl"
    telemetry.flush_metrics(str(tele))
    telemetry.reset()

    baseline = {
        "key_metrics": {
            # shared at 5x the current MFU: an MFU regression
            "exec.shared.mfu": 0.1,
            # old_kernel: renamed away since the baseline
            "exec.old_kernel.mfu": 0.3,
        }
    }
    base_path = tmp_path / "baseline.json"
    base_path.write_text(json.dumps(baseline))

    rc = report_main([
        "--telemetry", str(tele),
        "--out", str(tmp_path / "cmp.md"),
        "--compare", str(base_path), "--fail-on-regress",
    ])
    err = capsys.readouterr().err
    assert rc == 3  # the shared executable's MFU regressed
    assert "exec.new_kernel.mfu" in err and "is new" in err
    assert "exec.old_kernel.mfu" in err
    assert "only in the baseline" in err
    md = (tmp_path / "cmp.md").read_text()
    cmp_md = md[md.index("## Comparison vs baseline"):]
    assert "`exec.shared.mfu`" in cmp_md and "**REGRESSED**" in cmp_md
    # the one-sided rows were skipped, not compared
    assert "exec.new_kernel.mfu" not in cmp_md
    assert "exec.old_kernel.mfu" not in cmp_md
