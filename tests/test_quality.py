"""Quality observability (ISSUE 20): GAME-level bootstrap error bars,
the champion/challenger publish gate, and online calibration-drift
telemetry.

The acceptance spine: a deliberately degraded challenger (label-shuffled
delta) is quarantined by ``cli refresh`` AND by a conductor cycle, the
decision round-trips ``/healthz`` lineage, a healthy challenger
publishes unchanged, and the masked-lane bootstrap's CIs agree with a
full-lane bootstrap on the touched rows (the determinism contract of
``bootstrap_re_weights``). Plus the two quality fault seams:
``quality.publish_gate`` (a raise BEFORE any registry write leaves the
registry untouched) and ``quality.drift_flush`` (absorbed by the
snapshot provider-skip contract — the section vanishes from ONE
snapshot, nothing else breaks).
"""

import json
import math
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.faults import (
    FaultPlan,
    FaultRule,
    InjectedFault,
    clear_plan,
    install_plan,
)
from photon_ml_tpu.game.models import FixedEffectModel, GameModel
from photon_ml_tpu.quality import (
    GateDecision,
    QualityGateRefused,
    QualityStats,
    decide_gate,
    drift,
    game_quality_stats,
    weighted_auc,
)
from photon_ml_tpu.serving.registry import (
    champion_quality,
    publish_version,
    scan_versions,
)
from photon_ml_tpu.testing import generate_game_dataset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_D = 5  # fixed-effect dim shared by the in-process worlds


# ---------------------------------------------------------------------------
# weighted AUC + stats plumbing
# ---------------------------------------------------------------------------


def test_weighted_auc_hand_cases():
    y = np.array([0.0, 0.0, 1.0, 1.0])
    w = np.ones(4)
    # perfect separation, reversed separation, all tied
    assert weighted_auc(np.array([0.1, 0.2, 0.8, 0.9]), y, w) == 1.0
    assert weighted_auc(np.array([0.9, 0.8, 0.2, 0.1]), y, w) == 0.0
    assert weighted_auc(np.zeros(4), y, w) == 0.5
    # one concordant pair, one discordant, two ties of each -> hand value:
    # pairs (pos, neg): (.5,.5)=tie, (.5,.9)=wrong, (.9,.5)=right, (.9,.9)=tie
    got = weighted_auc(np.array([0.5, 0.9, 0.5, 0.9]), y, w)
    assert got == pytest.approx((1.0 + 0.5 + 0.5) / 4.0)
    # degenerate sets cannot gate: single-class or zero-weight class
    assert math.isnan(weighted_auc(np.array([0.1, 0.9]), np.ones(2), np.ones(2)))
    assert math.isnan(
        weighted_auc(np.array([0.1, 0.9]), y[:2], np.array([1.0, 0.0]))
    )


def test_weighted_auc_weights_matter():
    # the mis-ranked negative carries 3x weight: AUC drops below the
    # unweighted value by exactly the weighted pair count
    s = np.array([0.2, 0.7, 0.5, 0.9])
    y = np.array([0.0, 0.0, 1.0, 1.0])
    unweighted = weighted_auc(s, y, np.ones(4))
    weighted = weighted_auc(s, y, np.array([1.0, 3.0, 1.0, 1.0]))
    assert unweighted == pytest.approx(3 / 4)
    # pairs: (.5 vs .2) ok w=1, (.5 vs .7) wrong w=3, (.9 vs .2) ok w=1,
    # (.9 vs .7) ok w=3 -> 5/8
    assert weighted == pytest.approx(5 / 8)


def test_quality_stats_json_roundtrip():
    stats = QualityStats(
        auc=0.8, auc_ci_low=0.75, auc_ci_high=0.85, rows=100,
        bootstrap_samples=16,
    )
    doc = stats.to_json()
    assert "hl_p_value" not in doc  # None fields dropped
    # tolerant load: extra keys (the recorded gate decision, bootstrap
    # summaries) are ignored, not fatal
    doc["gate"] = {"decision": "published"}
    doc["bootstrap"] = {"entities": 3}
    back = QualityStats.from_json(doc)
    assert back.auc == 0.8 and back.rows == 100
    assert math.isnan(QualityStats.from_json({}).auc)


def _stats(auc, lo, hi, hl_p=None):
    return QualityStats(
        auc=auc, auc_ci_low=lo, auc_ci_high=hi, rows=200,
        bootstrap_samples=8, hl_p_value=hl_p,
    )


def test_decide_gate_matrix():
    champ = _stats(0.80, 0.75, 0.85, hl_p=0.4).to_json()

    # override always bypasses, champion or not
    d = decide_gate(_stats(0.10, 0.05, 0.15), champ, "v-1", override=True)
    assert d.decision == "bypassed"
    # no champion with recorded stats -> publish, recorded as such
    assert decide_gate(_stats(0.6, 0.5, 0.7), None).decision == "no_champion"
    # regression beyond the champion's error bars -> quarantined
    d = decide_gate(_stats(0.70, 0.65, 0.74), champ, "v-1")
    assert d.decision == "quarantined" and d.champion_version == "v-1"
    assert "below champion bootstrap CI" in d.reason
    # inside the CI -> published (the CI, not an epsilon, is the bar)
    assert decide_gate(_stats(0.76, 0.72, 0.80), champ, "v-1").decision == (
        "published"
    )
    # better than the champion, trivially published
    assert decide_gate(_stats(0.90, 0.86, 0.93), champ, "v-1").decision == (
        "published"
    )
    # degenerate eval set on either side -> cannot compare -> publish
    nan = float("nan")
    assert decide_gate(_stats(nan, nan, nan), champ, "v-1").decision == (
        "published"
    )
    # H-L collapse while the champion held -> quarantined even with AUC ok
    d = decide_gate(_stats(0.81, 0.78, 0.84, hl_p=1e-9), champ, "v-1")
    assert d.decision == "quarantined" and "Hosmer-Lemeshow" in d.reason
    # both collapsed (a hard dataset, not a regression) -> published
    champ_bad_hl = _stats(0.80, 0.75, 0.85, hl_p=1e-9).to_json()
    assert decide_gate(
        _stats(0.81, 0.78, 0.84, hl_p=1e-9), champ_bad_hl, "v-1"
    ).decision == "published"
    # decisions serialize round-trippably
    assert GateDecision(**{
        k: v for k, v in d.to_json().items()
    }).decision == "quarantined"


# ---------------------------------------------------------------------------
# game_quality_stats on a planted model
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def eval_world():
    data, truth = generate_game_dataset(
        n_users=8, rows_per_user=12, fe_dim=_D, re_dim=3, seed=7
    )
    model = GameModel(
        task="logistic",
        models={
            "fixed": FixedEffectModel(
                coefficients=jnp.asarray(truth["w_global"], jnp.float32),
                shard_name="global",
            )
        },
    )
    return data, model, truth


def test_game_quality_stats_ci_and_calibration(eval_world):
    data, model, _ = eval_world
    stats = game_quality_stats(model, data, num_samples=24, seed=3)
    assert stats.rows == data.num_rows
    assert stats.bootstrap_samples == 24
    # planted coefficients rank far better than chance, and the
    # bootstrap CI brackets the point estimate
    assert stats.auc > 0.6
    assert stats.auc_ci_low <= stats.auc <= stats.auc_ci_high
    assert stats.auc_ci_low < stats.auc_ci_high
    # logistic task -> Hosmer-Lemeshow calibration recorded
    assert stats.hl_chi_square is not None
    assert 0.0 <= stats.hl_p_value <= 1.0
    # resampling is seeded: same seed, same error bars
    again = game_quality_stats(model, data, num_samples=24, seed=3)
    assert again.auc_ci_low == stats.auc_ci_low
    assert again.auc_ci_high == stats.auc_ci_high


# ---------------------------------------------------------------------------
# masked-lane vs full-lane bootstrap agreement (the determinism contract)
# ---------------------------------------------------------------------------


def _entity_problem(rng, n_entities, rows, feats):
    """Dense-as-COO per-entity logistic problems with planted
    coefficients; returns host arrays so full and gathered batches are
    built from the SAME values."""
    x = rng.normal(size=(n_entities, rows, feats))
    w_true = rng.normal(size=(n_entities, feats)) * 0.5
    margins = np.einsum("erk,ek->er", x, w_true)
    y = (rng.random((n_entities, rows)) < 1.0 / (1.0 + np.exp(-margins)))
    return x, y.astype(np.float64)


def _entity_batch(x, y):
    from photon_ml_tpu.ops.sparse import SparseBatch

    e, rows, feats = x.shape
    nnz = rows * feats
    return SparseBatch(
        values=jnp.asarray(x.reshape(e, nnz), jnp.float32),
        rows=jnp.asarray(np.broadcast_to(
            np.repeat(np.arange(rows, dtype=np.int32), feats), (e, nnz)
        )),
        cols=jnp.asarray(np.broadcast_to(
            np.tile(np.arange(feats, dtype=np.int32), rows), (e, nnz)
        )),
        labels=jnp.asarray(y, jnp.float32),
        offsets=jnp.zeros((e, rows), jnp.float32),
        weights=jnp.ones((e, rows), jnp.float32),
        num_features=feats,
    )


def test_bootstrap_re_weights_deterministic_per_entity():
    from photon_ml_tpu.diagnostics.bootstrap import bootstrap_re_weights

    base = np.ones((5, 6))
    base[3, 4:] = 0.0  # padding rows stay zero in every draw
    a = bootstrap_re_weights(8, base, seed=5)
    b = bootstrap_re_weights(8, base, seed=5)
    assert np.array_equal(a, b)
    assert a.shape == (8, 5, 6)
    assert np.all(a[:, 3, 4:] == 0.0)
    # each lane resamples exactly its live rows (multinomial of n over n)
    assert np.array_equal(a.sum(axis=2)[:, 3], np.full(8, 4.0))
    assert np.all(a.sum(axis=2)[:, :3] == 6.0)
    # a different seed actually changes the draws
    assert not np.array_equal(a, bootstrap_re_weights(8, base, seed=6))


def test_masked_lane_bootstrap_matches_full_on_touched_rows():
    """The masked-lane path gathers ``counts[:, idx, :]`` out of the
    FULL bucket's seeded draw, so the touched lanes see byte-identical
    resample weights — and therefore the same CIs — as a full-lane
    bootstrap over the whole bucket."""
    from photon_ml_tpu.diagnostics.bootstrap import (
        bootstrap_random_effect,
        bootstrap_re_weights,
    )
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )

    rng = np.random.default_rng(21)
    n_entities, rows, feats = 6, 12, 3
    x, y = _entity_problem(rng, n_entities, rows, feats)
    config = OptimizerConfig(
        optimizer_type=OptimizerType.NEWTON,
        max_iterations=12,
        tolerance=1e-8,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    counts = bootstrap_re_weights(
        8, np.ones((n_entities, rows)), seed=4
    )
    full = bootstrap_random_effect(
        _entity_batch(x, y), "logistic", config,
        jnp.zeros((n_entities, feats), jnp.float32),
        lane_weights=counts,
    )

    idx = np.array([1, 3, 4])  # the "touched" entity lanes
    masked = bootstrap_random_effect(
        _entity_batch(x[idx], y[idx]), "logistic", config,
        jnp.zeros((len(idx), feats), jnp.float32),
        lane_weights=counts[:, idx, :],
    )
    for field in ("mean", "ci_low", "ci_high", "median", "std_dev"):
        np.testing.assert_allclose(
            getattr(masked, field),
            getattr(full, field)[idx],
            rtol=1e-5, atol=1e-6, err_msg=field,
        )
    assert masked.num_samples == full.num_samples == 8
    assert bool(np.all(masked.live_entities))
    # the error bars are real: nonzero width, bracketing the mean
    width = masked.ci_high - masked.ci_low
    assert float(width.max()) > 0.0
    assert np.all(masked.ci_low <= masked.mean + 1e-9)
    assert np.all(masked.mean <= masked.ci_high + 1e-9)


# ---------------------------------------------------------------------------
# drift telemetry: sketches, ring eviction, PSI, provider + seam
# ---------------------------------------------------------------------------


def test_drift_ring_eviction_bounded():
    drift.reset()
    telemetry.reset()
    for i in range(drift.MAX_VERSIONS + 3):
        drift.observe_scores(f"v-{i:08d}", np.full(4, 0.5))
    rows = drift.MONITOR.snapshot_rows()["versions"]
    assert len(rows) == drift.MAX_VERSIONS
    # ring-evicted oldest-first: the first three versions are gone
    assert "v-00000000" not in rows and "v-00000002" not in rows
    assert f"v-{drift.MAX_VERSIONS + 2:08d}" in rows
    snap = telemetry.snapshot()["counters"]
    assert snap["quality.versions_evicted"] == 3
    assert snap["quality.scores_observed"] == 4 * (drift.MAX_VERSIONS + 3)
    drift.reset()


def test_drift_psi_flags_shifted_distribution():
    drift.reset()
    rng = np.random.default_rng(0)
    # baseline needs MIN_BASELINE_SAMPLES scores before it anchors PSI
    drift.observe_scores("v-a", rng.uniform(0.2, 0.4, 200))
    drift.observe_scores("v-b", rng.uniform(0.2, 0.4, 120))
    drift.observe_scores("v-c", rng.uniform(0.6, 0.9, 120))
    doc = drift.MONITOR.snapshot_rows()
    assert doc["baseline_version"] == "v-a"
    assert "psi_vs_baseline" not in doc["versions"]["v-a"]
    # same distribution: stable; disjoint support: screaming drift
    assert doc["versions"]["v-b"]["psi_vs_baseline"] < 0.1
    assert doc["versions"]["v-c"]["psi_vs_baseline"] > 0.25
    s = doc["versions"]["v-a"]["scores"]
    assert s["count"] == 200 and sum(s["histogram"]) == 200
    assert 0.2 <= s["mean"] <= 0.4
    drift.reset()


def test_drift_calibration_gap():
    drift.reset()
    rng = np.random.default_rng(1)
    p = rng.uniform(0.05, 0.95, 400)
    calibrated = (rng.random(400) < p).astype(np.float64)
    drift.observe_labeled("v-good", p, calibrated)
    drift.observe_labeled("v-bad", p, 1.0 - calibrated)
    doc = drift.MONITOR.snapshot_rows()["versions"]
    good = doc["v-good"]["calibration"]
    bad = doc["v-bad"]["calibration"]
    assert good["count"] == bad["count"] == 400
    # labels drawn AT the predicted rate track it; inverted labels gap
    assert good["max_gap"] < 0.25
    assert bad["max_gap"] > 0.5
    assert len(good["predicted_mean"]) == drift.NUM_BINS
    drift.reset()


def test_quality_snapshot_provider_and_drift_flush_seam():
    """The ``"quality"`` section rides every telemetry snapshot, and an
    injected raise at ``quality.drift_flush`` is absorbed by the
    provider-skip contract: the section vanishes from that one snapshot,
    nothing else fails, and the next snapshot has it back."""
    drift.reset()
    drift.observe_scores("v-seam", np.array([0.3, 0.7]))
    snap = telemetry.snapshot()
    assert snap["quality"]["versions"]["v-seam"]["scores"]["count"] == 2

    install_plan(FaultPlan([FaultRule(point="quality.drift_flush",
                                      action="raise")]))
    try:
        broken = telemetry.snapshot()  # must not raise
        assert "quality" not in broken
        assert "counters" in broken  # the rest of the snapshot survives
    finally:
        clear_plan()
    again = telemetry.snapshot()
    assert "v-seam" in again["quality"]["versions"]
    drift.reset()


def test_engine_score_rows_feeds_drift_sketch(eval_world):
    from photon_ml_tpu.serving.engine import ScoringEngine

    _, model, truth = eval_world
    drift.reset()
    engine = ScoringEngine(model, max_batch=16, version="v-drift-e2e")
    Xg = np.asarray(truth["Xg"])
    rows = [
        {"features": {"global": [
            [j, float(Xg[i, j])] for j in range(_D) if Xg[i, j] != 0
        ]}}
        for i in range(40)
    ]
    scores = engine.score_rows(rows)
    doc = drift.MONITOR.snapshot_rows()["versions"]
    sketch = doc["v-drift-e2e"]["scores"]
    assert sketch["count"] == 40
    # the sketch saw exactly the served mean predictions
    assert sketch["mean"] == pytest.approx(float(np.mean(scores)), abs=1e-5)
    assert sketch["min"] >= 0.0 and sketch["max"] <= 1.0
    drift.reset()


# ---------------------------------------------------------------------------
# the gated registry publish: seam, quarantine, lineage round-trip
# ---------------------------------------------------------------------------


def _fe_model(scale=1.0):
    return GameModel(
        task="logistic",
        models={
            "fixed": FixedEffectModel(
                coefficients=jnp.asarray(
                    np.linspace(-0.5, 0.5, _D) * scale, jnp.float32
                ),
                shard_name="global",
            )
        },
    )


_FE_MAPS = {"global": [f"c{j}" for j in range(_D)]}


def test_publish_gate_seam_leaves_registry_untouched(tmp_path):
    """A raise at ``quality.publish_gate`` fires BEFORE any registry
    write: no new version, no ``.tmp-`` debris, no wrong quarantine —
    the in-process face of the ``tools/chaos.py --quality`` crash row."""
    reg = str(tmp_path / "registry")
    publish_version(
        reg, _fe_model(), _FE_MAPS,
        quality=_stats(0.80, 0.75, 0.85).to_json(),
    )
    before = sorted(os.listdir(reg))
    install_plan(FaultPlan([FaultRule(point="quality.publish_gate",
                                      action="raise")]))
    try:
        with pytest.raises(InjectedFault):
            publish_version(
                reg, _fe_model(0.1), _FE_MAPS,
                quality=_stats(0.55, 0.50, 0.60).to_json(),
            )
    finally:
        clear_plan()
    assert sorted(os.listdir(reg)) == before
    # ungated publishes (quality=None) never hit the seam
    install_plan(FaultPlan([FaultRule(point="quality.publish_gate",
                                      action="raise")]))
    try:
        publish_version(reg, _fe_model(), _FE_MAPS)
    finally:
        clear_plan()
    assert len(scan_versions(reg)) == 2


def test_publish_gate_quarantines_and_lineage_roundtrip(tmp_path):
    from photon_ml_tpu.serving.engine import ScoringEngine
    from photon_ml_tpu.serving.server import ScoringService

    telemetry.reset()
    reg = str(tmp_path / "registry")
    champ_stats = _stats(0.80, 0.75, 0.85)
    publish_version(
        reg, _fe_model(), _FE_MAPS,
        quality=champ_stats.to_json(),
        lineage={"base_kind": "test"},
    )
    champ_v, champ_q = champion_quality(reg)
    assert champ_v == "v-00000001"
    assert champ_q["auc"] == pytest.approx(0.80)
    assert champ_q["gate"]["decision"] == "no_champion"

    # a challenger regressing beyond the champion's CI is refused,
    # parked invisible to scans, with the decision in its metadata
    with pytest.raises(QualityGateRefused) as exc_info:
        publish_version(
            reg, _fe_model(0.1), _FE_MAPS,
            quality=_stats(0.55, 0.50, 0.60).to_json(),
            lineage={"base_kind": "test"},
        )
    exc = exc_info.value
    assert exc.decision.decision == "quarantined"
    assert exc.decision.champion_version == "v-00000001"
    qdir = exc.quarantine_path
    assert os.path.basename(qdir) == "quarantined-v-00000002"
    assert [v for _, v in scan_versions(reg)] == [
        os.path.join(reg, "v-00000001")
    ]
    with open(os.path.join(qdir, "model-metadata.json")) as fh:
        qmeta = json.load(fh)
    assert qmeta["extra"]["quality"]["gate"]["decision"] == "quarantined"
    assert qmeta["extra"]["lineage"]["quality_gate"]["decision"] == (
        "quarantined"
    )

    # a healthy challenger publishes unchanged, takes the refused slot's
    # version number, and the decision round-trips /healthz lineage
    good = _stats(0.82, 0.78, 0.86)
    path = publish_version(
        reg, _fe_model(1.1), _FE_MAPS,
        quality=good.to_json(),
        lineage={"base_kind": "test"},
    )
    assert os.path.basename(path) == "v-00000002"
    engine = ScoringEngine.load(path, max_batch=8)
    gate = engine.lineage["quality_gate"]
    assert gate["decision"] == "published"
    assert gate["champion_version"] == "v-00000001"
    assert gate["candidate"]["auc"] == pytest.approx(0.82)
    health = ScoringService(engine).health()
    assert health["lineage"]["quality_gate"]["decision"] == "published"
    # the new champion for the NEXT gate is the freshest published stats
    assert champion_quality(reg)[0] == "v-00000002"

    counters = telemetry.snapshot()["counters"]
    assert counters["quality.gate_quarantined"] == 1
    assert counters["quality.gate_published"] == 1
    assert counters["quality.gate_no_champion"] == 1


def test_gate_override_records_bypass(tmp_path):
    reg = str(tmp_path / "registry")
    publish_version(
        reg, _fe_model(), _FE_MAPS, quality=_stats(0.80, 0.75, 0.85).to_json()
    )
    # the same regressed challenger, but with --no-quality-gate semantics
    path = publish_version(
        reg, _fe_model(0.1), _FE_MAPS,
        quality=_stats(0.55, 0.50, 0.60).to_json(),
        gate_override=True,
    )
    with open(os.path.join(path, "model-metadata.json")) as fh:
        meta = json.load(fh)
    assert meta["extra"]["quality"]["gate"]["decision"] == "bypassed"
    assert len(scan_versions(reg)) == 2


# ---------------------------------------------------------------------------
# cli refresh: the label-shuffled challenger is quarantined end to end
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def quality_cli_base(tmp_path_factory):
    """One CLI base train plus three deltas: two clean (follow the
    planted model) and one label-shuffled (coin-flip labels, the
    degraded challenger)."""
    from photon_ml_tpu.data.avro import TRAINING_EXAMPLE_AVRO, write_avro

    rng = np.random.default_rng(42)
    tmp = tmp_path_factory.mktemp("cli_quality")
    d, n_users = _D, 5
    w = rng.normal(size=d)
    u_eff = rng.normal(size=n_users)

    def write_shard(path, n, seed, shuffle_labels=False):
        r = np.random.default_rng(seed)
        users = r.integers(0, n_users, n)
        X = r.normal(size=(n, d))
        logits = X @ w + u_eff[users]
        y = (r.random(n) < 1 / (1 + np.exp(-logits))).astype(float)
        if shuffle_labels:
            y = r.permutation(y)  # break the feature-label link

        def recs():
            for i in range(n):
                yield {
                    "uid": str(i),
                    "label": float(y[i]),
                    "features": [
                        {"name": f"c{j}", "term": "", "value": float(X[i, j])}
                        for j in range(d)
                    ],
                    "metadataMap": {"userId": str(users[i])},
                    "weight": None,
                    "offset": None,
                }

        write_avro(path, TRAINING_EXAMPLE_AVRO, recs())

    train_path = str(tmp / "train.avro")
    write_shard(train_path, 220, 1)
    clean_delta = str(tmp / "delta-clean.avro")
    write_shard(clean_delta, 60, 2)
    bad_delta = str(tmp / "delta-shuffled.avro")
    write_shard(bad_delta, 240, 3, shuffle_labels=True)
    clean_delta2 = str(tmp / "delta-clean-2.avro")
    write_shard(clean_delta2, 60, 4)

    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 0.1},
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "global",
                "id_name": "userId",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 1.0},
            },
        },
        "num_iterations": 1,
        "output_dir": str(tmp / "base-model"),
        "checkpoint": {"dir": str(tmp / "base-ckpt"), "resume": False},
    }
    cfg_path = tmp / "train.json"
    cfg_path.write_text(json.dumps(config))
    _run_cli(["train", "--config", str(cfg_path)], cwd=tmp)
    return dict(tmp=tmp, cfg_path=cfg_path, ckpt=str(tmp / "base-ckpt"),
                clean_delta=clean_delta, bad_delta=bad_delta,
                clean_delta2=clean_delta2)


def _run_cli(args, cwd, expect_rc=0):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli", *args],
        capture_output=True, text=True, cwd=str(cwd), env=env, timeout=600,
    )
    assert proc.returncode == expect_rc, (
        proc.returncode, proc.stderr[-3000:]
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_cli_refresh_quarantines_label_shuffled_delta(quality_cli_base):
    tmp = quality_cli_base["tmp"]
    reg = str(tmp / "registry")

    def refresh(delta, out_name):
        return _run_cli(
            [
                "refresh",
                "--config", str(quality_cli_base["cfg_path"]),
                "--warm-start", quality_cli_base["ckpt"],
                "--delta", delta,
                "--registry-dir", reg,
                "--output-dir", str(tmp / out_name),
            ],
            cwd=tmp,
        )["freshness"]

    # refresh 1: clean delta, empty registry -> published with error
    # bars recorded (no champion yet, and the gate says so)
    f1 = refresh(quality_cli_base["clean_delta"], "fresh-1")
    assert f1["published_version"].endswith("v-00000001")
    q1 = f1["quality"]
    assert q1["auc_ci_low"] <= q1["auc"] <= q1["auc_ci_high"]
    assert q1["bootstrap_samples"] == 32
    # the masked-lane bootstrap summary rides the published block
    assert q1["bootstrap"]["num_samples"] == 32
    buckets = q1["bootstrap"]["coordinates"]["perUser"]
    assert sum(b["touched_lanes"] for b in buckets.values()) >= 1
    assert any(b.get("mean_ci_width", 0) > 0 for b in buckets.values())
    assert "quality_gate" not in f1
    with open(os.path.join(reg, "v-00000001", "model-metadata.json")) as fh:
        meta = json.load(fh)
    assert meta["extra"]["quality"]["gate"]["decision"] == "no_champion"
    assert meta["extra"]["lineage"]["quality_gate"]["decision"] == (
        "no_champion"
    )

    # refresh 2: label-shuffled delta -> the candidate's AUC on its own
    # combined data collapses below the champion's CI -> quarantined,
    # rc 0 (a refused candidate is a RESULT), champion keeps serving
    f2 = refresh(quality_cli_base["bad_delta"], "fresh-2")
    assert "published_version" not in f2
    gate = f2["quality_gate"]
    assert gate["decision"] == "quarantined"
    assert gate["champion_version"] == "v-00000001"
    assert gate["candidate"]["auc"] < gate["champion"]["auc_ci_low"]
    assert os.path.basename(gate["quarantine_path"]) == (
        "quarantined-v-00000002"
    )
    assert os.path.isdir(gate["quarantine_path"])
    assert [os.path.basename(p) for _, p in scan_versions(reg)] == [
        "v-00000001"
    ]

    # refresh 3: a healthy challenger publishes unchanged into the slot
    # the refusal never consumed
    f3 = refresh(quality_cli_base["clean_delta2"], "fresh-3")
    assert f3["published_version"].endswith("v-00000002")
    assert "quality_gate" not in f3
    with open(os.path.join(reg, "v-00000002", "model-metadata.json")) as fh:
        meta3 = json.load(fh)
    g3 = meta3["extra"]["quality"]["gate"]
    assert g3["decision"] == "published"
    assert g3["champion_version"] == "v-00000001"


# ---------------------------------------------------------------------------
# conductor cycles: automatic quarantine mid-pipeline + the Quality report
# ---------------------------------------------------------------------------


def test_conductor_cycle_quarantine_and_quality_report(
    quality_cli_base, tmp_path
):
    """A 3-cycle conductor run over the same world: cycle 1 publishes
    the champion with error bars, cycle 2's label-shuffled delta is
    automatically quarantined (the champion keeps serving), cycle 3
    publishes a healthy challenger — and the whole story renders in the
    RunReport "Quality" section."""
    import shutil

    from photon_ml_tpu.pipeline import FreshnessPipeline, PipelineSpec
    from photon_ml_tpu.telemetry.report import RunReport

    telemetry.reset()
    drift.reset()
    tmp = quality_cli_base["tmp"]
    with open(quality_cli_base["cfg_path"]) as fh:
        config = json.load(fh)
    config.pop("output_dir", None)
    config.pop("checkpoint", None)
    delta_dir = tmp_path / "deltas"
    delta_dir.mkdir()
    spec = PipelineSpec(
        config=config,
        delta_dir=str(delta_dir),
        base_dir=quality_cli_base["ckpt"],
        registry_dir=str(tmp_path / "registry"),
        workdir=str(tmp_path / "work"),
        interval_s=0.01,
        escalate_touched_fraction=1.1,
        bootstrap_samples=16,
    )
    pipe = FreshnessPipeline(spec)
    try:
        shutil.copy(quality_cli_base["clean_delta"],
                    delta_dir / "delta-0001.avro")
        e1 = pipe.run_cycle()
        assert e1["published_version"] == "v-00000001"
        with open(os.path.join(spec.registry_dir, "v-00000001",
                               "model-metadata.json")) as fh:
            m1 = json.load(fh)
        q1 = m1["extra"]["quality"]
        assert q1["gate"]["decision"] == "no_champion"
        assert q1["auc_ci_low"] <= q1["auc"] <= q1["auc_ci_high"]
        # the masked-lane bootstrap summary rides the published block
        assert q1["bootstrap"]["num_samples"] == 16

        bad = delta_dir / "delta-0002.avro"
        shutil.copy(quality_cli_base["bad_delta"], bad)
        e2 = pipe.run_cycle()
        assert e2["published_version"] is None
        assert e2["quarantined_version"] == "quarantined-v-00000002"
        assert e2["quality_gate"]["decision"] == "quarantined"
        # the champion keeps serving through the refusal
        assert pipe._registry.current_version == "v-00000001"
        # the digest cursor advanced: the refused delta is NOT retried
        assert pipe.run_cycle()["idle"] is True

        # the degraded shard is cleaned out of the window; the next
        # cycle's healthy candidate publishes unchanged
        os.remove(bad)
        shutil.copy(quality_cli_base["clean_delta2"],
                    delta_dir / "delta-0003.avro")
        e4 = pipe.run_cycle()
        assert e4["published_version"] == "v-00000002"
        assert pipe._registry.current_version == "v-00000002"
        with open(os.path.join(spec.registry_dir, "v-00000002",
                               "model-metadata.json")) as fh:
            m4 = json.load(fh)
        g4 = m4["extra"]["quality"]["gate"]
        assert g4["decision"] == "published"
        assert g4["champion_version"] == "v-00000001"

        s = pipe.summary()
        assert s["published_versions"] == ["v-00000001", "v-00000002"]
        assert s["quarantined_versions"] == ["quarantined-v-00000002"]

        report = RunReport.from_live()
        doc = report.quality_summary()
        assert doc is not None
        assert doc["gate_quarantined"] == 1
        assert doc["gate_published"] == 1
        assert doc["pipeline_quarantines"] == 1
        assert doc["stats_computed"] == 3
        md = report.to_markdown()
        assert "## Quality" in md
        assert "**quarantined**" in md
        assert "regressed challenger" in md
    finally:
        pipe._close("completed")
    telemetry.reset()
    drift.reset()
