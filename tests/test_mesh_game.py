"""Mesh-parallel GAME training parity: GameEstimator.fit(mesh=...) on the
8-device virtual CPU mesh must reproduce the single-device fit.

This is the product-level guarantee the reference gets from Spark local[*]
testing (SparkTestUtils): same coefficients whether the FE solve shards rows
over the 'data' axis (distributed_solve) and RE buckets shard entities over
the 'entity' axis (shard_map), or everything runs on one device.
"""

import jax
import numpy as np
import pytest

from photon_ml_tpu.game import (
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    RandomEffectConfig,
    build_game_dataset,
)
from photon_ml_tpu.data.normalization import NormalizationType
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.parallel import make_mesh

pytestmark = pytest.mark.slow

_OPT = OptimizerConfig(
    optimizer_type=OptimizerType.LBFGS,
    max_iterations=60,
    tolerance=1e-9,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=0.5,
)


def _glmix(rng, n=300, n_users=13):
    # n_users deliberately NOT divisible by 8: exercises entity padding
    Xg = rng.normal(size=(n, 6)) * (rng.random((n, 6)) < 0.6)
    Xg[:, 0] = 1.0
    Xu = rng.normal(size=(n, 3))
    users = rng.integers(0, n_users, size=n)
    wg = rng.normal(size=6)
    wu = rng.normal(size=(n_users, 3))
    margin = Xg @ wg + np.einsum("ij,ij->i", Xu, wu[users])
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(float)
    return build_game_dataset(
        response=y,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y),
            "user": SparseBatch.from_dense(Xu, y),
        },
        id_columns={"userId": users},
    )


def _config(**fe_extra):
    return GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="global", optimizer=_OPT,
                                       **fe_extra),
            "per-user": RandomEffectConfig(
                shard_name="user", id_name="userId", optimizer=_OPT),
        },
        num_iterations=2,
    )


@pytest.fixture
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return make_mesh({"data": 8})


def test_estimator_mesh_matches_single_device(rng, mesh):
    gds = _glmix(rng)
    r_single = GameEstimator(_config()).fit(gds)
    r_mesh = GameEstimator(_config()).fit(gds, mesh=mesh)

    w_fe_s = np.asarray(r_single.model.models["fixed"].coefficients)
    w_fe_m = np.asarray(r_mesh.model.models["fixed"].coefficients)
    np.testing.assert_allclose(w_fe_m, w_fe_s, rtol=2e-3, atol=2e-4)

    re_s = r_single.model.models["per-user"]
    re_m = r_mesh.model.models["per-user"]
    assert len(re_s.buckets) == len(re_m.buckets)
    for bs, bm in zip(re_s.buckets, re_m.buckets):
        np.testing.assert_allclose(
            np.asarray(bm.coefficients), np.asarray(bs.coefficients),
            rtol=2e-3, atol=2e-4,
        )

    # scores agree end-to-end
    s_s = np.asarray(r_single.model.score(gds))
    s_m = np.asarray(r_mesh.model.score(gds))
    np.testing.assert_allclose(s_m, s_s, rtol=2e-3, atol=2e-3)


def test_estimator_mesh_with_normalization(rng, mesh):
    gds = _glmix(rng)
    cfg = _config(normalization=NormalizationType.STANDARDIZATION,
                  intercept_index=0)
    r_single = GameEstimator(cfg).fit(gds)
    r_mesh = GameEstimator(cfg).fit(gds, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(r_mesh.model.models["fixed"].coefficients),
        np.asarray(r_single.model.models["fixed"].coefficients),
        rtol=2e-3, atol=2e-4,
    )


def test_estimator_tiled_layout_matches_coo(rng):
    """The tiled one-hot-matmul fast path is the GAME FE training layout:
    forcing layout='tiled' (pallas interpret mode on CPU) must reproduce the
    COO fit, including residual offsets from the RE coordinate."""
    gds = _glmix(rng, n=150, n_users=7)

    def cfg(layout):
        return GameConfig(
            task="logistic",
            coordinates={
                "fixed": FixedEffectConfig(
                    shard_name="global", optimizer=_OPT, layout=layout),
                "per-user": RandomEffectConfig(
                    shard_name="user", id_name="userId", optimizer=_OPT),
            },
            num_iterations=2,
        )

    r_coo = GameEstimator(cfg("coo")).fit(gds)
    r_tiled = GameEstimator(cfg("tiled")).fit(gds)
    # 5e-3: the layouts round differently (bf16x2 split chains vs plain f32)
    # and the difference compounds over a full warm-started CD fit
    np.testing.assert_allclose(
        np.asarray(r_tiled.model.models["fixed"].coefficients),
        np.asarray(r_coo.model.models["fixed"].coefficients),
        rtol=5e-3, atol=5e-4,
    )


def test_estimator_tiled_layout_on_mesh_matches(rng, mesh):
    """Tiled layout under the mesh: tile groups shard over 'data', parity
    with the single-device COO fit holds."""
    gds = _glmix(rng, n=150, n_users=7)

    def cfg(layout):
        return GameConfig(
            task="logistic",
            coordinates={
                "fixed": FixedEffectConfig(
                    shard_name="global", optimizer=_OPT, layout=layout),
            },
            num_iterations=1,
        )

    r_coo = GameEstimator(cfg("coo")).fit(gds)
    r_tiled = GameEstimator(cfg("tiled")).fit(gds, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(r_tiled.model.models["fixed"].coefficients),
        np.asarray(r_coo.model.models["fixed"].coefficients),
        rtol=2e-3, atol=2e-4,
    )


def test_mesh_re_variances_and_constraints_match_single(rng, mesh):
    """Sharded RE solves with per-entity boxes + variances reproduce the
    single-device path (entity padding must not disturb either)."""
    import dataclasses as _dc

    gds = _glmix(rng)
    opt = _dc.replace(_OPT, box_constraints=((0, -0.1, 0.1),))

    def config():
        return GameConfig(
            task="logistic",
            coordinates={
                "per-user": RandomEffectConfig(
                    shard_name="user",
                    id_name="userId",
                    optimizer=opt,
                    compute_variances=True,
                ),
            },
        )

    r_single = GameEstimator(config()).fit(gds)
    r_mesh = GameEstimator(config()).fit(gds, mesh=mesh)
    re_s = r_single.model.models["per-user"]
    re_m = r_mesh.model.models["per-user"]
    # Iterates are NOT compared here: projected LBFGS with a binding box is
    # not a contraction (clipped (s, y) pairs), so the vmap and padded
    # shard_map compilations can stall at different near-optimal points.
    # The product guarantee is equal per-entity OBJECTIVE value + feasibility.
    from photon_ml_tpu.game import build_random_effect_dataset
    from photon_ml_tpu.ops.objective import make_objective

    obj = make_objective("logistic", l2_weight=0.5)
    red = build_random_effect_dataset(gds, "userId", "user")
    for b, bs, bm in zip(red.buckets, re_s.buckets, re_m.buckets):
        vals_s = np.asarray(
            jax.vmap(lambda w, eb: obj.value(w, eb))(
                bs.coefficients, b.entity_batch()
            )
        )
        vals_m = np.asarray(
            jax.vmap(lambda w, eb: obj.value(w, eb))(
                bm.coefficients, b.entity_batch()
            )
        )
        # 2.5% band: with a BINDING box the projected solve terminates at
        # MaxIterations while crawling the boundary (probe: the padded and
        # unpadded compilations track different near-optimal trajectories)
        np.testing.assert_allclose(vals_m, vals_s, rtol=2.5e-2, atol=1e-4)
        assert bs.variances is not None and bm.variances is not None
        assert np.all(np.asarray(bm.variances) > 0)
        for w, proj in (
            (np.asarray(bm.coefficients), np.asarray(bm.projection)),
            (np.asarray(bs.coefficients), np.asarray(bs.projection)),
        ):
            assert np.all(w[proj == 0] >= -0.1 - 1e-6)
            assert np.all(w[proj == 0] <= 0.1 + 1e-6)
