"""The freshness conductor (ISSUE 19): ``cli pipeline`` as a library.

The acceptance spine: a 3-cycle supervised run in which EVERY cycle
publishes a lineage-linked registry version (v1 → v2 → v3 chained via
``lineage.base_version``), one cycle idles on an unchanged delta digest,
the third non-idle cycle escalates to a full retrain into a fresh base
generation under the daemon workdir, and the event→served staleness p99
is measured and reported. Plus the hard design problem: nearline-vs-delta
reconciliation under the retrain-wins-touched rule, tested BIT-EXACTLY —
the winner's row equals a direct masked re-solve's row bit for bit, the
superseded nearline version stays auditable from the published lineage,
and the decision round-trips through ``/healthz``. Plus the three
``pipeline.*`` fault seams (typed in-process, hard-killed via
``tools/chaos.py --pipeline``), ``/statusz`` live status, and the
RunReport "Pipeline" section.
"""

from __future__ import annotations

import glob
import json
import os
import warnings

import numpy as np
import pytest

from photon_ml_tpu import faults, incremental, telemetry
from photon_ml_tpu.faults import (
    FaultPlan,
    FaultRule,
    InjectedFault,
    clear_plan,
    install_plan,
)
from photon_ml_tpu.game import GameEstimator
from photon_ml_tpu.game.checkpoint import CheckpointSpec
from photon_ml_tpu.pipeline import (
    RECONCILE_RULE,
    FreshnessPipeline,
    PipelineSpec,
)

_D = 6
_N_USERS = 10  # base users "0".."9"; deltas may add the NEW user "10"


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """One avro base + in-process base fit with a step checkpoint — the
    warm-start world every conductor test cycles on — plus a delta
    writer so each test appends its own shards."""
    from photon_ml_tpu.cli.train import read_input
    from photon_ml_tpu.config import parse_game_config
    from photon_ml_tpu.data.avro import TRAINING_EXAMPLE_AVRO, write_avro

    tmp = tmp_path_factory.mktemp("pipeline")
    rng = np.random.default_rng(11)
    n_base = 400
    w = rng.normal(size=_D)
    u_eff = rng.normal(size=_N_USERS + 2)

    def rows(users, seed):
        r = np.random.default_rng(seed)
        X = r.normal(size=(len(users), _D))
        logits = X @ w + u_eff[users]
        y = (r.random(len(users)) < 1 / (1 + np.exp(-logits))).astype(float)
        return X, y

    def recs(X, y, users):
        for i in range(len(users)):
            yield {
                "uid": str(i),
                "label": float(y[i]),
                "features": [
                    {"name": f"c{j}", "term": "", "value": float(X[i, j])}
                    for j in range(_D)
                ],
                "metadataMap": {"userId": str(users[i])},
                "weight": None,
                "offset": None,
            }

    # every base user appears at least once so the perUser vocab is full
    users = np.concatenate([
        np.arange(_N_USERS),
        rng.integers(0, _N_USERS, n_base - _N_USERS),
    ])
    Xb, yb = rows(users, 101)
    train_path = str(tmp / "train.avro")
    write_avro(train_path, TRAINING_EXAMPLE_AVRO, recs(Xb, yb, users))

    def write_delta(path, user_ids, seed):
        du = np.asarray(user_ids)
        Xd, yd = rows(du, seed)
        write_avro(path, TRAINING_EXAMPLE_AVRO, recs(Xd, yd, du))

    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 0.1},
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "global",
                "id_name": "userId",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 1.0},
            },
        },
        "num_iterations": 1,
    }
    ckpt = str(tmp / "base-ckpt")
    data, imaps = read_input(config["input"])
    GameEstimator(parse_game_config(config)).fit(
        data, checkpoint_spec=CheckpointSpec(directory=ckpt, resume=False)
    )
    telemetry.reset()
    return dict(tmp=tmp, config=config, ckpt=ckpt, train_path=train_path,
                write_delta=write_delta, imaps=imaps)


def _entity_coeffs(model, coord="perUser"):
    """entity value -> {global feature id: coefficient} (geometry-free;
    dict equality IS bitwise row equality — same helper as
    test_incremental)."""
    re = model.models[coord]
    out = {}
    for bm in re.buckets:
        P = np.asarray(bm.projection)
        W = np.asarray(bm.coefficients)
        codes = np.asarray(bm.entity_codes)
        for e in range(len(codes)):
            val = re.vocab[codes[e]]
            out[val] = {
                int(g): float(W[e, k]) for k, g in enumerate(P[e])
            }
    return out


def _spec(world, tmp_path, delta_dir, **kw):
    base = dict(
        config=world["config"],
        delta_dir=str(delta_dir),
        base_dir=world["ckpt"],
        registry_dir=str(tmp_path / "registry"),
        workdir=str(tmp_path / "work"),
        interval_s=0.01,
        # the fraction trigger is disabled by default so tests decide
        # escalation deterministically via the cycle-count trigger
        escalate_touched_fraction=1.1,
    )
    base.update(kw)
    return PipelineSpec(**base)


# ---------------------------------------------------------------------------
# the 3-cycle supervised run (the acceptance spine)
# ---------------------------------------------------------------------------


def test_three_cycle_supervised_run(world, tmp_path):
    """Three non-idle cycles: each publishes a lineage-linked version
    (base_version chains v1 → v2 → v3), an unchanged digest idles, the
    third trips escalate_after_cycles=3 into a full retrain that
    re-bases under the workdir, the live registry hot-swaps to the
    freshest version, staleness p99 is reported, a restarted conductor
    re-seeds its cursor and idles, and the RunReport renders Pipeline."""
    from photon_ml_tpu.data.model_store import load_game_model_metadata
    from photon_ml_tpu.telemetry.report import RunReport

    delta_dir = tmp_path / "deltas"
    delta_dir.mkdir()
    spec = _spec(world, tmp_path, delta_dir,
                 escalate_after_cycles=3, serve=True)
    reg = spec.registry_dir
    pipe = FreshnessPipeline(spec)
    try:
        world["write_delta"](
            str(delta_dir / "delta-0001.avro"), [1, 2, _N_USERS] * 8, 201
        )
        e1 = pipe.run_cycle()
        assert e1["idle"] is False
        assert e1["published_version"] == "v-00000001"
        assert e1["escalated"] is False
        assert e1["staleness_p99_s"] >= 0.0
        assert e1["reconciliation"]["rule"] == RECONCILE_RULE
        assert e1["reconciliation"]["nearline_version"] is None

        # unchanged digest -> idle: no read, no fit, no publish
        e2 = pipe.run_cycle()
        assert e2["idle"] is True and e2["published_version"] is None

        world["write_delta"](
            str(delta_dir / "delta-0002.avro"), [3, 4] * 10, 202
        )
        e3 = pipe.run_cycle()
        assert e3["published_version"] == "v-00000002"
        assert e3["escalated"] is False

        world["write_delta"](
            str(delta_dir / "delta-0003.avro"), [5] * 12, 203
        )
        e4 = pipe.run_cycle()
        assert e4["published_version"] == "v-00000003"
        assert e4["escalated"] is True  # 3rd non-idle cycle since full

        # every cycle published a version whose lineage names its
        # ancestor — the chain is auditable from the registry alone
        metas = {
            n: load_game_model_metadata(os.path.join(reg, n))
            for n in ("v-00000001", "v-00000002", "v-00000003")
        }
        lin = {n: m["extra"]["lineage"] for n, m in metas.items()}
        assert "base_version" not in lin["v-00000001"]  # empty registry
        assert lin["v-00000002"]["base_version"] == "v-00000001"
        assert lin["v-00000003"]["base_version"] == "v-00000002"
        for n in metas:
            assert lin[n]["delta_digest"]
            assert lin[n]["reconciliation"]["rule"] == RECONCILE_RULE
        # the recorded digest IS the conductor's cursor: the whole
        # delta-dir glob, so a restart sees nothing new
        paths = sorted(glob.glob(str(delta_dir / "*.avro")))
        assert lin["v-00000003"]["delta_digest"] == (
            incremental.delta_digest(paths)
        )
        assert metas["v-00000003"]["extra"]["pipeline"]["escalated"] is True
        assert metas["v-00000003"]["extra"]["pipeline"]["cycle"] == 4
        assert metas["v-00000002"]["extra"]["pipeline"]["escalated"] is False

        # the escalation re-based the conductor into a fresh generation
        # under ITS workdir — the original base is never written
        s = pipe.summary()
        assert s["base_dir"].startswith(str(tmp_path / "work"))
        assert "base-gen-" in s["base_dir"]
        assert s["cycles"] == 4 and s["idle_cycles"] == 1
        assert s["published_versions"] == [
            "v-00000001", "v-00000002", "v-00000003",
        ]
        assert s["escalations"] == 1
        assert s["event_to_served_staleness_p99_s"] is not None
        assert s["event_to_served_staleness_p99_s"] >= 0.0

        # the live registry hot-swapped to the freshest version
        assert pipe._registry is not None
        assert pipe._registry.current_version == "v-00000003"

        # the run's telemetry renders the Pipeline report section
        report = RunReport.from_live()
        doc = report.pipeline_summary()
        assert doc is not None
        assert doc["cycles"] == 4 and doc["idle_cycles"] == 1
        assert doc["publishes"] == 3 and doc["escalations"] == 1
        assert doc["event_to_served_staleness_p99_s"] >= 0.0
        assert doc["cycle_time_s"]["count"] == 3
        md = report.to_markdown()
        assert "## Pipeline" in md
        assert "staleness p99" in md
    finally:
        pipe._close("completed")

    # crash-restart idempotence: a NEW conductor over the same dirs
    # seeds its digest cursor from the newest published lineage and
    # idles instead of re-publishing the delta it already served
    pipe2 = FreshnessPipeline(spec)
    try:
        assert pipe2.run_cycle()["idle"] is True
    finally:
        pipe2._close("completed")


# ---------------------------------------------------------------------------
# nearline-vs-delta reconciliation, bit-exact
# ---------------------------------------------------------------------------


def test_reconciliation_retrain_wins_touched_bit_exact(world, tmp_path):
    """The conductor's hard case: user "1" is BOTH nearline-updated (a
    per-entity residual solve published as v2) and in the next delta's
    touched set. Retrain-wins-touched: the conductor's v3 carries the
    masked re-solve's row for "1" BIT-EXACTLY (equal to a direct
    fit_incremental over the same inputs), the superseded nearline row
    differs and stays auditable — v2 keeps its nearline metadata and
    v3's lineage names it — untouched users keep their BASE rows
    bit-identically, and the decision round-trips through /healthz."""
    from photon_ml_tpu.cli.train import read_input
    from photon_ml_tpu.config import parse_game_config
    from photon_ml_tpu.data.model_store import (
        load_game_model,
        load_game_model_metadata,
    )
    from photon_ml_tpu.serving.engine import ScoringEngine
    from photon_ml_tpu.serving.nearline import NearlineUpdater
    from photon_ml_tpu.serving.registry import publish_version
    from photon_ml_tpu.serving.server import ScoringService

    reg = str(tmp_path / "registry")
    ws = incremental.load_warm_start(world["ckpt"])
    base_map = _entity_coeffs(ws.model)

    # v1: the base model as served
    publish_version(reg, ws.model, world["imaps"])
    v1 = os.path.join(reg, "v-00000001")

    # v2: the nearline tier re-solves user "1" online and publishes
    engine = ScoringEngine.load(v1, max_batch=8).warmup()
    updater = NearlineUpdater(
        engine, id_name="userId", rows_per_solve=2,
        publish_dir=reg, index_maps=world["imaps"],
    )
    target = "1"
    updater.submit([
        {"ids": {"userId": target},
         "features": {"global": [[0, 1.0], [2, -0.5]]},
         "label": 1.0, "offset": 0.0},
        {"ids": {"userId": target},
         "features": {"global": [[1, 0.7], [3, 0.4]]},
         "label": 0.0, "offset": 0.0},
    ])
    flushed = updater.flush()
    assert flushed["applies"] >= 1
    seq = engine.nearline_seq
    assert seq >= 1
    v2 = updater.publish()
    assert os.path.basename(v2) == "v-00000002"
    v2_map = _entity_coeffs(load_game_model(v2))
    assert v2_map[target] != base_map[target]  # nearline moved the row

    # the delta touches the nearline-updated user "1" plus "5"
    delta_dir = tmp_path / "deltas"
    delta_dir.mkdir()
    delta_path = str(delta_dir / "delta-0001.avro")
    world["write_delta"](delta_path, [1, 5] * 12, 401)

    spec = _spec(world, tmp_path, delta_dir, serve=True)
    pipe = FreshnessPipeline(spec)
    try:
        entry = pipe.run_cycle()
    finally:
        pipe._close("completed")
    assert entry["published_version"] == "v-00000003"
    dec = entry["reconciliation"]
    assert dec["rule"] == RECONCILE_RULE
    assert dec["nearline_version"] == "v-00000002"
    assert dec["nearline_seq"] == seq
    assert dec["nearline_base_version"] == "v-00000001"
    assert dec["touched_count"] == 2

    # the winner's row, bit for bit: a direct masked re-solve over the
    # exact same base checkpoint + delta must reproduce v3's row for the
    # contested user (same readers, same estimator, same inputs)
    cfg = world["config"]
    delta_data, _ = read_input({**cfg["input"], "paths": [delta_path]})
    scan = incremental.scan_delta(
        delta_data, {"userId": ws.model.models["perUser"].vocab},
        paths=[delta_path],
    )
    comb_data, _ = read_input(
        {**cfg["input"], "paths": [world["train_path"], delta_path]}
    )
    ref = GameEstimator(parse_game_config(cfg)).fit_incremental(
        comb_data, ws, delta=scan
    )
    ref_map = _entity_coeffs(ref.model)
    v3_path = os.path.join(reg, "v-00000003")
    v3_map = _entity_coeffs(load_game_model(v3_path))
    assert v3_map[target] == ref_map[target]  # retrain won, EXACTLY
    assert v3_map[target] != v2_map[target]   # nearline row superseded
    # untouched users keep their BASE rows (not the nearline version's):
    # the masked fit warm-starts from the base checkpoint
    untouched = [v for v in base_map if v not in (target, "5")]
    assert untouched
    for val in untouched:
        assert v3_map[val] == base_map[val], val

    # the loser stays auditable: v2 keeps its nearline metadata, v3's
    # lineage names the superseded version + sequence
    meta2 = load_game_model_metadata(v2)
    assert meta2["extra"]["nearline_seq"] == seq
    assert meta2["extra"]["nearline_base_version"] == "v-00000001"
    lin3 = load_game_model_metadata(v3_path)["extra"]["lineage"]
    assert lin3["reconciliation"] == dec
    assert lin3["base_version"] == "v-00000002"

    # ... and round-trips through /healthz off the served version
    health = ScoringService(ScoringEngine.load(v3_path)).health()
    assert health["model_version"] == "v-00000003"
    assert health["lineage"]["reconciliation"]["nearline_version"] == (
        "v-00000002"
    )
    assert health["lineage"]["base_version"] == "v-00000002"


# ---------------------------------------------------------------------------
# /statusz + the daemon loop
# ---------------------------------------------------------------------------


def test_run_loop_writes_statusz_and_summary(world, tmp_path):
    """run() under max_cycles: the conductor is a 1-member fleet whose
    status document carries the cycle counters, publish/escalation
    counts, and staleness p99 — and lands outcome=completed on close."""
    delta_dir = tmp_path / "deltas"
    delta_dir.mkdir()
    world["write_delta"](str(delta_dir / "delta-0001.avro"), [1, 2] * 9, 301)
    status_file = str(tmp_path / "status.json")
    spec = _spec(world, tmp_path, delta_dir, max_cycles=2,
                 serve=False, status_file=status_file)
    summary = FreshnessPipeline(spec).run()
    assert summary["cycles"] == 2 and summary["idle_cycles"] == 1
    assert summary["published_versions"] == ["v-00000001"]
    assert summary["interrupted"] is False
    assert summary["event_to_served_staleness_p99_s"] >= 0.0

    with open(status_file, encoding="utf-8") as fh:
        doc = json.load(fh)
    assert doc["type"] == "fleet_status"
    assert doc["outcome"] == "completed"
    assert doc["generation"] == 2  # generation doubles as cycle count
    member = doc["members"]["0"]
    assert member["pipeline"]["publishes"] == 1
    assert member["pipeline"]["idle_cycles"] == 1
    assert member["pipeline"]["escalations"] == 0
    assert member["pipeline"]["staleness_p99_s"] >= 0.0
    assert member["pipeline"]["served_version"] is None  # serve=False
    assert member["pipeline"]["base_dir"] == world["ckpt"]


def test_request_stop_interrupts_cleanly(world, tmp_path):
    """A stop request before the loop starts exits with the interrupted
    outcome and zero cycles — the SIGTERM path minus the signal."""
    delta_dir = tmp_path / "deltas"
    delta_dir.mkdir()
    pipe = FreshnessPipeline(_spec(world, tmp_path, delta_dir, serve=False))
    pipe.request_stop()
    summary = pipe.run()
    assert summary["interrupted"] is True
    assert summary["cycles"] == 0 and summary["published_versions"] == []


# ---------------------------------------------------------------------------
# fault seams: typed in-process; hard kills via tools/chaos.py --pipeline
# ---------------------------------------------------------------------------


def test_pipeline_points_enumeration_is_stable():
    """The seam set tools/chaos.py --pipeline matrixes over is part of
    the contract: a new conductor seam must be added HERE (and thereby
    to the matrix and lint L016) to land."""
    import photon_ml_tpu.pipeline  # noqa: F401 (registers points)
    from tools import chaos

    assert list(chaos.PIPELINE_POINTS) == [
        "pipeline.cycle_start",
        "pipeline.reconcile",
        "pipeline.escalate",
    ]
    assert set(chaos.PIPELINE_POINTS) <= set(faults.registered_points())


def test_pipeline_seams_fire_typed(world, tmp_path):
    """Each pipeline.* seam raises the typed InjectedFault from inside
    run_cycle, and a cycle aborted at ANY seam leaves the registry
    without a published version (the publish never started)."""
    delta_dir = tmp_path / "deltas"
    delta_dir.mkdir()
    world["write_delta"](str(delta_dir / "delta-0001.avro"), [1, 2] * 9, 501)
    rows = (
        ("pipeline.cycle_start", {}),
        ("pipeline.reconcile", {}),
        # the escalate seam only fires when escalation actually trips
        ("pipeline.escalate", {"escalate_after_cycles": 1}),
    )
    for point, kw in rows:
        sub = tmp_path / point.replace(".", "_")
        sub.mkdir()
        spec = _spec(world, sub, delta_dir, serve=False, **kw)
        pipe = FreshnessPipeline(spec)
        install_plan(FaultPlan([FaultRule(point, action="raise")]))
        try:
            with pytest.raises(InjectedFault):
                pipe.run_cycle()
        finally:
            clear_plan()
            pipe._close("failed")
        reg = spec.registry_dir
        assert not os.path.isdir(reg) or not any(
            n.startswith("v-") for n in os.listdir(reg)
        ), point


@pytest.mark.chaos
def test_pipeline_crash_row_tier1(tmp_path):
    """Budget-capped tier-1 slice of the pipeline crash matrix: the
    cli pipeline daemon hard-killed (os._exit 113) at the top of a
    cycle leaves the base checkpoint byte-identical and the registry
    partial-free, and the unarmed rerun over the same directories
    publishes a lineage-linked version. The full 3-seam matrix runs
    under --slow / `python -m tools.chaos --pipeline`."""
    from tools import chaos

    budget = float(os.environ.get("PHOTON_CHAOS_BUDGET_S", "300"))
    report = chaos.run_pipeline_matrix(
        str(tmp_path), points=["pipeline.cycle_start"], budget_s=budget
    )
    if report["skipped"]:
        warnings.warn(
            "chaos budget truncated the pipeline matrix; uncovered this "
            f"run: {report['skipped']} (full matrix: python -m "
            "tools.chaos --pipeline)",
            stacklevel=1,
        )
        return
    assert report["ok"], json.dumps(report, indent=2)
    entry = report["results"]["pipeline.cycle_start"]
    assert entry["armed_rc"] == faults.DEFAULT_EXIT_CODE
    assert entry["published_versions"]
    assert entry["registry_after_resume"]


@pytest.mark.chaos
@pytest.mark.slow
def test_pipeline_crash_matrix_every_seam_recovers(tmp_path):
    """The full pipeline crash matrix: for EVERY pipeline.* seam, a
    daemon hard-killed at the seam leaves the base byte-identical and
    the registry partial-free, and the rerun publishes."""
    from tools import chaos

    budget = float(os.environ.get("PHOTON_CHAOS_BUDGET_S", "600"))
    report = chaos.run_pipeline_matrix(str(tmp_path), budget_s=budget)
    assert report["ok"], json.dumps(report, indent=2)
    covered = [p for p, e in report["results"].items() if e.get("passed")]
    assert covered, "the budget covered no pipeline point at all"
    for entry in report["results"].values():
        assert entry["armed_rc"] == faults.DEFAULT_EXIT_CODE
        assert entry["published_versions"]
    if report["skipped"]:
        warnings.warn(
            "chaos budget truncated the pipeline matrix; uncovered this "
            f"run: {report['skipped']}",
            stacklevel=1,
        )
