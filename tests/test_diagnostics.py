"""Diagnostics: bootstrap CI coverage on a known model, evaluation metrics
vs closed forms, fitting curves improve with data, H-L calibration
detection, feature importance ranking, Kendall-tau independence, report
rendering."""

import numpy as np
import pytest

from photon_ml_tpu.data.stats import summarize
from photon_ml_tpu.diagnostics import (
    bootstrap_train,
    diagnose_model,
    evaluate,
    expected_magnitude_importance,
    fitting_diagnostic,
    hosmer_lemeshow,
    kendall_tau_analysis,
    prediction_error_independence,
    render_html,
    render_text,
    variance_importance,
)
from photon_ml_tpu.diagnostics.evaluation import (
    AKAIKE_INFORMATION_CRITERION,
    AREA_UNDER_PRECISION_RECALL,
    AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS,
    DATA_LOG_LIKELIHOOD,
    ROOT_MEAN_SQUARE_ERROR,
)
from photon_ml_tpu.models.glm import make_model
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)


def _logistic(rng, n=500, d=6, w=None):
    X = rng.normal(size=(n, d))
    X[:, 0] = 1.0
    w = rng.normal(size=d) if w is None else w
    p = 1 / (1 + np.exp(-(X @ w)))
    y = (rng.random(n) < p).astype(float)
    return X, y, w, SparseBatch.from_dense(X, y)


def _cfg(lam=1.0):
    return OptimizerConfig(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=lam,
    )


# -- evaluation -------------------------------------------------------------


def test_evaluate_logistic_metrics(rng):
    X, y, w, batch = _logistic(rng)
    model = make_model("logistic", np.asarray(w, np.float32))
    m = evaluate(model, batch)
    assert 0.5 < m[AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] <= 1.0
    assert 0.0 < m[AREA_UNDER_PRECISION_RECALL] <= 1.0
    assert m[DATA_LOG_LIKELIHOOD] < 0.0
    # closed-form log likelihood
    p = np.clip(1 / (1 + np.exp(-(X @ w))), 1e-9, 1 - 1e-9)
    ll = np.mean(y * np.log(p) + (1 - y) * np.log1p(-p))
    assert m[DATA_LOG_LIKELIHOOD] == pytest.approx(ll, rel=1e-3)
    # AIC = 2(k - n*ll) + correction
    k = int(np.sum(np.abs(w) > 1e-9))
    n = len(y)
    base = 2 * (k - n * ll)
    assert m[AKAIKE_INFORMATION_CRITERION] == pytest.approx(
        base + 2 * k * (k + 1) / (n - k - 1), rel=1e-3
    )


def test_evaluate_regression_metrics(rng):
    n, d = 300, 5
    X = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = X @ w + 0.1 * rng.normal(size=n)
    batch = SparseBatch.from_dense(X, y)
    model = make_model("squared", np.asarray(w, np.float32))
    m = evaluate(model, batch)
    resid = y - X @ w
    assert m[ROOT_MEAN_SQUARE_ERROR] == pytest.approx(
        np.sqrt(np.mean(resid**2)), rel=1e-3
    )


def test_peak_f1_perfect_classifier(rng):
    X, y, w, batch = _logistic(rng, n=200)
    # scores equal to labels -> a threshold separates perfectly -> F1 = 1
    from photon_ml_tpu.diagnostics import peak_f1
    import jax.numpy as jnp

    assert float(
        peak_f1(jnp.asarray(y, jnp.float32), batch.labels, batch.weights)
    ) == pytest.approx(1.0, abs=1e-5)


# -- bootstrap --------------------------------------------------------------


@pytest.mark.slow
def test_bootstrap_ci_covers_true_coefficients(rng):
    X, y, w_true, batch = _logistic(rng, n=1500, d=5)
    report = bootstrap_train(
        batch, "logistic", _cfg(lam=1e-3), num_samples=16, seed=1
    )
    assert len(report.coefficient_summaries) == 5
    covered = sum(
        s.min <= wt <= s.max
        for s, wt in zip(report.coefficient_summaries, w_true)
    )
    assert covered >= 4  # bootstrap min..max range covers the truth
    # metric distributions exist and AUC samples are sane
    auc_sum = report.metric_summaries[
        AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS
    ]
    assert auc_sum.count == 16
    assert 0.5 < auc_sum.mean <= 1.0
    # strong true coefficients are flagged significant
    strong = np.nonzero(np.abs(w_true) > 1.0)[0]
    sig = set(report.significant_coefficients().tolist())
    assert set(strong.tolist()) <= sig


def test_bootstrap_validates_args(rng):
    _, _, _, batch = _logistic(rng, n=50)
    with pytest.raises(ValueError):
        bootstrap_train(batch, "logistic", _cfg(), num_samples=1)
    with pytest.raises(ValueError):
        bootstrap_train(batch, "logistic", _cfg(), train_portion=0.0)


# -- fitting ----------------------------------------------------------------


@pytest.mark.slow
def test_fitting_diagnostic_holdout_improves_with_data(rng):
    X, y, w, batch = _logistic(rng, n=1200, d=8)
    report = fitting_diagnostic(
        batch, "logistic", _cfg(lam=1e-2), lambdas=[1e-2], seed=2
    )
    assert len(report.portions) == 9
    assert report.portions == sorted(report.portions)
    curve = report.test_metrics[1e-2][AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS]
    # holdout AUC with 90% of data beats AUC with 10%
    assert curve[-1] >= curve[0] - 0.02
    assert report.fitting_msg()  # non-empty summary


# -- hosmer-lemeshow --------------------------------------------------------


def test_hl_calibrated_vs_miscalibrated(rng):
    n = 4000
    p = rng.uniform(0.05, 0.95, n)
    y_cal = (rng.random(n) < p).astype(float)
    # mean_prob expectation (classical H-L): calibrated data passes
    good = hosmer_lemeshow(p, y_cal, expected="mean_prob")
    assert good.p_value > 0.01
    # miscalibrated: predictions systematically overconfident
    p_bad = np.clip(p**3, 1e-3, 1 - 1e-3)
    bad = hosmer_lemeshow(p_bad, y_cal, expected="mean_prob")
    assert bad.chi_square > 10 * good.chi_square
    assert bad.p_value < 1e-6
    assert bad.degrees_of_freedom == 8
    assert len(bad.cutoffs) == 15
    assert "chi^2" in bad.to_summary_string()
    # reference-parity midpoint mode still separates good from bad
    good_mid = hosmer_lemeshow(p, y_cal)  # default expected="midpoint"
    bad_mid = hosmer_lemeshow(p_bad, y_cal)
    assert bad_mid.chi_square > good_mid.chi_square


# -- feature importance -----------------------------------------------------


def test_feature_importance_rankings(rng):
    d = 6
    w = np.zeros(d, np.float32)
    w[2] = 5.0
    w[4] = -0.1
    X = rng.normal(size=(400, d))
    X[:, 4] *= 100.0  # huge variance feature
    batch = SparseBatch.from_dense(X, np.zeros(400))
    summary = summarize(batch)
    model = make_model("squared", w)
    names = [f"f{j}" for j in range(d)]

    em = expected_magnitude_importance(model, summary, names)
    assert em.ranked[0][0] in ("f2", "f4")  # both large contributions
    vi = variance_importance(model, summary, names)
    # variance importance weights the 100x-variance column heavily:
    # |w4 * var4| = 0.1 * 1e4 ~ 1e3 vs |w2 * var2| ~ 5
    assert vi.ranked[0][0] == "f4"
    # without a summary both collapse to |coef|
    em0 = expected_magnitude_importance(model, None, names)
    assert em0.ranked[0][0] == "f2"
    assert "f2" in em0.to_summary_string(3)


# -- independence -----------------------------------------------------------


def test_kendall_tau_independent_vs_dependent(rng):
    n = 300
    a = rng.normal(size=n)
    ind = kendall_tau_analysis(a, rng.normal(size=n))
    dep = kendall_tau_analysis(a, a + 0.1 * rng.normal(size=n))
    assert ind.p_value > 0.01
    assert dep.p_value < 1e-10
    assert dep.tau_alpha > 0.8
    # tau vs scipy reference
    from scipy.stats import kendalltau

    ref = kendalltau(a, a + 0.1 * rng.normal(size=n))
    assert abs(dep.tau_beta - ref.statistic) < 0.1


def test_prediction_error_independence_subsamples(rng):
    n = 5000
    pred = rng.normal(size=n)
    rep = prediction_error_independence(pred, pred * 0.5, max_samples=500)
    assert rep.num_samples == 500
    assert "subsampled" in rep.message


# -- reports ----------------------------------------------------------------


@pytest.mark.slow
def test_diagnose_model_renders_html_and_text(rng):
    X, y, w, batch = _logistic(rng, n=400)
    model = make_model("logistic", np.asarray(w, np.float32))
    doc = diagnose_model(
        model, batch, summary=summarize(batch),
        feature_names=[f"f{j}" for j in range(X.shape[1])],
    )
    txt = render_text(doc)
    assert "Model diagnostics" in txt
    assert "Hosmer-Lemeshow" in txt
    assert "Kendall tau" in txt
    html = render_html(doc)
    assert html.startswith("<!DOCTYPE html>")
    assert "<table>" in html
    assert "Feature importance" in html


def test_report_line_plot_svg():
    from photon_ml_tpu.diagnostics import (
        Chapter,
        Document,
        LinePlot,
        Section,
    )

    doc = Document(
        "curves",
        [
            Chapter(
                "c",
                [
                    Section(
                        "s",
                        [
                            LinePlot(
                                x=[0.1, 0.5, 0.9],
                                series={"train": [1, 2, 3], "test": [1, 1.5, 2]},
                                title="learning curve",
                                x_label="portion",
                                y_label="auc",
                            )
                        ],
                    )
                ],
            )
        ],
    )
    html = render_html(doc)
    assert "<svg" in html and "polyline" in html
    txt = render_text(doc)
    assert "[plot] learning curve" in txt


@pytest.mark.slow
def test_fitting_report_sections_render(rng):
    X, y, w, batch = _logistic(rng, n=600, d=5)
    from photon_ml_tpu.diagnostics.fitting import fitting_report_sections  # noqa
    from photon_ml_tpu.diagnostics import Chapter, Document, render_html

    report = fitting_diagnostic(
        batch, "logistic", _cfg(lam=1e-2), lambdas=[1e-2], seed=3,
        num_partitions=5,
    )
    sections = fitting_report_sections(report)
    html = render_html(Document("fit", [Chapter("learning", sections)]))
    assert "polyline" in html and "Area under ROC" in html


def test_evaluate_counts_offsets_exactly_once(rng):
    """Regression: evaluate() must use margins = Xw + offset (once) — the
    GAME residual-offset case that previously double-counted."""
    import dataclasses as _dc
    import jax.numpy as jnp

    X, y, w, batch = _logistic(rng, n=300)
    offs = rng.normal(size=300)
    batch_o = _dc.replace(batch, offsets=jnp.asarray(
        np.pad(offs, (0, batch.num_rows - 300)), jnp.float32))
    model = make_model("logistic", np.asarray(w, np.float32))
    m = evaluate(model, batch_o)
    p = np.clip(1 / (1 + np.exp(-(X @ w + offs))), 1e-9, 1 - 1e-9)
    ll = np.mean(y * np.log(p) + (1 - y) * np.log1p(-p))
    assert m[DATA_LOG_LIKELIHOOD] == pytest.approx(ll, rel=1e-3)
