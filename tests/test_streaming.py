"""Dense local-design batch + streaming billion-coefficient trainer:
DenseBatch solves match SparseBatch solves; the streaming trainer matches
direct per-entity fits; the sharded table path matches single-device."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game.streaming import (
    ShardedCoefficientTable,
    StreamingRandomEffectTrainer,
)
from photon_ml_tpu.ops.dense import DenseBatch
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    glm_adapter,
    lbfgs_solve,
    solve,
)

_CFG = OptimizerConfig(
    max_iterations=60,
    tolerance=1e-9,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=0.3,
)


def _problem(rng, n=200, d=12):
    X = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(float)
    off = rng.normal(size=n) * 0.1
    wgt = rng.random(n) + 0.5
    return X, y, off, wgt


def test_dense_batch_matches_sparse_objective(rng):
    X, y, off, wgt = _problem(rng)
    db = DenseBatch.from_arrays(X, y, offsets=off, weights=wgt)
    sb = SparseBatch.from_dense(X, y, offsets=off, weights=wgt)
    obj = make_objective("logistic", l2_weight=0.3)
    w = jnp.asarray(rng.normal(size=X.shape[1]), jnp.float32)
    v = jnp.asarray(rng.normal(size=X.shape[1]), jnp.float32)

    vd, gd = obj.value_and_grad(w, db)
    vs, gs = obj.value_and_grad(w, sb)
    np.testing.assert_allclose(float(vd), float(vs), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gd), np.asarray(gs), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(obj.hessian_vector(w, v, db)),
        np.asarray(obj.hessian_vector(w, v, sb)),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(obj.hessian_diagonal(w, db)),
        np.asarray(obj.hessian_diagonal(w, sb)),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(obj.margins(w, db)), np.asarray(obj.margins(w, sb)),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON,
                                 OptimizerType.NEWTON])
def test_dense_batch_solves_match_sparse(rng, opt):
    X, y, off, wgt = _problem(rng)
    db = DenseBatch.from_arrays(X, y, offsets=off, weights=wgt)
    sb = SparseBatch.from_dense(X, y, offsets=off, weights=wgt)
    cfg = dataclasses.replace(_CFG, optimizer_type=opt)
    w0 = jnp.zeros(X.shape[1], jnp.float32)
    rd = solve("logistic", db, cfg, w0)
    rs = solve("logistic", sb, cfg, w0)
    np.testing.assert_allclose(np.asarray(rd.w), np.asarray(rs.w),
                               rtol=1e-3, atol=1e-4)


def _chunked_entities(rng, n_ent=24, rows=10, k=6):
    """Per-entity logistic problems as stacked dense chunks + flat list."""
    X = rng.normal(size=(n_ent, rows, k))
    W = rng.normal(size=(n_ent, k))
    z = np.einsum("erk,ek->er", X, W)
    y = (rng.random((n_ent, rows)) < 1 / (1 + np.exp(-z))).astype(float)
    return X, y


@pytest.mark.slow
def test_streaming_trainer_matches_direct_solves(rng):
    X, y = _chunked_entities(rng)
    n_ent, rows, k = X.shape
    table = ShardedCoefficientTable(n_ent, k)
    trainer = StreamingRandomEffectTrainer("logistic", _CFG)

    def host_chunk(lo, hi):
        return DenseBatch(
            x=X[lo:hi].astype(np.float32),
            labels=y[lo:hi].astype(np.float32),
            offsets=np.zeros((hi - lo, rows), np.float32),
            weights=np.ones((hi - lo, rows), np.float32),
        )

    chunks = [(0, host_chunk(0, 8)), (8, host_chunk(8, 16)),
              (16, lambda: jax.tree.map(jnp.asarray, host_chunk(16, 24)))]
    stats = trainer.train(table, chunks)
    assert stats.total_entities == n_ent
    assert stats.total_coefficients == n_ent * k
    assert stats.num_chunks == 3
    assert stats.mean_iterations > 0

    got = table.to_numpy()
    obj = make_objective("logistic", l2_weight=0.3)
    for e in range(0, n_ent, 5):
        ref = lbfgs_solve(
            glm_adapter(obj, DenseBatch.from_arrays(X[e], y[e])),
            jnp.zeros(k, jnp.float32),
        )
        np.testing.assert_allclose(got[e], np.asarray(ref.w), rtol=5e-3,
                                   atol=5e-4)


def test_streaming_warm_start_reuses_table(rng):
    """A second train() pass warm-starts from the resident table: with the
    same data the solves converge immediately."""
    X, y = _chunked_entities(rng, n_ent=8)
    n_ent, rows, k = X.shape
    table = ShardedCoefficientTable(n_ent, k)
    trainer = StreamingRandomEffectTrainer("logistic", _CFG)
    chunk = DenseBatch(
        x=X.astype(np.float32), labels=y.astype(np.float32),
        offsets=np.zeros((n_ent, rows), np.float32),
        weights=np.ones((n_ent, rows), np.float32),
    )
    s1 = trainer.train(table, [(0, chunk)])
    w1 = table.to_numpy()
    s2 = trainer.train(table, [(0, chunk)])
    assert s2.mean_iterations <= max(s1.mean_iterations * 0.25, 1.5)
    # the warm-started re-solve may take one tiny polish step
    np.testing.assert_allclose(table.to_numpy(), w1, rtol=1e-3, atol=2e-4)


@pytest.mark.slow
def test_sharded_table_matches_single_device(rng):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from photon_ml_tpu.parallel import make_mesh

    mesh = make_mesh({"entity": 8})
    X, y = _chunked_entities(rng, n_ent=32, rows=8, k=5)
    n_ent, rows, k = X.shape

    def run(mesh_arg):
        table = ShardedCoefficientTable(n_ent, k, mesh=mesh_arg)
        trainer = StreamingRandomEffectTrainer("logistic", _CFG,
                                               mesh=mesh_arg)
        chunk = DenseBatch(
            x=X.astype(np.float32), labels=y.astype(np.float32),
            offsets=np.zeros((n_ent, rows), np.float32),
            weights=np.ones((n_ent, rows), np.float32),
        )
        trainer.train(table, [(0, chunk)])
        return table

    t_single = run(None)
    t_mesh = run(mesh)
    assert t_mesh.sharding is not None
    # per-device residency: table bytes / 8
    shard_bytes = {
        s.data.nbytes for s in t_mesh.coefficients.addressable_shards
    }
    assert shard_bytes == {t_mesh.nbytes // 8}
    np.testing.assert_allclose(
        t_mesh.to_numpy(), t_single.to_numpy(), rtol=2e-3, atol=2e-4
    )


def test_sharded_table_rejects_misaligned_entities(rng):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from photon_ml_tpu.parallel import make_mesh

    with pytest.raises(ValueError, match="divide"):
        ShardedCoefficientTable(30, 4, mesh=make_mesh({"entity": 8}))


def _stream_train(rng, cfg, n_ent=12, rows=8, k=4, **trainer_kw):
    """Train one streamed table over 2 chunks; returns (table, stats, X, y,
    extra tables passed through trainer_kw['train_kw'])."""
    X, y = _chunked_entities(rng, n_ent=n_ent, rows=rows, k=k)
    train_kw = trainer_kw.pop("train_kw", {})
    table = ShardedCoefficientTable(n_ent, k)
    trainer = StreamingRandomEffectTrainer("logistic", cfg, **trainer_kw)
    half = n_ent // 2

    def chunk(lo, hi):
        return DenseBatch(
            x=X[lo:hi].astype(np.float32),
            labels=y[lo:hi].astype(np.float32),
            offsets=np.zeros((hi - lo, rows), np.float32),
            weights=np.ones((hi - lo, rows), np.float32),
        )

    stats = trainer.train(
        table, [(0, chunk(0, half)), (half, chunk(half, n_ent))], **train_kw
    )
    return table, stats, X, y


def test_streaming_box_constraints_match_bucket_semantics(rng):
    """The streaming path honors config.box_constraints: solves project
    into the same hypercube the per-entity bucket path enforces."""
    cfg = dataclasses.replace(
        _CFG,
        max_iterations=100,
        box_constraints=((0, -0.05, 0.05), (2, 0.0, float("inf"))),
    )
    table, stats, X, y = _stream_train(rng, cfg)
    got = table.to_numpy()
    assert np.all(got[:, 0] >= -0.05 - 1e-6) and np.all(got[:, 0] <= 0.05 + 1e-6)
    assert np.all(got[:, 2] >= -1e-6)
    # reference: direct constrained solve per entity
    from photon_ml_tpu.optim.common import BoxConstraints

    obj = make_objective("logistic", l2_weight=0.3)
    lower, upper = cfg.dense_box_bounds(X.shape[2])
    cons = BoxConstraints(lower=jnp.asarray(lower), upper=jnp.asarray(upper))
    for e in (0, 5, 11):
        from photon_ml_tpu.optim.lbfgs import LBFGSConfig

        ref = lbfgs_solve(
            glm_adapter(obj, DenseBatch.from_arrays(X[e], y[e])),
            jnp.zeros(X.shape[2], jnp.float32),
            config=LBFGSConfig(max_iterations=100, tolerance=1e-9),
            constraints=cons,
        )
        # projected LBFGS converges slowly along active faces, so exact
        # coefficient agreement is not expected at a finite budget; parity
        # = a feasible point at least as good (within 1%) as the direct
        # constrained solve's
        adapter = glm_adapter(obj, DenseBatch.from_arrays(X[e], y[e]))
        v_stream = float(adapter.value_and_grad(jnp.asarray(got[e]))[0])
        v_ref = float(ref.value)
        assert v_stream <= v_ref * 1.01 + 1e-6, (v_stream, v_ref)


def test_streaming_unconstrained_config_trains_free(rng):
    """No silent constraint drop the other way: an unconstrained config
    must NOT produce clipped coefficients (regression guard for the old
    silently-ignored-constraints bug)."""
    table, stats, X, y = _stream_train(rng, _CFG)
    got = table.to_numpy()
    assert np.any(np.abs(got) > 0.05)  # free fit reaches past the tiny box


def test_streaming_variances_match_bucket_path(rng):
    """compute_variances writes Hessian-diagonal-inverse variances into the
    variance table, matching the per-entity formula the bucket path uses
    (SingleNodeOptimizationProblem.scala:57-88)."""
    n_ent, k = 12, 4
    var_table = ShardedCoefficientTable(n_ent, k)
    table, stats, X, y = _stream_train(
        rng, _CFG, n_ent=n_ent, k=k,
        compute_variances=True,
        train_kw=dict(variance_table=var_table),
    )
    got_w = table.to_numpy()
    got_v = var_table.to_numpy()
    obj = make_objective("logistic", l2_weight=0.3)
    for e in (0, 7):
        hd = obj.hessian_diagonal(
            jnp.asarray(got_w[e]), DenseBatch.from_arrays(X[e], y[e])
        )
        np.testing.assert_allclose(
            got_v[e], 1.0 / (np.asarray(hd) + 1e-12), rtol=1e-4
        )


def test_streaming_variances_require_table_and_hessian():
    with pytest.raises(ValueError, match="twice-differentiable"):
        StreamingRandomEffectTrainer(
            "smoothed_hinge", _CFG, compute_variances=True
        )
    tr = StreamingRandomEffectTrainer("logistic", _CFG,
                                      compute_variances=True)
    table = ShardedCoefficientTable(4, 3)
    with pytest.raises(ValueError, match="variance_table"):
        tr.train(table, [])


def test_streaming_tracker_reports_per_entity_telemetry(rng):
    table, stats, X, y = _stream_train(
        rng, _CFG, train_kw=dict(with_tracker=True)
    )
    t = stats.tracker
    assert t is not None
    assert len(t.iterations) == stats.total_entities
    assert len(t.reasons) == stats.total_entities
    assert np.all(t.iterations > 0)
    assert np.isfinite(t.final_values).all()
    assert "iterations" in t.to_summary_string()


def test_streaming_guard_rolls_back_nan_chunk(rng):
    """A NaN-poisoned chunk rolls back (its table rows keep their pre-solve
    coefficients) while healthy chunks train; the divergence is counted and
    the run summary stays finite."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.optim.guard import GuardSpec

    X, y = _chunked_entities(rng, n_ent=8, rows=6, k=3)
    n_ent, rows, k = X.shape
    Xbad = X[:4].astype(np.float32).copy()
    Xbad[1, 2, 0] = np.nan

    def chunk(x, yy):
        return DenseBatch(
            x=x.astype(np.float32), labels=yy.astype(np.float32),
            offsets=np.zeros(yy.shape, np.float32),
            weights=np.ones(yy.shape, np.float32),
        )

    telemetry.reset()
    try:
        table = ShardedCoefficientTable(n_ent, k)
        trainer = StreamingRandomEffectTrainer(
            "logistic", _CFG, guard=GuardSpec(max_retries=1)
        )
        stats = trainer.train(
            table, [(0, chunk(Xbad, y[:4])), (4, chunk(X[4:], y[4:]))]
        )
        got = table.to_numpy()
        np.testing.assert_array_equal(got[:4], 0.0)  # rolled back
        assert np.any(np.abs(got[4:]) > 0)  # healthy chunk trained
        assert np.isfinite(stats.total_final_value)
        counters = telemetry.snapshot()["counters"]
        assert counters["solves.rolled_back"] == 1
        assert counters["solves.retried"] == 1
    finally:
        telemetry.reset()


def test_streaming_feed_retry_survives_transient_failures(rng):
    """host->device chunk feeding retries up to feed_retries times before
    surfacing; a source that fails twice then succeeds still trains."""
    from photon_ml_tpu import telemetry

    X, y = _chunked_entities(rng, n_ent=4, rows=6, k=3)
    n_ent, rows, k = X.shape
    chunk = DenseBatch(
        x=X.astype(np.float32), labels=y.astype(np.float32),
        offsets=np.zeros((n_ent, rows), np.float32),
        weights=np.ones((n_ent, rows), np.float32),
    )
    attempts = [0]

    def flaky_source():
        attempts[0] += 1
        if attempts[0] < 3:
            raise OSError("transient read failure")
        return jax.tree.map(jnp.asarray, chunk)

    telemetry.reset()
    try:
        table = ShardedCoefficientTable(n_ent, k)
        trainer = StreamingRandomEffectTrainer(
            "logistic", _CFG, feed_retries=2
        )
        stats = trainer.train(table, [(0, flaky_source)])
        assert stats.total_entities == n_ent
        assert telemetry.snapshot()["counters"]["streaming.feed_retries"] == 2
        assert np.any(np.abs(table.to_numpy()) > 0)

        # retries are bounded: a source that keeps failing surfaces
        trainer2 = StreamingRandomEffectTrainer(
            "logistic", _CFG, feed_retries=1
        )

        def always_fails():
            raise OSError("dead source")

        with pytest.raises(OSError, match="dead source"):
            trainer2.train(ShardedCoefficientTable(n_ent, k),
                           [(0, always_fails)])
    finally:
        telemetry.reset()


def test_streaming_prefetch_arms_match(rng):
    """prefetch=True (one-chunk-ahead enqueue) and the synchronous control
    arm produce identical tables — the overlap is pure scheduling."""
    X, y = _chunked_entities(rng, n_ent=12, rows=6, k=3)
    n_ent, rows, k = X.shape

    def run(prefetch):
        table = ShardedCoefficientTable(n_ent, k)
        tr = StreamingRandomEffectTrainer("logistic", _CFG,
                                          prefetch=prefetch)
        half = n_ent // 2

        def chunk(lo, hi):
            return DenseBatch(
                x=X[lo:hi].astype(np.float32),
                labels=y[lo:hi].astype(np.float32),
                offsets=np.zeros((hi - lo, rows), np.float32),
                weights=np.ones((hi - lo, rows), np.float32),
            )

        tr.train(table, [(0, chunk(0, half)), (half, chunk(half, n_ent))])
        return table.to_numpy()

    np.testing.assert_allclose(run(True), run(False), atol=1e-7)


# ---------------------------------------------------------------------------
# chunk-boundary checkpointing + graceful preemption (ISSUE 9)
# ---------------------------------------------------------------------------


def _stream_fit_chunks(rng, n_ent=16, rows=8, k=4, n_chunks=4):
    X, y = _chunked_entities(rng, n_ent=n_ent, rows=rows, k=k)
    per = n_ent // n_chunks

    def host_chunk(lo, hi):
        return DenseBatch(
            x=X[lo:hi].astype(np.float32),
            labels=y[lo:hi].astype(np.float32),
            offsets=np.zeros((hi - lo, rows), np.float32),
            weights=np.ones((hi - lo, rows), np.float32),
        )

    return [
        (i * per, host_chunk(i * per, (i + 1) * per))
        for i in range(n_chunks)
    ], (n_ent, k)


def test_streaming_checkpoint_roundtrip_and_resume(rng, tmp_path):
    """A streamed fit checkpoints at chunk boundaries; a resumed fit
    (restore table + start_chunk) reproduces the uninterrupted result
    exactly — the deterministic chunk order replays the same stream."""
    from photon_ml_tpu.game.checkpoint import (
        CheckpointSpec,
        StreamingCheckpointManager,
    )

    chunks, (n_ent, k) = _stream_fit_chunks(rng)
    trainer = StreamingRandomEffectTrainer("logistic", _CFG)

    # uninterrupted reference
    ref = ShardedCoefficientTable(n_ent, k)
    trainer.train(ref, chunks)
    expected = ref.to_numpy()

    # first run: solve only the first two chunks, checkpoint each
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path / "ckpt"), every=1)
    )
    table = ShardedCoefficientTable(n_ent, k)
    trainer.train(table, chunks[:2], checkpointer=mgr)
    state = mgr.restore()
    assert state is not None and state.next_chunk == 2

    # resume into a FRESH process-analog: new table seeded from the
    # checkpoint, stream replayed from the next chunk boundary
    table2 = ShardedCoefficientTable(n_ent, k)
    table2.write_chunk(0, jnp.asarray(state.coefficients))
    trainer.train(
        table2, chunks, checkpointer=mgr, start_chunk=state.next_chunk
    )
    np.testing.assert_array_equal(table2.to_numpy(), expected)


def test_streaming_sigterm_checkpoints_and_resume_replays(rng, tmp_path):
    """SIGTERM mid-stream: the trainer finishes the in-flight chunk,
    writes a final checkpoint, raises TrainingInterrupted; the resumed
    run replays from the next chunk boundary and matches the
    uninterrupted fit exactly."""
    import signal

    from photon_ml_tpu.game.checkpoint import (
        CheckpointSpec,
        GracefulStop,
        StreamingCheckpointManager,
        TrainingInterrupted,
    )

    chunks, (n_ent, k) = _stream_fit_chunks(rng)
    trainer = StreamingRandomEffectTrainer("logistic", _CFG,
                                           prefetch=False)
    ref = ShardedCoefficientTable(n_ent, k)
    trainer.train(ref, chunks)
    expected = ref.to_numpy()

    # chunk 1's source raises SIGTERM while "decoding" — the preemption
    # arrives mid-stream, not between runs
    fired = {}

    def preempting_source(batch=chunks[1][1]):
        if not fired.get("yes"):
            fired["yes"] = True
            signal.raise_signal(signal.SIGTERM)
        return jax.tree.map(jnp.asarray, batch)

    preempt_chunks = [chunks[0], (chunks[1][0], preempting_source),
                      *chunks[2:]]
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path / "ckpt"), every=10)
    )
    table = ShardedCoefficientTable(n_ent, k)
    prev = signal.getsignal(signal.SIGTERM)
    try:
        stop = GracefulStop().install(signums=(signal.SIGTERM,))
        with pytest.raises(TrainingInterrupted) as ei:
            trainer.train(
                table, preempt_chunks, should_stop=stop, checkpointer=mgr
            )
    finally:
        signal.signal(signal.SIGTERM, prev)
    # the in-flight chunk was finished and certified before exiting
    assert ei.value.checkpoint_path is not None
    state = mgr.restore()
    assert state is not None
    assert state.next_chunk == ei.value.step + 1
    assert 0 < state.next_chunk < len(chunks)  # genuinely mid-stream

    table2 = ShardedCoefficientTable(n_ent, k)
    table2.write_chunk(0, jnp.asarray(state.coefficients))
    trainer2 = StreamingRandomEffectTrainer("logistic", _CFG)
    trainer2.train(table2, chunks, start_chunk=state.next_chunk)
    np.testing.assert_array_equal(table2.to_numpy(), expected)


def test_streaming_stop_without_checkpointer_still_interrupts(rng):
    chunks, (n_ent, k) = _stream_fit_chunks(rng)
    from photon_ml_tpu.game.checkpoint import TrainingInterrupted

    trainer = StreamingRandomEffectTrainer("logistic", _CFG,
                                           prefetch=False)
    table = ShardedCoefficientTable(n_ent, k)
    with pytest.raises(TrainingInterrupted) as ei:
        trainer.train(table, chunks, should_stop=lambda: True)
    assert ei.value.checkpoint_path is None
    assert ei.value.step == 0  # stopped at the first boundary
