"""Pointwise-loss unit tests: closed forms, derivatives vs autodiff, stability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.losses import LOSSES, get_loss


@pytest.mark.parametrize("name", sorted(LOSSES))
def test_dz_matches_autodiff(name, rng):
    loss = LOSSES[name]
    z = jnp.asarray(rng.normal(size=64) * 3, jnp.float32)
    y = jnp.asarray((rng.random(64) > 0.5).astype(np.float32))
    if name == "poisson":
        y = jnp.asarray(rng.poisson(2.0, size=64).astype(np.float32))
    if name == "squared":
        y = jnp.asarray(rng.normal(size=64).astype(np.float32))
    auto = jax.vmap(jax.grad(lambda zi, yi: loss.loss(zi, yi)))(z, y)
    np.testing.assert_allclose(loss.dz(z, y), auto, rtol=1e-3, atol=1e-5)


@pytest.mark.parametrize("name", ["logistic", "squared", "poisson"])
def test_d2z_matches_autodiff(name, rng):
    loss = LOSSES[name]
    z = jnp.asarray(rng.normal(size=64) * 2, jnp.float32)
    y = jnp.asarray((rng.random(64) > 0.5).astype(np.float32))
    auto = jax.vmap(jax.grad(jax.grad(lambda zi, yi: loss.loss(zi, yi))))(z, y)
    np.testing.assert_allclose(loss.d2z(z, y), auto, rtol=1e-3, atol=1e-5)


def test_logistic_closed_form():
    loss = get_loss("logistic")
    z = jnp.asarray([0.0, 1.0, -1.0])
    # positive label: log(1 + exp(-z))
    np.testing.assert_allclose(
        loss.loss(z, jnp.ones(3)), np.log1p(np.exp(-np.asarray(z))), rtol=1e-5
    )
    # negative label: log(1 + exp(z)); accepts both 0 and -1 encodings
    for neg in (jnp.zeros(3), -jnp.ones(3)):
        np.testing.assert_allclose(
            loss.loss(z, neg), np.log1p(np.exp(np.asarray(z))), rtol=1e-5
        )


def test_logistic_stability_large_margins():
    loss = get_loss("logistic")
    z = jnp.asarray([1e4, -1e4], jnp.float32)
    v_pos = loss.loss(z, jnp.ones(2))
    v_neg = loss.loss(z, jnp.zeros(2))
    assert np.all(np.isfinite(v_pos)) and np.all(np.isfinite(v_neg))
    np.testing.assert_allclose(v_pos, [0.0, 1e4], rtol=1e-5)
    np.testing.assert_allclose(v_neg, [1e4, 0.0], rtol=1e-5)


def test_smoothed_hinge_piecewise():
    loss = get_loss("smoothed_hinge")
    # u = y*z regions: u<=0 -> 0.5-u ; 0<u<1 -> 0.5(1-u)^2 ; u>=1 -> 0
    z = jnp.asarray([-2.0, 0.5, 3.0])
    y = jnp.ones(3)
    np.testing.assert_allclose(loss.loss(z, y), [2.5, 0.125, 0.0], rtol=1e-5)
    np.testing.assert_allclose(loss.dz(z, y), [-1.0, -0.5, 0.0], rtol=1e-5)
    # negative label flips the margin
    np.testing.assert_allclose(loss.loss(-z, jnp.zeros(3)), [2.5, 0.125, 0.0], rtol=1e-5)
    assert not loss.has_hessian


def test_task_aliases():
    assert get_loss("LOGISTIC_REGRESSION").name == "logistic"
    assert get_loss("linear_regression").name == "squared"
    assert get_loss("POISSON_REGRESSION").name == "poisson"
    assert get_loss("smoothed_hinge_loss_linear_svm").name == "smoothed_hinge"
