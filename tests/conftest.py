"""Test configuration: force an 8-device virtual CPU mesh.

The reference tests distributed code in Spark local[*] mode
(SparkTestUtils.scala:56-75); the TPU-native analog is JAX's host-platform
device-count override, which gives real multi-device sharding/collective
semantics on CPU without TPU hardware (SURVEY.md §4).

Must run before jax initializes, hence module-level os.environ writes in
conftest (pytest imports conftest before test modules import jax).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep CPU compiles single-threaded-ish and quiet for CI stability.
os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")
# Persistent XLA compile cache: the suite is compile-dominated (dozens of
# while_loop optimizer programs). Env vars (read by jax at import) rather
# than jax.config.update so CLI-subprocess tests inherit the SAME cache
# through dict(os.environ); per-user path to avoid /tmp collisions on
# shared hosts; a pre-set JAX_COMPILATION_CACHE_DIR wins.
import getpass
import tempfile

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(),
                 f"photon_jax_cache_{getpass.getuser()}"),
)
# Cache EVERY program: the suite's cost is hundreds of 0.1-0.5s compiles
# (profiled: 81 compiles x 0.138s in ONE game test), all below the 1s
# default write threshold — without this the "warm" suite recompiles
# nearly everything, and the CLI subprocess tests can never hit the cache
# their parent process populated.
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU-tunnel sitecustomize imports jax at interpreter startup, which
# latches JAX_PLATFORMS before this conftest runs — override via the config
# API as well so tests really run on the 8-device CPU mesh.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "multichip: needs an 8-device mesh; when this process has fewer "
        "devices the test transparently re-runs itself in a subprocess "
        "under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
        "JAX_PLATFORMS=cpu (the multichip fixture)",
    )


@pytest.fixture
def multichip(request):
    """Tier-1-runnable multichip CI: guarantee the test sees >= 8 devices.

    In the normal suite this conftest already forced an 8-device virtual
    CPU platform, so the fixture is a pass-through. When the suite runs in
    an environment that latched a different platform (a 1-chip TPU host,
    a site customization importing jax early), the test re-execs ITSELF
    via pytest in a subprocess with the forced flags — so sharded-vs-
    single-device parity always runs somewhere, never silently skips.
    """
    if jax.device_count() >= 8:
        return jax.devices()[:8]
    if os.environ.get("PHOTON_MULTICHIP_SUBPROCESS") == "1":
        pytest.fail(
            "forced 8-device CPU provisioning failed: subprocess still "
            f"sees {jax.device_count()} devices on "
            f"{jax.devices()[0].platform}"
        )
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PHOTON_MULTICHIP_SUBPROCESS"] = "1"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "-q",
            "-p", "no:cacheprovider", request.node.nodeid,
        ],
        env=env,
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=900,
    )
    if proc.returncode != 0:
        pytest.fail(
            "multichip subprocess rerun failed "
            f"(rc={proc.returncode}):\n{proc.stdout[-2000:]}\n"
            f"{proc.stderr[-1000:]}"
        )
    pytest.skip(
        "passed in a forced 8-device CPU subprocess (this process has "
        f"only {jax.device_count()} devices)"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """Telemetry is process-global (spans, counters, sinks, env-configured
    atexit flushes); without a guard, test ORDER decides whether one
    test's sink or stats provider leaks into the next. Reset after every
    test — telemetry.reset() restores full import-time defaults."""
    yield
    from photon_ml_tpu import telemetry

    telemetry.reset()
