"""Evaluator tests vs sklearn/naive references."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.evaluation import (
    auc,
    better_than,
    parse_evaluator,
    rmse,
    sharded_auc,
    sharded_precision_at_k,
)


def _naive_weighted_auc(scores, labels, weights):
    pos = labels > 0.5
    num = 0.0
    den = 0.0
    for i in np.where(pos)[0]:
        for j in np.where(~pos)[0]:
            wij = weights[i] * weights[j]
            den += wij
            if scores[i] > scores[j]:
                num += wij
            elif scores[i] == scores[j]:
                num += 0.5 * wij
    return num / den


def test_auc_unweighted_matches_sklearn(rng):
    from sklearn.metrics import roc_auc_score

    scores = rng.normal(size=200)
    labels = (rng.random(200) > 0.4).astype(float)
    w = np.ones(200)
    ours = float(auc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
    assert np.isclose(ours, roc_auc_score(labels, scores), atol=1e-6)


def test_auc_weighted_matches_naive(rng):
    scores = np.round(rng.normal(size=40), 1)  # induce ties
    labels = (rng.random(40) > 0.5).astype(float)
    w = rng.random(40) + 0.1
    ours = float(auc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
    assert np.isclose(ours, _naive_weighted_auc(scores, labels, w), atol=1e-5)


def test_auc_degenerate_single_class():
    s = jnp.asarray([0.1, 0.5, 0.9])
    assert float(auc(s, jnp.ones(3), jnp.ones(3))) == 0.5
    assert float(auc(s, jnp.zeros(3), jnp.ones(3))) == 0.5


def test_auc_padding_inert(rng):
    scores = rng.normal(size=50)
    labels = (rng.random(50) > 0.5).astype(float)
    base = float(auc(jnp.asarray(scores), jnp.asarray(labels), jnp.ones(50)))
    s2 = np.concatenate([scores, rng.normal(size=7)])
    l2 = np.concatenate([labels, np.ones(7)])
    w2 = np.concatenate([np.ones(50), np.zeros(7)])
    padded = float(auc(jnp.asarray(s2), jnp.asarray(l2), jnp.asarray(w2)))
    assert np.isclose(base, padded, atol=1e-6)


def test_rmse():
    s = jnp.asarray([1.0, 2.0, 3.0])
    y = jnp.asarray([1.0, 1.0, 5.0])
    w = jnp.asarray([1.0, 2.0, 1.0])
    expected = np.sqrt((0 + 2 * 1 + 4) / 4)
    assert np.isclose(float(rmse(s, y, w)), expected, atol=1e-6)


def test_sharded_auc_matches_per_group_mean(rng):
    from sklearn.metrics import roc_auc_score

    G, per = 6, 30
    scores, labels, gids = [], [], []
    for g in range(G):
        scores.append(rng.normal(size=per))
        labels.append((rng.random(per) > 0.5).astype(float))
        gids.append(np.full(per, g))
    scores, labels, gids = map(np.concatenate, (scores, labels, gids))
    expected = np.mean(
        [
            roc_auc_score(labels[gids == g], scores[gids == g])
            for g in range(G)
            if len(np.unique(labels[gids == g])) == 2
        ]
    )
    ours = float(
        sharded_auc(
            jnp.asarray(scores),
            jnp.asarray(labels),
            jnp.ones(len(scores)),
            jnp.asarray(gids, jnp.int32),
            num_groups=G,
        )
    )
    assert np.isclose(ours, expected, atol=1e-5)


def test_sharded_precision_at_k(rng):
    # two groups with known top-k composition
    scores = jnp.asarray([0.9, 0.8, 0.1, 0.95, 0.2, 0.3])
    labels = jnp.asarray([1.0, 0.0, 1.0, 0.0, 1.0, 1.0])
    gids = jnp.asarray([0, 0, 0, 1, 1, 1], jnp.int32)
    # group 0 top-2: scores .9(pos) .8(neg) -> 0.5 ; group 1 top-2: .95(neg) .3(pos) -> 0.5
    out = float(
        sharded_precision_at_k(scores, labels, jnp.ones(6), gids, num_groups=2, k=2)
    )
    assert np.isclose(out, 0.5, atol=1e-6)


def test_parse_and_direction():
    assert parse_evaluator("AUC") == ("auc", None, None)
    assert parse_evaluator("precision@5:queryId") == (
        "sharded_precision_at_k",
        "queryid",
        5,
    )
    assert parse_evaluator("auc:memberId") == ("sharded_auc", "memberid", None)
    with pytest.raises(ValueError):
        parse_evaluator("nope")
    assert better_than("auc", 0.9, 0.8)
    assert better_than("rmse", 0.1, 0.2)
    assert better_than("precision@5:q", 0.9, 0.2)

def test_sharded_auc_weighted_matches_naive(rng):
    """Regression (VERDICT r2 weak #5): sharded AUC must be weight-aware —
    mean of per-group WEIGHTED AUCs, matching the naive pair count."""
    G, per = 4, 25
    scores, labels, weights, gids = [], [], [], []
    for g in range(G):
        scores.append(np.round(rng.normal(size=per), 1))  # induce ties
        labels.append((rng.random(per) > 0.5).astype(float))
        weights.append(rng.random(per) + 0.1)
        gids.append(np.full(per, g))
    scores, labels, weights, gids = map(
        np.concatenate, (scores, labels, weights, gids))
    per_group = [
        _naive_weighted_auc(scores[gids == g], labels[gids == g], weights[gids == g])
        for g in range(G)
        if len(np.unique(labels[gids == g])) == 2
    ]
    ours = float(
        sharded_auc(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights),
            jnp.asarray(gids, jnp.int32), num_groups=G,
        )
    )
    assert np.isclose(ours, np.mean(per_group), atol=1e-5)


def test_sharded_auc_zero_weight_rows_inert(rng):
    scores = rng.normal(size=40)
    labels = (rng.random(40) > 0.5).astype(float)
    gids = np.repeat([0, 1], 20)
    base = float(sharded_auc(
        jnp.asarray(scores), jnp.asarray(labels), jnp.ones(40),
        jnp.asarray(gids, jnp.int32), num_groups=2))
    s2 = np.concatenate([scores, rng.normal(size=6)])
    l2 = np.concatenate([labels, np.ones(6)])
    w2 = np.concatenate([np.ones(40), np.zeros(6)])
    g2 = np.concatenate([gids, np.repeat([0, 1], 3)])
    padded = float(sharded_auc(
        jnp.asarray(s2), jnp.asarray(l2), jnp.asarray(w2),
        jnp.asarray(g2, jnp.int32), num_groups=2))
    assert np.isclose(base, padded, atol=1e-6)


# ---------------------------------------------------------------------------
# ISSUE 8 satellite: degenerate inputs the sweep selector will hit.
# Contract: every case either yields the evaluator's DOCUMENTED fallback
# (tied scores -> mid-rank averaging; single-class -> 0.5; empty split ->
# 0.5) or a non-finite value that sweep.select turns into a typed error /
# lane exclusion — never a silent argmax over NaNs.
# ---------------------------------------------------------------------------


def test_auc_all_tied_scores_is_half():
    """Every pair tied -> mid-rank averaging gives exactly 0.5."""
    s = jnp.full((8,), 0.25)
    labels = jnp.asarray([1, 0, 1, 0, 1, 0, 1, 0], jnp.float32)
    assert float(auc(s, labels, jnp.ones(8))) == pytest.approx(0.5, abs=1e-7)


def test_auc_tied_blocks_match_naive(rng):
    """Heavily tied (3 distinct values) scores match the O(n^2) pair
    count — the tie handling the selector relies on for coarse models."""
    scores = rng.integers(0, 3, size=30).astype(float)
    labels = (rng.random(30) > 0.5).astype(float)
    w = rng.random(30) + 0.5
    ours = float(auc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
    assert np.isclose(ours, _naive_weighted_auc(scores, labels, w), atol=1e-6)


def test_auc_empty_split_all_zero_weights_is_half():
    """An all-padding (weight-0) validation split has no pair mass: the
    documented fallback is 0.5, finite and selectable."""
    s = jnp.asarray([0.1, 0.9])
    labels = jnp.asarray([1.0, 0.0])
    out = float(auc(s, labels, jnp.zeros(2)))
    assert out == pytest.approx(0.5)


def test_rmse_empty_split_is_finite_zero():
    from photon_ml_tpu.evaluation import rmse as _rmse

    out = float(_rmse(jnp.asarray([1.0, 2.0]), jnp.asarray([0.0, 0.0]),
                      jnp.zeros(2)))
    assert out == 0.0


def test_nan_score_columns_propagate_to_nan_not_garbage():
    """All-NaN score columns must surface as NaN metrics (which the sweep
    selector excludes / errors on), never as a plausible finite value."""
    from photon_ml_tpu.evaluation import logistic_loss

    s = jnp.full((4,), jnp.nan)
    labels = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    w = jnp.ones(4)
    assert np.isnan(float(rmse(s, labels, w)))
    assert np.isnan(float(logistic_loss(s, labels, w)))


def test_selector_raises_on_all_nan_metric_column():
    """End-to-end: NaN evaluator outputs become a typed selection error,
    not a silent argmax (ISSUE 8 satellite acceptance)."""
    from photon_ml_tpu.sweep.select import SweepSelectionError, select_best

    with pytest.raises(SweepSelectionError, match="non-finite"):
        select_best(np.asarray([np.nan, np.nan, np.nan]), "rmse")


def test_selector_excludes_partial_nan_lanes():
    from photon_ml_tpu.sweep.select import select_best

    assert select_best(np.asarray([np.nan, 2.0, 3.0]), "rmse") == 1
