"""Shard-owning serving fleet (ISSUE 17): deterministic entity-block
ownership (`member_row_range`), member slices whose folded margins match
the single-process engine EXACTLY, the stage/commit resize barrier with
version pinning, the routing front end's degraded mode (sheds accuracy,
never availability), graceful drain (503 + Retry-After -> exit 75), the
serving fault seams (serving.member_load, serving.route_fanout,
serving.resize_swap), and the subprocess e2e: a 3-process fleet serving
a model whose tables exceed one member's HBM budget, surviving a
mid-traffic hard kill with zero non-shed failures."""

import json
import os
import signal
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.parallel.sharding import (
    ElasticPlacementError,
    member_row_range,
    owner_of_row,
    valid_fleet_sizes,
)
from photon_ml_tpu.serving import (
    FleetRouter,
    ScoringEngine,
    ScoringServer,
    ScoringService,
    ShardBudgetError,
    ShardMemberSource,
    fleet_lookups_from_version_dir,
    load_member_engine,
    member_owned_ranges,
    scan_announce,
    slice_model_for_member,
    write_announce,
)
from photon_ml_tpu.serving.batcher import Draining
from photon_ml_tpu.serving.shard import serving_table_bytes
from tools import fleet as fleet_tools

N_ENTITIES = 12


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    faults.clear_plan()


@pytest.fixture(scope="module")
def published(tmp_path_factory):
    """One published model (FE `global` + 12-entity `userId` RE) shared
    by every in-process fleet in this file."""
    registry = tmp_path_factory.mktemp("fleet-registry")
    version_dir = fleet_tools.make_serving_model(
        str(registry), n_entities=N_ENTITIES
    )
    task, link, lookups = fleet_lookups_from_version_dir(version_dir)
    return {
        "version_dir": version_dir,
        "task": task,
        "link": link,
        "lookups": lookups,
    }


@pytest.fixture(scope="module")
def member_engine(published):
    """Memoized member-slice engines: warming a slice is the slow part,
    so every test shares one engine per (member, fleet_size)."""
    cache: dict = {}

    def get(member: int, fleet_size: int) -> ScoringEngine:
        key = (member, fleet_size)
        if key not in cache:
            cache[key] = load_member_engine(
                published["version_dir"], member, fleet_size, max_batch=16
            )
        return cache[key]

    return get


def _request_rows(n=N_ENTITIES, with_offset=True):
    rows = []
    for i in range(n):
        row = {
            "features": {
                "global": [[0, 0.5], [1, -0.25], [2, float(i) / 10]],
                "user": [[0, 1.0], [1, 0.5]],
            },
            "ids": {"userId": str(i)},
        }
        if with_offset:
            row["offset"] = 0.1 * (i % 3)
        rows.append(row)
    return rows


def _start_fleet(published, member_engine, announce_dir, fleet_size=3,
                 epoch=0):
    """In-process fleet: one ScoringServer per member over a
    ShardMemberSource wrapping the cached slice engine."""
    os.makedirs(announce_dir, exist_ok=True)
    out = []
    for m in range(fleet_size):
        source = ShardMemberSource(
            lambda fs, version=None, _m=m: member_engine(_m, fs),
            member=m,
            fleet_size=fleet_size,
        )
        source.commit(*source.stage(fleet_size))
        server = ScoringServer(
            ScoringService(source, max_batch=16), port=0
        ).start()
        write_announce(announce_dir, {
            "member": m, "fleet_size": fleet_size, "epoch": epoch,
            "url": f"http://127.0.0.1:{server.port}",
            "version": source.engine.version, "ready": True,
            "pid": os.getpid(), "owned": {},
        })
        out.append((server, source))
    return out


# ---------------------------------------------------------------------------
# 1. ownership math + slices
# ---------------------------------------------------------------------------


def test_member_ranges_partition_and_invert():
    for fleet_size in (1, 2, 3, 4, 6, 12):
        ranges = [
            member_row_range(N_ENTITIES, m, fleet_size)
            for m in range(fleet_size)
        ]
        covered = [c for lo, hi in ranges for c in range(lo, hi)]
        assert covered == list(range(N_ENTITIES))  # exact partition
        for m, (lo, hi) in enumerate(ranges):
            for code in (lo, hi - 1):
                assert owner_of_row(N_ENTITIES, code, fleet_size) == m


def test_indivisible_fleet_size_lists_valid_sizes():
    with pytest.raises(ElasticPlacementError) as exc:
        member_row_range(N_ENTITIES, 0, 5)
    msg = str(exc.value)
    assert "valid fleet sizes" in msg
    assert str(valid_fleet_sizes(N_ENTITIES)) in msg
    with pytest.raises(ValueError):
        member_row_range(N_ENTITIES, 3, 3)  # member outside the fleet


def test_sliced_margins_fold_to_single_engine_scores(
    published, member_engine
):
    """The tentpole invariant: per-member margins (entity block + one FE
    designate) fold + offset + link == the single-process engine's
    predict_mean, to 1e-6."""
    rows = _request_rows()
    full = ScoringEngine.load(published["version_dir"], max_batch=16)
    ref = np.asarray(full.score_rows(rows), np.float64)
    fleet_size = 3
    totals = np.zeros(len(rows), np.float64)
    for m in range(fleet_size):
        include_fixed = [
            owner_of_row(N_ENTITIES, i, fleet_size) == m for i in range(
                len(rows)
            )
        ]
        totals += np.asarray(
            member_engine(m, fleet_size).margin_rows(
                rows, include_fixed=include_fixed
            ),
            np.float64,
        )
    offsets = np.asarray([r.get("offset") or 0.0 for r in rows])
    folded = 1.0 / (1.0 + np.exp(-(totals + offsets)))
    np.testing.assert_allclose(folded, ref, atol=1e-6)


def test_owned_ranges_and_slice_budget(published):
    from photon_ml_tpu.data.model_store import load_game_model

    model = load_game_model(published["version_dir"])
    assert member_owned_ranges(model, 1, 3) == {"userId": (4, 8)}
    full_bytes = serving_table_bytes(model)
    slice_bytes = serving_table_bytes(slice_model_for_member(model, 0, 3))
    assert slice_bytes < full_bytes
    # a budget the FULL model exceeds but the slice fits — the fleet's
    # reason to exist — loads; an impossible budget names the remedy
    budget = (slice_bytes + full_bytes) // 2
    engine = load_member_engine(
        published["version_dir"], 0, 3, max_batch=16,
        hbm_budget_bytes=budget, warm=False,
    )
    assert engine.version == os.path.basename(published["version_dir"])
    with pytest.raises(ShardBudgetError) as exc:
        load_member_engine(
            published["version_dir"], 0, 3, max_batch=16,
            hbm_budget_bytes=16, warm=False,
        )
    assert "grow the fleet" in str(exc.value)


def test_member_source_stage_commit_resolve(published, member_engine):
    calls = []

    def loader(fleet_size, version=None):
        calls.append((fleet_size, version))
        return member_engine(0, fleet_size)

    src = ShardMemberSource(loader, member=0, fleet_size=3)
    with pytest.raises(RuntimeError):
        _ = src.engine  # nothing committed yet
    with pytest.raises(KeyError):
        src.commit(3, "v-never-staged")
    key3 = src.stage(3)
    src.commit(*key3)
    version = src.engine.version
    assert src.fleet_size == 3
    # staging is idempotent per key: a version-pinned re-stage is free
    src.stage(3, version)
    assert calls == [(3, None)]
    # resize staging: both sides of the barrier resolve (mixed window)
    key6 = src.stage(6)
    src.commit(*key6)
    assert src.fleet_size == 6
    assert src.resolve(3, version) is member_engine(0, 3)
    assert src.resolve(6, version) is member_engine(0, 6)
    assert src.resolve() is member_engine(0, 6)
    with pytest.raises(KeyError) as exc:
        src.resolve(6, "v-unknown")
    assert "staged" in str(exc.value)


# ---------------------------------------------------------------------------
# 2. the router: parity, version pinning, degraded mode
# ---------------------------------------------------------------------------


def test_router_matches_single_engine_and_pins_versions(
    published, member_engine, tmp_path
):
    members = _start_fleet(
        published, member_engine, str(tmp_path / "announce")
    )
    router = FleetRouter(
        str(tmp_path / "announce"), published["lookups"],
        task=published["task"], link=published["link"],
        member_timeout_s=5.0, cooldown_s=0.05, backoff_s=0.01,
    )
    try:
        router.refresh()
        assert router.view.fleet_size == 3
        rows = _request_rows()
        full = ScoringEngine.load(published["version_dir"], max_batch=16)
        ref = np.asarray(full.score_rows(rows))
        got = np.asarray(router.score_rows(rows))
        np.testing.assert_allclose(got, ref, atol=1e-6)
        # a request pinned to a version this member never staged is 409
        # (the mixed-swap window contract), not a 500
        url = router.view.endpoints[0] + "/v1/margins"
        req = urllib.request.Request(
            url,
            data=json.dumps({
                "rows": rows[:2], "fleet_size": 3, "version": "v-bogus",
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 409
        assert json.loads(exc.value.read())["error"] == (
            "version_unavailable"
        )
    finally:
        router.close()
        for server, _src in members:
            server.stop()


def test_degraded_mode_sheds_exactly_the_lost_entities(
    published, member_engine, tmp_path
):
    """Kill member 1's endpoint: rows whose entity it owns degrade to
    FE-only (EXACT accounting — 4 of 12 rows), every other row stays on
    exact parity, and no request fails."""
    members = _start_fleet(
        published, member_engine, str(tmp_path / "announce")
    )
    router = FleetRouter(
        str(tmp_path / "announce"), published["lookups"],
        task=published["task"], link=published["link"],
        member_timeout_s=2.0, cooldown_s=30.0, backoff_s=0.01,
    )
    try:
        router.refresh()
        rows = _request_rows()
        full = ScoringEngine.load(published["version_dir"], max_batch=16)
        ref = np.asarray(full.score_rows(rows))
        fe_only = np.asarray(full.score_rows([
            {k: v for k, v in r.items() if k != "ids"} for r in rows
        ]))
        members[1][0].stop()  # member 1 owns codes [4, 8)
        degraded0 = telemetry.counter("serving.degraded_scores").value
        failures0 = telemetry.counter("serving.member_failures").value
        got = np.asarray(router.score_rows(rows))
        lost = [
            i for i in range(len(rows))
            if owner_of_row(N_ENTITIES, i, 3) == 1
        ]
        kept = [i for i in range(len(rows)) if i not in lost]
        assert lost == [4, 5, 6, 7]
        delta = telemetry.counter("serving.degraded_scores").value
        assert delta - degraded0 == len(lost)  # exact shed accounting
        assert telemetry.counter(
            "serving.member_failures"
        ).value > failures0
        np.testing.assert_allclose(got[kept], ref[kept], atol=1e-6)
        np.testing.assert_allclose(got[lost], fe_only[lost], atol=1e-6)
        status = router.members_status()
        assert status[1]["cooling_down"]
    finally:
        router.close()
        for server, _src in members:
            server.stop()


def test_live_resize_adopts_new_epoch_and_keeps_parity(
    published, member_engine, tmp_path
):
    """An in-process 3 -> 2 resize through the announce protocol: the
    router holds the old ownership view until the NEW epoch's member set
    is complete, then swaps atomically (serving.resize_swaps) and scores
    stay on parity at the new size."""
    announce = str(tmp_path / "announce")
    gen0 = _start_fleet(published, member_engine, announce, fleet_size=3)
    router = FleetRouter(
        announce, published["lookups"], task=published["task"],
        link=published["link"], member_timeout_s=5.0,
        cooldown_s=0.05, backoff_s=0.01,
    )
    gen1 = []
    try:
        router.refresh()
        rows = _request_rows()
        full = ScoringEngine.load(published["version_dir"], max_batch=16)
        ref = np.asarray(full.score_rows(rows))
        assert router.view.fleet_size == 3
        swaps0 = telemetry.counter("serving.resize_swaps").value
        # an INCOMPLETE next epoch must not swap: announce member 0 of 2
        write_announce(announce, {
            "member": 0, "fleet_size": 2, "epoch": 1,
            "url": "http://127.0.0.1:1", "version": "x", "ready": True,
        })
        router.refresh()
        assert router.view.epoch == 0
        gen1 = _start_fleet(
            published, member_engine, announce, fleet_size=2, epoch=1
        )
        router.refresh()
        assert (router.view.epoch, router.view.fleet_size) == (1, 2)
        assert telemetry.counter(
            "serving.resize_swaps"
        ).value == swaps0 + 1
        got = np.asarray(router.score_rows(rows))
        np.testing.assert_allclose(got, ref, atol=1e-6)
    finally:
        router.close()
        for server, _src in gen0 + gen1:
            server.stop()


def test_scan_announce_skips_torn_files(tmp_path):
    write_announce(str(tmp_path), {
        "member": 0, "fleet_size": 1, "epoch": 0, "url": "http://x",
        "ready": True,
    })
    (tmp_path / "member-1.json").write_text('{"member": 1, "fle')
    records = scan_announce(str(tmp_path))
    assert [r["member"] for r in records] == [0]


# ---------------------------------------------------------------------------
# 3. the serving fault seams (L016 string-literal coverage)
# ---------------------------------------------------------------------------


def test_member_load_seam_fails_the_load_then_retries_clean(published):
    faults.install_plan(faults.FaultPlan([
        faults.FaultRule("serving.member_load", action="io", nth=1),
    ]))
    with pytest.raises(OSError):
        load_member_engine(
            published["version_dir"], 0, 3, max_batch=16, warm=False
        )
    faults.clear_plan()
    engine = load_member_engine(
        published["version_dir"], 0, 3, max_batch=16, warm=False
    )
    assert engine.version == os.path.basename(published["version_dir"])


def test_route_fanout_seam_degrades_never_fails(
    published, member_engine, tmp_path
):
    members = _start_fleet(
        published, member_engine, str(tmp_path / "announce"),
        fleet_size=2,
    )
    router = FleetRouter(
        str(tmp_path / "announce"), published["lookups"],
        task=published["task"], link=published["link"],
        member_timeout_s=5.0, cooldown_s=0.05, backoff_s=0.01,
    )
    try:
        router.refresh()
        rows = _request_rows()
        degraded0 = telemetry.counter("serving.degraded_scores").value
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.route_fanout", action="io", nth=1),
        ]))
        got = router.score_rows(rows)  # the injected failure sheds, only
        faults.clear_plan()
        assert len(got) == len(rows)
        assert telemetry.counter(
            "serving.degraded_scores"
        ).value > degraded0
        time.sleep(0.1)  # cooldown lapses; the seam is exhausted
        full = ScoringEngine.load(published["version_dir"], max_batch=16)
        np.testing.assert_allclose(
            np.asarray(router.score_rows(rows)),
            np.asarray(full.score_rows(rows)),
            atol=1e-6,
        )
    finally:
        router.close()
        for server, _src in members:
            server.stop()


def test_resize_swap_seam_preserves_the_old_view(
    published, member_engine, tmp_path
):
    announce = str(tmp_path / "announce")
    members = _start_fleet(
        published, member_engine, announce, fleet_size=2
    )
    router = FleetRouter(
        announce, published["lookups"], task=published["task"],
        link=published["link"], member_timeout_s=5.0,
        cooldown_s=0.05, backoff_s=0.01,
    )
    try:
        router.refresh()
        rows = _request_rows()
        ref = np.asarray(router.score_rows(rows))
        for m, (server, source) in enumerate(members):
            write_announce(announce, {
                "member": m, "fleet_size": 2, "epoch": 1,
                "url": f"http://127.0.0.1:{server.port}",
                "version": source.engine.version, "ready": True,
            })
        fails0 = telemetry.counter("serving.resize_swap_failures").value
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.resize_swap", action="raise", nth=1),
        ]))
        router.refresh()
        faults.clear_plan()
        # the failed swap left the OLD ownership map serving untouched
        assert router.view.epoch == 0
        assert telemetry.counter(
            "serving.resize_swap_failures"
        ).value == fails0 + 1
        np.testing.assert_allclose(
            np.asarray(router.score_rows(rows)), ref, atol=1e-6
        )
        router.refresh()  # unarmed: the swap completes
        assert router.view.epoch == 1
    finally:
        router.close()
        for server, _src in members:
            server.stop()


# ---------------------------------------------------------------------------
# 4. graceful drain
# ---------------------------------------------------------------------------


def test_drain_rejects_new_work_with_retry_after(
    published, member_engine, tmp_path
):
    source = ShardMemberSource(
        lambda fs, version=None: member_engine(0, fs),
        member=0, fleet_size=3,
    )
    source.commit(*source.stage(3))
    service = ScoringService(source, max_batch=16)
    server = ScoringServer(service, port=0).start()
    try:
        service.drain()
        assert service.draining
        with pytest.raises(Draining):
            service.margin_request({"rows": _request_rows(2)})
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/score",
            data=json.dumps({"rows": _request_rows(2)}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 503
        assert exc.value.headers.get("Retry-After") == "2"
        service.drain()  # idempotent
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# 5. the subprocess e2e + the chaos matrix slices
# ---------------------------------------------------------------------------


@pytest.mark.chaos_serving
def test_three_process_fleet_parity_budget_kill_drain(
    published, tmp_path
):
    """The acceptance e2e: a REAL 3-process `cli serve --member` fleet
    under a per-member HBM budget the FULL model exceeds (a) matches the
    single-process engine to 1e-6, (b) survives a SIGKILLed member with
    zero failed requests and exact degraded accounting, (c) drains
    every survivor to exit 75 on SIGTERM, and (d) — ISSUE 18 — yields
    ONE joined per-request trace spanning router + members, plus a
    harvested flight record ("last words") for the hard-killed member
    in `cli report --fleet`."""
    from photon_ml_tpu.cli import report as cli_report
    from photon_ml_tpu.data.model_store import load_game_model
    from photon_ml_tpu.telemetry import requests as rq
    from photon_ml_tpu.telemetry.fleet_report import FleetReport

    model = load_game_model(published["version_dir"])
    full_bytes = serving_table_bytes(model)
    slice_bytes = serving_table_bytes(slice_model_for_member(model, 0, 3))
    budget_mb = ((slice_bytes + full_bytes) / 2) / 2**20
    spec = fleet_tools.ServingFleetSpec(
        workdir=str(tmp_path),
        model_dir=published["version_dir"],
        fleet_size=3,
        max_batch=16,
        hbm_budget_mb=budget_mb,
        heartbeat_deadline_s=2.0,
    )
    os.makedirs(spec.announce_dir(), exist_ok=True)
    os.makedirs(spec.fleet_dir(), exist_ok=True)
    tdir = os.path.dirname(spec.telemetry_base())
    members = {
        m: fleet_tools._launch_serving_member(spec, m, 3, 0)
        for m in range(3)
    }
    router = None
    try:
        fleet_tools._wait_for_epoch(
            spec, 0, 3, time.monotonic() + spec.warm_timeout_s
        )
        # router-side span stream + head-sample EVERY request: members
        # see `X-Photon-Trace ...;s=1` and persist their half of the tree
        telemetry.configure(
            trace_out=os.path.join(tdir, "trace.router.jsonl")
        )
        router = FleetRouter(
            spec.announce_dir(), published["lookups"],
            task=published["task"], link=published["link"],
            member_timeout_s=3.0, cooldown_s=0.2, backoff_s=0.02,
            sample_every=1,
        )
        router.refresh()
        rows = _request_rows()
        full = ScoringEngine.load(published["version_dir"], max_batch=16)
        ref = np.asarray(full.score_rows(rows))
        np.testing.assert_allclose(
            np.asarray(router.score_rows(rows)), ref, atol=1e-6
        )
        # hard-kill member 1 mid-service: the fleet sheds its entity
        # block (FE-only), exactly, and no request fails
        members[1].proc.kill()
        members[1].proc.wait()
        degraded0 = telemetry.counter("serving.degraded_scores").value
        got = np.asarray(router.score_rows(rows))
        assert len(got) == len(rows)
        lost = [i for i in range(N_ENTITIES) if owner_of_row(
            N_ENTITIES, i, 3
        ) == 1]
        assert telemetry.counter(
            "serving.degraded_scores"
        ).value - degraded0 == len(lost)
        fe_only = np.asarray(full.score_rows([
            {k: v for k, v in r.items() if k != "ids"} for r in rows
        ]))
        np.testing.assert_allclose(got[lost], fe_only[lost], atol=1e-6)
        # the supervisor-side flight harvest: member 1 never ran its own
        # drain dump, so its "last words" come from the span-stream tail
        assert rq.harvest_flight(
            telemetry.member_artifact_path(spec.trace_base(), 1),
            rq.flight_path(tdir, 1),
        )
        # graceful drain: SIGTERM -> drain -> exit 75 (the supervisor's
        # relaunch-vs-crash verdict keys on this)
        for m in (0, 2):
            members[m].proc.send_signal(signal.SIGTERM)
        assert members[0].proc.wait(timeout=30) == 75
        assert members[2].proc.wait(timeout=30) == 75

        # -- ISSUE 18 acceptance: the joined per-request trace ------------
        fr = FleetReport.load(str(tmp_path))
        joined = [
            t for t in fr.request_traces()
            if "router" in t["sources"]
            and sum(s.startswith("proc-") for s in t["sources"]) >= 2
        ]
        assert joined, "no request trace spans router + >=2 members"
        member_hops = [
            h for h in joined[0]["hops"]
            if h["source"].startswith("proc-")
        ]
        for hop in member_hops:
            assert hop["phases"], hop  # non-empty phase decomposition
            assert "version" in hop["attrs"]
            assert hop["attrs"]["fleet_size"] == 3
        # the hard-killed member's flight record surfaces as last words
        # through the real CLI fleet report
        assert 1 in [m.process_index for m in fr.members if m.flight]
        out_md = str(tmp_path / "fleet-report.md")
        assert cli_report.main(
            ["--fleet", str(tmp_path), "--out", out_md]
        ) == 0
        with open(out_md, encoding="utf-8") as fh:
            content = fh.read()
        assert "Last words — member 1" in content
        assert "## Requests" in content
    finally:
        if router is not None:
            router.close()
        for mem in members.values():
            if mem.proc.poll() is None:
                mem.proc.kill()
                mem.proc.wait()


@pytest.mark.chaos_serving
def test_serving_chaos_tier1_slice(tmp_path):
    """Budget-capped tier-1 slice of the serving chaos matrix: the three
    IN-PROCESS seam rows (member_load_io, route_fanout_io, resize_swap)
    plus the cheap flight-recorder kill row (flight_dump_kill: exit 113
    mid-dump, fleet discovery never adopts the torn .tmp). The full
    matrix — including the subprocess hard-kill-under-traffic row —
    runs under --slow / `python -m tools.chaos --serving-fleet`."""
    from tools import chaos

    budget = float(os.environ.get("PHOTON_CHAOS_BUDGET_S", "300"))
    report = chaos.run_serving_matrix(
        str(tmp_path),
        rows=[
            "member_load_io", "route_fanout_io", "resize_swap",
            "flight_dump_kill",
        ],
        budget_s=budget,
    )
    if report["skipped"]:
        warnings.warn(
            "chaos budget truncated the serving matrix; uncovered this "
            f"run: {report['skipped']} (full matrix: python -m "
            "tools.chaos --serving-fleet)",
            stacklevel=1,
        )
        return
    assert report["ok"], json.dumps(report, indent=2, default=str)
    assert report["results"]["route_fanout_io"]["degraded_scores"] > 0
    flight = report["results"]["flight_dump_kill"]
    assert flight["armed_rc"] == 113
    assert flight["adopted_after_kill"] == []


@pytest.mark.slow
@pytest.mark.chaos_serving
def test_serving_chaos_full_matrix(tmp_path):
    """Every serving chaos row, including the 3-process hard-kill-under-
    traffic one: zero non-shed failures, exact shed accounting, recovery
    inside the budget, every member drained to 75."""
    from tools import chaos

    report = chaos.run_serving_matrix(str(tmp_path))
    assert not report["skipped"]
    assert report["ok"], json.dumps(report, indent=2, default=str)
    kill = report["results"]["member_hard_kill"]
    assert kill["failures"] == 0
    assert kill["degraded_scores"] > 0
    assert kill["kill"]["recovery_s"] <= chaos.KILL_RECOVERY_BUDGET_S


@pytest.mark.slow
@pytest.mark.chaos_serving
def test_live_elastic_resize_under_sustained_load(tmp_path):
    """The headline: 3 -> 6 -> 3 live resize under sustained router
    traffic through the stage/commit barrier — zero failed requests,
    both swaps complete (epoch 2, fleet back at the original size), and
    every member (including the retired growth slots) drains to 75."""
    registry = tmp_path / "registry"
    version_dir = fleet_tools.make_serving_model(
        str(registry), n_entities=N_ENTITIES
    )
    spec = fleet_tools.ServingFleetSpec(
        workdir=str(tmp_path / "run"),
        model_dir=version_dir,
        fleet_size=3,
        max_batch=16,
        traffic_seconds=26.0,
        traffic_hz=10.0,
        traffic_rows=6,
        traffic_features=(("global", 2), ("user", 2)),
        heartbeat_deadline_s=2.0,
        resizes=((3.0, 6), (14.0, 3)),
    )
    run = fleet_tools.run_serving_fleet(spec)
    assert run["ok"], json.dumps(run.get("failures"), default=str)[:2000]
    assert run["failures"] == []
    assert run["fleet_size"] == 3
    assert run["epoch"] == 2
    resizes = [ev["resize"] for ev in run["events"] if "resize" in ev]
    assert [(r["from"], r["to"]) for r in resizes] == [(3, 6), (6, 3)]
    assert all(rc == 75 for rc in run["rcs"].values())
    assert run["routed_rows"] > 0
