"""Fixture-driven coverage for the static-analysis gate (tools/analysis).

Every rule L001-L015 gets positive + negative snippets; the suppression,
baseline-diff, and ``--json`` surfaces are pinned; and the ISSUE 7
acceptance demos run the REAL ``tools/check.py`` CLI against miniature
package trees carrying the production seed names (``ScoringEngine
.score_rows``, ``MicroBatcher``), asserting the exit code flips and the
finding names the call chain / attribute.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analysis import core, driver, local
from tools.analysis.callgraph import build_graph, module_name_for

CHECK = os.path.join(REPO, "tools", "check.py")


def lint(code: str, rel: str = "photon_ml_tpu/mod.py", library=None):
    tree = ast.parse(textwrap.dedent(code))
    if library is None:
        library = rel.startswith("photon_ml_tpu/")
    return local.lint_file(rel, tree, library=library)


def codes(findings):
    return sorted(f.code for f in findings)


def write_tree(tmp_path, files: dict):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    return tmp_path


def analyze(tmp_path, files: dict, **kw):
    write_tree(tmp_path, files)
    kw.setdefault("require_seeds", False)
    return driver.analyze(str(tmp_path), **kw)


def graph_of(tmp_path, files: dict):
    write_tree(tmp_path, files)
    srcs = []
    for rel in files:
        if rel.startswith("photon_ml_tpu/") and rel.endswith(".py"):
            srcs.append(core.load_source(rel, str(tmp_path / rel)))
    return build_graph(srcs)


# ---------------------------------------------------------------------------
# Per-file rules L001-L012
# ---------------------------------------------------------------------------


class TestLocalRules:
    def test_l001_unused_import(self):
        assert codes(lint("import os\n")) == ["L001"]

    def test_l001_all_export_is_a_use(self):
        assert lint('import os\n__all__ = ["os"]\n') == []

    def test_l001_used_import_clean(self):
        assert lint("import os\nX = os.sep\n") == []

    def test_l002_bare_except(self):
        src = "try:\n    pass\nexcept:\n    pass\n"
        assert codes(lint(src)) == ["L002"]

    def test_l003_mutable_default(self):
        assert codes(lint("def f(a=[]):\n    return a\n")) == ["L003"]
        assert lint("def f(a=None):\n    return a\n") == []

    def test_l004_none_comparison(self):
        assert codes(lint("def f(a):\n    return a == None\n")) == ["L004"]
        assert lint("def f(a):\n    return a is None\n") == []

    def test_l005_fstring_no_placeholder(self):
        assert codes(lint('def f():\n    return f"static"\n')) == ["L005"]
        assert lint('def f(x):\n    return f"{x}"\n') == []

    def test_l006_wall_clock_spellings(self):
        assert codes(
            lint("import time\n\ndef f():\n    return time.time()\n")
        ) == ["L006"]
        assert codes(
            lint("from time import time\n\ndef f():\n    return time()\n")
        ) == ["L006"]

    def test_l006_module_alias_blind_spot_fixed(self):
        # the satellite regression: `import time as t; t.time()` escaped
        # the literal matcher before the module-alias table existed
        assert codes(
            lint("import time as t\n\ndef f():\n    return t.time()\n")
        ) == ["L006"]

    def test_l006_function_local_alias(self):
        src = "def f():\n    import time as clock\n    return clock.time()\n"
        assert codes(lint(src)) == ["L006"]

    def test_l006_monotonic_clean(self):
        assert lint(
            "import time\n\ndef f():\n    return time.monotonic()\n"
        ) == []

    def test_l006_not_in_benches(self):
        src = "import time\n\ndef f():\n    return time.time()\n"
        assert lint(src, rel="bench_x.py", library=False) == []

    def test_l007_bare_block_until_ready(self):
        src = "def f(x):\n    x.block_until_ready()\n"
        assert codes(lint(src)) == ["L007"]

    def test_l007_used_result_clean(self):
        assert lint("def f(x):\n    return x.block_until_ready()\n") == []

    def test_l008_non_atomic_persist(self):
        src = "import json\n\ndef f(d, fh):\n    json.dump(d, fh)\n"
        assert codes(lint(src)) == ["L008"]
        src = "import numpy as np\n\ndef f(p, a):\n    np.savez(p, a=a)\n"
        assert codes(lint(src)) == ["L008"]

    def test_l008_blessed_writer_exempt(self):
        src = "import json\n\ndef f(d, fh):\n    json.dump(d, fh)\n"
        assert lint(src, rel="photon_ml_tpu/utils/atomic.py") == []

    def test_l009_print_in_library(self):
        assert codes(lint('def f():\n    print("x")\n')) == ["L009"]

    def test_l009_cli_exempt(self):
        assert lint(
            'def f():\n    print("x")\n', rel="photon_ml_tpu/cli/train.py"
        ) == []

    def test_l010_syncs_in_hot_path(self):
        rel = "photon_ml_tpu/serving/engine.py"
        assert codes(lint("def f(x):\n    return float(x)\n", rel)) == [
            "L010"
        ]
        assert codes(
            lint(
                "import numpy as np\n\ndef f(x):\n    return np.asarray(x)\n",
                rel,
            )
        ) == ["L010"]
        assert codes(
            lint(
                "import jax\n\ndef f(x):\n    return jax.device_get(x)\n",
                rel,
            )
        ) == ["L010"]

    def test_l010_constant_float_and_cold_module_clean(self):
        rel = "photon_ml_tpu/serving/engine.py"
        assert lint('def f():\n    return float("1.5")\n', rel) == []
        assert lint("def f(x):\n    return float(x)\n") == []

    def test_l011_bare_jit_spellings(self):
        rel = "photon_ml_tpu/game/util.py"
        assert codes(
            lint("import jax\n\ndef f(g):\n    return jax.jit(g)\n", rel)
        ) == ["L011"]
        assert codes(
            lint(
                "import jax\n\n@jax.jit\ndef f(x):\n    return x\n", rel
            )
        ) == ["L011"]
        assert codes(
            lint(
                "from jax import jit\n\ndef f(g):\n    return jit(g)\n", rel
            )
        ) == ["L011"]

    def test_l011_allowlist_and_instrumented_clean(self):
        src = "import jax\n\ndef f(g):\n    return jax.jit(g)\n"
        assert lint(src, rel="photon_ml_tpu/parallel/multihost.py") == []
        src = (
            "from photon_ml_tpu.telemetry.xla import instrumented_jit\n\n"
            'def f(g):\n    return instrumented_jit(g, name="f")\n'
        )
        assert lint(src, rel="photon_ml_tpu/game/util.py") == []

    def test_l012_device_put_and_pmap(self):
        rel = "photon_ml_tpu/parallel/x.py"
        assert codes(
            lint(
                "import jax\n\ndef f(x):\n    return jax.device_put(x)\n",
                rel,
            )
        ) == ["L012"]
        assert codes(
            lint("import jax\n\ndef f(g):\n    return jax.pmap(g)\n", rel)
        ) == ["L012"]

    def test_l012_explicit_placement_clean(self):
        rel = "photon_ml_tpu/parallel/x.py"
        assert lint(
            "import jax\n\ndef f(x, s):\n    return jax.device_put(x, s)\n",
            rel,
        ) == []
        assert lint(
            "import jax\n\n"
            "def f(x, s):\n    return jax.device_put(x, device=s)\n",
            rel,
        ) == []


# ---------------------------------------------------------------------------
# Single-parse syntax phase
# ---------------------------------------------------------------------------


class TestSyntaxPhase:
    def test_syntax_error_is_a_finding_and_rest_still_runs(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/bad.py": "def broken(:\n    pass\n",
                "photon_ml_tpu/good.py": "import os\n",
            },
        )
        got = {(f.path, f.code) for f in res.findings}
        assert ("photon_ml_tpu/bad.py", "SYNTAX") in got
        # the other file was linted from the same single parse
        assert ("photon_ml_tpu/good.py", "L001") in got


# ---------------------------------------------------------------------------
# Suppressions + baseline
# ---------------------------------------------------------------------------


class TestSuppressions:
    def test_noqa_suppresses_exact_line_and_code(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/m.py": (
                    'def f():\n    print("x")  # photon: noqa[L009]\n'
                ),
            },
        )
        assert res.findings == []

    def test_wrong_code_does_not_suppress(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/m.py": (
                    'def f():\n    print("x")  # photon: noqa[L008]\n'
                ),
            },
        )
        assert codes(res.findings) == ["L009", "W001"]

    def test_unused_suppression_warns(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/m.py": (
                    "def f():\n    return 1  # photon: noqa[L009]\n"
                ),
            },
        )
        assert codes(res.findings) == ["W001"]
        assert "unused suppression" in res.findings[0].message

    def test_noqa_inside_string_literal_is_inert(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/m.py": (
                    'SNIPPET = "x = 1  # photon: noqa[L009]"\n'
                ),
            },
        )
        assert res.findings == []  # neither suppresses nor warns W001

    def test_multi_code_suppression(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/serving/__init__.py": "",
                "photon_ml_tpu/serving/engine.py": (
                    "def f(x):\n"
                    "    return float(x)  # photon: noqa[L010,L013]\n"
                ),
            },
        )
        # L010 used; L013 never fires on a per-file-covered module -> W001
        assert codes(res.findings) == ["W001"]


class TestBaseline:
    FILES = {
        "photon_ml_tpu/__init__.py": "",
        "photon_ml_tpu/m.py": 'def f():\n    print("x")\n',
    }

    def test_grandfathered_finding_passes(self, tmp_path):
        first = analyze(tmp_path, self.FILES)
        assert codes(first.findings) == ["L009"]
        baseline = {f.key() for f in first.findings}
        again = driver.analyze(
            str(tmp_path), baseline=baseline, require_seeds=False
        )
        assert again.findings == [] and len(again.grandfathered) == 1

    def test_new_finding_still_fails(self, tmp_path):
        first = analyze(tmp_path, self.FILES)
        baseline = {f.key() for f in first.findings}
        write_tree(
            tmp_path,
            {"photon_ml_tpu/m2.py": "import os\n"},
        )
        res = driver.analyze(
            str(tmp_path), baseline=baseline, require_seeds=False
        )
        assert codes(res.findings) == ["L001"]

    def test_stale_baseline_reported(self, tmp_path):
        write_tree(tmp_path, {"photon_ml_tpu/__init__.py": ""})
        baseline = {("photon_ml_tpu/gone.py", "L009", "whatever")}
        res = driver.analyze(
            str(tmp_path), baseline=baseline, require_seeds=False
        )
        assert res.findings == []
        assert res.stale_baseline == [
            ("photon_ml_tpu/gone.py", "L009", "whatever")
        ]

    def test_second_occurrence_of_baselined_rule_still_fails(self, tmp_path):
        # multiset semantics: one grandfathered print() must NOT
        # green-light a second, new print() in the same file — per-file
        # rules have constant messages, so set semantics would
        # (code-review regression)
        first = analyze(tmp_path, self.FILES)
        baseline = {f.key(): 1 for f in first.findings}
        write_tree(
            tmp_path,
            {
                "photon_ml_tpu/m.py": (
                    'def f():\n    print("x")\n\n\n'
                    'def g():\n    print("y")\n'
                ),
            },
        )
        res = driver.analyze(
            str(tmp_path), baseline=baseline, require_seeds=False
        )
        assert codes(res.findings) == ["L009"]
        assert len(res.grandfathered) == 1
        assert res.stale_baseline == []

    def test_baseline_survives_line_drift(self, tmp_path):
        # L015 messages embed write line numbers; Finding.key() normalizes
        # digits so pure line drift cannot resurrect a grandfathered
        # finding (code-review regression)
        files = _batcher_tree(
            "self._pending_rows -= 1", "self._pending_rows += 1"
        )
        first = analyze(tmp_path, files)
        assert codes(first.findings) == ["L015"]
        baseline = {f.key() for f in first.findings}
        mod = tmp_path / "photon_ml_tpu" / "serving" / "batcher.py"
        mod.write_text(
            "# a new leading comment shifts every line\n" + mod.read_text()
        )
        res = driver.analyze(
            str(tmp_path), baseline=baseline, require_seeds=False
        )
        assert res.findings == []
        assert len(res.grandfathered) == 1
        assert res.stale_baseline == []

    def test_write_baseline_keeps_grandfathered_entries(self, tmp_path):
        # refreshing a baseline WITH --baseline on the command line must
        # not drop previously-accepted findings (code-review regression)
        write_tree(tmp_path, self.FILES)
        b1, b2 = tmp_path / "a1.json", tmp_path / "a2.json"
        subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path),
             "--write-baseline", str(b1)],
            capture_output=True, text=True, timeout=120, check=True,
        )
        subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path),
             "--baseline", str(b1), "--write-baseline", str(b2)],
            capture_output=True, text=True, timeout=120, check=True,
        )
        assert {k[1] for k in core.load_baseline(str(b2))} == {"L009"}

    def test_baseline_file_round_trip(self, tmp_path):
        write_tree(tmp_path, self.FILES)
        base_path = tmp_path / "accepted.json"
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path),
             "--write-baseline", str(base_path)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        loaded = core.load_baseline(str(base_path))
        assert {k[1] for k in loaded} == {"L009"}
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path),
             "--baseline", str(base_path), "--no-external"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Call graph (pass 1)
# ---------------------------------------------------------------------------


class TestCallGraph:
    def test_module_names(self):
        assert module_name_for("photon_ml_tpu/serving/engine.py") == (
            "photon_ml_tpu.serving.engine", False,
        )
        assert module_name_for("photon_ml_tpu/serving/__init__.py") == (
            "photon_ml_tpu.serving", True,
        )

    def test_reexport_self_method_and_nested_resolution(self, tmp_path):
        g = graph_of(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/impl.py": (
                    "def real(x):\n    return x\n"
                ),
                "photon_ml_tpu/api.py": "from photon_ml_tpu.impl import real\n",
                "photon_ml_tpu/user.py": (
                    "from photon_ml_tpu import api\n\n"
                    "class C:\n"
                    "    def a(self):\n"
                    "        return self.b()\n\n"
                    "    def b(self):\n"
                    "        return api.real(1)\n\n"
                    "def outer():\n"
                    "    def inner():\n"
                    "        return 2\n"
                    "    return inner()\n"
                ),
            },
        )
        a = g.functions["photon_ml_tpu.user.C.a"]
        assert [t for t, _ in g.callees(a.qname)] == [
            "photon_ml_tpu.user.C.b"
        ]
        b_edges = [t for t, _ in g.callees("photon_ml_tpu.user.C.b")]
        assert b_edges == ["photon_ml_tpu.impl.real"]  # through the re-export
        outer_edges = [t for t, _ in g.callees("photon_ml_tpu.user.outer")]
        assert "photon_ml_tpu.user.outer.inner" in outer_edges

    def test_external_names_resolve_dotted(self, tmp_path):
        g = graph_of(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/m.py": (
                    "import time as t\n\n"
                    "def f():\n    return t.monotonic()\n"
                ),
            },
        )
        fn = g.functions["photon_ml_tpu.m.f"]
        assert fn.calls[0][0] == "time.monotonic"


# ---------------------------------------------------------------------------
# L013 hot-path propagation (pass 2)
# ---------------------------------------------------------------------------

_SYNC_TREE = {
    "photon_ml_tpu/__init__.py": "",
    "photon_ml_tpu/serving/__init__.py": "",
    "photon_ml_tpu/serving/engine.py": (
        "from photon_ml_tpu.utils.convert import as_scalar\n\n\n"
        "class ScoringEngine:\n"
        "    def score_rows(self, rows):\n"
        "        return as_scalar(rows)\n"
    ),
    "photon_ml_tpu/utils/__init__.py": "",
    "photon_ml_tpu/utils/convert.py": (
        "def as_scalar(x):\n    return float(x)\n"
    ),
}


class TestHotPathL013:
    def test_transitive_sync_flagged_with_chain(self, tmp_path):
        res = analyze(tmp_path, _SYNC_TREE)
        assert codes(res.findings) == ["L013"]
        f = res.findings[0]
        assert f.path == "photon_ml_tpu/utils/convert.py"
        assert f.chain == (
            "serving.engine.ScoringEngine.score_rows",
            "utils.convert.as_scalar",
        )
        assert "float() on a non-constant" in f.message

    def test_two_hop_chain(self, tmp_path):
        files = dict(_SYNC_TREE)
        files["photon_ml_tpu/utils/convert.py"] = (
            "def as_scalar(x):\n    return _inner(x)\n\n\n"
            "def _inner(x):\n    return float(x)\n"
        )
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L013"]
        assert res.findings[0].chain == (
            "serving.engine.ScoringEngine.score_rows",
            "utils.convert.as_scalar",
            "utils.convert._inner",
        )

    def test_sanctioned_sync_fetch_not_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/serving/__init__.py": "",
                "photon_ml_tpu/serving/engine.py": (
                    "from photon_ml_tpu.telemetry.device import sync_fetch\n"
                    "\n\n"
                    "class ScoringEngine:\n"
                    "    def score_rows(self, rows):\n"
                    "        return sync_fetch(rows)\n"
                ),
                "photon_ml_tpu/telemetry/__init__.py": "",
                "photon_ml_tpu/telemetry/device.py": (
                    "import numpy as np\n\n\n"
                    "def sync_fetch(x, label=None):\n"
                    "    return np.asarray(x)\n"
                ),
            },
        )
        assert res.findings == []

    def test_unreachable_sync_not_flagged(self, tmp_path):
        files = dict(_SYNC_TREE)
        files["photon_ml_tpu/serving/engine.py"] = (
            "class ScoringEngine:\n"
            "    def score_rows(self, rows):\n"
            "        return rows\n"
        )
        res = analyze(tmp_path, files)
        assert res.findings == []

    def test_transitive_bare_jit_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/game/__init__.py": "",
                "photon_ml_tpu/game/solver.py": (
                    "from photon_ml_tpu.utils.compile import make_fast\n\n\n"
                    "def solve(f):\n    return make_fast(f)\n"
                ),
                "photon_ml_tpu/utils/__init__.py": "",
                "photon_ml_tpu/utils/compile.py": (
                    "import jax\n\n\n"
                    "def make_fast(f):\n    return jax.jit(f)\n"
                ),
            },
        )
        assert codes(res.findings) == ["L013"]
        f = res.findings[0]
        assert f.path == "photon_ml_tpu/utils/compile.py"
        assert f.chain == (
            "game.solver.solve", "utils.compile.make_fast",
        )
        assert "instrumented_jit" in f.message

    def test_missing_seed_is_w002(self, tmp_path):
        write_tree(tmp_path, {"photon_ml_tpu/__init__.py": ""})
        res = driver.analyze(str(tmp_path), require_seeds=True)
        assert "W002" in codes(res.findings)
        assert any("SYNC_SEEDS" in f.message for f in res.findings)
        # the jit scope gets the same rename guard as the sync seeds
        assert any("L011 hot file" in f.message for f in res.findings)
        assert any("L011 hot dir" in f.message for f in res.findings)


# ---------------------------------------------------------------------------
# L014 jit-purity (pass 3)
# ---------------------------------------------------------------------------


class TestJitPurityL014:
    def test_wall_clock_through_chain(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/solver.py": (
                    "import time\n\n"
                    "import jax\n\n\n"
                    "def _scale(x):\n"
                    "    return x * time.monotonic()\n\n\n"
                    "def build():\n"
                    "    def run(x):\n"
                    "        return _scale(x) + 1\n"
                    "    return jax.jit(run)\n"
                ),
            },
        )
        assert codes(res.findings) == ["L014"]
        f = res.findings[0]
        assert f.path == "photon_ml_tpu/solver.py"
        assert "time.monotonic" in f.message
        assert f.chain == ("solver.build.run", "solver._scale")

    def test_telemetry_counter_in_while_loop_body(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": (
                    "from photon_ml_tpu.telemetry.metrics import counter\n"
                ),
                "photon_ml_tpu/telemetry/__init__.py": "",
                "photon_ml_tpu/telemetry/metrics.py": (
                    "def counter(name):\n    return name\n"
                ),
                "photon_ml_tpu/loop.py": (
                    "from jax import lax\n\n"
                    "from photon_ml_tpu.telemetry.metrics import counter\n"
                    "\n\n"
                    "def solve(x):\n"
                    "    def body(s):\n"
                    '        counter("iters")\n'
                    "        return s\n\n"
                    "    def cond(s):\n"
                    "        return s\n\n"
                    "    return lax.while_loop(cond, body, x)\n"
                ),
            },
        )
        assert codes(res.findings) == ["L014"]
        assert "records telemetry (counter)" in res.findings[0].message

    def test_global_mutation_and_decorator_form(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/telemetry/__init__.py": "",
                "photon_ml_tpu/telemetry/xla.py": (
                    "def instrumented_jit(fn=None, **kw):\n"
                    "    return fn\n"
                ),
                "photon_ml_tpu/m.py": (
                    "from photon_ml_tpu.telemetry.xla import "
                    "instrumented_jit\n\n"
                    "_CALLS = 0\n\n\n"
                    '@instrumented_jit(name="m")\n'
                    "def traced(x):\n"
                    "    global _CALLS\n"
                    "    _CALLS += 1\n"
                    "    return x\n"
                ),
            },
        )
        assert codes(res.findings) == ["L014"]
        assert "module global" in res.findings[0].message

    def test_vmap_wrapper_unwrapped(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/telemetry/__init__.py": "",
                "photon_ml_tpu/telemetry/xla.py": (
                    "def instrumented_jit(fn=None, **kw):\n"
                    "    return fn\n"
                ),
                "photon_ml_tpu/v.py": (
                    "import jax\n\n"
                    "from photon_ml_tpu.telemetry.xla import "
                    "instrumented_jit\n\n\n"
                    "def solve_one(x):\n"
                    '    print("solving")\n'
                    "    return x\n\n\n"
                    "def build():\n"
                    "    return instrumented_jit(\n"
                    '        jax.vmap(solve_one), name="v"\n'
                    "    )\n"
                ),
            },
        )
        # print inside the traced function: one L014; the local L009 for
        # bare print in library code also fires — both are correct
        assert codes(res.findings) == ["L009", "L014"]
        l014 = [f for f in res.findings if f.code == "L014"][0]
        assert "prints to stdout" in l014.message

    def test_pure_traced_function_clean(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/pure.py": (
                    "import jax\n\n\n"
                    "def build():\n"
                    "    def run(x):\n"
                    "        return x * 2\n"
                    "    return jax.jit(run)\n"
                ),
            },
        )
        # the bare jit is outside any hot dir, and run is pure
        assert res.findings == []


# ---------------------------------------------------------------------------
# L015 lock discipline (pass 4)
# ---------------------------------------------------------------------------


def _batcher_tree(write_stmt: str, public_stmt: str) -> dict:
    return {
        "photon_ml_tpu/__init__.py": "",
        "photon_ml_tpu/serving/__init__.py": "",
        "photon_ml_tpu/serving/batcher.py": (
            "import threading\n\n\n"
            "class MicroBatcher:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._pending_rows = 0\n"
            "        self._thread = None\n\n"
            "    def start(self):\n"
            "        self._thread = threading.Thread(target=self._loop)\n"
            "        self._thread.start()\n\n"
            "    def submit(self, rows):\n"
            f"        {public_stmt}\n\n"
            "    def _loop(self):\n"
            f"        {write_stmt}\n"
        ),
    }


class TestLockDisciplineL015:
    def test_unlocked_cross_thread_write_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            _batcher_tree(
                "self._pending_rows -= 1", "self._pending_rows += 1"
            ),
        )
        assert codes(res.findings) == ["L015"]
        f = res.findings[0]
        assert "`self._pending_rows`" in f.message
        assert "MicroBatcher" in f.message

    def test_locked_writes_clean(self, tmp_path):
        res = analyze(
            tmp_path,
            _batcher_tree(
                "with self._lock:\n            self._pending_rows -= 1",
                "with self._lock:\n            self._pending_rows += 1",
            ),
        )
        assert res.findings == []

    def test_condition_variable_counts_as_lock(self, tmp_path):
        res = analyze(
            tmp_path,
            _batcher_tree(
                "with self._cv:\n            self._pending_rows -= 1",
                "with self._cv:\n            self._pending_rows += 1",
            ),
        )
        assert res.findings == []

    def test_one_unlocked_side_still_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            _batcher_tree(
                "with self._lock:\n            self._pending_rows -= 1",
                "self._pending_rows += 1",
            ),
        )
        assert codes(res.findings) == ["L015"]

    def test_public_only_attr_not_flagged(self, tmp_path):
        # self._thread is written in start()/__init__ but never from the
        # thread side: not a cross-thread attribute
        res = analyze(
            tmp_path,
            _batcher_tree("pass", "self._pending_rows += 1"),
        )
        assert res.findings == []

    def test_tuple_and_subscript_writes_detected(self, tmp_path):
        res = analyze(
            tmp_path,
            _batcher_tree(
                "self._pending_rows, self._x = 0, 1",
                "self._pending_rows[0] = 1",
            ),
        )
        assert codes(res.findings) == ["L015"]
        assert "`self._pending_rows`" in res.findings[0].message

    def test_no_thread_spawn_no_findings(self, tmp_path):
        res = analyze(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/plain.py": (
                    "class Plain:\n"
                    "    def a(self):\n"
                    "        self._x = 1\n\n"
                    "    def _b(self):\n"
                    "        self._x = 2\n"
                ),
            },
        )
        assert res.findings == []


# ---------------------------------------------------------------------------
# Acceptance demos (ISSUE 7): the real CLI flips to exit 1 on the
# demonstration diffs and names the chain / the attribute
# ---------------------------------------------------------------------------


class TestAcceptanceDemos:
    def _run(self, root):
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(root), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        return proc, json.loads(proc.stdout)

    def test_sync_in_util_reachable_from_score_rows_fails_gate(
        self, tmp_path
    ):
        write_tree(tmp_path, _SYNC_TREE)
        proc, doc = self._run(tmp_path)
        assert proc.returncode == 1
        (finding,) = doc["findings"]
        assert finding["code"] == "L013"
        assert finding["path"] == "photon_ml_tpu/utils/convert.py"
        assert finding["chain"] == [
            "serving.engine.ScoringEngine.score_rows",
            "utils.convert.as_scalar",
        ]

    def test_unlocked_microbatcher_write_fails_gate(self, tmp_path):
        write_tree(
            tmp_path,
            _batcher_tree(
                "self._pending_rows -= 1", "self._pending_rows += 1"
            ),
        )
        proc, doc = self._run(tmp_path)
        assert proc.returncode == 1
        (finding,) = doc["findings"]
        assert finding["code"] == "L015"
        assert "_pending_rows" in finding["message"]

    def test_clean_tree_exits_zero_with_schema(self, tmp_path):
        write_tree(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/ok.py": "def f(x):\n    return x\n",
            },
        )
        proc, doc = self._run(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert doc["version"] == 1
        assert doc["findings"] == []
        assert doc["counts"] == {}
        assert doc["files"] == 2
        assert doc["graph"]["modules"] == 2
        assert set(doc) >= {
            "version", "root", "files", "findings", "grandfathered",
            "stale_baseline", "counts", "graph",
        }


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))


# ---------------------------------------------------------------------------
# ISSUE 8: the sweep subsystem is inside the gate
# ---------------------------------------------------------------------------


_SWEEP_TREE = {
    "photon_ml_tpu/__init__.py": "",
    "photon_ml_tpu/telemetry/__init__.py": "",
    "photon_ml_tpu/telemetry/xla.py": (
        "def instrumented_jit(fn, name=None, multi_shape=False):\n"
        "    return fn\n"
    ),
    "photon_ml_tpu/sweep/__init__.py": "",
    # the sweep runner idiom: a closure factory returning
    # instrumented_jit(run) where run vmaps a per-config solve body —
    # with a wall-clock read planted in the traced inner loop
    "photon_ml_tpu/sweep/runner.py": (
        "import time\n\n"
        "import jax\n\n"
        "from photon_ml_tpu.telemetry.xla import instrumented_jit\n\n\n"
        "def _tick(w):\n"
        "    return w * time.time()\n\n\n"
        "def _sweep_solver():\n"
        "    def run(w0, l2s):\n"
        "        def one(w_g, l2_g):\n"
        "            return _tick(w_g) + l2_g\n"
        "        return jax.vmap(one)(w0, l2s)\n"
        "    return instrumented_jit(run, name='sweep_fe_solve',\n"
        "                            multi_shape=True)\n"
    ),
}


class TestSweepGateRegistration:
    def test_sweep_modules_are_l011_hot(self):
        assert local.is_l011_hot("photon_ml_tpu/sweep/runner.py")
        assert local.is_l011_hot("photon_ml_tpu/sweep/select.py")

    def test_bare_jit_in_sweep_runner_is_l011(self):
        src = (
            "import jax\n\n"
            "def solver(fn):\n"
            "    return jax.jit(fn)\n"
        )
        assert "L011" in codes(lint(src, rel="photon_ml_tpu/sweep/runner.py"))

    def test_l014_discovers_vmapped_sweep_solver_as_traced_root(
        self, tmp_path
    ):
        """The closure-factory + vmap idiom the real sweep runner uses
        must be resolvable: instrumented_jit(run) -> run -> one (the
        vmapped per-config body) -> helpers."""
        from tools.analysis import jitpurity

        g = graph_of(tmp_path, _SWEEP_TREE)
        roots = {r[0] for r in jitpurity.trace_roots(g)}
        assert "photon_ml_tpu.sweep.runner._sweep_solver.run" in roots

    def test_planted_wall_clock_in_sweep_inner_loop_fails_gate(
        self, tmp_path
    ):
        """ISSUE 8 satellite acceptance: a time.time() in the sweep inner
        loop fails the REAL CLI with the chain from the traced root."""
        write_tree(tmp_path, _SWEEP_TREE)
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        l014 = [f for f in doc["findings"] if f["code"] == "L014"]
        assert l014, doc["findings"]
        (finding,) = l014
        assert finding["path"] == "photon_ml_tpu/sweep/runner.py"
        assert "wall clock" in finding["message"]
        assert finding["chain"] == [
            "sweep.runner._sweep_solver.run",
            "sweep.runner._sweep_solver.run.one",
            "sweep.runner._tick",
        ]

    def test_real_sweep_runner_solvers_are_traced_roots(self):
        """On the REAL tree, every sweep executable registers through
        instrumented_jit and is discovered by the purity pass."""
        from tools.analysis import jitpurity
        from tools.analysis.callgraph import build_graph
        from tools.analysis.core import load_source

        srcs = []
        pkg = os.path.join(REPO, "photon_ml_tpu", "sweep")
        for name in sorted(os.listdir(pkg)):
            if name.endswith(".py"):
                rel = os.path.join("photon_ml_tpu", "sweep", name)
                srcs.append(load_source(rel, os.path.join(REPO, rel)))
        # the xla shim so instrumented_jit resolves inside the mini-graph
        srcs.append(
            load_source(
                os.path.join("photon_ml_tpu", "telemetry", "xla.py"),
                os.path.join(REPO, "photon_ml_tpu", "telemetry", "xla.py"),
            )
        )
        g = build_graph(srcs)
        roots = {r[0] for r in jitpurity.trace_roots(g)}
        for expected in (
            "photon_ml_tpu.sweep.runner._fe_sweep_solver.run",
            "photon_ml_tpu.sweep.runner._re_sweep_solver.run",
            "photon_ml_tpu.sweep.select._sweep_evaluator.run",
        ):
            assert expected in roots, sorted(roots)


def _ingest_stream_tree(worker_stmt: str, public_stmt: str) -> dict:
    """A ChunkStream-shaped fixture: decode worker threads + a public
    iterator API sharing pipeline state — the exact class shape the new
    ingest subsystem introduces; L015 must cover it from day one."""
    return {
        "photon_ml_tpu/__init__.py": "",
        "photon_ml_tpu/ingest/__init__.py": "",
        "photon_ml_tpu/ingest/pipeline.py": (
            "import threading\n\n\n"
            "class ChunkStream:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._queue_depth = 0\n"
            "        self._threads = []\n\n"
            "    def start(self):\n"
            "        t = threading.Thread(target=self._decode_loop)\n"
            "        self._threads.append(t)\n"
            "        t.start()\n\n"
            "    def _decode_loop(self):\n"
            f"        {worker_stmt}\n\n"
            "    def __next__(self):\n"
            f"        {public_stmt}\n"
        ),
    }


class TestLockDisciplineIngestL015:
    def test_unlocked_decode_worker_attr_flagged(self, tmp_path):
        """An attribute written by both a decode worker thread and the
        ChunkStream public iterator without a lock is an L015 finding
        naming the attribute and both sides."""
        res = analyze(
            tmp_path,
            _ingest_stream_tree(
                "self._queue_depth += 1", "self._queue_depth -= 1"
            ),
        )
        assert codes(res.findings) == ["L015"]
        f = res.findings[0]
        assert "`self._queue_depth`" in f.message
        assert "ChunkStream" in f.message
        assert "_decode_loop" in f.message

    def test_locked_both_sides_clean(self, tmp_path):
        res = analyze(
            tmp_path,
            _ingest_stream_tree(
                "with self._lock:\n            self._queue_depth += 1",
                "with self._lock:\n            self._queue_depth -= 1",
            ),
        )
        assert res.findings == []

    def test_real_ingest_package_is_in_scope(self):
        """The shipped photon_ml_tpu/ingest/ package must be inside the
        L011 hot scope (which seeds the interprocedural jit pass) so its
        device programs stay accounted."""
        from tools.analysis import local

        rel = os.path.join("photon_ml_tpu", "ingest", "pipeline.py")
        assert local.is_l011_hot(rel)


# ---------------------------------------------------------------------------
# L016 fault-point test coverage (tools/analysis/faultcov.py)
# ---------------------------------------------------------------------------


class TestFaultCoverageL016:
    """Every registered fault point must be named by a test literal —
    an unarmed injection seam is untested recovery code wearing a
    coverage badge."""

    PKG = """
        from photon_ml_tpu import faults

        _FP = faults.register_point("pkg.seam.covered", write_path=True)
        _FP2 = faults.register_point("pkg.seam.orphan")
    """

    def _run(self, tmp_path, files):
        from tools.analysis import faultcov

        write_tree(tmp_path, files)
        srcs = [
            core.load_source(rel, str(tmp_path / rel)) for rel in files
        ]
        return faultcov.run(srcs)

    def test_uncovered_point_flagged_with_its_id(self, tmp_path):
        findings = self._run(tmp_path, {
            "photon_ml_tpu/mod.py": self.PKG,
            "tests/test_mod.py": """
                def test_covered():
                    assert "pkg.seam.covered" in CATALOG
            """,
        })
        assert codes(findings) == ["L016"]
        assert "pkg.seam.orphan" in findings[0].message
        assert findings[0].path == "photon_ml_tpu/mod.py"

    def test_coverage_via_json_plan_literal_counts(self, tmp_path):
        # a substring inside an env-transported JSON plan blob covers too
        findings = self._run(tmp_path, {
            "photon_ml_tpu/mod.py": self.PKG,
            "tests/test_mod.py": """
                PLAN = '{"rules": [{"point": "pkg.seam.covered"}]}'

                def test_orphan_armed():
                    arm('{"rules": [{"point": "pkg.seam.orphan"}]}')
            """,
        })
        assert findings == []

    def test_non_literal_registration_is_flagged(self, tmp_path):
        findings = self._run(tmp_path, {
            "photon_ml_tpu/mod.py": """
                from photon_ml_tpu import faults

                NAME = "dyn" + ".seam"
                _FP = faults.register_point(NAME)
            """,
            "tests/test_mod.py": "LIT = 'dyn.seam'\n",
        })
        assert codes(findings) == ["L016"]
        assert "non-literal" in findings[0].message

    def test_tree_without_tests_is_skipped(self, tmp_path):
        # reduced fixture trees carry no tests/ — the pass must not
        # flag every point as uncovered there
        findings = self._run(tmp_path, {
            "photon_ml_tpu/mod.py": self.PKG,
        })
        assert findings == []

    def test_driver_runs_l016_only_on_real_trees(self, tmp_path):
        # require_seeds=False (reduced fixture tree) skips the pass...
        res = analyze(tmp_path, {
            "photon_ml_tpu/__init__.py": "",
            "photon_ml_tpu/mod.py": self.PKG,
            "tests/test_mod.py": "LIT = 'pkg.seam.covered'\n",
        })
        assert "L016" not in codes(res.findings)

    def test_real_tree_catalog_satisfies_l016(self):
        """The shipped package's own registry passes: every registered
        point is named by at least one test literal (the EXPECTED_POINTS
        catalog in tests/test_faults.py keeps this true by construction)."""
        from tools.analysis import faultcov

        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(driver.__file__))))
        files = [
            core.load_source(os.path.relpath(p, root), p)
            for p in driver.source_files(root)
        ]
        assert faultcov.run(files) == []


# ---------------------------------------------------------------------------
# ISSUE 13: fleet observability joins the analysis scope
# ---------------------------------------------------------------------------


_FLEET_OBS_TREE = {
    "photon_ml_tpu/__init__.py": "",
    "photon_ml_tpu/telemetry/__init__.py": "",
    # the supervisor's tail parser with a PLANTED device sync: the status
    # thread must never touch a device, so the L013 walk seeded at
    # tail_heartbeat_fields has to flag it
    "photon_ml_tpu/telemetry/progress.py": (
        "import json\n\n"
        "import numpy as np\n\n\n"
        "def tail_heartbeat_fields(path, max_bytes=65536,\n"
        "                          expect_proc=None):\n"
        "    with open(path, 'rb') as fh:\n"
        "        tail = fh.read()\n"
        "    rec = json.loads(tail.splitlines()[-1])\n"
        "    rec['rows'] = np.asarray(rec['rows'])\n"
        "    return rec\n"
    ),
}


class TestFleetObservabilityGate:
    def test_status_seeds_are_registered(self):
        from tools.analysis import hotpath

        for seed in (
            "photon_ml_tpu.telemetry.progress.tail_heartbeat_fields",
            "photon_ml_tpu.parallel.fleet_status.FleetStatusWriter"
            ".snapshot",
            "photon_ml_tpu.parallel.fleet_status.FleetStatusWriter"
            ".write_once",
        ):
            assert seed in hotpath.SYNC_SEEDS

    def test_planted_sync_in_tail_parser_flagged(self, tmp_path):
        res = analyze(tmp_path, _FLEET_OBS_TREE)
        assert codes(res.findings) == ["L013"]
        f = res.findings[0]
        assert f.path == "photon_ml_tpu/telemetry/progress.py"
        assert "np.asarray" in f.message
        assert f.chain == ("telemetry.progress.tail_heartbeat_fields",)

    def test_planted_sync_fails_the_real_cli(self, tmp_path):
        write_tree(tmp_path, _FLEET_OBS_TREE)
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        l013 = [f for f in doc["findings"] if f["code"] == "L013"]
        assert l013, doc["findings"]
        assert l013[0]["path"] == "photon_ml_tpu/telemetry/progress.py"

    def test_real_status_writer_passes_lock_discipline(self):
        """The REAL FleetStatusWriter (a thread-spawning class with
        supervisor-pushed shared state) carries no unlocked cross-thread
        writes (L015), and no sync reachable from its seeds (L013)."""
        from tools.analysis import hotpath, locks
        from tools.analysis.callgraph import build_graph

        rels = (
            os.path.join("photon_ml_tpu", "parallel", "fleet_status.py"),
            os.path.join("photon_ml_tpu", "parallel", "multihost.py"),
            os.path.join("photon_ml_tpu", "telemetry", "progress.py"),
            os.path.join("photon_ml_tpu", "telemetry", "identity.py"),
        )
        srcs = [core.load_source(rel, os.path.join(REPO, rel))
                for rel in rels]
        g = build_graph(srcs)
        assert (
            "photon_ml_tpu.parallel.fleet_status.FleetStatusWriter"
            in g.classes
        )
        assert locks.run(g) == []
        findings = hotpath.run(g, require_seeds=False)
        assert [f for f in findings if f.code == "L013"] == []


# ---------------------------------------------------------------------------
# ISSUE 16: the executable profiler's sampler joins the analysis scope
# ---------------------------------------------------------------------------


def _profiler_tree(fetch_stmt: str) -> dict:
    """A profile.py-shaped fixture: the dispatch sampler with its
    synchronizing fetch spelled ``fetch_stmt`` — bare np.asarray re-opens
    the fake-timing trap; routing through sync_fetch is sanctioned."""
    return {
        "photon_ml_tpu/__init__.py": "",
        "photon_ml_tpu/telemetry/__init__.py": "",
        "photon_ml_tpu/telemetry/device.py": (
            "import numpy as np\n\n\n"
            "def sync_fetch(x, label=None):\n"
            "    return np.asarray(x)\n"
        ),
        "photon_ml_tpu/telemetry/profile.py": (
            ("import numpy as np\n\n" if "np." in fetch_stmt else "")
            + ("from photon_ml_tpu.telemetry.device import "
               "sync_fetch\n\n\n" if "sync_fetch" in fetch_stmt else "")
            + "def profile_dispatch(rec, target, args, kwargs):\n"
            "    out = target(*args, **kwargs)\n"
            f"    {fetch_stmt}\n"
            "    return out\n"
        ),
    }


class TestProfilerGateRegistration:
    def test_sampler_seed_and_hot_file_are_registered(self):
        from tools.analysis import hotpath

        assert (
            "photon_ml_tpu.telemetry.profile.profile_dispatch"
            in hotpath.SYNC_SEEDS
        )
        rel = os.path.join("photon_ml_tpu", "telemetry", "profile.py")
        assert local.is_l011_hot(rel)

    def test_bare_asarray_in_sampler_fails_the_real_cli(self, tmp_path):
        """ISSUE 16 satellite acceptance: a bare np.asarray in the
        dispatch sampler — an unaccounted device sync on the hottest
        path in the process — flips the REAL CLI to exit 1."""
        write_tree(tmp_path, _profiler_tree("np.asarray(out)"))
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        l013 = [f for f in doc["findings"] if f["code"] == "L013"]
        assert l013, doc["findings"]
        (finding,) = l013
        assert finding["path"] == "photon_ml_tpu/telemetry/profile.py"
        assert "np.asarray" in finding["message"]
        assert finding["chain"] == ["telemetry.profile.profile_dispatch"]

    def test_sanctioned_sync_fetch_route_passes(self, tmp_path):
        write_tree(
            tmp_path,
            _profiler_tree("sync_fetch(out, label=rec.name)"),
        )
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout)["findings"] == []
