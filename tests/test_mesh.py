"""parallel/mesh.py + parallel/sharding.py unit coverage.

The ``shard_map_compat`` shim unbroke the 7 seed-failing distributed
tests (PR 5) but its two API branches were never directly tested: newer
jax exposes top-level ``jax.shard_map`` with ``check_vma`` (and some
releases spell it ``check_rep``), older jax only ships
``jax.experimental.shard_map.shard_map`` with ``check_rep``. Both
branches are pinned here via monkeypatched availability, plus one real
collective through whichever branch the installed jax provides.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.parallel import sharding as psharding
from photon_ml_tpu.parallel.mesh import make_mesh, shard_map_compat


@pytest.fixture
def mesh(multichip):
    return make_mesh({"data": 8})


# ---------------------------------------------------------------------------
# shard_map_compat: real execution through the installed branch
# ---------------------------------------------------------------------------


def test_compat_executes_a_psum(mesh):
    x = jnp.arange(8.0)

    def local_sum(block):
        return jax.lax.psum(jnp.sum(block), "data")

    f = shard_map_compat(local_sum, mesh, in_specs=P("data"), out_specs=P())
    assert float(jax.jit(f)(x)) == float(np.sum(np.arange(8.0)))


# ---------------------------------------------------------------------------
# shard_map_compat: branch selection via monkeypatched availability
# ---------------------------------------------------------------------------


def _call_through(mesh, check=False):
    return shard_map_compat(
        lambda x: x, mesh, in_specs=P("data"), out_specs=P("data"),
        check=check,
    )


def test_top_level_branch_uses_check_vma(monkeypatch, mesh):
    seen = {}

    def fake_shard_map(f, mesh, in_specs, out_specs, **kwargs):
        seen.update(kwargs)
        return lambda *a: "top-level"

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    assert _call_through(mesh, check=True)() == "top-level"
    assert seen == {"check_vma": True}


def test_top_level_branch_falls_back_to_check_rep_spelling(monkeypatch, mesh):
    calls = []

    def fake_shard_map(f, mesh, in_specs, out_specs, **kwargs):
        if "check_vma" in kwargs:
            raise TypeError("got an unexpected keyword argument 'check_vma'")
        calls.append(kwargs)
        return lambda *a: "old-keyword"

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    assert _call_through(mesh)() == "old-keyword"
    assert calls == [{"check_rep": False}]


def test_experimental_branch_uses_check_rep(monkeypatch, mesh):
    # no top-level jax.shard_map at all -> the jax.experimental path
    monkeypatch.delattr(jax, "shard_map", raising=False)
    import jax.experimental.shard_map as esm

    seen = {}

    def fake_shard_map(f, mesh, in_specs, out_specs, **kwargs):
        seen.update(kwargs)
        return lambda *a: "experimental"

    monkeypatch.setattr(esm, "shard_map", fake_shard_map)
    assert _call_through(mesh, check=True)() == "experimental"
    assert seen == {"check_rep": True}


# ---------------------------------------------------------------------------
# sharding primitives
# ---------------------------------------------------------------------------


def test_axis_resolution_named_and_legacy(multichip):
    named = make_mesh({"batch": 4, "model": 2})
    assert psharding.data_axis(named) == "batch"
    assert psharding.model_axis(named) == "model"
    legacy_data = make_mesh({"data": 8})
    assert psharding.data_axis(legacy_data) == "data"
    assert psharding.model_axis(legacy_data) is None
    legacy_entity = make_mesh({"entity": 8})
    assert psharding.data_axis(legacy_entity) is None
    assert psharding.model_axis(legacy_entity) == "entity"


def test_sharding_builders_reject_missing_axes(multichip):
    entity_only = make_mesh({"entity": 8})
    with pytest.raises(ValueError, match="batch/data axis"):
        psharding.batch_sharding(entity_only)
    batch_only = make_mesh({"batch": 8})
    with pytest.raises(ValueError, match="model/entity axis"):
        psharding.entity_sharding(batch_only)


def test_place_entities_shards_leading_axis(multichip):
    mesh = make_mesh({"model": 8})
    table = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    placed = psharding.place_entities(table, mesh)
    assert placed.sharding.spec == P("model")
    sizes = {s.data.shape for s in placed.addressable_shards}
    assert sizes == {(2, 4)}
    np.testing.assert_array_equal(np.asarray(placed), table)


def test_place_batch_pads_and_shards_sparse(rng, multichip):
    from photon_ml_tpu.ops.sparse import SparseBatch

    X = rng.normal(size=(13, 5)) * (rng.random((13, 5)) < 0.7)
    y = (rng.random(13) > 0.5).astype(float)
    batch = SparseBatch.from_dense(X, y)
    mesh = make_mesh({"batch": 8})
    placed = psharding.place_batch(batch, mesh)
    assert placed.num_rows % 8 == 0
    assert placed.nnz % 8 == 0
    # padded rows are inert: weights 0 beyond the original row count
    w = np.asarray(placed.weights)
    assert np.all(w[batch.num_rows:] == 0)
    # objective parity: padding must not change the value/grad
    from photon_ml_tpu.ops.objective import make_objective

    obj = make_objective("logistic", l2_weight=0.3)
    wvec = jnp.asarray(rng.normal(size=batch.num_features) * 0.1, jnp.float32)
    v0, g0 = obj.value_and_grad(wvec, batch)
    v1, g1 = obj.value_and_grad(wvec, placed)
    np.testing.assert_allclose(v1, v0, rtol=1e-5)
    np.testing.assert_allclose(g1, g0, rtol=1e-4, atol=1e-5)


def test_place_batch_pads_tiles(rng, multichip):
    from photon_ml_tpu.ops.tiled import TiledBatch

    n, d = 300, 40
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.4)
    y = (rng.random(n) > 0.5).astype(float)
    nz = np.nonzero(X)
    tb = TiledBatch.from_coo(
        values=X[nz], rows=nz[0], cols=nz[1], labels=y, num_features=d
    )
    mesh = make_mesh({"batch": 8})
    placed = psharding.place_batch(tb, mesh)
    assert placed.num_tiles % 8 == 0
    wvec = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    z_ref = np.asarray(tb.dot_rows(wvec))
    z = np.asarray(placed.dot_rows(wvec))
    np.testing.assert_allclose(z[: len(z_ref)], z_ref, rtol=1e-5, atol=1e-5)


def test_pad_count():
    assert psharding.pad_count(16, 8) == 16
    assert psharding.pad_count(17, 8) == 24
    assert psharding.pad_count(0, 8) == 0


def test_make_mesh_named_axes(multichip):
    mesh = make_mesh({"batch": 2, "model": 4})
    assert dict(mesh.shape) == {"batch": 2, "model": 4}
    assert isinstance(mesh, Mesh)
