"""Ingest pipeline tests: planner determinism, streamed-vs-in-core array
parity (native AND pure-Python fallback), stall/backpressure protocol,
capacity growth, resident-budget sizing, the out-of-core `cli train`
acceptance path, and the generic double buffer."""

import os
import time

import numpy as np
import pytest

from photon_ml_tpu.data.avro import (
    TRAINING_EXAMPLE_AVRO,
    read_game_dataset_from_avro,
    write_avro,
)
from photon_ml_tpu.ingest import (
    ChunkStream,
    IngestConfigError,
    IngestSpec,
    IngestStall,
    double_buffered,
    plan_chunks,
    read_game_dataset_streamed,
)


def _write_shards(tmp_path, rng, n_rows=1200, n_files=2, d=40, k=5,
                  block_records=128, codec="deflate"):
    """TrainingExampleAvro shard files with ids, weights and offsets."""
    paths = []
    per = n_rows // n_files
    row = 0
    for s in range(n_files):
        rows = per if s < n_files - 1 else n_rows - per * (n_files - 1)

        def recs(rows=rows):
            nonlocal row
            for _ in range(rows):
                yield {
                    "uid": str(row),
                    "label": float(row % 2),
                    "features": [
                        {"name": f"f{rng.integers(0, d)}", "term": "",
                         "value": float(rng.normal())}
                        for _ in range(k)
                    ],
                    "metadataMap": {"userId": str(row % 29)},
                    "weight": float(1.0 + (row % 3)),
                    "offset": float(row % 5) * 0.1,
                }
                row += 1

        p = str(tmp_path / f"shard-{s:02d}.avro")
        write_avro(p, TRAINING_EXAMPLE_AVRO, recs(),
                   block_records=block_records, codec=codec)
        paths.append(p)
    return paths


def _assert_datasets_equal(ds_a, ds_b):
    np.testing.assert_array_equal(ds_a.response, ds_b.response)
    np.testing.assert_array_equal(ds_a.offset, ds_b.offset)
    np.testing.assert_array_equal(ds_a.weight, ds_b.weight)
    for name in ds_b.feature_shards:
        a, b = ds_a.shard(name), ds_b.shard(name)
        assert a.num_features == b.num_features
        for leaf in ("values", "rows", "cols", "labels", "offsets",
                     "weights"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, leaf)), np.asarray(getattr(b, leaf)),
                err_msg=f"{name}.{leaf}",
            )
    assert set(ds_a.id_columns) == set(ds_b.id_columns)
    for c in ds_b.id_columns:
        np.testing.assert_array_equal(
            ds_a.id_columns[c].codes, ds_b.id_columns[c].codes
        )
        np.testing.assert_array_equal(
            ds_a.id_columns[c].vocab, ds_b.id_columns[c].vocab
        )


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


def test_planner_deterministic_and_block_aligned(tmp_path, rng):
    paths = _write_shards(tmp_path, rng, n_rows=900, n_files=2,
                          block_records=100)
    metas, plans = plan_chunks(paths, chunk_rows=250)
    metas2, plans2 = plan_chunks(paths, chunk_rows=250)
    assert plans == plans2  # the determinism contract resume relies on
    assert [p.index for p in plans] == list(range(len(plans)))
    assert sum(p.n_rows for p in plans) == 900
    # global row offsets are cumulative and gap-free
    off = 0
    for p in plans:
        assert p.row_start == off
        off += p.n_rows
    # chunks never span files, and each covers >= chunk_rows except a
    # file's tail chunk
    by_path = {}
    for p in plans:
        by_path.setdefault(p.path, []).append(p)
    for path, file_plans in by_path.items():
        for p in file_plans[:-1]:
            assert p.n_rows >= 250
    # byte ranges tile each file exactly from its first block
    for meta in metas:
        file_plans = by_path[meta.path]
        assert file_plans[0].byte_start == meta.header_end
        for a, b in zip(file_plans, file_plans[1:]):
            assert a.byte_end == b.byte_start
        assert file_plans[-1].byte_end == meta.file_bytes


def test_planner_stable_when_shard_list_grows(tmp_path, rng):
    """The incremental-retrain contract (ISSUE 14): appending delta
    files to the shard list must keep every OLD chunk's id, byte range,
    and global row offset — "yesterday's data ∪ today's delta" replays
    yesterday's prefix identically, so a checkpoint's next_chunk cursor
    stays valid across the grown list."""
    paths = _write_shards(tmp_path, rng, n_rows=900, n_files=2,
                          block_records=100)
    _, plans_old = plan_chunks(paths, chunk_rows=250)
    (tmp_path / "delta").mkdir()
    delta = _write_shards(tmp_path / "delta", rng, n_rows=300, n_files=1,
                          block_records=100)
    _, plans_new = plan_chunks(paths + delta, chunk_rows=250)
    assert len(plans_new) > len(plans_old)
    # the old plan IS a prefix of the grown plan, field for field
    assert plans_new[: len(plans_old)] == plans_old
    # appended chunks continue ids and row offsets gap-free
    off = sum(p.n_rows for p in plans_old)
    for i, p in enumerate(plans_new[len(plans_old):]):
        assert p.index == len(plans_old) + i
        assert p.row_start == off
        off += p.n_rows
    # per-host splits of the shared prefix are unchanged: the resume
    # contract holds for every fleet member under the grown file list
    from photon_ml_tpu.ingest import plans_for_host

    for nproc in (2, 3):
        for pid in range(nproc):
            old_split = plans_for_host(plans_old, pid, nproc)
            new_split = [
                p for p in plans_for_host(plans_new, pid, nproc)
                if p.index < len(plans_old)
            ]
            assert new_split == old_split


def test_planner_rejects_corrupt_sync(tmp_path, rng):
    [path] = _write_shards(tmp_path, rng, n_rows=300, n_files=1)
    data = bytearray(open(path, "rb").read())
    data[-8] ^= 0xFF  # corrupt the final sync marker
    open(path, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="sync marker"):
        plan_chunks([path], chunk_rows=100)


# ---------------------------------------------------------------------------
# streamed dataset == in-core dataset, bit for bit
# ---------------------------------------------------------------------------


def test_streamed_dataset_matches_incore_exactly(tmp_path, rng):
    paths = _write_shards(tmp_path, rng, n_rows=1100, n_files=3)
    ds_in, maps = read_game_dataset_from_avro(
        paths, id_columns=("userId",), return_index_maps=True
    )
    ds_st, maps_st = read_game_dataset_streamed(
        paths,
        id_columns=("userId",),
        spec=IngestSpec(workers=2, chunk_rows=200, nnz_per_row_hint=8),
        return_index_maps=True,
    )
    assert set(maps_st) == set(maps)
    _assert_datasets_equal(ds_st, ds_in)


def test_python_fallback_pipeline_matches_and_degrades(
    tmp_path, rng, monkeypatch
):
    """Hiding libphoton_native.so must switch the pipeline to pure-Python
    decode workers — same arrays, no crash."""
    paths = _write_shards(tmp_path, rng, n_rows=600, n_files=2)
    ds_native, maps = read_game_dataset_from_avro(
        paths, id_columns=("userId",), return_index_maps=True
    )
    monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
    spec = IngestSpec(workers=2, chunk_rows=150, nnz_per_row_hint=8)
    stream = ChunkStream(
        paths, index_maps=maps, id_columns=("userId",), spec=spec
    )
    try:
        assert not stream.using_native_decoder
    finally:
        stream.close()
    ds_py = read_game_dataset_streamed(
        paths, index_maps=maps, id_columns=("userId",), spec=spec
    )
    _assert_datasets_equal(ds_py, ds_native)


def test_buffer_growth_keeps_arrays_exact(tmp_path, rng):
    """A hopeless nnz hint must grow the ring (counted), not corrupt or
    refuse the stream."""
    from photon_ml_tpu import telemetry

    paths = _write_shards(tmp_path, rng, n_rows=500, n_files=1, k=7)
    ds_in, maps = read_game_dataset_from_avro(
        paths, id_columns=("userId",), return_index_maps=True
    )
    before = telemetry.metrics.peek_counter("ingest.buffer_growths") or 0
    ds_st = read_game_dataset_streamed(
        paths,
        index_maps=maps,
        id_columns=("userId",),
        spec=IngestSpec(workers=2, chunk_rows=120, nnz_per_row_hint=1),
    )
    after = telemetry.metrics.peek_counter("ingest.buffer_growths") or 0
    assert after > before
    _assert_datasets_equal(ds_st, ds_in)


def test_stream_resume_replays_suffix(tmp_path, rng):
    paths = _write_shards(tmp_path, rng, n_rows=800, n_files=2)
    _, maps = read_game_dataset_from_avro(
        paths, id_columns=("userId",), return_index_maps=True
    )
    spec = IngestSpec(workers=1, chunk_rows=150, nnz_per_row_hint=8)
    with ChunkStream(
        paths, index_maps=maps, id_columns=("userId",), spec=spec
    ) as full:
        chunks = list(full)
        vocab = full.id_vocabulary("userId")
    start = 3
    # resume seeds the original run's id vocabulary so interned codes
    # stay consistent with the interrupted stream
    with ChunkStream(
        paths, index_maps=maps, id_columns=("userId",), spec=spec,
        start_chunk=start, id_vocabularies={"userId": list(vocab)},
    ) as resumed:
        tail = list(resumed)
    assert [c.index for c in tail] == [c.index for c in chunks[start:]]
    for a, b in zip(tail, chunks[start:]):
        assert a.row_start == b.row_start
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(
            a.id_codes["userId"], b.id_codes["userId"]
        )
        np.testing.assert_array_equal(
            np.asarray(a.batch.values), np.asarray(b.batch.values)
        )


# ---------------------------------------------------------------------------
# spec validation, budget sizing, stall protocol
# ---------------------------------------------------------------------------


def test_ingest_spec_validation():
    with pytest.raises(IngestConfigError):
        IngestSpec(prefetch_depth=0)
    with pytest.raises(IngestConfigError):
        IngestSpec(chunk_rows=0)
    with pytest.raises(IngestConfigError):
        IngestSpec(resident_budget_mb=-1)
    with pytest.raises(IngestConfigError, match="unknown ingest config"):
        IngestSpec.from_config({"wrokers": 2})
    assert IngestSpec.from_config(True) == IngestSpec()
    assert IngestSpec.from_config({"workers": 3}).workers == 3


def test_resident_budget_bounds_staging(tmp_path, rng):
    paths = _write_shards(tmp_path, rng, n_rows=900, n_files=1)
    _, maps = read_game_dataset_from_avro(
        paths, id_columns=("userId",), return_index_maps=True
    )
    budget_mb = 4.0
    with ChunkStream(
        paths,
        index_maps=maps,
        spec=IngestSpec(
            workers=2, chunk_rows=200, nnz_per_row_hint=8,
            resident_budget_mb=budget_mb,
        ),
    ) as stream:
        rows = sum(c.rows for c in stream)
        stats = stream.stats()
    assert rows == 900
    assert stats.staging_bytes <= budget_mb * 2**20

    # a budget that cannot even fit two slots is a typed refusal with
    # the sizing math, not a hang or a silent single-buffer pipeline
    with pytest.raises(IngestConfigError, match="staging slot"):
        ChunkStream(
            paths,
            index_maps=maps,
            spec=IngestSpec(
                chunk_rows=400, nnz_per_row_hint=64,
                resident_budget_mb=0.05,
            ),
        )


def test_backpressure_bounds_queue_and_stall_is_typed(tmp_path, rng):
    paths = _write_shards(tmp_path, rng, n_rows=1000, n_files=1)
    _, maps = read_game_dataset_from_avro(
        paths, id_columns=("userId",), return_index_maps=True
    )
    stream = ChunkStream(
        paths,
        index_maps=maps,
        spec=IngestSpec(
            workers=1, chunk_rows=100, prefetch_depth=1,
            nnz_per_row_hint=8, stall_timeout_s=0.3,
        ),
    )
    try:
        # never consume: decode+upload fill the bounded queue and ring,
        # then hit the stall timeout — a typed error, not a hang
        time.sleep(1.2)
        with pytest.raises(IngestStall):
            next(stream)
    finally:
        stream.close()


def test_decode_error_names_file_and_chunk(tmp_path, rng):
    from photon_ml_tpu.ingest import ChunkDecodeError

    [path] = _write_shards(tmp_path, rng, n_rows=200, n_files=1)
    _, maps = read_game_dataset_from_avro(
        path, id_columns=("userId",), return_index_maps=True
    )
    with pytest.raises((ChunkDecodeError, KeyError)):
        # asking for an id column the records don't carry fails the chunk
        # with the path + chunk index (not a worker-thread hang)
        read_game_dataset_streamed(
            [path],
            index_maps=maps,
            id_columns=("memberId",),
            spec=IngestSpec(workers=1, chunk_rows=100, nnz_per_row_hint=8),
        )


def test_transient_read_failure_is_retried_not_fatal(tmp_path, rng):
    """A flaky OSError on ONE chunk's byte-range read must not kill the
    stream: the bounded per-chunk retry re-reads it, the dataset comes
    out bit-identical, and the absorbed flake is visible in IngestStats
    + ``ingest.read_retries``. Injected at the real read seam
    (``ingest.decode.read``) rather than a mock, so the retry loop is
    exercised exactly where production flakes land."""
    from photon_ml_tpu import faults, telemetry

    paths = _write_shards(tmp_path, rng, n_rows=400, n_files=1)
    ds_ref, maps = read_game_dataset_from_avro(
        paths[0], id_columns=("userId",), return_index_maps=True
    )
    from photon_ml_tpu.ingest.pipeline import ChunkStream

    spec = IngestSpec(workers=1, chunk_rows=100, nnz_per_row_hint=8,
                      read_retries=2, retry_backoff_s=0.0)
    telemetry.reset()
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("ingest.decode.read", action="io", nth=2),
        ]))
        ds = read_game_dataset_streamed(
            paths, index_maps=maps, id_columns=("userId",), spec=spec
        )
        _assert_datasets_equal(ds, ds_ref)
        counters = telemetry.snapshot()["counters"]
        assert counters["ingest.read_retries"] == 1
        assert counters["faults.injected"] == 1
    finally:
        faults.clear_plan()
        telemetry.reset()

    # the absorbed flake is visible on the stream's own stats too
    telemetry.reset()
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("ingest.decode.read", action="io", nth=1),
        ]))
        stream = ChunkStream(paths, index_maps=maps,
                             id_columns=("userId",), spec=spec)
        for _ in stream:
            pass
        assert stream.stats().read_retries == 1
    finally:
        faults.clear_plan()
        telemetry.reset()


def test_read_retries_exhausted_propagates_and_deterministic_skips_retry(
    tmp_path, rng
):
    """Two failure shapes stay distinct: a read that flakes on EVERY
    attempt propagates after the retry budget (stream dies with the
    typed error), while a deterministic ChunkDecodeError never burns a
    retry at all — re-reading corrupt bytes cannot help."""
    from photon_ml_tpu import faults, telemetry
    from photon_ml_tpu.ingest import ChunkDecodeError
    from photon_ml_tpu.ingest.pipeline import ChunkStream

    paths = _write_shards(tmp_path, rng, n_rows=200, n_files=1)
    _, maps = read_game_dataset_from_avro(
        paths[0], id_columns=("userId",), return_index_maps=True
    )
    spec = IngestSpec(workers=1, chunk_rows=100, nnz_per_row_hint=8,
                      read_retries=1, retry_backoff_s=0.0)
    telemetry.reset()
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("ingest.decode.read", action="io",
                             probability=1.0),
        ]))
        with pytest.raises(OSError):
            list(ChunkStream(paths, index_maps=maps,
                             id_columns=("userId",), spec=spec))
        # attempts = retries + 1 per chunk; only the RETRY is counted
        assert (
            telemetry.snapshot()["counters"]["ingest.read_retries"] >= 1
        )
    finally:
        faults.clear_plan()
        telemetry.reset()

    # deterministic decode failure: no retry counter movement
    telemetry.reset()
    try:
        with pytest.raises((ChunkDecodeError, KeyError)):
            read_game_dataset_streamed(
                paths, index_maps=maps, id_columns=("memberId",), spec=spec
            )
        assert telemetry.snapshot()["counters"].get(
            "ingest.read_retries") is None
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# double_buffered (the game/streaming feeding facility)
# ---------------------------------------------------------------------------


def test_double_buffered_preserves_order_and_items():
    items = list(range(12))
    got = list(double_buffered(items, lambda x: x * 10, depth=3))
    assert got == [(x, x * 10) for x in items]


def test_double_buffered_bounded_lookahead():
    fed = []

    def feed(x):
        fed.append(x)
        return x

    gen = double_buffered(range(100), feed, depth=2)
    next(gen)
    time.sleep(0.3)  # let the feeder run as far ahead as it can
    # one yielded + at most depth queued + one in flight
    assert len(fed) <= 1 + 2 + 1
    gen.close()


def test_double_buffered_propagates_feed_errors():
    def feed(x):
        if x == 3:
            raise RuntimeError("boom at 3")
        return x

    got = []
    with pytest.raises(RuntimeError, match="boom at 3"):
        for item, fed in double_buffered(range(6), feed, depth=1):
            got.append(item)
    assert got == [0, 1, 2]


# ---------------------------------------------------------------------------
# the out-of-core acceptance path: `cli train` from shards
# ---------------------------------------------------------------------------


def test_out_of_core_cli_train_matches_incore_fit(tmp_path, rng):
    """A fit through the ingest pipeline (shard set larger than the
    configured resident staging budget) must match the in-core fit's
    final loss to 1e-6 — it trains on bit-identical arrays."""
    from photon_ml_tpu.cli.train import run

    data_dir = tmp_path / "train"
    data_dir.mkdir()
    # uncompressed shards so the on-disk set genuinely exceeds the
    # host-resident staging budget configured below
    paths = _write_shards(
        data_dir, rng, n_rows=4000, n_files=3, d=30, k=6, codec="null"
    )
    total_bytes = sum(os.path.getsize(p) for p in paths)
    budget_mb = 0.35
    base = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [str(data_dir)],
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "features",
                "optimizer": {
                    "regularization": "l2",
                    "regularization_weight": 1.0,
                },
            }
        },
        "num_iterations": 1,
        "evaluators": ["auc"],
        "heartbeat": False,
        "validation": {"paths": [str(data_dir)]},
    }
    s_in = run(dict(base))
    ooc = dict(base)
    ooc["input"] = {
        **base["input"],
        "ingest": {
            "workers": 2,
            "chunk_rows": 250,
            "nnz_per_row_hint": 8,
            "resident_budget_mb": budget_mb,
        },
    }
    s_st = run(ooc)
    # genuinely out-of-core w.r.t. the staging budget: the shard set is
    # bigger than the host-resident ring the stream was allowed
    assert total_bytes > budget_mb * 2**20
    from photon_ml_tpu import telemetry

    staging = telemetry.metrics.peek_gauge("ingest.staging_bytes")
    assert staging is not None and staging <= budget_mb * 2**20
    assert s_in["best_metric"] is not None
    assert s_st["best_metric"] == pytest.approx(
        s_in["best_metric"], abs=1e-6
    )


def test_plans_for_host_partitions_deterministically():
    """The per-host split is a pure function of (plans, fleet size):
    disjoint, covering, round-robin balanced — and a SURVIVOR fleet's
    recomputed split absorbs the dead host's chunks with no coordination
    state (the fleet supervisor's elastic-resume contract)."""
    from photon_ml_tpu.ingest import ChunkPlan, plans_for_host

    plans = [
        ChunkPlan(index=i, path=f"f{i % 2}.avro", byte_start=0,
                  byte_end=10, n_rows=5, row_start=5 * i, n_blocks=1)
        for i in range(7)
    ]
    split = [plans_for_host(plans, pid, 3) for pid in range(3)]
    # disjoint and covering, in global order
    all_indices = sorted(p.index for host in split for p in host)
    assert all_indices == list(range(7))
    assert [p.index for p in split[0]] == [0, 3, 6]
    assert [p.index for p in split[1]] == [1, 4]
    assert [p.index for p in split[2]] == [2, 5]
    # round-robin balance: host loads differ by at most one chunk
    sizes = [len(h) for h in split]
    assert max(sizes) - min(sizes) <= 1
    # survivor elasticity: hosts 0 and 1 survive a 3->2 shrink; the NEW
    # split covers everything, including the dead host's chunks
    survivors = [plans_for_host(plans, pid, 2) for pid in range(2)]
    assert sorted(
        p.index for host in survivors for p in host
    ) == list(range(7))
    # single host owns the whole stream
    assert plans_for_host(plans, 0, 1) == plans


def test_plans_for_host_validates_ids():
    from photon_ml_tpu.ingest import plans_for_host

    with pytest.raises(ValueError, match="num_processes"):
        plans_for_host([], 0, 0)
    with pytest.raises(ValueError, match="out of range"):
        plans_for_host([], 2, 2)
    with pytest.raises(ValueError, match="out of range"):
        plans_for_host([], -1, 2)
