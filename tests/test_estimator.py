"""GameEstimator: typed-config end-to-end training + normalization wiring.

Parity targets: GameEstimator.scala:76-398 (fit flow), NormalizationTest
(same optimum with/without standardization), training driver output layout
(cli/game/training/Driver.scala:262-312).
"""

import numpy as np
import pytest

from photon_ml_tpu.data.model_store import load_game_model, load_game_model_metadata
from photon_ml_tpu.data.normalization import NormalizationType
from photon_ml_tpu.game import (
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    RandomEffectConfig,
    build_game_dataset,
)
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)

_OPT = OptimizerConfig(
    optimizer_type=OptimizerType.LBFGS,
    max_iterations=60,
    tolerance=1e-9,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=1.0,
)


def _glmix(rng, n=500, n_users=15):
    Xg = rng.normal(size=(n, 10)) * (rng.random((n, 10)) < 0.5)
    Xg[:, 0] = 1.0  # intercept column
    Xu = rng.normal(size=(n, 4)) * (rng.random((n, 4)) < 0.7)
    users = rng.integers(0, n_users, size=n)
    wg = rng.normal(size=10)
    wu = rng.normal(size=(n_users, 4))
    margin = Xg @ wg + np.einsum("ij,ij->i", Xu, wu[users])
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(float)
    gds = build_game_dataset(
        response=y,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y),
            "user": SparseBatch.from_dense(Xu, y),
        },
        id_columns={"userId": users},
    )
    return gds


@pytest.mark.slow
def test_estimator_end_to_end_with_save(tmp_path, rng):
    gds = _glmix(rng)
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="global", optimizer=_OPT),
            "per-user": RandomEffectConfig(
                shard_name="user", id_name="userId", optimizer=_OPT),
        },
        num_iterations=2,
        evaluators=["auc", "logistic_loss"],
    )
    result = GameEstimator(config).fit(
        gds, validation_data=gds, output_dir=str(tmp_path / "out"))
    assert result.best_metric is not None and 0.5 < result.best_metric <= 1.0

    # reload the persisted best model; scores must match in-memory model
    loaded = load_game_model(str(tmp_path / "out" / "best"))
    s_mem = np.asarray(result.best_model.score(gds))
    s_disk = np.asarray(loaded.score(gds))
    np.testing.assert_allclose(s_disk, s_mem, rtol=1e-6, atol=1e-7)

    meta = load_game_model_metadata(str(tmp_path / "out" / "best"))
    cfg_meta = meta["extra"]["config"]
    assert cfg_meta["coordinates"]["per-user"]["type"] == "random_effect"
    assert cfg_meta["coordinates"]["fixed"]["optimizer"]["type"] == "lbfgs"


def _scaled_logistic_data(rng, scales, n=400):
    d = len(scales)
    X = rng.normal(size=(n, d)) * scales
    X[:, 0] = 1.0  # intercept
    w_true = rng.normal(size=d) / scales
    margin = X @ w_true
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(float)
    gds = build_game_dataset(
        response=y, feature_shards={"g": SparseBatch.from_dense(X, y)})
    return gds, X, y, w_true


def _fit_fixed(gds, opt, norm):
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="g", optimizer=opt, normalization=norm,
                intercept_index=0),
        },
    )
    res = GameEstimator(config).fit(gds)
    return np.asarray(res.model.models["fixed"].coefficients)


def test_standardization_reaches_same_optimum_unregularized(rng):
    """NormalizationTest.scala:33 analog: WITHOUT regularization the trained
    model (in original space) is the same with and without standardization —
    normalization only changes conditioning, not the optimum. (Under L2 the
    penalty applies in normalized space, so invariance does NOT hold; see
    test_l2_penalty_applies_in_normalized_space.)"""
    # mild scale spread: the unnormalized baseline must also converge
    scales = np.array([1, 10, 0.1, 1, 5, 0.5, 2, 4.0])
    gds, _, _, w_true = _scaled_logistic_data(rng, scales)
    opt = OptimizerConfig(
        optimizer_type=OptimizerType.LBFGS,
        max_iterations=200,
        tolerance=1e-10,
    )
    w_plain = _fit_fixed(gds, opt, NormalizationType.NONE)
    w_std = _fit_fixed(gds, opt, NormalizationType.STANDARDIZATION)
    w_scale = _fit_fixed(gds, opt, NormalizationType.SCALE_WITH_STANDARD_DEVIATION)
    # same optimum in ORIGINAL space regardless of normalization, lambda=0
    np.testing.assert_allclose(w_std, w_plain, rtol=5e-2, atol=5e-3)
    np.testing.assert_allclose(w_scale, w_plain, rtol=5e-2, atol=5e-3)
    # sanity: the fit found the signal
    assert np.corrcoef(w_std, w_true)[0, 1] > 0.9


def test_l2_penalty_applies_in_normalized_space(rng):
    """Reference-parity semantics check (L2Regularization.scala): with
    normalization active, the L2 penalty applies to the coefficients in
    NORMALIZED space. The standardized estimator fit must therefore equal
    an explicit solve on materialized standardized features (penalized
    plainly there), mapped back to original space."""
    scales = np.array([1, 100, 0.01, 1, 5, 0.5, 10, 2.0])
    gds, X, y, _ = _scaled_logistic_data(rng, scales)
    w_std = _fit_fixed(gds, _OPT, NormalizationType.STANDARDIZATION)

    # externally: standardize X by its own stats, fit plain L2 GLM, map back
    mean = X.mean(axis=0)
    std = X.std(axis=0, ddof=1)  # summarize() uses the unbiased estimator
    mean[0], std[0] = 0.0, 1.0
    std[std == 0.0] = 1.0
    Xn = (X - mean) / std
    gds_n = build_game_dataset(
        response=y, feature_shards={"g": SparseBatch.from_dense(Xn, y)})
    wn = _fit_fixed(gds_n, _OPT, NormalizationType.NONE)
    w_expected = wn / std
    w_expected[0] -= np.dot(w_expected, mean)
    np.testing.assert_allclose(w_std, w_expected, rtol=2e-3, atol=2e-4)


def test_normalized_warm_start_roundtrip(rng):
    """update_model must inverse-transform the warm start: re-running from
    the previous solution stays at the optimum."""
    gds = _glmix(rng, n=300)
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="global", optimizer=_OPT,
                normalization=NormalizationType.STANDARDIZATION,
                intercept_index=0),
        },
        num_iterations=1,
    )
    est = GameEstimator(config)
    r1 = est.fit(gds)
    w1 = np.asarray(r1.model.models["fixed"].coefficients)
    r2 = est.fit(gds, initial_models={"fixed": r1.model.models["fixed"]})
    w2 = np.asarray(r2.model.models["fixed"].coefficients)
    np.testing.assert_allclose(w2, w1, rtol=1e-3, atol=1e-4)


def test_config_validation():
    try:
        GameConfig(task="logistic", coordinates={})
        raise AssertionError("expected ValueError")
    except ValueError:
        pass
