"""GameEstimator: typed-config end-to-end training + normalization wiring.

Parity targets: GameEstimator.scala:76-398 (fit flow), NormalizationTest
(same optimum with/without standardization), training driver output layout
(cli/game/training/Driver.scala:262-312).
"""

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.data.model_store import load_game_model, load_game_model_metadata
from photon_ml_tpu.data.normalization import NormalizationType
from photon_ml_tpu.game import (
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    RandomEffectConfig,
    build_game_dataset,
)
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)

_OPT = OptimizerConfig(
    optimizer_type=OptimizerType.LBFGS,
    max_iterations=60,
    tolerance=1e-9,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=1.0,
)


def _glmix(rng, n=500, n_users=15):
    Xg = rng.normal(size=(n, 10)) * (rng.random((n, 10)) < 0.5)
    Xg[:, 0] = 1.0  # intercept column
    Xu = rng.normal(size=(n, 4)) * (rng.random((n, 4)) < 0.7)
    users = rng.integers(0, n_users, size=n)
    wg = rng.normal(size=10)
    wu = rng.normal(size=(n_users, 4))
    margin = Xg @ wg + np.einsum("ij,ij->i", Xu, wu[users])
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(float)
    gds = build_game_dataset(
        response=y,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y),
            "user": SparseBatch.from_dense(Xu, y),
        },
        id_columns={"userId": users},
    )
    return gds


def test_estimator_end_to_end_with_save(tmp_path, rng):
    gds = _glmix(rng)
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="global", optimizer=_OPT),
            "per-user": RandomEffectConfig(
                shard_name="user", id_name="userId", optimizer=_OPT),
        },
        num_iterations=2,
        evaluators=["auc", "logistic_loss"],
    )
    result = GameEstimator(config).fit(
        gds, validation_data=gds, output_dir=str(tmp_path / "out"))
    assert result.best_metric is not None and 0.5 < result.best_metric <= 1.0

    # reload the persisted best model; scores must match in-memory model
    loaded = load_game_model(str(tmp_path / "out" / "best"))
    s_mem = np.asarray(result.best_model.score(gds))
    s_disk = np.asarray(loaded.score(gds))
    np.testing.assert_allclose(s_disk, s_mem, rtol=1e-6, atol=1e-7)

    meta = load_game_model_metadata(str(tmp_path / "out" / "best"))
    cfg_meta = meta["extra"]["config"]
    assert cfg_meta["coordinates"]["per-user"]["type"] == "random_effect"
    assert cfg_meta["coordinates"]["fixed"]["optimizer"]["type"] == "lbfgs"


def test_standardization_reaches_same_optimum(rng):
    """NormalizationTest.scala analog: the trained model (in original space)
    must be the same with and without standardization; normalization only
    changes conditioning, not the optimum."""
    n = 400
    X = rng.normal(size=(n, 8)) * np.array([1, 100, 0.01, 1, 5, 0.5, 10, 2.0])
    X[:, 0] = 1.0  # intercept
    w_true = rng.normal(size=8) / np.array([1, 100, 0.01, 1, 5, 0.5, 10, 2.0])
    margin = X @ w_true
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(float)
    gds = build_game_dataset(
        response=y, feature_shards={"g": SparseBatch.from_dense(X, y)})

    def fit(norm):
        config = GameConfig(
            task="logistic",
            coordinates={
                "fixed": FixedEffectConfig(
                    shard_name="g", optimizer=_OPT, normalization=norm,
                    intercept_index=0),
            },
        )
        res = GameEstimator(config).fit(gds)
        return np.asarray(res.model.models["fixed"].coefficients)

    w_plain = fit(NormalizationType.NONE)
    w_std = fit(NormalizationType.STANDARDIZATION)
    w_scale = fit(NormalizationType.SCALE_WITH_STANDARD_DEVIATION)
    # same optimum in ORIGINAL space regardless of normalization
    np.testing.assert_allclose(w_std, w_plain, rtol=5e-2, atol=5e-3)
    np.testing.assert_allclose(w_scale, w_plain, rtol=5e-2, atol=5e-3)
    # and the standardized fit actually used normalization (sanity: the
    # badly-scaled columns converged to the true signs)
    assert np.corrcoef(w_std, w_true)[0, 1] > 0.95


def test_normalized_warm_start_roundtrip(rng):
    """update_model must inverse-transform the warm start: re-running from
    the previous solution stays at the optimum."""
    gds = _glmix(rng, n=300)
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="global", optimizer=_OPT,
                normalization=NormalizationType.STANDARDIZATION,
                intercept_index=0),
        },
        num_iterations=1,
    )
    est = GameEstimator(config)
    r1 = est.fit(gds)
    w1 = np.asarray(r1.model.models["fixed"].coefficients)
    r2 = est.fit(gds, initial_models={"fixed": r1.model.models["fixed"]})
    w2 = np.asarray(r2.model.models["fixed"].coefficients)
    np.testing.assert_allclose(w2, w1, rtol=1e-3, atol=1e-4)


def test_config_validation():
    try:
        GameConfig(task="logistic", coordinates={})
        raise AssertionError("expected ValueError")
    except ValueError:
        pass
