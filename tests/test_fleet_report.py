"""Fleet observability: identity suffixing, per-member artifact fields,
the heartbeat tail parser, collective-wait attribution, and the
FleetReport aggregation (telemetry/identity.py, telemetry/fleet_report.py).

The real 2-process gloo end-to-end lives in
tests/test_fleet_observability.py; these tests pin each layer's contract
on synthetic artifacts, including the degraded killed-member case the
distributed crash matrix produces.
"""

from __future__ import annotations

import json
import os

import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import identity
from photon_ml_tpu.telemetry.fleet_report import (
    FleetReport,
    discover_member_streams,
)
from photon_ml_tpu.telemetry.progress import Heartbeat, tail_heartbeat_fields


# ---------------------------------------------------------------------------
# identity + per-member suffixing
# ---------------------------------------------------------------------------


def test_member_artifact_path_contract(monkeypatch):
    monkeypatch.delenv(identity.ENV_PROC_ID, raising=False)
    # outside a fleet: unchanged (single-process artifact names are pinned)
    assert identity.member_artifact_path("a/trace.jsonl") == "a/trace.jsonl"
    # explicit proc: suffix before the extension
    assert (
        identity.member_artifact_path("a/trace.jsonl", proc=2)
        == "a/trace.proc-2.jsonl"
    )
    assert identity.member_artifact_path("report.md", 0) == "report.proc-0.md"
    assert identity.member_artifact_path("noext", 1) == "noext.proc-1"
    # idempotent: a pre-suffixed path is left alone
    assert (
        identity.member_artifact_path("a/trace.proc-2.jsonl", proc=2)
        == "a/trace.proc-2.jsonl"
    )


def test_identity_env_resolution(monkeypatch):
    monkeypatch.setenv(identity.ENV_PROC_ID, "3")
    monkeypatch.setenv(identity.ENV_PROC_COUNT, "4")
    assert identity.fleet_process_index() == 3
    assert identity.fleet_process_count() == 4
    assert (
        identity.member_artifact_path("x/t.jsonl") == "x/t.proc-3.jsonl"
    )
    # malformed env degrades to "not a fleet", never raises
    monkeypatch.setenv(identity.ENV_PROC_ID, "banana")
    assert identity.fleet_process_index() is None
    monkeypatch.delenv(identity.ENV_PROC_ID)
    monkeypatch.delenv(identity.ENV_PROC_COUNT)
    # single-process jax (the test env) is not a fleet either
    assert identity.fleet_process_index() is None
    assert identity.fleet_process_count() is None


def test_configure_from_env_suffixes_per_member(tmp_path, monkeypatch):
    monkeypatch.setenv(identity.ENV_PROC_ID, "1")
    monkeypatch.setenv(identity.ENV_PROC_COUNT, "2")
    monkeypatch.setenv("PHOTON_TRACE_OUT", str(tmp_path / "trace.jsonl"))
    monkeypatch.setenv(
        "PHOTON_TELEMETRY_OUT", str(tmp_path / "telemetry.jsonl")
    )
    telemetry.configure_from_env()
    with telemetry.span("fit"):
        pass
    assert (tmp_path / "trace.proc-1.jsonl").exists()
    assert not (tmp_path / "trace.jsonl").exists()
    header = json.loads(
        (tmp_path / "trace.proc-1.jsonl").read_text().splitlines()[0]
    )
    assert header["type"] == "trace_header"
    assert header["process_index"] == 1
    assert header["num_processes"] == 2
    assert isinstance(header["hostname"], str)
    # the monotonic<->epoch anchor pair fleet alignment rides on
    assert isinstance(header["anchor_unix_s"], float)
    assert "monotonic_anchor" in header


def test_trace_header_single_process_has_no_member_fields(
    tmp_path, monkeypatch
):
    monkeypatch.delenv(identity.ENV_PROC_ID, raising=False)
    telemetry.configure(trace_out=str(tmp_path / "t.jsonl"))
    header = json.loads(
        (tmp_path / "t.jsonl").read_text().splitlines()[0]
    )
    assert "process_index" not in header
    # hostname + anchor are ALWAYS recorded (harmless single-process,
    # load-bearing for fleet alignment)
    assert "hostname" in header and "anchor_unix_s" in header


def test_metrics_flush_carries_member_identity(tmp_path, monkeypatch):
    monkeypatch.setenv(identity.ENV_PROC_ID, "2")
    telemetry.counter("progress.rows").inc(5)
    out = tmp_path / "m.jsonl"
    telemetry.flush_metrics(str(out))
    line = json.loads(out.read_text().splitlines()[0])
    assert line["process_index"] == 2
    assert isinstance(line["hostname"], str)
    # single-process lines stay identity-free (format pinned)
    monkeypatch.delenv(identity.ENV_PROC_ID)
    out2 = tmp_path / "m2.jsonl"
    telemetry.flush_metrics(str(out2))
    line2 = json.loads(out2.read_text().splitlines()[0])
    assert "process_index" not in line2


# ---------------------------------------------------------------------------
# heartbeat proc field + tail parser
# ---------------------------------------------------------------------------


def test_heartbeat_proc_field_only_inside_a_fleet(tmp_path, monkeypatch):
    monkeypatch.delenv(identity.ENV_PROC_ID, raising=False)
    hb = Heartbeat(interval=99.0)
    line = hb.beat()
    assert "proc" not in line  # single-process format pinned unchanged
    monkeypatch.setenv(identity.ENV_PROC_ID, "1")
    line = hb.beat()
    assert line["proc"] == 1


def test_tail_heartbeat_fields_reads_newest_valid_line(tmp_path):
    path = tmp_path / "telemetry.proc-0.jsonl"
    with open(path, "w") as fh:
        fh.write(json.dumps({"type": "metrics", "snapshot": {}}) + "\n")
        for seq in (1, 2, 3):
            fh.write(
                json.dumps(
                    {"type": "heartbeat", "seq": seq, "proc": 0,
                     "uptime_s": seq * 1.0}
                )
                + "\n"
            )
        # a member hard-killed mid-write leaves a truncated last line
        fh.write('{"type": "heartbeat", "seq": 4, "pro')
    rec = tail_heartbeat_fields(str(path))
    assert rec["seq"] == 3  # the truncated line is skipped, not fatal
    assert tail_heartbeat_fields(str(path), expect_proc=0)["seq"] == 3
    # attribution is REQUIRED when asked for: a mis-pointed file must
    # read as silence, never as another member's progress
    assert tail_heartbeat_fields(str(path), expect_proc=1) is None
    assert tail_heartbeat_fields(str(tmp_path / "missing.jsonl")) is None


def test_tail_heartbeat_fields_bounded_read(tmp_path):
    path = tmp_path / "big.jsonl"
    with open(path, "w") as fh:
        for seq in range(5000):
            fh.write(
                json.dumps({"type": "heartbeat", "seq": seq, "proc": 0})
                + "\n"
            )
    rec = tail_heartbeat_fields(str(path), max_bytes=512)
    assert rec["seq"] == 4999  # newest line, from the bounded tail only


# ---------------------------------------------------------------------------
# collective-wait attribution
# ---------------------------------------------------------------------------


def test_collective_wait_noop_single_process():
    from photon_ml_tpu.parallel import multihost

    with multihost.collective_wait("test_label"):
        pass
    snap = telemetry.snapshot()
    assert "comms.wait_calls" not in snap["counters"]
    assert not telemetry.finished_spans("collective_wait")


def test_collective_wait_records_span_and_histogram(monkeypatch):
    from photon_ml_tpu.parallel import multihost

    monkeypatch.setattr(multihost.jax, "process_count", lambda: 2)
    with multihost.collective_wait("streaming_chunk_solve"):
        pass
    snap = telemetry.snapshot()
    assert snap["counters"]["comms.wait_calls"] == 1
    assert snap["counters"]["comms.wait_seconds_total"] >= 0.0
    assert snap["histograms"]["comms.wait_s"]["count"] == 1
    (span,) = telemetry.finished_spans("collective_wait")
    assert span.attrs["label"] == "streaming_chunk_solve"
    assert span.attrs["wait_s"] >= 0.0


# ---------------------------------------------------------------------------
# FleetReport aggregation (synthetic member artifacts)
# ---------------------------------------------------------------------------


def _write_member(
    directory,
    proc: int,
    *,
    anchor_unix: float,
    wait_s: float = None,
    rows_per_sec: float = None,
    mfu: float = None,
    heartbeat_uptimes=(),
    truncate_trace: bool = False,
    write_metrics: bool = True,
    rendezvous_end: float = None,
    extra_gauges: dict = None,
    extra_counters: dict = None,
):
    """One member's artifact pair in the identity naming contract. The
    truncate/no-metrics combination is EXACTLY the leftover shape a
    hard-killed member (tools/chaos.py --fleet victim, os._exit 113)
    produces: spans up to the death, a torn final line, no atexit flush."""
    header = {
        "type": "trace_header",
        "wall_time": "2026-08-03T00:00:00+00:00",
        "monotonic_anchor": 5.0,
        "anchor_unix_s": anchor_unix,
        "hostname": f"host{proc}",
        "process_index": proc,
        "num_processes": 2,
    }
    spans = [
        {"type": "span", "id": 1, "parent": None, "name": "fit",
         "ts": 6.0, "dur": 10.0, "thread": "MainThread", "attrs": {},
         "events": []},
    ]
    if rendezvous_end is not None:
        spans.append(
            {"type": "span", "id": 2, "parent": 1,
             "name": "checkpoint:save", "ts": rendezvous_end - 1.0,
             "dur": 1.0, "thread": "MainThread",
             "attrs": {"coordinated": True, "next_chunk": 1},
             "events": []}
        )
    with open(
        os.path.join(directory, f"trace.proc-{proc}.jsonl"), "w"
    ) as fh:
        fh.write(json.dumps(header) + "\n")
        for s in spans:
            fh.write(json.dumps(s) + "\n")
        if truncate_trace:
            fh.write('{"type": "span", "id": 99, "name": "torn')
    with open(
        os.path.join(directory, f"telemetry.proc-{proc}.jsonl"), "w"
    ) as fh:
        for i, up in enumerate(heartbeat_uptimes):
            fh.write(
                json.dumps(
                    {"type": "heartbeat", "seq": i + 1, "proc": proc,
                     "uptime_s": up}
                )
                + "\n"
            )
        if write_metrics:
            counters = {"streaming_chunks": 4}
            gauges = {}
            if wait_s is not None:
                counters["comms.wait_seconds_total"] = wait_s
                counters["comms.wait_calls"] = 4
            if rows_per_sec is not None:
                gauges["progress.rows_per_sec"] = rows_per_sec
            if mfu is not None:
                # mfu derives from xla flops + peak + span time: fake the
                # minimal counters/gauges RunReport needs
                counters["xla.flops_total"] = mfu * 1e12 * 10.0
                gauges["device.peak_flops"] = 1e12
            if extra_gauges:
                gauges.update(extra_gauges)
            if extra_counters:
                counters.update(extra_counters)
            fh.write(
                json.dumps(
                    {"type": "metrics",
                     "wall_time": "2026-08-03T00:00:30+00:00",
                     "process_index": proc,
                     "snapshot": {"counters": counters, "gauges": gauges,
                                  "histograms": {}}}
                )
                + "\n"
            )


def test_discover_member_streams_classifies_by_content(tmp_path):
    _write_member(tmp_path, 0, anchor_unix=1000.0, wait_s=1.0)
    streams = discover_member_streams(str(tmp_path))
    assert set(streams) == {0}
    assert streams[0]["trace"].endswith("trace.proc-0.jsonl")
    assert streams[0]["telemetry"].endswith("telemetry.proc-0.jsonl")


def test_fleet_report_rows_straggler_and_roundtrip(tmp_path):
    # member 1 is the straggler: it waited least (everyone waited on it)
    _write_member(
        tmp_path, 0, anchor_unix=1000.0, wait_s=3.0, rows_per_sec=100.0,
        mfu=0.30, heartbeat_uptimes=(1.0, 2.0, 3.0), rendezvous_end=9.0,
    )
    _write_member(
        tmp_path, 1, anchor_unix=1002.0, wait_s=0.2, rows_per_sec=80.0,
        mfu=0.20, heartbeat_uptimes=(1.0, 2.5), rendezvous_end=7.1,
    )
    report = FleetReport.load(str(tmp_path))
    assert [m.process_index for m in report.members] == [0, 1]
    assert report.lost_members() == []
    # clock skew from the shared coordinated-save endpoint:
    # abs end member1 = 1002 + (7.1 - 5) = 1004.1; member0 = 1000 + 4 = 1004
    assert report.members[1].clock_skew_s == pytest.approx(0.1, abs=1e-6)

    straggler = report.straggler()
    assert straggler["process_index"] == 1
    assert straggler["wait_s"] == pytest.approx(0.2)
    assert straggler["fleet_max_wait_s"] == pytest.approx(3.0)

    km = report.key_metrics()
    assert km["fleet_rows_per_sec"] == pytest.approx(180.0)
    assert km["fleet_collective_wait_s"] == pytest.approx(3.2)
    # wait fraction over both members' traced run time (10 s each)
    assert km["fleet_collective_wait_fraction"] == pytest.approx(
        3.2 / 20.0, abs=1e-5
    )
    assert km["fleet_mfu_spread"] == pytest.approx(0.1, abs=1e-6)
    assert km["fleet_lost_members"] == 0.0

    # JSON round-trip: per-member rows + straggler + key metrics survive
    doc = json.loads(json.dumps(report.to_json(), default=str))
    assert doc["type"] == "fleet_report"
    assert [r["process_index"] for r in doc["members"]] == [0, 1]
    by_proc = {r["process_index"]: r for r in doc["members"]}
    assert by_proc[0]["collective_wait_s"] == pytest.approx(3.0)
    assert by_proc[0]["status"] == "ok"
    assert by_proc[1]["hostname"] == "host1"
    assert doc["straggler"]["process_index"] == 1
    assert doc["key_metrics"]["fleet_rows_per_sec"] == pytest.approx(180.0)

    md = report.to_markdown()
    assert "Straggler: member 1" in md
    assert "| 0 (host0) | ok |" in md


def test_fleet_report_merged_spans_align_on_anchors(tmp_path):
    _write_member(tmp_path, 0, anchor_unix=1000.0, rendezvous_end=9.0)
    _write_member(tmp_path, 1, anchor_unix=1002.0, rendezvous_end=7.0)
    report = FleetReport.load(str(tmp_path))
    merged = report.merged_spans()
    fits = [s for s in merged if s["name"] == "fit"]
    assert {s["process_index"] for s in fits} == {0, 1}
    # member 0 fit starts at 1000 + (6-5) = 1001; member 1 at
    # 1002 + 1 - skew(1004-1004=0... rendezvous: m1=1002+2=1004, m0=1004)
    by_proc = {s["process_index"]: s["abs_ts"] for s in fits}
    assert by_proc[0] == pytest.approx(1001.0, abs=1e-3)
    assert by_proc[1] == pytest.approx(1003.0, abs=1e-3)


def test_fleet_report_degraded_killed_member_marked_lost(tmp_path):
    """The chaos-matrix leftover shape: the victim's trace is truncated
    mid-line and its final metrics snapshot never flushed (os._exit).
    The report must render partial — member marked lost — never crash,
    never silently read as complete."""
    _write_member(
        tmp_path, 0, anchor_unix=1000.0, wait_s=2.0, rows_per_sec=50.0,
        heartbeat_uptimes=(1.0, 2.0),
    )
    _write_member(
        tmp_path, 1, anchor_unix=1000.1, truncate_trace=True,
        write_metrics=False, heartbeat_uptimes=(1.0,),
    )
    report = FleetReport.load(str(tmp_path))
    assert report.lost_members() == [1]
    rows = {r["process_index"]: r for r in report.rows()}
    assert rows[1]["status"] == "lost"
    assert rows[0]["status"] == "ok"
    # the survivor's data still aggregates; the victim's surviving
    # heartbeats still render
    assert rows[1]["heartbeats"] == 1
    km = report.key_metrics()
    assert km["fleet_lost_members"] == 1.0
    assert km["fleet_rows_per_sec"] == pytest.approx(50.0)
    md = report.to_markdown()
    assert "lost" in md
    json.dumps(report.to_json(), default=str)  # JSON-safe throughout


def test_fleet_report_member_with_no_artifacts_is_synthesized_lost(
    tmp_path,
):
    """A member that never wrote ANYTHING (killed before its first span)
    still gets a row: fleet size is known from a peer's header."""
    _write_member(tmp_path, 0, anchor_unix=1000.0, wait_s=1.0)
    report = FleetReport.load(str(tmp_path))
    assert report.num_processes == 2
    assert report.lost_members() == [1]
    rows = {r["process_index"]: r for r in report.rows()}
    assert rows[1]["artifacts"] == {
        "trace": None, "telemetry": None, "flight": None,
    }


def test_discover_falls_back_to_newest_generation_dir(tmp_path):
    """`--fleet <workdir>` on a supervisor directory finds the NEWEST
    generation's streams under telemetry/gen<g> (the tools/fleet.py
    layout — relaunch generations renumber members, so generations
    never share a directory)."""
    gen0 = tmp_path / "telemetry" / "gen0"
    gen1 = tmp_path / "telemetry" / "gen1"
    gen0.mkdir(parents=True)
    gen1.mkdir(parents=True)
    _write_member(gen0, 0, anchor_unix=1000.0, wait_s=1.0)
    _write_member(gen0, 1, anchor_unix=1000.0, wait_s=1.0)
    _write_member(gen1, 0, anchor_unix=2000.0, wait_s=2.0)
    streams = discover_member_streams(str(tmp_path))
    assert set(streams) == {0}  # gen1: the survivor fleet only
    assert "gen1" in streams[0]["trace"]
    # pointing at a generation dir directly still works
    assert set(discover_member_streams(str(gen0))) == {0, 1}


def test_fleet_report_empty_dir_has_no_members(tmp_path):
    report = FleetReport.load(str(tmp_path))
    assert report.members == []
    assert report.key_metrics()["fleet_members"] == 0.0


def test_fleet_report_compare_gates_aggregated_metrics(tmp_path):
    _write_member(
        tmp_path, 0, anchor_unix=1000.0, wait_s=3.0, rows_per_sec=100.0,
    )
    _write_member(
        tmp_path, 1, anchor_unix=1000.0, wait_s=0.5, rows_per_sec=100.0,
    )
    report = FleetReport.load(str(tmp_path))
    # identical baseline: nothing regresses
    deltas = report.compare(report.to_json())
    assert deltas and not any(d.regressed for d in deltas)
    # a baseline with much lower wait fraction: ours regressed (higher
    # wait is WORSE — the lower-is-better direction)
    km = report.key_metrics()
    baseline = dict(km)
    baseline["fleet_collective_wait_fraction"] = (
        km["fleet_collective_wait_fraction"] / 10.0
    )
    regressed = {
        d.metric for d in report.compare(baseline) if d.regressed
    }
    assert "fleet_collective_wait_fraction" in regressed
    # and a baseline with much higher throughput: rows/s regressed
    baseline = dict(km)
    baseline["fleet_rows_per_sec"] = km["fleet_rows_per_sec"] * 10.0
    regressed = {
        d.metric for d in report.compare(baseline) if d.regressed
    }
    assert "fleet_rows_per_sec" in regressed


# ---------------------------------------------------------------------------
# cli report --fleet
# ---------------------------------------------------------------------------


def test_cli_report_fleet_renders_and_gates(tmp_path, capsys):
    from photon_ml_tpu.cli.report import main as report_main

    fleet_dir = tmp_path / "fleet_artifacts"
    fleet_dir.mkdir()
    _write_member(
        fleet_dir, 0, anchor_unix=1000.0, wait_s=3.0, rows_per_sec=100.0,
        heartbeat_uptimes=(1.0, 2.0),
    )
    _write_member(
        fleet_dir, 1, anchor_unix=1000.0, wait_s=0.1, rows_per_sec=90.0,
        heartbeat_uptimes=(1.0,),
    )
    out_md = tmp_path / "fleet.md"
    out_json = tmp_path / "fleet.json"
    rc = report_main([
        "--fleet", str(fleet_dir), "--out", str(out_md),
        "--json", str(out_json),
    ])
    assert rc == 0
    md = out_md.read_text()
    assert "# Fleet report" in md and "Straggler: member 1" in md
    doc = json.loads(out_json.read_text())
    assert doc["type"] == "fleet_report"
    assert len(doc["members"]) == 2

    # --compare --fail-on-regress on the aggregated key metrics: exit 3
    # when the wait fraction blew up vs baseline
    baseline = dict(doc["key_metrics"])
    baseline["fleet_collective_wait_fraction"] /= 10.0
    base_path = tmp_path / "baseline.json"
    base_path.write_text(json.dumps({"key_metrics": baseline}))
    rc = report_main([
        "--fleet", str(fleet_dir), "--compare", str(base_path),
        "--fail-on-regress",
    ])
    assert rc == 3
    # self-compare passes
    rc = report_main([
        "--fleet", str(fleet_dir), "--compare", str(out_json),
        "--fail-on-regress",
    ])
    assert rc == 0
    capsys.readouterr()


def test_cli_report_fleet_usage_errors(tmp_path, capsys):
    from photon_ml_tpu.cli.report import main as report_main

    with pytest.raises(SystemExit):
        report_main(["--fleet", str(tmp_path), "--trace", "x.jsonl"])
    # an empty directory is an error, not an empty report
    empty = tmp_path / "empty"
    empty.mkdir()
    assert report_main(["--fleet", str(empty)]) == 1
    assert report_main(["--fleet", str(tmp_path / "missing")]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# cli train explicit-flag suffixing (satellite: env path and flag path
# must agree on the member naming contract)
# ---------------------------------------------------------------------------


def test_train_explicit_artifact_flags_suffix_per_member(
    tmp_path, monkeypatch
):
    """`cli train --trace-out/--telemetry-out/--report-out` under a fleet
    identity writes per-member suffixed paths — the same contract
    configure_from_env applies to PHOTON_*_OUT — instead of
    last-writer-wins (the real 2-process gloo run is exercised in
    tests/test_fleet_observability.py via the env path)."""
    from photon_ml_tpu.cli.train import run

    data = tmp_path / "train.libsvm"
    lines = []
    for i in range(32):
        label = i % 2
        lines.append(f"{label} 1:{(i % 5) * 0.2:.1f} 2:{(i % 3) * 0.5:.1f}")
    data.write_text("\n".join(lines) + "\n")
    config = {
        "task": "logistic",
        "input": {"format": "libsvm", "paths": str(data)},
        "coordinates": {
            "fixed": {
                "shard_name": "features",
                "optimizer": {"max_iterations": 3},
            }
        },
        "num_iterations": 1,
        "heartbeat": False,
        "trace_out": str(tmp_path / "run.trace.jsonl"),
        "telemetry_out": str(tmp_path / "run.telemetry.jsonl"),
        "report_out": str(tmp_path / "run.report.md"),
    }
    monkeypatch.setenv(identity.ENV_PROC_ID, "1")
    monkeypatch.setenv(identity.ENV_PROC_COUNT, "2")
    summary = run(config)
    assert (tmp_path / "run.trace.proc-1.jsonl").exists()
    assert not (tmp_path / "run.trace.jsonl").exists()
    assert (tmp_path / "run.telemetry.proc-1.jsonl").exists()
    assert summary["report"] == str(tmp_path / "run.report.proc-1.md")
    assert (tmp_path / "run.report.proc-1.md").exists()
    assert (tmp_path / "run.report.proc-1.json").exists()
    header = json.loads(
        (tmp_path / "run.trace.proc-1.jsonl").read_text().splitlines()[0]
    )
    assert header["process_index"] == 1


# ---------------------------------------------------------------------------
# ISSUE 16: fleet-merged hot-executable list
# ---------------------------------------------------------------------------


def _profile_gauges(name, excl, dispatches, mfu, bound_code):
    return {
        f"profile.exec.{name}.est_exclusive_seconds": excl,
        f"profile.exec.{name}.dispatches": dispatches,
        f"profile.exec.{name}.mfu": mfu,
        f"profile.exec.{name}.bound_code": bound_code,
    }


def test_fleet_report_merged_hot_executables(tmp_path):
    """The fleet hot list sums exclusive seconds per executable NAME
    across members (SPMD: the fleet pays every member's copy), reports
    the best-observed MFU, collects the bound classes seen, and rides
    the member rows / JSON / markdown."""
    g0 = dict(_profile_gauges("solve", 4.0, 100, 0.30, 1))
    g0.update(_profile_gauges("aux", 1.0, 50, 0.05, 4))
    _write_member(
        tmp_path, 0, anchor_unix=1000.0, wait_s=1.0, rows_per_sec=100.0,
        extra_gauges=g0,
    )
    _write_member(
        tmp_path, 1, anchor_unix=1000.0, wait_s=1.0, rows_per_sec=90.0,
        extra_gauges=_profile_gauges("solve", 2.0, 100, 0.40, 3),
    )
    report = FleetReport.load(str(tmp_path))

    hot = report.merged_hot_executables()
    assert [e["name"] for e in hot] == ["solve", "aux"]
    solve = hot[0]
    assert solve["est_exclusive_seconds"] == pytest.approx(6.0)
    assert solve["dispatches"] == 200
    assert solve["members"] == 2
    assert solve["mfu_max"] == pytest.approx(0.40)
    assert solve["bound_classes"] == ["HBM-bound", "MXU-bound"]
    assert solve["timing_suspect"] is False
    assert hot[1]["members"] == 1
    assert hot[1]["bound_classes"] == ["dispatch-bound"]

    # each member row names ITS hottest executable
    rows = {r["process_index"]: r for r in report.rows()}
    assert rows[0]["hot_exec"] == "solve"
    assert rows[1]["hot_exec"] == "solve"

    doc = json.loads(json.dumps(report.to_json(), default=str))
    assert doc["hot_executables"][0]["name"] == "solve"
    assert doc["hot_executables"][0]["members"] == 2

    md = report.to_markdown()
    assert "## Fleet hot executables" in md
    assert "| `solve` |" in md
    assert "HBM-bound, MXU-bound" in md
    assert "| hot exec |" in md.replace("\n", " ")  # Members column


def test_fleet_report_members_without_profiles_render_unknown(tmp_path):
    _write_member(tmp_path, 0, anchor_unix=1000.0, wait_s=1.0)
    _write_member(tmp_path, 1, anchor_unix=1000.0, wait_s=1.0)
    report = FleetReport.load(str(tmp_path))
    assert report.merged_hot_executables() == []
    assert all(r["hot_exec"] is None for r in report.rows())
    md = report.to_markdown()
    assert "## Fleet hot executables" not in md
    assert "unknown" in md  # the hot-exec member column stays unknown
