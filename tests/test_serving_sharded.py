"""Sharded nearline serving (ISSUE 12): entity-sharded engine on the
8-device CPU mesh, the continuous batcher + asyncio front end, nearline
per-entity updates, fault seams (serving.async_dispatch,
serving.nearline_event, serving.nearline_apply) with the hard-kill
chaos row, and the sustained-load SLO smoke slice."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectBucketModel,
    RandomEffectModel,
)
from photon_ml_tpu.optim.factory import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.parallel.sharding import ElasticPlacementError
from photon_ml_tpu.serving import (
    AsyncScoringServer,
    BadRequest,
    ContinuousBatcher,
    MicroBatcher,
    ModelRegistry,
    NearlineUpdater,
    Overloaded,
    ScoringEngine,
    ScoringServer,
    ScoringService,
    publish_version,
    scan_versions,
)
from photon_ml_tpu.testing import generate_game_dataset


def _make_model(truth, scale=1.0, n_buckets=2, task="logistic"):
    """FE + per-user RE GameModel straight from planted coefficients."""
    w_users = truth["w_users"] * scale
    n_users, local_k = w_users.shape
    fe = FixedEffectModel(
        coefficients=jnp.asarray(truth["w_global"] * scale, jnp.float32),
        shard_name="global",
    )
    entity_bucket = (np.arange(n_users) % n_buckets).astype(np.int64)
    entity_pos = np.zeros(n_users, np.int64)
    buckets = []
    for b in range(n_buckets):
        codes_b = np.nonzero(entity_bucket == b)[0]
        entity_pos[codes_b] = np.arange(len(codes_b))
        proj = np.tile(np.arange(local_k, dtype=np.int32), (len(codes_b), 1))
        buckets.append(
            RandomEffectBucketModel(
                coefficients=jnp.asarray(w_users[codes_b], jnp.float32),
                projection=jnp.asarray(proj),
                entity_codes=jnp.asarray(codes_b, jnp.int32),
            )
        )
    re = RandomEffectModel(
        id_name="userId",
        shard_name="user",
        buckets=tuple(buckets),
        entity_bucket=entity_bucket,
        entity_pos=entity_pos,
        vocab=np.arange(n_users),
    )
    return GameModel(task=task, models={"fixed": fe, "perUser": re})


def _request_rows(truth, data, indices):
    Xg, Xu, users = truth["Xg"], truth["Xu"], truth["users"]
    rows = []
    for i in indices:
        rows.append(
            {
                "features": {
                    "global": [
                        [j, float(Xg[i, j])]
                        for j in range(Xg.shape[1])
                        if Xg[i, j] != 0
                    ],
                    "user": [
                        [j, float(Xu[i, j])]
                        for j in range(Xu.shape[1])
                        if Xu[i, j] != 0
                    ],
                },
                "ids": {"userId": int(users[i])},
                "offset": float(data.offset[i]),
            }
        )
    return rows


@pytest.fixture(scope="module")
def mesh_world():
    """32 users (16 per geometry bucket — divisible by the 8-device
    entity axis) so the same model serves replicated AND sharded."""
    data, truth = generate_game_dataset(
        n_users=32, rows_per_user=6, fe_dim=6, re_dim=4, seed=11
    )
    return data, truth


_INDEX_MAPS = {
    "global": [f"g{j}" for j in range(6)],
    "user": [f"u{j}" for j in range(4)],
}


def _entity_mesh(n=8):
    return make_mesh({"model": n})


def _post(port, path, body, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(port, path, timeout=15):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# entity-sharded engine
# ---------------------------------------------------------------------------


def test_sharded_engine_matches_predict_mean(mesh_world, multichip):
    """RE tables placed across the 8-device entity axis score identically
    to the replicated engine and to the batch predict_mean path."""
    data, truth = mesh_world
    model = _make_model(truth)
    expected = np.asarray(model.predict_mean(data))[: data.num_rows]
    rows = _request_rows(truth, data, range(data.num_rows))
    engine = ScoringEngine(
        model, max_batch=32, version="sharded", mesh=_entity_mesh()
    ).warmup()
    assert engine.entity_axis == "model"
    got = engine.score_rows(rows)
    np.testing.assert_allclose(got, expected, atol=1e-6)
    # the tables really are distributed: one device holds 1/8 of the rows
    table = engine.re_tables(0)[0][1]
    shard_shapes = {s.data.shape for s in table.addressable_shards}
    assert shard_shapes == {(2, 4)}  # 16 entities / 8 devices


def test_sharded_engine_rejects_indivisible_axis_with_valid_sizes(
    mesh_world, multichip
):
    """An entity count that does not divide the serving mesh's entity
    axis lists the axis sizes that CAN hold the table (the elastic
    restore formatting), not a bare modulus."""
    data, truth = mesh_world
    model = _make_model(truth, n_buckets=3)  # 32 users -> buckets of 11/11/10
    with pytest.raises(ElasticPlacementError) as ei:
        ScoringEngine(model, mesh=_entity_mesh())
    message = str(ei.value)
    assert "valid target axis sizes" in message
    assert "serving mesh" in message
    assert "[1]" in message  # 11 entities: only a 1-wide axis divides


def test_sharded_engine_from_streamed_checkpoint(
    tmp_path, mesh_world, multichip
):
    """load(re_checkpoints=...) restores a sharded training checkpoint's
    table straight onto the serving mesh via restore_placed and serves
    the CHECKPOINT's coefficients, not the model dir's."""
    from photon_ml_tpu.data.model_store import save_game_model
    from photon_ml_tpu.game.checkpoint import (
        CheckpointSpec,
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    import dataclasses

    data, truth = mesh_world
    fresh = _make_model(truth, n_buckets=1)
    # stale differs ONLY in the RE table (the thing the checkpoint
    # replaces); FE stays identical so parity isolates the restore
    re_sub = fresh.models["perUser"]
    stale = fresh.with_model(
        "perUser",
        dataclasses.replace(
            re_sub,
            buckets=(
                dataclasses.replace(
                    re_sub.buckets[0],
                    coefficients=jnp.zeros_like(
                        re_sub.buckets[0].coefficients
                    ),
                ),
            ),
        ),
    )
    model_dir = str(tmp_path / "model")
    save_game_model(stale, model_dir)
    for shard, names in _INDEX_MAPS.items():
        from photon_ml_tpu.data.index_map import IndexMap

        IndexMap(names).save(
            os.path.join(model_dir, "feature-indexes", shard)
        )
    ckpt_dir = str(tmp_path / "ckpt")
    mgr = StreamingCheckpointManager(CheckpointSpec(directory=ckpt_dir))
    mgr.save(
        StreamCheckpointState(
            next_chunk=1,
            coefficients=np.asarray(
                fresh.models["perUser"].buckets[0].coefficients
            ),
        )
    )
    engine = ScoringEngine.load(
        model_dir,
        max_batch=16,
        mesh=_entity_mesh(),
        re_checkpoints={"perUser": ckpt_dir},
    ).warmup()
    expected = np.asarray(fresh.predict_mean(data))[: data.num_rows]
    got = engine.score_rows(_request_rows(truth, data, range(data.num_rows)))
    np.testing.assert_allclose(got, expected, atol=1e-6)
    # and the read-only restore manager refuses to write
    from photon_ml_tpu.game.checkpoint import CheckpointError

    ro = StreamingCheckpointManager.open_for_restore(ckpt_dir)
    with pytest.raises(CheckpointError, match="read-only"):
        ro.save(
            StreamCheckpointState(next_chunk=2, coefficients=np.zeros((2, 2)))
        )


# ---------------------------------------------------------------------------
# continuous batcher + deadline edges (ISSUE satellite)
# ---------------------------------------------------------------------------


def test_continuous_batcher_never_waits_on_a_timer():
    """A lone request dispatches immediately even with a huge deadline
    configured — the continuous scheduler has no timer to wait out."""
    b = ContinuousBatcher(
        lambda rows: (np.zeros(len(rows), np.float32), "v"),
        max_batch=8, max_delay_ms=10_000.0,
    ).start()
    try:
        t0 = time.monotonic()
        b.submit([{}]).result(timeout=10)
        assert time.monotonic() - t0 < 5.0  # not the 10s deadline
    finally:
        b.stop()


def test_continuous_batcher_admits_into_next_bucket_as_capacity_frees():
    """Requests arriving while a batch is in flight ride the NEXT bucket
    together: batch size grows with offered load instead of a deadline."""
    dispatched = []
    gate = threading.Event()

    def scorer(rows):
        dispatched.append(len(rows))
        if len(dispatched) == 1:
            gate.wait(timeout=10)  # hold the first batch in flight
        return np.zeros(len(rows), np.float32), "v"

    b = ContinuousBatcher(scorer, max_batch=8, queue_depth=100).start()
    try:
        first = b.submit([{}])
        time.sleep(0.1)  # dispatcher now blocked in scorer on batch 1
        later = [b.submit([{}]) for _ in range(4)]
        gate.set()
        assert len(first.result(timeout=10)["scores"]) == 1
        for f in later:
            f.result(timeout=10)
    finally:
        b.stop()
    assert dispatched[0] == 1
    assert dispatched[1] == 4  # all four queued units rode one bucket


def test_batcher_request_arriving_exactly_at_bucket_full():
    """A unit that lands when the forming batch is exactly at max_batch
    rows must ride the NEXT dispatch, not overflow or stall this one."""
    dispatched = []
    gate = threading.Event()

    def scorer(rows):
        dispatched.append(len(rows))
        if len(dispatched) == 1:
            gate.wait(timeout=10)
        return np.zeros(len(rows), np.float32), "v"

    b = ContinuousBatcher(scorer, max_batch=4, queue_depth=100).start()
    try:
        first = b.submit([{}])
        time.sleep(0.1)
        fill = b.submit([{}] * 4)  # exactly max_batch rows on its own
        extra = b.submit([{}])  # must NOT join fill's bucket
        gate.set()
        first.result(timeout=10)
        assert len(fill.result(timeout=10)["scores"]) == 4
        assert len(extra.result(timeout=10)["scores"]) == 1
    finally:
        b.stop()
    assert dispatched == [1, 4, 1]


def test_batcher_timed_out_future_cancelled_mid_dispatch():
    """A caller that times out cancels its future while the unit is
    ALREADY in dispatch: result delivery must tolerate the cancelled
    future and the dispatcher must survive to serve the next request."""
    entered = threading.Event()
    gate = threading.Event()

    def scorer(rows):
        entered.set()
        gate.wait(timeout=10)
        return np.zeros(len(rows), np.float32), "v"

    b = MicroBatcher(scorer, max_batch=4, max_delay_ms=1.0).start()
    try:
        doomed = b.submit([{}])
        assert entered.wait(timeout=10)  # the unit is inside the scorer
        assert doomed.cancel() is False or True  # running future: either way
        doomed.cancel()
        gate.set()
        time.sleep(0.1)
        # the dispatcher survived the InvalidStateError path
        assert len(b.submit([{}]).result(timeout=10)["scores"]) == 1
    finally:
        gate.set()
        b.stop()


def test_shed_accounting_matches_returned_503s_exactly(mesh_world):
    """Under a burst, the serving.shed counter and the 503 responses are
    the SAME number — shed accounting can't drift from what callers saw."""
    data, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=4).warmup()
    gate = threading.Event()
    base = telemetry.snapshot()["counters"].get("serving.shed", 0)

    def slow_scorer(rows):
        gate.wait(timeout=10)
        return engine.score_rows(rows), engine.version

    service = ScoringService.__new__(ScoringService)
    service._source = engine
    service.request_timeout_s = 30.0
    service._batcher = ContinuousBatcher(
        slow_scorer, max_batch=4, queue_depth=4
    )
    service._updater = None
    server = ScoringServer(service, port=0).start()
    try:
        rows = _request_rows(truth, data, range(2))
        results = []
        lock = threading.Lock()

        def client():
            try:
                _post(server.port, "/v1/score", {"rows": rows})
                code = 200
            except urllib.error.HTTPError as e:
                code = e.code
            with lock:
                results.append(code)

        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        gate.set()
        for t in threads:
            t.join(timeout=30)
        got_503 = sum(1 for c in results if c == 503)
        assert got_503 > 0  # the burst actually overflowed the queue
        assert sum(1 for c in results if c == 200) == len(results) - got_503
        shed = telemetry.snapshot()["counters"].get("serving.shed", 0) - base
        assert shed == got_503
    finally:
        gate.set()
        server.stop()


# ---------------------------------------------------------------------------
# asyncio front end
# ---------------------------------------------------------------------------


def test_async_server_scores_and_maps_errors(mesh_world):
    data, truth = mesh_world
    model = _make_model(truth)
    engine = ScoringEngine(model, max_batch=8, version="v-aio").warmup()
    service = ScoringService(engine, max_batch=8, batcher="continuous")
    server = AsyncScoringServer(service, port=0).start()
    try:
        rows = _request_rows(truth, data, range(4))
        expected = np.asarray(model.predict_mean(data))[:4]
        result = _post(server.port, "/v1/score", {"rows": rows})
        np.testing.assert_allclose(result["scores"], expected, atol=1e-6)
        assert result["model_version"] == "v-aio"
        health = _get(server.port, "/healthz")
        assert health["status"] == "serving" and health["warm"]
        metrics = _get(server.port, "/metricsz")
        assert "counters" in metrics and "xla_executables" in metrics
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, "/v1/score", {"not_rows": []})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(server.port, "/nope")
        assert ei.value.code == 404
        # keep-alive: one connection, two requests
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=15)
        try:
            for _ in range(2):
                conn.request(
                    "POST", "/v1/score",
                    body=json.dumps({"rows": rows[:1]}),
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                assert resp.status == 200
                resp.read()
        finally:
            conn.close()
    finally:
        server.stop()


def test_health_and_metrics_stay_responsive_while_scoring_is_wedged(
    mesh_world,
):
    """The ISSUE-named fix: /healthz and /metricsz must answer with
    bounded latency while the scoring path is saturated/wedged (engine
    mid-warmup, batcher queue full, dispatcher blocked) — on BOTH front
    ends, because they read telemetry registries and never queue behind
    the batcher."""
    data, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=4).warmup()
    gate = threading.Event()

    def wedged_scorer(rows):
        gate.wait(timeout=30)
        return engine.score_rows(rows), engine.version

    for server_cls, batcher in (
        (ScoringServer, "deadline"),
        (AsyncScoringServer, "continuous"),
    ):
        service = ScoringService.__new__(ScoringService)
        service._source = engine
        service.request_timeout_s = 30.0
        batcher_cls = (
            ContinuousBatcher if batcher == "continuous" else MicroBatcher
        )
        service._batcher = batcher_cls(
            wedged_scorer, max_batch=4, queue_depth=8
        )
        service._updater = None
        server = server_cls(service, port=0).start()
        try:
            rows = _request_rows(truth, data, range(2))
            # wedge the dispatcher and fill some queue
            pending = threading.Thread(
                target=lambda: service._batcher.submit(rows), daemon=True
            )
            pending.start()
            time.sleep(0.1)
            for path in ("/healthz", "/metricsz"):
                t0 = time.monotonic()
                body = _get(server.port, path, timeout=5)
                assert time.monotonic() - t0 < 2.0, (server_cls, path)
                assert body
        finally:
            gate.set()
            server.stop()
            gate.clear()


# ---------------------------------------------------------------------------
# nearline personalization
# ---------------------------------------------------------------------------


_NEARLINE_CONFIG = OptimizerConfig(
    max_iterations=30,
    tolerance=1e-8,
    regularization=RegularizationContext(reg_type=RegularizationType.L2),
    regularization_weight=0.5,
)


def test_nearline_resolve_matches_direct_solve(mesh_world):
    """The nearline row swap equals solving the same warm-started
    per-entity problem directly: projection mapping, residual offsets
    (fixed-effect margin folded in), and the in-place commit all line
    up with the training solver's answer."""
    from photon_ml_tpu.game.coordinates import _re_solver
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.ops.losses import get_loss
    from photon_ml_tpu.optim.factory import build_objective

    data, truth = mesh_world
    model = _make_model(truth)
    engine = ScoringEngine(model, max_batch=8, version="t").warmup()
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG, rows_per_solve=4
    )
    target = 6  # bucket 0, some position
    events = [
        {
            "ids": {"userId": target},
            "features": {
                "global": [[0, 1.0], [2, -0.5]],
                "user": [[0, 1.0], [1, 0.5], [3, -1.0]],
            },
            "label": 1.0,
            "offset": 0.2,
        },
        {
            "ids": {"userId": target},
            "features": {"user": [[2, 2.0]]},
            "label": 0.0,
        },
    ]
    # expected: assemble the dense local problem by hand
    w_global = truth["w_global"]
    bucket = int(np.asarray(model.models["perUser"].entity_bucket)[target])
    pos = int(np.asarray(model.models["perUser"].entity_pos)[target])
    w0 = np.asarray(
        model.models["perUser"].buckets[bucket].coefficients
    )[pos]
    R, K = 4, 4
    x = np.zeros((1, R, K), np.float32)
    x[0, 0, [0, 1, 3]] = [1.0, 0.5, -1.0]
    x[0, 1, 2] = 2.0
    labels = np.zeros((1, R), np.float32)
    labels[0, 0] = 1.0
    offsets = np.zeros((1, R), np.float32)
    offsets[0, 0] = 0.2 + 1.0 * w_global[0] - 0.5 * w_global[2]
    weights = np.zeros((1, R), np.float32)
    weights[0, :2] = 1.0
    obj = build_objective(get_loss("logistic").name, _NEARLINE_CONFIG)
    solver = _re_solver(_NEARLINE_CONFIG, "logistic")
    res, _ = solver(
        obj,
        DenseBatch(
            x=jnp.asarray(x), labels=jnp.asarray(labels),
            offsets=jnp.asarray(offsets), weights=jnp.asarray(weights),
        ),
        jnp.asarray(w0[None, :]),
        jnp.float32(0.0),
        None,
    )
    expected_row = np.asarray(res.w)[0]

    accepted = updater.submit(events)
    assert accepted == 2
    stats = updater.flush()
    assert stats == {"entities": 1, "rows": 2, "applies": 1}
    got_row = np.asarray(engine.re_tables(0)[bucket][1])[pos]
    np.testing.assert_allclose(got_row, expected_row, atol=1e-6)
    assert not np.allclose(got_row, w0)  # the solve actually moved


def test_nearline_event_validation_and_buffer_semantics(mesh_world):
    _, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=8)
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG,
        rows_per_solve=2, queue_depth=4,
    )
    with pytest.raises(BadRequest, match="'ids' must contain"):
        updater.submit([{"features": {}, "label": 1.0}])
    with pytest.raises(BadRequest, match="'label' must be a number"):
        updater.submit([{"ids": {"userId": 1}, "label": "x"}])
    with pytest.raises(BadRequest, match="col, value"):
        updater.submit([
            {"ids": {"userId": 1}, "label": 1.0,
             "features": {"user": [["named", "", 1.0]]}}
        ])
    # unknown entities are dropped+counted, not errors
    base = telemetry.snapshot()["counters"].get(
        "serving.nearline.unknown_entities", 0
    )
    assert updater.submit(
        [{"ids": {"userId": 424242}, "label": 1.0, "features": {}}]
    ) == 0
    assert telemetry.snapshot()["counters"][
        "serving.nearline.unknown_entities"
    ] == base + 1
    # queue depth sheds with the typed Overloaded
    ev = {"ids": {"userId": 1}, "label": 1.0, "features": {}}
    updater.submit([ev] * 2)
    updater.submit([dict(ev, ids={"userId": 2})] * 2)
    with pytest.raises(Overloaded, match="nearline buffer at capacity"):
        updater.submit([dict(ev, ids={"userId": 3})])
    # per-entity ring keeps the NEWEST rows_per_solve events
    assert len(updater._buffers["1"]) == 2


def test_nearline_untouched_entities_bit_identical(mesh_world):
    data, truth = mesh_world
    model = _make_model(truth)
    engine = ScoringEngine(model, max_batch=32, version="t").warmup()
    rows = _request_rows(truth, data, range(data.num_rows))
    before = engine.score_rows(rows).copy()
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG, rows_per_solve=2
    )
    target = 5
    updater.submit([
        {"ids": {"userId": target}, "label": 1.0,
         "features": {"user": [[0, 1.0]]}}
    ])
    updater.flush()
    after = engine.score_rows(rows)
    users = truth["users"]
    touched = np.asarray([int(u) == target for u in users[: data.num_rows]])
    assert touched.any()
    # the updated entity's scores moved; everyone else's are BIT-identical
    assert not np.allclose(before[touched], after[touched])
    np.testing.assert_array_equal(before[~touched], after[~touched])


def test_nearline_publish_roundtrip(tmp_path, mesh_world):
    """publish() persists the LIVE (nearline-updated) tables as the next
    registry version: a fresh registry load scores exactly like the
    mutated in-memory engine."""
    data, truth = mesh_world
    model = _make_model(truth)
    registry_dir = str(tmp_path / "registry")
    publish_version(registry_dir, model, _INDEX_MAPS)
    engine = ScoringEngine(model, max_batch=16, version="v-00000001").warmup()
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG,
        rows_per_solve=2, publish_dir=registry_dir,
        publish_interval_s=0.0, index_maps=_INDEX_MAPS,
    )
    assert updater.publish() is None  # nothing applied yet
    updater.submit([
        {"ids": {"userId": 9}, "label": 1.0,
         "features": {"user": [[1, 1.0]]}}
    ])
    updater.flush()
    path = updater.publish()
    assert path is not None and path.endswith("v-00000002")
    meta = json.loads(
        open(os.path.join(path, "model-metadata.json")).read()
    )
    assert meta["extra"]["nearline_seq"] == 1
    registry = ModelRegistry(registry_dir, max_batch=16, warm=False,
                             poll_interval=60).start()
    try:
        assert registry.engine.version == "v-00000002"
        rows = _request_rows(truth, data, range(data.num_rows))
        np.testing.assert_allclose(
            registry.engine.score_rows(rows),
            engine.score_rows(rows),
            atol=1e-6,
        )
    finally:
        registry.stop()


# ---------------------------------------------------------------------------
# fault seams (L016) + the chaos row
# ---------------------------------------------------------------------------


def test_async_dispatch_fault_seam_isolated_to_callers():
    """An injected fault at serving.async_dispatch fails the riding
    requests with the typed error; the continuous dispatcher survives."""
    b = ContinuousBatcher(
        lambda rows: (np.zeros(len(rows), np.float32), "v"), max_batch=4
    ).start()
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.async_dispatch", action="raise", nth=1),
        ]))
        doomed = b.submit([{}])
        with pytest.raises(faults.InjectedFault):
            doomed.result(timeout=10)
        faults.clear_plan()
        assert len(b.submit([{}]).result(timeout=10)["scores"]) == 1
    finally:
        faults.clear_plan()
        b.stop()


def test_nearline_event_fault_seam(mesh_world):
    _, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=8)
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG
    )
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.nearline_event", action="raise",
                             nth=1),
        ]))
        with pytest.raises(faults.InjectedFault):
            updater.submit(
                [{"ids": {"userId": 1}, "label": 1.0, "features": {}}]
            )
    finally:
        faults.clear_plan()
    assert updater.submit(
        [{"ids": {"userId": 1}, "label": 1.0, "features": {}}]
    ) == 1


def test_nearline_apply_fault_leaves_tables_untouched(mesh_world):
    """A fault at the serving.nearline_apply commit point aborts BEFORE
    the table swap: the serving tables and nearline_seq are exactly as
    before — no torn in-memory state."""
    data, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=16).warmup()
    rows = _request_rows(truth, data, range(8))
    before = engine.score_rows(rows).copy()
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG, rows_per_solve=2
    )
    updater.submit([
        {"ids": {"userId": 3}, "label": 1.0,
         "features": {"user": [[0, 1.0]]}}
    ])
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.nearline_apply", action="raise",
                             nth=1),
        ]))
        with pytest.raises(faults.InjectedFault):
            updater.flush()
    finally:
        faults.clear_plan()
    assert engine.nearline_seq == 0
    np.testing.assert_array_equal(engine.score_rows(rows), before)
    # the aborted bucket's events were REQUEUED, not discarded: the next
    # (un-faulted) flush applies them
    assert updater.flush()["applies"] == 1
    assert engine.nearline_seq == 1


def test_nearline_oov_only_event_leaves_row_untouched(mesh_world):
    """An event whose features all miss the entity's local projection
    carries no data about the row: with a weight-1 zero-design row the
    pure L2 re-solve would wipe the live coefficients to ~0. Such events
    must be dropped whole and the live row left untouched."""
    data, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=32).warmup()
    rows = _request_rows(truth, data, range(data.num_rows))
    before = engine.score_rows(rows).copy()
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG, rows_per_solve=2
    )
    base = telemetry.snapshot()["counters"].get(
        "serving.nearline.dropped_events", 0
    )
    # col 99 is outside every projection row (local space is cols 0..3);
    # an explicit weight of 0 is a tombstone, NOT a falsy-default 1.0
    assert updater.submit([
        {"ids": {"userId": 5}, "label": 1.0,
         "features": {"user": [[99, 1.0]]}},
        {"ids": {"userId": 6}, "label": 1.0, "features": {}},
        {"ids": {"userId": 7}, "label": 1.0, "weight": 0.0,
         "features": {"user": [[0, 1.0]]}},
    ]) == 3
    assert updater.flush() == {"entities": 0, "rows": 0, "applies": 0}
    assert engine.nearline_seq == 0
    np.testing.assert_array_equal(engine.score_rows(rows), before)
    assert telemetry.snapshot()["counters"][
        "serving.nearline.dropped_events"
    ] == base + 3


def test_nearline_bucket_failure_isolated_and_requeued(mesh_world):
    """One bucket's commit failure must not discard the OTHER bucket's
    apply, and the failed bucket's events retry on the next flush."""
    data, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=16).warmup()
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG, rows_per_solve=2
    )
    # userId 2 -> geometry bucket 0 (solved first), userId 3 -> bucket 1
    updater.submit([
        {"ids": {"userId": 2}, "label": 1.0,
         "features": {"user": [[0, 1.0]]}},
        {"ids": {"userId": 3}, "label": 0.0,
         "features": {"user": [[1, 1.0]]}},
    ])
    try:
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule("serving.nearline_apply", action="raise",
                             nth=1),
        ]))
        with pytest.raises(faults.InjectedFault):
            updater.flush()
    finally:
        faults.clear_plan()
    # bucket 0 failed (requeued), bucket 1 applied
    assert engine.nearline_seq == 1
    assert "2" in updater._buffers and "3" not in updater._buffers
    assert updater.flush()["entities"] == 1
    assert engine.nearline_seq == 2


def test_nearline_submit_accepts_new_entities_after_swap(mesh_world):
    """After a hot swap the cached host view is stale: submit must not
    drop events for entities that exist only in the NEW model — the
    pre-check is skipped until flush rebuilds the view."""
    data, truth = mesh_world
    small = dict(truth)
    small["w_users"] = truth["w_users"][:16]
    old_engine = ScoringEngine(_make_model(small), max_batch=8)
    new_engine = ScoringEngine(_make_model(truth), max_batch=8)

    class Src:
        def __init__(self, engine):
            self.engine = engine

    src = Src(old_engine)
    updater = NearlineUpdater(
        src, id_name="userId", config=_NEARLINE_CONFIG, rows_per_solve=2
    )
    # userId 20 exists only in the 32-user model: dropped while the view
    # matches the live engine, accepted unchecked right after the swap
    ev = {"ids": {"userId": 20}, "label": 1.0,
          "features": {"user": [[0, 1.0]]}}
    assert updater.submit([ev]) == 0
    src.engine = new_engine
    assert updater.submit([ev]) == 1
    res = updater.flush()
    assert res["entities"] == 1
    assert new_engine.nearline_seq == 1
    assert old_engine.nearline_seq == 0


def test_nearline_applied_rows_counts_real_entities(mesh_world):
    """serving.nearline.applied_rows counts real entity rows, not the
    power-of-two padded lanes the solve dispatches."""
    data, truth = mesh_world
    engine = ScoringEngine(_make_model(truth), max_batch=16).warmup()
    updater = NearlineUpdater(
        engine, id_name="userId", config=_NEARLINE_CONFIG, rows_per_solve=2
    )
    base = telemetry.snapshot()["counters"].get(
        "serving.nearline.applied_rows", 0
    )
    # three entities in bucket 0: 3 lanes padded to 4 on device
    updater.submit([
        {"ids": {"userId": u}, "label": 1.0,
         "features": {"user": [[0, 1.0]]}}
        for u in (0, 2, 4)
    ])
    assert updater.flush()["entities"] == 3
    assert telemetry.snapshot()["counters"][
        "serving.nearline.applied_rows"
    ] == base + 3


_CHAOS_WORKER = r"""
import json, sys
import numpy as np
from photon_ml_tpu.serving import ModelRegistry, NearlineUpdater
from photon_ml_tpu.optim.factory import (
    OptimizerConfig, RegularizationContext, RegularizationType,
)

registry_dir = sys.argv[1]
registry = ModelRegistry(registry_dir, max_batch=8, warm=False,
                         poll_interval=60).start()
try:
    updater = NearlineUpdater(
        registry, id_name="userId",
        config=OptimizerConfig(
            max_iterations=10,
            regularization=RegularizationContext(
                reg_type=RegularizationType.L2),
            regularization_weight=0.5,
        ),
        rows_per_solve=2, publish_dir=registry_dir,
        publish_interval_s=0.0,
        index_maps={"global": [f"g{j}" for j in range(6)],
                    "user": [f"u{j}" for j in range(4)]},
    )
    updater.submit([{"ids": {"userId": 2}, "label": 1.0,
                     "features": {"user": [[0, 1.0]]}}])
    updater.flush()      # serving.nearline_apply hit 1: the table swap
    path = updater.publish()  # hit 2: the registry publish
    print(json.dumps({"published": path}))
finally:
    registry.stop()
"""


def test_chaos_hard_kill_during_nearline_swap_keeps_registry_consistent(
    tmp_path, mesh_world
):
    """The chaos row: a subprocess hard-killed (os._exit, no unwinding)
    at the serving.nearline_apply commit — at the in-memory swap AND at
    the registry publish — must leave the on-disk registry serving a
    consistent version: the old one, never a torn one. An unarmed rerun
    then publishes cleanly and the registry hot-swaps forward."""
    _, truth = mesh_world
    registry_dir = str(tmp_path / "registry")
    publish_version(registry_dir, _make_model(truth), _INDEX_MAPS)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"

    def run(plan):
        e = dict(env)
        if plan is not None:
            e["PHOTON_FAULT_PLAN"] = json.dumps(plan)
        else:
            e.pop("PHOTON_FAULT_PLAN", None)
        return subprocess.run(
            [sys.executable, "-c", _CHAOS_WORKER, registry_dir],
            capture_output=True, text=True, timeout=600, cwd=repo, env=e,
        )

    for nth in (1, 2):  # kill at the table swap, then at the publish
        proc = run({"rules": [{"point": "serving.nearline_apply",
                               "action": "exit", "nth": nth}]})
        assert proc.returncode == faults.DEFAULT_EXIT_CODE, proc.stderr[-2000:]
        versions = [v for v, _p in scan_versions(registry_dir)]
        assert versions == [1], (nth, versions)
        # the registry still loads and serves the intact old version
        registry = ModelRegistry(registry_dir, max_batch=8, warm=False,
                                 poll_interval=60).start()
        try:
            assert registry.engine.version == "v-00000001"
        finally:
            registry.stop()

    proc = run(None)  # unarmed: the publish lands atomically
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["published"].endswith("v-00000002")
    assert [v for v, _p in scan_versions(registry_dir)] == [1, 2]


# ---------------------------------------------------------------------------
# the e2e acceptance: sharded + async + hot swap + nearline, mid-traffic
# ---------------------------------------------------------------------------


def test_sharded_async_serving_survives_swap_and_nearline_mid_traffic(
    tmp_path, mesh_world, multichip
):
    """ISSUE 12 acceptance: RE tables across the forced 8-device CPU
    mesh, concurrent HTTP scores matching predict_mean to 1e-6, correct
    across BOTH a registry hot-swap and a nearline per-entity update
    applied mid-traffic (updated entity reflects the re-solve, untouched
    entities bit-identical), zero failed requests, jit-compile counter
    flat post-warmup."""
    data, truth = mesh_world
    mesh = _entity_mesh()
    m1 = _make_model(truth)
    m2 = _make_model(truth, scale=0.5)
    expected = {
        "v-00000001": np.asarray(m1.predict_mean(data))[: data.num_rows],
        "v-00000002": np.asarray(m2.predict_mean(data))[: data.num_rows],
    }
    registry_dir = str(tmp_path / "registry")
    publish_version(registry_dir, m1, _INDEX_MAPS)
    registry = ModelRegistry(
        registry_dir, max_batch=16, poll_interval=0.2,
        mesh=mesh, entity_axis="model",
    ).start()
    updater = NearlineUpdater(
        registry, id_name="userId", config=_NEARLINE_CONFIG,
        rows_per_solve=2,
    )
    service = ScoringService(
        registry, max_batch=16, queue_depth=10_000, batcher="continuous"
    ).attach_nearline(updater)
    server = AsyncScoringServer(service, port=0).start()
    port = server.port
    indices = list(range(12))  # rows of users 0 and 1 (6 rows each)
    target = int(truth["users"][0])  # the updated entity IS in the rows
    warm_entity = int(truth["users"][-1])  # ...the warmup entity is NOT
    assert warm_entity not in {int(truth["users"][i]) for i in indices}
    t_mask = np.asarray(
        [int(truth["users"][i]) == target for i in indices]
    )
    assert t_mask.any() and not t_mask.all()
    try:
        assert _get(port, "/healthz")["entity_axis"] == "model"
        rows = _request_rows(truth, data, indices)

        # warm every moving part OFF the measured window: score buckets
        # (registry warmed at load), the nearline solve + row-swap traces
        # (same mini-batch shape as the mid-traffic update, against an
        # entity whose rows are NOT scored here so predict_mean parity
        # holds), and the v2 engine structure (shared executable: same
        # structure + same sharding)
        updater.submit([{
            "ids": {"userId": warm_entity}, "label": 0.0,
            "features": {"user": [[0, 0.0]]},
        }])
        updater.flush()
        _post(port, "/v1/score", {"rows": rows})
        compiles_before = telemetry.snapshot()["counters"].get(
            "jit_compiles", 0
        )

        failures, seen_versions = [], set()
        stop = threading.Event()
        nearline_applied = threading.Event()
        post_update_scores = []

        def check(result, version):
            if nearline_applied.is_set() and version == "v-00000002":
                return  # checked against the re-solved row below
            exp = expected[version][indices]
            np.testing.assert_allclose(result, exp, atol=1e-6)

        def client():
            while not stop.is_set():
                try:
                    got = _post(port, "/v1/score", {"rows": rows})
                    check(np.asarray(got["scores"]), got["model_version"])
                    seen_versions.add(got["model_version"])
                    if nearline_applied.is_set():
                        post_update_scores.append(np.asarray(got["scores"]))
                except Exception as e:  # noqa: BLE001 — asserted empty
                    failures.append(repr(e))

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        # disturbance 1: hot swap to v2, mid-traffic
        publish_version(registry_dir, m2, _INDEX_MAPS)
        deadline = time.monotonic() + 60
        while (
            "v-00000002" not in seen_versions
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert "v-00000002" in seen_versions
        # disturbance 2: nearline per-entity update via POST /v1/update.
        # pre_update is the v2 ENGINE's served answer (1e-6 to
        # predict_mean; the bit-identity claim below is engine-vs-engine)
        pre_update = np.asarray(
            _post(port, "/v1/score", {"rows": rows})["scores"]
        )
        np.testing.assert_allclose(
            pre_update, expected["v-00000002"][indices], atol=1e-6
        )
        accepted = _post(port, "/v1/update", {"events": [
            {"ids": {"userId": target}, "label": 1.0,
             "features": {"user": [[0, 1.0], [2, -1.0]]}},
        ]})
        assert accepted == {"accepted": 1}
        updater.flush()  # deterministic commit (no cadence thread racing)
        nearline_applied.set()
        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not failures, failures[:3]

        # post-update scores: untouched entities BIT-identical to v2,
        # the updated entity moved to the re-solved row's scores
        final = np.asarray(
            _post(port, "/v1/score", {"rows": rows})["scores"]
        )
        np.testing.assert_array_equal(
            np.float32(final[~t_mask]), np.float32(pre_update[~t_mask])
        )
        assert not np.allclose(final[t_mask], pre_update[t_mask])
        engine_direct = registry.engine.score_rows(rows)
        np.testing.assert_allclose(final, engine_direct, atol=1e-7)
        if post_update_scores:
            # the last mid-traffic response landed well after the apply
            np.testing.assert_allclose(
                post_update_scores[-1], final, atol=1e-7
            )

        # zero recompiles across warmup-complete traffic, the hot swap
        # (same structure + same mesh sharding -> shared executable),
        # and the nearline update (warmed trace)
        assert (
            telemetry.snapshot()["counters"].get("jit_compiles", 0)
            == compiles_before
        )
        health = _get(port, "/healthz")
        assert health["model_version"] == "v-00000002"
        assert health["nearline_seq"] >= 1
    finally:
        server.stop()
        registry.stop()


# ---------------------------------------------------------------------------
# SLO bench smoke slice (tier-1: seconds, not minutes)
# ---------------------------------------------------------------------------


def test_serving_slo_smoke():
    """The bench_serving SLO sweep runs end-to-end at a tiny offered
    load: every metric lands (or is None only if truncated — not here),
    the grid carries the shed budget accounting, and the CPU run is
    marked simulated."""
    from bench_serving import SLO_METRICS, run_serving_slo

    detail = {}
    results = run_serving_slo(
        n_features=64, n_entities=64, local_dim=4, row_nnz=4,
        max_batch=8, rates=(40,), queue_depths=(64,),
        measure_s=0.6, n_clients=2, detail_out=detail,
    )
    assert set(results) == set(SLO_METRICS)
    assert results["serving_slo_rows_per_sec"] > 0
    assert results["serving_slo_p99_ms"] > 0
    assert results["serving_slo_p99_swap_ratio"] > 0
    assert results["serving_slo_p99_nearline_ratio"] > 0
    assert results["serving_nearline_apply_ms"] > 0
    assert detail["simulated_on_cpu"] is True
    assert detail["grid"] and detail["grid"][0]["shed_fraction"] is not None
    assert detail["shed_budget"] == 0.01
    assert "window" in detail and "marks_s" in detail["window"]


def test_gate_skips_serving_slo_metrics_missing_from_baseline(capsys):
    """An old baseline that predates the serving_slo_* metrics skips
    them with a note (never fails or crashes the gate); once baselined,
    the latency/ratio metrics gate LOWER-is-better — a p99 RISE is the
    regression."""
    import bench_suite

    results = {
        "linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0,
        "serving_slo_rows_per_sec": 500.0,
        "serving_slo_p99_ms": 12.0,
        "serving_slo_p99_swap_ratio": 1.02,
        "serving_nearline_apply_ms": None,  # budget-truncated
    }
    baseline = {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 90.0}
    rc = bench_suite.run_gate(results, baseline, threshold=0.2)
    err = capsys.readouterr().err
    assert rc == 0
    assert "serving_slo_p99_ms: new metric" in err
    assert "skipped" in err
    assert "truncated, not gated" in err
    # once the baseline carries them, a p99 RISE regresses...
    rc = bench_suite.run_gate(
        {"serving_slo_p99_ms": 20.0}, {"serving_slo_p99_ms": 10.0},
        threshold=0.2,
    )
    assert rc == bench_suite.GATE_EXIT_CODE
    # ...and a p99 DROP passes (lower-is-better direction)
    rc = bench_suite.run_gate(
        {"serving_slo_p99_ms": 5.0}, {"serving_slo_p99_ms": 10.0},
        threshold=0.2,
    )
    assert rc == 0


def test_serving_report_section_roundtrip():
    """The RunReport Serving section renders from live serving counters
    (requests, swaps, nearline applies + lag) in both JSON and markdown."""
    from photon_ml_tpu.telemetry.report import RunReport

    snapshot = {
        "counters": {
            "serving.requests": 2242,
            "serving.scored_rows": 8968,
            "serving.shed": 3,
            "serving.model_swaps": 2,
            "serving.nearline.applies": 3,
            "serving.nearline.applied_rows": 96,
            "serving.unseen_entities": 1,
        },
        "gauges": {},
        "histograms": {
            "serving.total_ms": {
                "count": 2242, "mean": 33.5, "p50": 33.4, "p99": 35.1,
            },
            "serving.batch_size": {"count": 600, "mean": 14.8},
            "serving.nearline.update_lag_ms": {
                "count": 96, "mean": 9.0, "p99": 11.4,
            },
        },
    }
    report = RunReport(snapshot=snapshot, spans=[], sources={})
    doc = report.to_json()
    assert doc["serving"]["requests"] == 2242
    assert doc["serving"]["nearline_lag_p99_ms"] == 11.4
    md = report.to_markdown()
    assert "## Serving" in md
    assert "p99 35.1 ms" in md
    assert "3 nearline apply(ies) covering 96 entity row(s)" in md
    assert "p99 event->applied 11.4 ms" in md
    assert "3 request(s) shed" in md


def test_serving_slo_budget_truncation():
    """An exhausted budget yields all-None metrics (the truncated-line
    contract) instead of partial work past the deadline."""
    from bench_serving import SLO_METRICS, run_serving_slo

    results = run_serving_slo(deadline=time.monotonic() - 1)
    assert results == {m: None for m in SLO_METRICS}
