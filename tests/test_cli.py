"""CLI drivers: config parsing round-trip and a subprocess end-to-end
train -> save -> score pipeline over Avro files."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.config import (
    game_config_to_json,
    parse_game_config,
    parse_optimizer_config,
)
from photon_ml_tpu.data.avro import TRAINING_EXAMPLE_AVRO, write_avro
from photon_ml_tpu.game.estimator import (
    FactoredRandomEffectConfig,
    FixedEffectConfig,
    RandomEffectConfig,
)
from photon_ml_tpu.optim import OptimizerType, RegularizationType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_optimizer_config():
    cfg = parse_optimizer_config(
        {
            "type": "tron",
            "max_iterations": 15,
            "tolerance": 1e-5,
            "regularization": "l2",
            "regularization_weight": 2.5,
        }
    )
    assert cfg.optimizer_type == OptimizerType.TRON
    assert cfg.max_iterations == 15
    assert cfg.regularization.reg_type == RegularizationType.L2
    assert cfg.regularization_weight == 2.5
    with pytest.raises(ValueError, match="unknown optimizer config keys"):
        parse_optimizer_config({"max_iter": 3})


def test_parse_game_config_round_trip():
    doc = {
        "task": "logistic",
        "num_iterations": 2,
        "evaluators": ["auc", "rmse"],
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "normalization": "standardization",
                "intercept_index": 0,
                "optimizer": {"regularization": "l2", "regularization_weight": 1.0},
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "user",
                "id_name": "userId",
                "active_rows_per_entity": 64,
            },
            "mf": {
                "type": "factored_random_effect",
                "shard_name": "user",
                "id_name": "userId",
                "latent_dim": 4,
                "mf_iterations": 2,
            },
        },
    }
    cfg = parse_game_config(doc)
    assert list(cfg.coordinates) == ["fixed", "perUser", "mf"]  # order kept
    assert isinstance(cfg.coordinates["fixed"], FixedEffectConfig)
    assert isinstance(cfg.coordinates["perUser"], RandomEffectConfig)
    assert isinstance(cfg.coordinates["mf"], FactoredRandomEffectConfig)
    assert cfg.coordinates["mf"].latent_dim == 4
    # JSON metadata re-parses to an equivalent config
    cfg2 = parse_game_config(game_config_to_json(cfg))
    assert cfg2 == cfg


@pytest.fixture(scope="module")
def avro_dataset(tmp_path_factory):
    rng = np.random.default_rng(99)
    tmp = tmp_path_factory.mktemp("cli")
    n, d, n_users = 240, 8, 6
    X = rng.normal(size=(n, d))
    users = rng.integers(0, n_users, n)
    w = rng.normal(size=d)
    u_eff = rng.normal(size=n_users)
    logits = X @ w + u_eff[users]
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(float)

    def recs(lo, hi):
        for i in range(lo, hi):
            yield {
                "uid": str(i),
                "label": float(y[i]),
                "features": [
                    {"name": f"c{j}", "term": "", "value": float(X[i, j])}
                    for j in range(d)
                ],
                "metadataMap": {"userId": str(users[i])},
                "weight": None,
                "offset": None,
            }

    train_path = str(tmp / "train.avro")
    score_path = str(tmp / "holdout.avro")
    write_avro(train_path, TRAINING_EXAMPLE_AVRO, recs(0, 200))
    write_avro(score_path, TRAINING_EXAMPLE_AVRO, recs(200, 240))
    return tmp, train_path, score_path


def _run_cli(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli", *args],
        capture_output=True,
        text=True,
        cwd=str(cwd),
        env=env,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_cli_train_save_score_end_to_end(avro_dataset):
    tmp, train_path, score_path = avro_dataset
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {
                    "regularization": "l2",
                    "regularization_weight": 0.1,
                },
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "global",
                "id_name": "userId",
                "optimizer": {
                    "regularization": "l2",
                    "regularization_weight": 1.0,
                },
            },
        },
        "num_iterations": 1,
        "output_dir": str(tmp / "model"),
    }
    cfg_path = tmp / "train.json"
    cfg_path.write_text(json.dumps(config))

    summary = _run_cli(["train", "--config", str(cfg_path)], cwd=tmp)
    assert summary["num_rows"] == 200
    assert os.path.exists(tmp / "model" / "final" / "model-metadata.json")
    assert os.path.exists(tmp / "model" / "best" / "model-metadata.json")

    # the model dir carries the training feature index maps, so scoring a
    # NEW file reproduces training-time feature ids (prepareFeatureMaps)
    assert os.path.isdir(tmp / "model" / "final" / "feature-indexes" / "global")
    score_cfg = {
        "input": {
            "format": "avro",
            "paths": [score_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        }
    }
    score_cfg_path = tmp / "score.json"
    score_cfg_path.write_text(json.dumps(score_cfg))
    out_path = str(tmp / "scores.avro")
    summary = _run_cli(
        [
            "score",
            "--model-dir", str(tmp / "model" / "final"),
            "--config", str(score_cfg_path),
            "--output", out_path,
            "--evaluators", "auc", "logistic_loss",
        ],
        cwd=tmp,
    )
    assert summary["num_rows"] == 40
    assert summary["metrics"]["auc"] > 0.6  # true holdout
    from photon_ml_tpu.data.avro import read_scoring_results

    recs = read_scoring_results(out_path)
    assert len(recs) == 40
    assert all(np.isfinite(r["predictionScore"]) for r in recs)


def test_parse_coordinate_config_rejects_unknown_keys():
    from photon_ml_tpu.config import parse_coordinate_config

    with pytest.raises(ValueError, match="unknown keys"):
        parse_coordinate_config(
            {"type": "fixed_effect", "shard_name": "g", "normalisation": "none"}
        )


@pytest.mark.slow
def test_cli_sigterm_checkpoint_then_resume(avro_dataset):
    """ISSUE 2 acceptance: a train CLI run killed with SIGTERM mid-fit
    writes a final checkpoint and exits gracefully; restarting with
    --resume reproduces the uninterrupted fit's final model."""
    import signal
    import time

    tmp, train_path, _ = avro_dataset
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 0.1},
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "global",
                "id_name": "userId",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 1.0},
            },
        },
        "num_iterations": 4,
        "output_dir": str(tmp / "model"),
    }
    cfg_path = tmp / "train.json"
    cfg_path.write_text(json.dumps(config))

    # reference: the same fit, never interrupted
    ref_cfg = dict(config, output_dir=str(tmp / "model_ref"))
    ref_cfg_path = tmp / "train_ref.json"
    ref_cfg_path.write_text(json.dumps(ref_cfg))
    _run_cli(["train", "--config", str(ref_cfg_path)], cwd=tmp)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    ckpt_dir = tmp / "ckpt"
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.cli", "train",
         "--config", str(cfg_path), "--checkpoint-dir", str(ckpt_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=str(tmp), env=env,
    )
    # SIGTERM as soon as the first checkpoint lands (i.e. mid-fit, after
    # the handler is installed); the run must finish its step, write a
    # final checkpoint, and exit 75 with "interrupted": true
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline and proc.poll() is None:
        if ckpt_dir.is_dir() and any(
            n.startswith("step-") for n in os.listdir(ckpt_dir)
        ):
            proc.send_signal(signal.SIGTERM)
            break
        time.sleep(0.005)
    out, err = proc.communicate(timeout=600)
    if proc.returncode == 0:
        pytest.skip("fit completed before SIGTERM landed; timing-dependent")
    assert proc.returncode == 75, err[-3000:]
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["interrupted"] is True
    assert any(n.startswith("step-") for n in os.listdir(ckpt_dir))

    summary = _run_cli(
        ["train", "--config", str(cfg_path),
         "--checkpoint-dir", str(ckpt_dir), "--resume"],
        cwd=tmp,
    )
    assert "interrupted" not in summary

    import numpy as np

    for sub in ("fixed-effect/fixed/coefficients.npz",
                "random-effect/perUser/model.npz"):
        with np.load(tmp / "model" / "final" / sub) as got, \
                np.load(tmp / "model_ref" / "final" / sub) as ref:
            for key in ref.files:
                if ref[key].dtype.kind == "f":
                    np.testing.assert_allclose(
                        got[key], ref[key], rtol=1e-5, atol=1e-6,
                        err_msg=f"{sub}:{key}",
                    )


@pytest.mark.slow
def test_cli_index_job(avro_dataset, tmp_path):
    """FeatureIndexingJob analog: scan avro -> persisted mmap index store."""
    from photon_ml_tpu.cli.index import main as index_main
    from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, MmapIndexMap

    tmp, train_path, _ = avro_dataset
    out = str(tmp_path / "idx")
    rc = index_main(
        ["--input", train_path, "--output", out,
         "--shards", "global:features"]
    )
    assert rc == 0
    imap = IndexMap.load(os.path.join(out, "global"))
    assert imap.get(INTERCEPT_KEY) >= 0
    assert len(imap) == 9  # c0..c7 + intercept
    # mmap store loads and answers lookups
    mm = MmapIndexMap(os.path.join(out, "global"))
    assert mm.get("c3") == imap.get("c3")


def test_parse_optimizer_config_string_dsl():
    """Reference mini-DSL: maxIter,tol,lambda,downSample,optType,regType
    (GLMOptimizationConfiguration.parseAndBuildFromString)."""
    from photon_ml_tpu.config import parse_optimizer_config

    cfg = parse_optimizer_config("50, 1e-6, 0.3, 0.8, LBFGS, L2")
    assert cfg.max_iterations == 50
    assert cfg.tolerance == 1e-6
    assert cfg.regularization_weight == 0.3
    assert cfg.down_sampling_rate == 0.8
    assert cfg.optimizer_type == OptimizerType.LBFGS
    assert cfg.regularization.reg_type == RegularizationType.L2
    en = parse_optimizer_config("10,1e-4,1.0,1.0,LBFGS,ELASTIC_NET,0.3")
    assert en.regularization.reg_type == RegularizationType.ELASTIC_NET
    assert en.regularization.alpha == 0.3
    with pytest.raises(ValueError, match="expected"):
        parse_optimizer_config("10,1e-4,1.0")
    with pytest.raises(ValueError, match="unknown optimizer"):
        parse_optimizer_config("10,1e-4,1,1,SGD,L2")


def test_dsl_alpha_only_for_elastic_net():
    from photon_ml_tpu.config import parse_optimizer_config

    with pytest.raises(ValueError, match="elastic_net"):
        parse_optimizer_config("50,1e-6,0.3,0.8,LBFGS,L2,0.5")


def test_load_listener_specs():
    from photon_ml_tpu.utils.events import load_listener, load_listeners

    fn = load_listener("photon_ml_tpu.utils.events:load_listeners")
    assert callable(fn)
    fn2 = load_listener("photon_ml_tpu.utils.events.load_listeners")
    assert callable(fn2)
    with pytest.raises(ValueError, match="dotted path"):
        load_listener("nodots")
    with pytest.raises(ValueError, match="cannot load"):
        load_listener("photon_ml_tpu.utils.events:NoSuchThing")
    with pytest.raises(ValueError, match="cannot load"):
        load_listener("no.such.module:thing")
    assert len(load_listeners([])) == 0


@pytest.mark.slow
def test_cli_train_config_driven_event_listener(avro_dataset):
    """--event-listeners analog: dotted-path listener specs in the train
    config are import-registered at driver startup (Driver.scala:110-118)."""
    tmp, train_path, _ = avro_dataset
    (tmp / "my_listeners.py").write_text(
        "class Recorder:\n"
        "    def __call__(self, event):\n"
        "        with open('events.log', 'a') as f:\n"
        "            f.write(type(event).__name__ + '\\n')\n"
    )
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"max_iterations": 5},
            },
        },
        "event_listeners": ["my_listeners:Recorder"],
    }
    cfg_path = tmp / "train_listener.json"
    cfg_path.write_text(json.dumps(config))
    _run_cli(["train", "--config", str(cfg_path)], cwd=tmp)
    log = (tmp / "events.log").read_text().splitlines()
    assert "SetupEvent" in log
    assert "TrainingStartEvent" in log
    assert "OptimizationLogEvent" in log
    assert "TrainingFinishEvent" in log


def test_train_parse_mesh_flag():
    """--mesh 'batch=N,model=M' -> the named GSPMD mesh config dict."""
    from photon_ml_tpu.cli.train import parse_mesh_flag

    assert parse_mesh_flag("batch=8") == {"batch": 8}
    assert parse_mesh_flag("batch=4,model=2") == {"batch": 4, "model": 2}
    assert parse_mesh_flag("model=8") == {"model": 8}
    assert parse_mesh_flag("auto") is True
    assert parse_mesh_flag("off") is False
    with pytest.raises(ValueError, match="axis=N"):
        parse_mesh_flag("batch")
    with pytest.raises(ValueError, match="integer size"):
        parse_mesh_flag("batch=many")
    with pytest.raises(ValueError, match="no axes"):
        parse_mesh_flag(" , ")


# ---------------------------------------------------------------------------
# ISSUE 8: sweep-spec validation + the train --sweep path
# ---------------------------------------------------------------------------


def test_sweep_flag_malformed_grids_are_typed_config_errors():
    """Malformed --sweep grids raise SweepSpecError NAMING the offending
    token — a typo must never silently train the default grid."""
    from photon_ml_tpu.sweep.grid import SweepSpecError, parse_sweep_spec

    for spec, fragment in (
        ("lambda=", "lambda="),
        ("lambda=10:1:log4", "inverted range"),
        ("lambda=1:10:log0", "zero/negative point count"),
        ("lambda=-0.5,1", "negative regularization"),
        ("gamma=1", "unknown key"),
    ):
        with pytest.raises(SweepSpecError) as err:
            parse_sweep_spec(spec)
        assert fragment in str(err.value)
        assert spec in str(err.value)  # the offending token, verbatim


def test_parse_sweep_config_object_and_shorthand():
    from photon_ml_tpu.cli.sweep import parse_sweep_config

    parsed = parse_sweep_config("lambda=1,10")
    assert parsed["grid"].default == (10.0, 1.0)
    assert parsed["policy"] == "best"
    parsed = parse_sweep_config(
        {"grid": ["lambda=1:100:log3", "lambda.perUser=5"],
         "metric": "rmse", "policy": "parsimonious", "rel_tol": 0.05}
    )
    assert parsed["grid"].size == 3
    assert parsed["metric"] == "rmse"
    assert parsed["rel_tol"] == 0.05
    with pytest.raises(ValueError, match="unknown sweep config keys"):
        parse_sweep_config({"grid": "lambda=1", "metrik": "auc"})
    from photon_ml_tpu.sweep.grid import SweepSpecError

    with pytest.raises(SweepSpecError, match="no lambda grid"):
        parse_sweep_config({})
    # the SweepGrid.to_json round-trip form is accepted back
    parsed = parse_sweep_config({"grid": {"lambda": [1.0, 10.0]}})
    assert parsed["grid"].default == (10.0, 1.0)


def test_train_main_sweep_flags_require_grid(tmp_path):
    from photon_ml_tpu.cli.train import main as train_main

    cfg = tmp_path / "c.json"
    cfg.write_text(json.dumps({"task": "logistic", "input": {},
                               "coordinates": {}}))
    with pytest.raises(SystemExit):
        train_main(["--config", str(cfg), "--sweep-metric", "auc"])


def test_sweep_without_validation_split_is_typed(tmp_path):
    from photon_ml_tpu.cli.sweep import run_sweep_fit

    with pytest.raises(ValueError, match="validation split"):
        run_sweep_fit(None, {"grid": "lambda=1"}, None, None, None, None)


@pytest.mark.slow
def test_cli_train_sweep_end_to_end(avro_dataset):
    """ISSUE 8: `cli train --sweep lambda=...` runs the vmapped sweep,
    reports the per-config table, saves the winner under best/, and
    publishes it into a registry a ModelRegistry can serve from."""
    tmp, train_path, holdout_path = avro_dataset
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "validation": {"paths": [holdout_path]},
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"regularization": "l2",
                              "max_iterations": 30},
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "global",
                "id_name": "userId",
                "optimizer": {"regularization": "l2",
                              "max_iterations": 30},
            },
        },
        "num_iterations": 2,
        "output_dir": str(tmp / "sweep_model"),
    }
    cfg_path = tmp / "train_sweep.json"
    cfg_path.write_text(json.dumps(config))
    registry_dir = tmp / "sweep_registry"

    summary = _run_cli(
        ["train", "--config", str(cfg_path),
         "--sweep", "lambda=0.1:10:log4",
         "--sweep-registry-dir", str(registry_dir)],
        cwd=tmp,
    )
    sweep = summary["sweep"]
    assert len(sweep["configs"]) == 4
    assert sweep["metric"] == "auc"
    assert 0 <= sweep["selected_index"] < 4
    lams = [c["lambdas"]["fixed"] for c in sweep["configs"]]
    assert lams == sorted(lams, reverse=True)  # descending path order
    assert summary["best_metric"] == sweep["selected_metric"]
    # winner + feature indexes on disk in the best/ layout
    assert os.path.exists(tmp / "sweep_model" / "best" / "model-metadata.json")
    assert os.path.isdir(
        tmp / "sweep_model" / "best" / "feature-indexes" / "global"
    )
    # registry publish is complete and loadable
    version_dir = sweep["published_version"]
    assert os.path.basename(version_dir) == "v-00000001"
    from photon_ml_tpu.serving import ModelRegistry

    registry = ModelRegistry(str(registry_dir), warm=False,
                             poll_interval=3600)
    assert registry.refresh()
    assert registry.current_version == "v-00000001"
    registry.stop()


@pytest.mark.slow
def test_cli_sweep_subcommand(avro_dataset):
    """`cli sweep` reruns selection over the same config/dataset without
    the single-fit driver outputs."""
    tmp, train_path, holdout_path = avro_dataset
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "validation": {"paths": [holdout_path]},
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"regularization": "l2",
                              "max_iterations": 20},
            },
        },
        "num_iterations": 1,
    }
    cfg_path = tmp / "sweep_only.json"
    cfg_path.write_text(json.dumps(config))
    summary = _run_cli(
        ["sweep", "--config", str(cfg_path),
         "--sweep", "lambda=0.1,1,10",
         "--sweep-policy", "parsimonious"],
        cwd=tmp,
    )
    sweep = summary["sweep"]
    assert sweep["policy"] == "parsimonious"
    assert len(sweep["configs"]) == 3
    assert all(c["metric"] is not None for c in sweep["configs"])


def test_parse_sweep_config_mapping_form_is_validated():
    """The JSON round-trip grid form goes through the same validators as
    the string grammar — negative/NaN/empty lists must not sneak in."""
    from photon_ml_tpu.cli.sweep import parse_sweep_config
    from photon_ml_tpu.sweep.grid import SweepSpecError

    with pytest.raises(SweepSpecError, match="negative"):
        parse_sweep_config({"grid": {"lambda": [-1.0, 2.0]}})
    with pytest.raises(SweepSpecError, match="empty grid"):
        parse_sweep_config({"grid": {"lambda": []}})
    with pytest.raises(SweepSpecError, match="not finite"):
        parse_sweep_config({"grid": {"lambda.fixed": [float("nan")]}})
    # valid values dedupe + sort descending like the string path
    parsed = parse_sweep_config({"grid": {"lambda": [1.0, 10.0, 1.0]}})
    assert parsed["grid"].default == (10.0, 1.0)


def test_train_run_refuses_checkpoint_or_mesh_with_sweep(tmp_path):
    """A checkpointed sweep would install GracefulStop (swallowing the
    scheduler's SIGTERM) and then never save anything — refuse upfront."""
    from photon_ml_tpu.cli.train import run

    base = {
        "task": "logistic",
        "input": {"format": "libsvm", "paths": "unused"},
        "coordinates": {"fixed": {"shard_name": "features"}},
        "sweep": {"grid": "lambda=1"},
    }
    with pytest.raises(ValueError, match="checkpointing is not supported"):
        run({**base, "checkpoint": {"dir": str(tmp_path / "ckpt")}})
    with pytest.raises(ValueError, match="mesh training is not supported"):
        run({**base, "mesh": {"batch": 2}})


def test_merge_sweep_flags_shared_helper():
    from photon_ml_tpu.cli.sweep import merge_sweep_flags

    assert merge_sweep_flags({}) is None
    merged = merge_sweep_flags(
        {"sweep": "lambda=1"}, metric="rmse", registry_dir="r/"
    )
    assert merged == {"grid": "lambda=1", "metric": "rmse",
                      "registry_dir": "r/"}
    merged = merge_sweep_flags(
        {"sweep": {"grid": "lambda=1", "policy": "best"}},
        grid=["lambda=2"], policy="parsimonious",
    )
    assert merged["grid"] == ["lambda=2"]
    assert merged["policy"] == "parsimonious"
