"""Model persistence round-trips: train -> save -> load -> identical scores.

Reference parity: ModelProcessingUtils.scala:72 (save), :137 (load), :516
(metadata); scoring driver cli/game/scoring/Driver.scala:51-201. The
fresh-process test proves nothing is captured in interpreter state.
"""

import os
import subprocess
import sys

import numpy as np
import pytest
import jax.numpy as jnp

from photon_ml_tpu.data.model_store import (
    load_game_model,
    load_game_model_metadata,
    load_glm,
    save_game_model,
    save_glm,
    score_game_dataset,
)
from photon_ml_tpu.game import (
    FixedEffectCoordinate,
    GameModel,
    RandomEffectCoordinate,
    build_game_dataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.models.glm import make_model
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)

_CFG = OptimizerConfig(
    optimizer_type=OptimizerType.LBFGS,
    max_iterations=20,
    tolerance=1e-7,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=1.0,
)


def _game_setup(rng, n=300, n_users=12):
    Xg = rng.normal(size=(n, 8)) * (rng.random((n, 8)) < 0.5)
    Xu = rng.normal(size=(n, 5)) * (rng.random((n, 5)) < 0.7)
    users = rng.integers(0, n_users, size=n)
    y = (rng.random(n) > 0.5).astype(float)
    gds = build_game_dataset(
        response=y,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y),
            "user": SparseBatch.from_dense(Xu, y),
        },
        id_columns={"userId": [f"u{u:03d}" for u in users]},
    )
    return gds, users


def _train_game_model(gds):
    fe = FixedEffectCoordinate("fixed", gds, "global", "logistic", _CFG)
    red = build_random_effect_dataset(gds, "userId", "user")
    re = RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG)
    model = GameModel(task="logistic", models={})
    model = model.with_model("fixed", fe.update_model(fe.initialize_model(), None))
    model = model.with_model("per-user", re.update_model(re.initialize_model(), None))
    return model


def test_glm_roundtrip(tmp_path, rng):
    m = make_model(
        "poisson",
        means=jnp.asarray(rng.normal(size=9), jnp.float32),
        variances=jnp.asarray(rng.random(9), jnp.float32),
    )
    save_glm(m, str(tmp_path / "glm"))
    m2 = load_glm(str(tmp_path / "glm"))
    assert m2.task == "poisson"
    np.testing.assert_array_equal(
        np.asarray(m2.coefficients.means), np.asarray(m.coefficients.means))
    np.testing.assert_array_equal(
        np.asarray(m2.coefficients.variances),
        np.asarray(m.coefficients.variances))


@pytest.mark.slow
def test_game_model_roundtrip_scores_identical(tmp_path, rng):
    gds, _ = _game_setup(rng)
    model = _train_game_model(gds)
    s_before = np.asarray(model.score(gds))[: gds.num_rows]

    save_game_model(model, str(tmp_path / "game"),
                    extra_metadata={"note": "round-trip"})
    model2 = load_game_model(str(tmp_path / "game"))
    s_after = np.asarray(model2.score(gds))[: gds.num_rows]
    np.testing.assert_allclose(s_after, s_before, rtol=1e-6, atol=1e-7)

    meta = load_game_model_metadata(str(tmp_path / "game"))
    assert meta["task"] == "logistic"
    assert meta["extra"] == {"note": "round-trip"}
    assert meta["coordinate_order"] == ["fixed", "per-user"]
    assert meta["coordinates"]["per-user"]["type"] == "random_effect"


def test_score_entry_point_with_unseen_entities(tmp_path, rng):
    gds, _ = _game_setup(rng)
    model = _train_game_model(gds)
    save_game_model(model, str(tmp_path / "game"))

    # scoring dataset with a mix of seen and UNSEEN entities
    n2 = 100
    Xg = rng.normal(size=(n2, 8))
    Xu = rng.normal(size=(n2, 5))
    ids = [f"u{i:03d}" if i % 2 == 0 else f"new{i}" for i in range(n2)]
    y2 = np.zeros(n2)
    gds2 = build_game_dataset(
        response=y2,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y2),
            "user": SparseBatch.from_dense(Xu, y2),
        },
        id_columns={"userId": ids},
    )
    scores = score_game_dataset(str(tmp_path / "game"), gds2)
    assert scores.shape == (n2,)
    assert np.all(np.isfinite(scores))

    # unseen entities get ONLY the fixed-effect contribution
    fe_scores = np.asarray(model.models["fixed"].score(gds2))[:n2]
    unseen = np.array([not i.startswith("u") for i in ids])
    np.testing.assert_allclose(
        scores[unseen], fe_scores[unseen], rtol=1e-6, atol=1e-7)
    # seen entities differ from FE-only (the RE part contributes)
    assert not np.allclose(scores[~unseen], fe_scores[~unseen])


@pytest.mark.slow
def test_load_in_fresh_process(tmp_path, rng):
    gds, _ = _game_setup(rng, n=150, n_users=6)
    model = _train_game_model(gds)
    s_before = np.asarray(model.score(gds))[: gds.num_rows]
    save_game_model(model, str(tmp_path / "game"))
    np.save(tmp_path / "xg.npy", np.asarray(gds.shard("global").to_dense()))
    np.save(tmp_path / "xu.npy", np.asarray(gds.shard("user").to_dense()))
    np.save(tmp_path / "y.npy", gds.response)
    ids = gds.id_columns["userId"]
    np.save(tmp_path / "ids.npy", ids.vocab[ids.codes])
    np.save(tmp_path / "expected.npy", s_before)

    script = f"""
import numpy as np
from photon_ml_tpu.data.model_store import score_game_dataset
from photon_ml_tpu.game import build_game_dataset
from photon_ml_tpu.ops.sparse import SparseBatch
d = {str(tmp_path)!r}
y = np.load(d + "/y.npy")
n = len(y)
gds = build_game_dataset(
    response=y,
    feature_shards={{
        "global": SparseBatch.from_dense(np.load(d + "/xg.npy")[:n], y),
        "user": SparseBatch.from_dense(np.load(d + "/xu.npy")[:n], y),
    }},
    id_columns={{"userId": np.load(d + "/ids.npy", allow_pickle=True)}},
)
scores = score_game_dataset(d + "/game", gds)
np.testing.assert_allclose(scores, np.load(d + "/expected.npy"),
                           rtol=1e-5, atol=1e-6)
print("FRESH-PROCESS-OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "FRESH-PROCESS-OK" in out.stdout


def test_load_errors_are_typed_and_name_the_path(tmp_path, rng):
    """Crash injection: missing files, truncated npz containers, missing
    npz keys, and unsupported format versions all surface as ModelLoadError
    naming the offending path — not bare KeyError/BadZipFile."""
    import json

    from photon_ml_tpu.data.model_store import ModelLoadError

    m = make_model("logistic", means=jnp.zeros(4))

    # missing metadata file
    with pytest.raises(ModelLoadError, match="missing metadata"):
        load_glm(str(tmp_path / "nope"))

    # truncated npz (simulates a crash mid-write of a non-atomic save)
    save_glm(m, str(tmp_path / "trunc"))
    npz = tmp_path / "trunc" / "coefficients.npz"
    npz.write_bytes(npz.read_bytes()[:16])
    with pytest.raises(ModelLoadError, match="coefficients.npz"):
        load_glm(str(tmp_path / "trunc"))

    # missing npz key
    save_glm(m, str(tmp_path / "nokey"))
    np.savez(tmp_path / "nokey" / "coefficients.npz", other=np.zeros(2))
    with pytest.raises(ModelLoadError, match="missing array key 'means'"):
        load_glm(str(tmp_path / "nokey"))

    # unsupported format_version
    save_glm(m, str(tmp_path / "vers"))
    meta_path = tmp_path / "vers" / "model-metadata.json"
    meta = json.loads(meta_path.read_text())
    meta["format_version"] = 999
    meta_path.write_text(json.dumps(meta))
    with pytest.raises(ModelLoadError, match="format_version 999"):
        load_glm(str(tmp_path / "vers"))

    # corrupt metadata JSON
    save_glm(m, str(tmp_path / "badjson"))
    (tmp_path / "badjson" / "model-metadata.json").write_text("{ nope")
    with pytest.raises(ModelLoadError, match="corrupt metadata"):
        load_glm(str(tmp_path / "badjson"))

    # ModelLoadError is a ValueError: existing callers keep working
    assert issubclass(ModelLoadError, ValueError)


def test_game_model_load_errors_typed(tmp_path, rng):
    from photon_ml_tpu.data.model_store import ModelLoadError

    gds, _ = _game_setup(rng)
    model = _train_game_model(gds)
    save_game_model(model, str(tmp_path / "game"))
    npz = tmp_path / "game" / "random-effect" / "per-user" / "model.npz"
    npz.write_bytes(npz.read_bytes()[:32])
    with pytest.raises(ModelLoadError, match="model.npz"):
        load_game_model(str(tmp_path / "game"))


def test_wrong_model_type_errors(tmp_path, rng):
    m = make_model("logistic", means=jnp.zeros(3))
    save_glm(m, str(tmp_path / "m"))
    try:
        load_game_model(str(tmp_path / "m"))
        raise AssertionError("expected ValueError")
    except ValueError as e:
        assert "GAME" in str(e)
