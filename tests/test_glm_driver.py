"""Legacy staged GLM driver: stage sequencing (MockDriver-style
assertions), lambda sweep + best selection, text models, diagnostics
report rendering."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli.glm import DriverStage, GLMDriver


@pytest.fixture()
def libsvm_files(rng, tmp_path):
    from photon_ml_tpu.testing import write_libsvm

    def write(path, n, d=10, seed=None):
        # one planted model (fixed seed) for train AND validation so
        # selection metrics are meaningful; fresh rows per file
        w = np.asarray([1.5, -2.0, 0.0, 1.0, 0.5, -1.0, 0.0, 0.8, -0.3, 0.2])
        X = (rng.random((n, d)) < 0.5) * rng.normal(size=(n, d))
        y = np.sign(X @ w + 0.2 * rng.normal(size=n))
        return write_libsvm(str(path), X, y)

    train = write(tmp_path / "train.libsvm", 300)
    val = write(tmp_path / "val.libsvm", 150)
    return tmp_path, train, val


def _config(train, val=None, **kw):
    cfg = {
        "task": "logistic",
        "input": {"format": "libsvm", "paths": [train]},
        "optimizer": {"regularization": "l2"},
        "lambdas": [10.0, 1.0, 0.1],
        **kw,
    }
    if val:
        cfg["validation"] = {"paths": [val]}
    return cfg


def test_stage_sequence_train_only(libsvm_files):
    tmp, train, val = libsvm_files
    driver = GLMDriver(_config(train))
    summary = driver.run()
    assert summary["stages"] == ["INIT", "PREPROCESSED", "TRAINED"]
    assert summary["best_lambda"] is None
    assert len(summary["lambdas"]) == 3


@pytest.mark.slow
def test_stage_sequence_full_pipeline(libsvm_files):
    tmp, train, val = libsvm_files
    out = str(tmp / "out")
    driver = GLMDriver(
        _config(
            train, val, diagnostics=True, output_dir=out,
            bootstrap_samples=4, compute_variances=True,
        )
    )
    summary = driver.run()
    assert summary["stages"] == [
        "INIT", "PREPROCESSED", "TRAINED", "VALIDATED", "DIAGNOSED",
    ]
    # best lambda selected by validation AUC
    assert summary["best_lambda"] in (10.0, 1.0, 0.1)
    assert 0.5 < summary["best_metric"] <= 1.0
    # per-lambda validation metrics recorded
    assert set(summary["metrics"]) == {"10.0", "1.0", "0.1"}
    assert all("Area under ROC" in m for m in summary["metrics"].values())
    # diagnostics report written
    assert os.path.exists(summary["report"]["html"])
    html = open(summary["report"]["html"]).read()
    assert "Hosmer-Lemeshow" in html and "Bootstrap" in html
    assert "Fitting curves" in html
    # text models: one file per lambda, index<TAB>value<TAB>variance lines
    txts = sorted(os.listdir(summary["models_text_dir"]))
    assert txts == ["lambda-0.1.txt", "lambda-1.0.txt", "lambda-10.0.txt"]
    first = open(
        os.path.join(summary["models_text_dir"], txts[0])
    ).read().strip().splitlines()
    parts = first[0].split("\t")
    assert len(parts) == 3  # variance column present
    int(parts[0]); float(parts[1]); float(parts[2])
    # npz models load back
    from photon_ml_tpu.data.model_store import load_glm

    m = load_glm(os.path.join(out, "models", "lambda-1.0"))
    assert m.task == "logistic"
    assert m.coefficients.variances is not None


def test_stage_assertion_rejects_out_of_order(libsvm_files):
    tmp, train, val = libsvm_files
    driver = GLMDriver(_config(train))
    with pytest.raises(RuntimeError, match="PREPROCESSED"):
        driver._assert_stage(DriverStage.PREPROCESSED)
    driver.preprocess()
    driver._update_stage(DriverStage.PREPROCESSED)
    driver._assert_stage(DriverStage.PREPROCESSED)


def test_driver_with_normalization(libsvm_files):
    tmp, train, val = libsvm_files
    driver = GLMDriver(
        _config(
            train, val,
            normalization="scale_with_standard_deviation",
        )
    )
    summary = driver.run()
    assert summary["stages"][-1] == "VALIDATED"
    assert summary["best_metric"] > 0.5


@pytest.mark.slow
def test_cli_glm_subprocess(libsvm_files):
    import subprocess
    import sys

    tmp, train, val = libsvm_files
    cfg_path = tmp / "glm.json"
    cfg_path.write_text(json.dumps(_config(train, val, output_dir=str(tmp / "o"))))
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli", "glm",
         "--config", str(cfg_path)],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["stages"][-1] == "VALIDATED"


@pytest.mark.slow
def test_validation_feature_space_pinned_to_training(rng, tmp_path):
    """A validation file whose max feature id is smaller than training's
    must still align (num_features pinned; regression for the libsvm
    per-file dimension inference)."""
    from photon_ml_tpu.testing import write_libsvm

    d = 12
    w = rng.normal(size=d)
    Xt = (rng.random((200, d)) < 0.5) * rng.normal(size=(200, d))
    Xt[0, d - 1] = 1.0  # training definitely reaches feature id d
    yt = np.sign(Xt @ w + 0.1 * rng.normal(size=200))
    Xv = Xt[:80].copy()
    Xv[:, d - 1] = 0.0  # validation NEVER contains the highest feature id
    yv = np.sign(Xv @ w + 0.1 * rng.normal(size=80))
    train = write_libsvm(str(tmp_path / "t.libsvm"), Xt, yt)
    val = write_libsvm(str(tmp_path / "v.libsvm"), Xv, yv)

    driver = GLMDriver(_config(train, val, normalization="standardization"))
    summary = driver.run()
    assert summary["stages"][-1] == "VALIDATED"
    assert summary["best_metric"] > 0.8  # same planted model -> real AUC
