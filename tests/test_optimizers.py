"""Optimizer tests: convergence to closed forms / KKT conditions, parity
between LBFGS and TRON, vmap-batched solves, box constraints, warm starts."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    FUNCTION_VALUES_CONVERGED,
    GRADIENT_CONVERGED,
    BoxConstraints,
    LBFGSConfig,
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    from_value_and_grad,
    glm_adapter,
    lbfgs_solve,
    owlqn_solve,
    solve,
    tron_solve,
)


def _make_batch(rng, n=200, d=15, loss="squared", density=0.5):
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < density)
    if loss == "squared":
        y = X @ rng.normal(size=d) + 0.1 * rng.normal(size=n)
    elif loss == "poisson":
        rate = np.exp(np.clip(X @ (rng.normal(size=d) * 0.3), -3, 3))
        y = rng.poisson(rate).astype(np.float64)
    else:
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ rng.normal(size=d))))).astype(
            np.float64
        )
    wt = rng.random(n) + 0.5
    return X, y, wt, SparseBatch.from_dense(X, y, weights=wt)


def _ridge_closed_form(X, y, wt, l2):
    W = np.diag(wt)
    return np.linalg.solve(X.T @ W @ X + l2 * np.eye(X.shape[1]), X.T @ (wt * y))


def test_lbfgs_matches_ridge_closed_form(rng):
    X, y, wt, batch = _make_batch(rng)
    w_star = _ridge_closed_form(X, y, wt, l2=2.0)
    obj = make_objective("squared", l2_weight=2.0)
    res = lbfgs_solve(glm_adapter(obj, batch), jnp.zeros(X.shape[1], jnp.float32))
    np.testing.assert_allclose(res.w, w_star, rtol=2e-3, atol=2e-3)
    assert int(res.reason) in (FUNCTION_VALUES_CONVERGED, GRADIENT_CONVERGED)


def test_tron_matches_ridge_closed_form(rng):
    X, y, wt, batch = _make_batch(rng)
    w_star = _ridge_closed_form(X, y, wt, l2=2.0)
    obj = make_objective("squared", l2_weight=2.0)
    res = tron_solve(glm_adapter(obj, batch), jnp.zeros(X.shape[1], jnp.float32))
    np.testing.assert_allclose(res.w, w_star, rtol=2e-3, atol=2e-3)


def test_lbfgs_tron_agree_logistic(rng):
    X, y, wt, batch = _make_batch(rng, loss="logistic")
    obj = make_objective("logistic", l2_weight=1.0)
    ad = glm_adapter(obj, batch)
    d = X.shape[1]
    r1 = lbfgs_solve(ad, jnp.zeros(d, jnp.float32))
    r2 = tron_solve(ad, jnp.zeros(d, jnp.float32))
    np.testing.assert_allclose(r1.w, r2.w, rtol=5e-3, atol=5e-3)
    # both at a stationary point
    assert float(jnp.linalg.norm(obj.grad(r1.w, batch))) < 1e-2
    assert float(jnp.linalg.norm(obj.grad(r2.w, batch))) < 1e-2


def test_poisson_convergence(rng):
    X, y, wt, batch = _make_batch(rng, loss="poisson")
    obj = make_objective("poisson", l2_weight=0.5)
    res = lbfgs_solve(glm_adapter(obj, batch), jnp.zeros(X.shape[1], jnp.float32))
    gn = float(jnp.linalg.norm(obj.grad(res.w, batch)))
    assert gn < 5e-2, f"gradient norm {gn}"


def test_owlqn_lasso_kkt(rng):
    X, y, wt, batch = _make_batch(rng)
    obj = make_objective("squared", l2_weight=0.0)
    # pick l1 between the at-zero gradient magnitudes so SOME coords stay zero
    g0 = np.abs(np.asarray(obj.grad(jnp.zeros(X.shape[1], jnp.float32), batch)))
    l1 = float(np.median(g0))
    res = owlqn_solve(glm_adapter(obj, batch), jnp.zeros(X.shape[1], jnp.float32), l1)
    w, g = np.asarray(res.w), np.asarray(obj.grad(res.w, batch))
    # KKT: |g_j| <= l1 where w_j = 0 ; g_j = -l1*sign(w_j) where w_j != 0
    tol = 5e-2 * max(1.0, np.abs(g).max())
    zero = w == 0.0
    assert np.all(np.abs(g[zero]) <= l1 + tol)
    np.testing.assert_allclose(g[~zero], -l1 * np.sign(w[~zero]), atol=tol)
    # sparsity actually induced
    assert zero.sum() > 0


def test_owlqn_produces_sparser_models_with_larger_l1(rng):
    X, y, wt, batch = _make_batch(rng)
    obj = make_objective("squared")
    ad = glm_adapter(obj, batch)
    g0 = np.abs(np.asarray(obj.grad(jnp.zeros(X.shape[1], jnp.float32), batch)))
    nnz = []
    for l1 in (0.01 * float(g0.min()), 0.9 * float(g0.max())):
        res = owlqn_solve(ad, jnp.zeros(X.shape[1], jnp.float32), l1)
        nnz.append(int(np.sum(np.asarray(res.w) != 0)))
    assert nnz[1] < nnz[0]


def test_box_constraints_projection_and_kkt(rng):
    X, y, wt, batch = _make_batch(rng)
    d = X.shape[1]
    lo = jnp.full((d,), -0.1)
    hi = jnp.full((d,), 0.1)
    obj = make_objective("squared", l2_weight=1.0)
    res = lbfgs_solve(
        glm_adapter(obj, batch),
        jnp.zeros(d, jnp.float32),
        constraints=BoxConstraints(lower=lo, upper=hi),
    )
    w = np.asarray(res.w)
    assert np.all(w >= -0.1 - 1e-6) and np.all(w <= 0.1 + 1e-6)
    # KKT for box: at interior points gradient ~ 0; at bounds gradient pushes out
    g = np.asarray(obj.grad(res.w, batch))
    interior = (w > -0.1 + 1e-4) & (w < 0.1 - 1e-4)
    scale = max(1.0, np.abs(g).max())
    assert np.all(np.abs(g[interior]) < 0.05 * scale)
    assert np.all(g[w >= 0.1 - 1e-6] <= 1e-3 * scale)
    assert np.all(g[w <= -0.1 + 1e-6] >= -1e-3 * scale)


@pytest.mark.slow
def test_vmap_batched_lbfgs_matches_individual(rng):
    # the random-effect pattern: vmap over K independent problems
    K, n, d = 5, 40, 6
    Xs = rng.normal(size=(K, n, d))
    ys = np.stack([X @ rng.normal(size=d) for X in Xs])
    obj = make_objective("squared", l2_weight=1.0)

    # build K batches with identical shapes, stack their arrays
    batches = [SparseBatch.from_dense(Xs[k], ys[k]) for k in range(K)]
    nnz_max = max(b.nnz for b in batches)
    batches = [b.pad_rows_to(n, nnz_max) for b in batches]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)

    cfg = LBFGSConfig(max_iterations=50)

    def solve_one(b):
        return lbfgs_solve(glm_adapter(obj, b), jnp.zeros(d, jnp.float32), cfg)

    batched = jax.jit(jax.vmap(solve_one))(stacked)
    for k in range(K):
        single = solve_one(batches[k])
        np.testing.assert_allclose(batched.w[k], single.w, rtol=1e-3, atol=1e-3)


def test_warm_start_converges_quickly(rng):
    X, y, wt, batch = _make_batch(rng)
    obj = make_objective("squared", l2_weight=2.0)
    ad = glm_adapter(obj, batch)
    d = X.shape[1]
    cold = lbfgs_solve(ad, jnp.zeros(d, jnp.float32))
    warm = lbfgs_solve(
        ad,
        cold.w,
        init_value=cold.values[0],
        init_grad_norm=cold.grad_norms[0],
    )
    assert int(warm.iterations) <= 3
    np.testing.assert_allclose(warm.w, cold.w, rtol=1e-3, atol=1e-3)


def test_factory_dispatch_and_validation(rng):
    X, y, wt, batch = _make_batch(rng, loss="logistic")
    d = X.shape[1]
    w0 = jnp.zeros(d, jnp.float32)
    for opt, reg in [
        (OptimizerType.LBFGS, RegularizationType.L2),
        (OptimizerType.TRON, RegularizationType.L2),
        (OptimizerType.LBFGS, RegularizationType.ELASTIC_NET),
    ]:
        cfg = OptimizerConfig(
            optimizer_type=opt,
            regularization=RegularizationContext(reg, alpha=0.5),
            regularization_weight=1.0,
            max_iterations=40,
        )
        res = solve("logistic", batch, cfg, w0)
        assert np.all(np.isfinite(np.asarray(res.w)))

    with pytest.raises(ValueError, match="TRON does not support L1"):
        solve(
            "logistic",
            batch,
            OptimizerConfig(
                optimizer_type=OptimizerType.TRON,
                regularization=RegularizationContext(RegularizationType.L1),
                regularization_weight=1.0,
            ),
            w0,
        )
    with pytest.raises(ValueError, match="twice-differentiable"):
        solve(
            "smoothed_hinge",
            batch,
            OptimizerConfig(optimizer_type=OptimizerType.TRON),
            w0,
        )


def test_generic_objective_rosenbrock():
    # non-GLM objective through the generic adapter: Rosenbrock in 2D
    def f(w):
        v = 100.0 * (w[1] - w[0] ** 2) ** 2 + (1.0 - w[0]) ** 2
        return v

    ad = from_value_and_grad(jax.value_and_grad(f))
    res = lbfgs_solve(
        ad,
        jnp.asarray([-1.2, 1.0], jnp.float32),
        LBFGSConfig(max_iterations=200, tolerance=1e-12),
    )
    np.testing.assert_allclose(res.w, [1.0, 1.0], atol=2e-2)


# -- batched Newton (TPU-first small-d fast path) ----------------------------


def test_newton_matches_lbfgs_logistic(rng):
    X, y, wt, batch = _make_batch(rng, loss="logistic")
    w0 = jnp.zeros(X.shape[1], jnp.float32)
    cfg_n = OptimizerConfig(
        optimizer_type=OptimizerType.NEWTON,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.5,
        tolerance=1e-9,
    )
    cfg_l = dataclasses.replace(cfg_n, optimizer_type=OptimizerType.LBFGS)
    rn = solve("logistic", batch, cfg_n, w0)
    rl = solve("logistic", batch, cfg_l, w0)
    np.testing.assert_allclose(rn.w, rl.w, rtol=2e-3, atol=2e-3)
    # quadratic convergence: far fewer iterations than LBFGS
    assert int(rn.iterations) <= int(rl.iterations)


def test_newton_ridge_closed_form(rng):
    X, y, wt, batch = _make_batch(rng)
    w_star = _ridge_closed_form(X, y, wt, l2=2.0)
    cfg = OptimizerConfig(
        optimizer_type=OptimizerType.NEWTON,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=2.0,
        tolerance=1e-10,
    )
    res = solve("squared", batch, cfg, jnp.zeros(X.shape[1], jnp.float32))
    np.testing.assert_allclose(res.w, w_star, rtol=2e-3, atol=2e-3)
    # a quadratic solves in ~1 Newton step
    assert int(res.iterations) <= 3


def test_newton_vmapped_batch(rng):
    """Batched per-entity solves: vmap over independent problems."""
    E, n, d = 8, 40, 6
    Xs = rng.normal(size=(E, n, d))
    ys = rng.normal(size=(E, n))
    batches = [SparseBatch.from_dense(Xs[e], ys[e]) for e in range(E)]
    import jax

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
    cfg = OptimizerConfig(
        optimizer_type=OptimizerType.NEWTON,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
        tolerance=1e-10,
    )
    res = jax.vmap(
        lambda b, w0: solve("squared", b, cfg, w0), in_axes=(0, None)
    )(stacked, jnp.zeros(d, jnp.float32))
    for e in range(E):
        w_star = _ridge_closed_form(Xs[e], ys[e], np.ones(n), l2=1.0)
        np.testing.assert_allclose(res.w[e], w_star, rtol=3e-3, atol=3e-3)


def test_newton_rejects_l1_and_hinge():
    cfg = OptimizerConfig(
        optimizer_type=OptimizerType.NEWTON,
        regularization=RegularizationContext(RegularizationType.L1),
        regularization_weight=1.0,
    )
    with pytest.raises(ValueError, match="NEWTON"):
        cfg.validate("logistic")
    cfg2 = OptimizerConfig(optimizer_type=OptimizerType.NEWTON)
    with pytest.raises(ValueError, match="twice-differentiable"):
        cfg2.validate("smoothed_hinge")


def test_newton_with_box_constraints(rng):
    X, y, wt, batch = _make_batch(rng)
    cfg = OptimizerConfig(
        optimizer_type=OptimizerType.NEWTON,
        box_constraints=((0, 0.0, 0.0),),
        tolerance=1e-9,
    )
    res = solve("squared", batch, cfg, jnp.zeros(X.shape[1], jnp.float32))
    assert abs(float(res.w[0])) < 1e-7
