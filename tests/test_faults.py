"""Deterministic fault injection: plan semantics, typed errors, env
transport, and the registry catalog the crash matrix + lint L016 key on.

In-process injection tests live here (nan-poisoned solves, flaky-read
retries at each subsystem's seam); the true-crash (`exit`) matrix runs
through tools/chaos.py in tests/test_chaos.py.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from photon_ml_tpu import faults


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test leaves the process unarmed — an armed plan leaking into
    another test would inject faults nobody asked for."""
    yield
    faults.clear_plan()


# ---------------------------------------------------------------------------
# the catalog: every production seam, enumerable and stable
# ---------------------------------------------------------------------------

#: Every fault point the package registers, by owning subsystem. This
#: list is load-bearing twice: the test below fails when a seam appears
#: or vanishes without this catalog (and the README) being updated, and
#: static-analysis rule L016 keys on these literals to prove each point
#: is named by at least one test.
EXPECTED_POINTS = {
    # checkpoint atomic-write protocol (write-path: the crash matrix set)
    "checkpoint.save.before_tmp",
    "checkpoint.save.before_manifest",
    "checkpoint.save.before_rename",
    "checkpoint.save.after_rename",
    "checkpoint.manifest.read",
    # training loops
    "cd.step.boundary",
    "guard.solve_health",
    "streaming.solve.result",
    "streaming.chunk.boundary",
    # ingest pipeline
    "ingest.decode.read",
    "ingest.ring.acquire",
    "ingest.upload.chunk",
    # serving
    "serving.dispatch",
    "serving.async_dispatch",
    "serving.registry.poll",
    "serving.registry.load",
    "serving.nearline_event",
    "serving.nearline_apply",
    # distributed fleet seams (the distributed crash matrix set)
    "multihost.init",
    "fleet.heartbeat",
    "checkpoint.peer_manifest",
    "parallel.collective.entry",
    # serving-fleet seams (distributed, but they fire in router/member
    # processes — tools/chaos.py --serving-fleet owns their matrix)
    "serving.member_load",
    "serving.route_fanout",
    "serving.resize_swap",
    # fleet observability (supervisor-side: neither matrix — status is
    # observability, never control; covered by tests/test_fleet_status)
    "fleet.status_write",
    # incremental warm-start retrains (plain points — the warm restore
    # and delta scan are read-only, and the publish rides the registry's
    # tmp-then-rename; the incremental crash row in
    # tests/test_incremental.py kills at incremental.publish and proves
    # the base checkpoint and registry stay intact)
    "incremental.warm_restore",
    "incremental.delta_scan",
    "incremental.publish",
    # request-scoped tracing (plain point — the dump itself rides
    # utils.atomic tmp-then-rename; tools/chaos.py --serving-fleet row
    # flight_dump_kill kills mid-dump and proves fleet discovery never
    # adopts the torn .tmp; ring/parse coverage in tests/test_requests)
    "telemetry.flight_dump",
    # freshness-conductor daemon cycle seams (plain points — every write
    # in a cycle rides the registry's tmp-then-rename or lands in a
    # fresh escalation generation dir; tools/chaos.py --pipeline
    # hard-kills the cli pipeline daemon at each of pipeline.cycle_start,
    # pipeline.reconcile, and pipeline.escalate and proves the base
    # checkpoint stays byte-identical and the registry partial-free)
    "pipeline.cycle_start",
    "pipeline.reconcile",
    "pipeline.escalate",
    # quality observability seams (plain points — the publish gate fires
    # before ANY registry write so a kill leaves the registry untouched,
    # and a drift-flush failure drops one snapshot section and nothing
    # else; both armed in tests/test_quality.py and the chaos --quality
    # row)
    "quality.publish_gate",
    "quality.drift_flush",
}

WRITE_PATH_POINTS = [
    "checkpoint.save.after_rename",
    "checkpoint.save.before_manifest",
    "checkpoint.save.before_rename",
    "checkpoint.save.before_tmp",
]

#: the multi-process seams (sorted). tools/chaos.py --fleet runs the
#: training-fleet subset (one 2-process kill-one-member row per seam);
#: the serving.* entries fire in serving router/member processes and are
#: exercised by tools/chaos.py --serving-fleet instead
DISTRIBUTED_POINTS = [
    "checkpoint.peer_manifest",
    "fleet.heartbeat",
    "multihost.init",
    "parallel.collective.entry",
    "serving.member_load",
    "serving.resize_swap",
    "serving.route_fanout",
]


def test_registry_catalog_is_complete_and_stable():
    # import every module that owns a seam: registration is import-time
    import photon_ml_tpu.game.checkpoint  # noqa: F401
    import photon_ml_tpu.game.coordinate_descent  # noqa: F401
    import photon_ml_tpu.game.streaming  # noqa: F401
    import photon_ml_tpu.ingest.buffers  # noqa: F401
    import photon_ml_tpu.ingest.decode  # noqa: F401
    import photon_ml_tpu.ingest.pipeline  # noqa: F401
    import photon_ml_tpu.serving.batcher  # noqa: F401
    import photon_ml_tpu.serving.nearline  # noqa: F401
    import photon_ml_tpu.serving.registry  # noqa: F401
    import photon_ml_tpu.serving.router  # noqa: F401
    import photon_ml_tpu.serving.shard  # noqa: F401
    import photon_ml_tpu.parallel.distributed  # noqa: F401
    import photon_ml_tpu.parallel.fleet_status  # noqa: F401
    import photon_ml_tpu.parallel.multihost  # noqa: F401
    import photon_ml_tpu.incremental  # noqa: F401
    import photon_ml_tpu.pipeline  # noqa: F401
    import photon_ml_tpu.telemetry.requests  # noqa: F401
    import photon_ml_tpu.quality.drift  # noqa: F401
    import photon_ml_tpu.quality.gate  # noqa: F401

    registered = faults.registered_points()
    assert set(registered) == EXPECTED_POINTS
    assert faults.write_path_points() == WRITE_PATH_POINTS
    assert faults.distributed_points() == DISTRIBUTED_POINTS
    for name, info in registered.items():
        assert info.name == name
        assert info.description  # a seam nobody can describe is a smell


def test_reregistration_is_idempotent_but_write_path_conflicts_raise():
    import photon_ml_tpu.game.checkpoint  # noqa: F401

    assert faults.register_point(
        "checkpoint.manifest.read"
    ) == "checkpoint.manifest.read"
    with pytest.raises(ValueError, match="write_path"):
        faults.register_point("checkpoint.manifest.read", write_path=True)
    with pytest.raises(ValueError, match="distributed"):
        faults.register_point("checkpoint.manifest.read", distributed=True)


# ---------------------------------------------------------------------------
# plan semantics
# ---------------------------------------------------------------------------


def test_nth_hit_fires_exactly_once_on_the_nth_call():
    plan = faults.FaultPlan(
        [faults.FaultRule("t.nth", nth=3)]
    )
    faults.install_plan(plan)
    faults.fault_point("t.nth")
    faults.fault_point("t.nth")
    with pytest.raises(faults.InjectedFault, match="t.nth"):
        faults.fault_point("t.nth")
    faults.fault_point("t.nth")  # 4th hit: silent again
    assert plan.hit_counts() == {"t.nth": 4}


def test_io_action_is_an_oserror():
    faults.install_plan(
        faults.FaultPlan([faults.FaultRule("t.io", action="io")])
    )
    with pytest.raises(OSError) as ei:
        faults.fault_point("t.io")
    assert isinstance(ei.value, faults.InjectedFault)
    assert ei.value.point == "t.io"


def test_probability_draws_are_seed_deterministic():
    def pattern(seed):
        plan = faults.FaultPlan(
            [faults.FaultRule("t.p", action="raise", probability=0.5)],
            seed=seed,
        )
        out = []
        for _ in range(64):
            out.append(plan.hit("t.p") is not None)
        return out

    a, b = pattern(7), pattern(7)
    assert a == b  # same seed, same schedule
    assert pattern(8) != a  # different seed, different schedule
    assert any(a) and not all(a)


def test_plan_validation_rejects_malformed_rules():
    with pytest.raises(faults.FaultPlanError, match="unknown fault action"):
        faults.FaultRule("x", action="explode")
    with pytest.raises(faults.FaultPlanError, match="mutually exclusive"):
        faults.FaultRule("x", nth=1, probability=0.5)
    with pytest.raises(faults.FaultPlanError, match="nth must be >= 1"):
        faults.FaultRule("x", nth=0)
    with pytest.raises(faults.FaultPlanError, match="probability"):
        faults.FaultRule("x", probability=1.5)
    with pytest.raises(faults.FaultPlanError, match="duplicate"):
        faults.FaultPlan([faults.FaultRule("x"), faults.FaultRule("x")])
    with pytest.raises(faults.FaultPlanError, match="malformed"):
        faults.FaultPlan.from_json("{nope")
    with pytest.raises(faults.FaultPlanError, match="unknown rule keys"):
        faults.FaultPlan.from_json(
            {"rules": [{"point": "x", "severity": "bad"}]}
        )


def test_plan_roundtrips_through_json_and_names_unregistered_points():
    plan = faults.FaultPlan(
        [
            faults.FaultRule("checkpoint.manifest.read", action="io",
                             nth=2),
            faults.FaultRule("no.such.point", action="exit", exit_code=99),
        ],
        seed=5,
    )
    doc = plan.to_json()
    again = faults.FaultPlan.from_json(json.dumps(doc))
    assert again.to_json() == doc
    assert again.seed == 5
    import photon_ml_tpu.game.checkpoint  # noqa: F401 (registers)

    assert again.unregistered_points() == ["no.such.point"]


def test_env_transport_arms_without_code_cooperation(monkeypatch, tmp_path):
    doc = {"rules": [{"point": "t.env", "action": "raise"}]}
    monkeypatch.setenv(faults.ENV_VAR, json.dumps(doc))
    plan = faults.install_from_env()
    assert plan is not None and plan.points == ["t.env"]
    assert faults.warn_if_armed() is True
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("t.env")
    # @file indirection for plans too big for an env var
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(doc))
    monkeypatch.setenv(faults.ENV_VAR, f"@{p}")
    assert faults.install_from_env().points == ["t.env"]
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.install_from_env() is None
    assert faults.warn_if_armed() is False


def test_unarmed_fault_point_is_a_noop_and_counts_nothing():
    from photon_ml_tpu import telemetry

    faults.clear_plan()
    faults.fault_point("t.anything")
    assert telemetry.snapshot()["counters"].get("faults.injected") is None


def test_injections_are_counted_per_point():
    from photon_ml_tpu import telemetry

    telemetry.reset()
    try:
        faults.install_plan(
            faults.FaultPlan([faults.FaultRule("t.counted")])
        )
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("t.counted")
        counters = telemetry.snapshot()["counters"]
        assert counters["faults.injected"] == 1
        assert counters["faults.injected.t.counted"] == 1
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# value-corruption seams
# ---------------------------------------------------------------------------


def test_corrupt_array_poisons_first_element_numpy_and_jax():
    import jax.numpy as jnp

    faults.install_plan(
        faults.FaultPlan(
            [faults.FaultRule("t.nan", action="nan", nth=1)]
        )
    )
    host = np.ones((2, 3))
    out = faults.corrupt_array("t.nan", host)
    assert np.isnan(out[0, 0]) and not np.isnan(host[0, 0])  # copy, not mutate
    # second hit: untouched pass-through
    assert faults.corrupt_array("t.nan", host) is host

    faults.install_plan(
        faults.FaultPlan([faults.FaultRule("t.nan2", action="nan")])
    )
    dev = jnp.ones((4,))
    poisoned = faults.corrupt_array("t.nan2", dev)
    assert bool(jnp.isnan(poisoned[0]))


def test_corrupt_health_forces_diverged_verdict():
    import jax.numpy as jnp

    faults.install_plan(
        faults.FaultPlan(
            [faults.FaultRule("guard.solve_health", action="nan")]
        )
    )
    assert not bool(
        faults.corrupt_health("guard.solve_health", jnp.bool_(True))
    )
    # unarmed point: verdict passes through
    assert bool(faults.corrupt_health("t.other", jnp.bool_(True)))


def test_corrupt_sites_degrade_non_nan_actions_to_their_trigger():
    faults.install_plan(
        faults.FaultPlan([faults.FaultRule("t.deg", action="io")])
    )
    with pytest.raises(faults.InjectedIOError):
        faults.corrupt_array("t.deg", np.ones(3))


# ---------------------------------------------------------------------------
# in-process seam integration: the nan seam drives the streaming guard
# ---------------------------------------------------------------------------


def test_nan_injection_at_solve_result_drives_guard_rollback(rng):
    """Arming `streaming.solve.result` with a nan rule makes a HEALTHY
    chunk diverge on demand: the guard retries damped, rolls back, and
    the run survives — divergence recovery without crafting NaN data."""
    import jax.numpy as jnp  # noqa: F401

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.game.streaming import (
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.optim.guard import GuardSpec

    n_ent, rows, k = 8, 6, 3
    X = rng.normal(size=(n_ent, rows, k))
    y = (rng.random((n_ent, rows)) < 0.5).astype(float)

    def chunk(lo, hi):
        return DenseBatch(
            x=X[lo:hi].astype(np.float32),
            labels=y[lo:hi].astype(np.float32),
            offsets=np.zeros((hi - lo, rows), np.float32),
            weights=np.ones((hi - lo, rows), np.float32),
        )

    cfg = OptimizerConfig(
        max_iterations=40,
        tolerance=1e-8,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.3,
    )
    telemetry.reset()
    try:
        # chunk 0's solve result is poisoned on EVERY attempt (nth=1 and
        # nth=2 cover the first solve + its damped retry), so the guard
        # must roll it back; chunk 1 is untouched and trains
        faults.install_plan(
            faults.FaultPlan(
                [faults.FaultRule("streaming.solve.result",
                                  action="nan", probability=1.0)],
                seed=1,
            )
        )
        table = ShardedCoefficientTable(n_ent, k)
        trainer = StreamingRandomEffectTrainer(
            "logistic", cfg, guard=GuardSpec(max_retries=1)
        )
        trainer.train(table, [(0, chunk(0, 4))])
        faults.clear_plan()
        trainer.train(table, [(4, chunk(4, n_ent))], start_chunk=0)
        got = table.to_numpy()
        np.testing.assert_array_equal(got[:4], 0.0)  # rolled back
        assert np.any(np.abs(got[4:]) > 0)  # healthy rows trained
        counters = telemetry.snapshot()["counters"]
        assert counters["solves.rolled_back"] == 1
        assert counters["faults.injected"] >= 2  # solve + damped retry
    finally:
        telemetry.reset()


def test_raise_injection_at_chunk_boundary_leaves_resumable_state(
    rng, tmp_path
):
    """An InjectedFault at `streaming.chunk.boundary` surfaces as a typed
    error AFTER the previous boundary's checkpoint was certified — the
    rerun resumes from it and completes."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.checkpoint import (
        CheckpointSpec,
        StreamingCheckpointManager,
    )
    from photon_ml_tpu.game.streaming import (
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )

    n_ent, rows, k = 8, 6, 3
    X = rng.normal(size=(n_ent, rows, k))
    y = (rng.random((n_ent, rows)) < 0.5).astype(float)

    def chunk(lo, hi):
        return DenseBatch(
            x=X[lo:hi].astype(np.float32),
            labels=y[lo:hi].astype(np.float32),
            offsets=np.zeros((hi - lo, rows), np.float32),
            weights=np.ones((hi - lo, rows), np.float32),
        )

    chunks = [(0, chunk(0, 4)), (4, chunk(4, n_ent))]
    cfg = OptimizerConfig(
        max_iterations=40,
        tolerance=1e-8,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.3,
    )
    trainer = StreamingRandomEffectTrainer("logistic", cfg, prefetch=False)

    ref = ShardedCoefficientTable(n_ent, k)
    trainer.train(ref, chunks)
    expected = ref.to_numpy()

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path / "ckpt"), every=1)
    )
    table = ShardedCoefficientTable(n_ent, k)
    faults.install_plan(
        faults.FaultPlan(
            [faults.FaultRule("streaming.chunk.boundary", nth=2)]
        )
    )
    with pytest.raises(faults.InjectedFault,
                       match="streaming.chunk.boundary"):
        trainer.train(table, chunks, checkpointer=mgr)
    faults.clear_plan()
    state = mgr.restore()
    assert state is not None and state.next_chunk == 1
    table2 = ShardedCoefficientTable(n_ent, k)
    table2.write_chunk(0, jnp.asarray(state.coefficients))
    trainer.train(table2, chunks, checkpointer=mgr,
                  start_chunk=state.next_chunk)
    np.testing.assert_array_equal(table2.to_numpy(), expected)


def test_bench_suite_gate_refuses_while_armed(tmp_path):
    """An armed plan under a GATED bench run is refused outright (exit
    2): numbers produced under injection are not comparable to any
    baseline, and a silent pass would corrupt the CI perf contract.
    (bench.py / bench_suite.py also warn loudly on any armed run, same
    as cli train/serve.)"""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PHOTON_FAULT_PLAN"] = json.dumps(
        {"rules": [{"point": "cd.step.boundary", "action": "raise"}]}
    )
    baseline = tmp_path / "baseline.json"
    baseline.write_text("{}")
    proc = subprocess.run(
        [sys.executable, "bench_suite.py", "--gate", str(baseline)],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 2
    assert "refusing --gate" in proc.stderr
    assert "FAULT INJECTION ARMED" in proc.stderr
