"""Lambda-sweep training API (ModelTraining analog): warm-start chaining,
single compiled program across lambdas, variances, best-model selection."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.normalization import (
    NormalizationType,
    build_normalization_context,
)
from photon_ml_tpu.data.stats import summarize
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
    solve,
)
from photon_ml_tpu.training import select_best_model, train_glm


def _logistic_data(rng, n=400, d=12):
    X = rng.normal(size=(n, d))
    X[:, 0] = 1.0  # intercept column
    w_true = rng.normal(size=d)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.random(n) < p).astype(np.float64)
    return X, y, SparseBatch.from_dense(X, y)


def _l2_config(**kw):
    return OptimizerConfig(
        regularization=RegularizationContext(RegularizationType.L2),
        **kw,
    )


def test_sweep_matches_individual_solves(rng):
    X, y, batch = _logistic_data(rng)
    lambdas = [0.1, 10.0, 1.0]
    entries = train_glm(batch, "logistic", lambdas, _l2_config())
    assert [e.reg_weight for e in entries] == lambdas  # caller order preserved
    for lam, e in zip(lambdas, entries):
        cfg = _l2_config(regularization_weight=lam)
        ref = solve(
            "logistic", batch, cfg, jnp.zeros(X.shape[1], jnp.float32)
        )
        np.testing.assert_allclose(
            e.model.coefficients.means, ref.w, rtol=1e-3, atol=1e-3
        )


@pytest.mark.slow
def test_warm_start_beats_cold_start_iterations(rng):
    X, y, batch = _logistic_data(rng, n=600)
    lambdas = [100.0, 10.0, 1.0, 0.1, 0.01]
    entries = train_glm(batch, "logistic", lambdas, _l2_config())
    warm_iters = sum(int(e.result.iterations) for e in entries)
    cold_iters = 0
    for lam in lambdas:
        cfg = _l2_config(regularization_weight=lam)
        cold_iters += int(
            solve("logistic", batch, cfg, jnp.zeros(X.shape[1], jnp.float32))
            .iterations
        )
    # descending warm-started sweep must do no more total work
    assert warm_iters <= cold_iters
    # and the later (small-lambda) solves individually benefit
    assert int(entries[-1].result.iterations) < int(
        solve(
            "logistic",
            batch,
            _l2_config(regularization_weight=0.01),
            jnp.zeros(X.shape[1], jnp.float32),
        ).iterations
    )


def test_sweep_compiles_once(rng):
    X, y, batch = _logistic_data(rng, n=100, d=6)
    with jax.log_compiles():
        import logging

        class Counter(logging.Handler):
            count = 0

            def emit(self, record):
                msg = record.getMessage()
                if "Finished XLA compilation" in msg and "_sweep_solve" in msg:
                    type(self).count += 1

        h = Counter()
        logging.getLogger("jax").addHandler(h)
        try:
            train_glm(batch, "logistic", [3.0, 1.0, 0.3, 0.1], _l2_config())
        finally:
            logging.getLogger("jax").removeHandler(h)
    # all lambdas share ONE compiled solve program (traced reg weight)
    assert Counter.count == 1


def test_variances_match_inverse_hessian_diagonal(rng):
    X, y, batch = _logistic_data(rng)
    lam = 2.0
    entries = train_glm(
        batch, "logistic", [lam], _l2_config(), compute_variances=True
    )
    m = entries[0].model
    assert m.coefficients.variances is not None
    w = m.coefficients.means
    z = X @ np.asarray(w)
    p = 1.0 / (1.0 + np.exp(-z))
    hdiag = (X**2 * (p * (1 - p))[:, None]).sum(axis=0) + lam
    np.testing.assert_allclose(
        m.coefficients.variances, 1.0 / (hdiag + 1e-12), rtol=5e-3
    )


def test_variances_round_trip_model_store(rng, tmp_path):
    from photon_ml_tpu.data.model_store import load_glm, save_glm

    X, y, batch = _logistic_data(rng, n=150, d=8)
    entries = train_glm(
        batch, "logistic", [1.0], _l2_config(), compute_variances=True
    )
    save_glm(entries[0].model, str(tmp_path / "m"))
    loaded = load_glm(str(tmp_path / "m"))
    np.testing.assert_allclose(
        loaded.coefficients.variances,
        entries[0].model.coefficients.variances,
        rtol=1e-6,
    )


def test_sweep_with_normalization_round_trips_space(rng):
    X, y, batch = _logistic_data(rng)
    # badly scaled column: normalization should still converge to the
    # optimum of the (normalized-space-regularized) problem; at lambda=0
    # the original-space optimum is normalization-invariant
    Xs = X.copy()
    Xs[:, 3] *= 100.0
    batch_s = SparseBatch.from_dense(Xs, y)
    summary = summarize(batch_s)
    norm = build_normalization_context(
        NormalizationType.STANDARDIZATION, summary, intercept_index=0
    )
    entries = train_glm(
        batch_s,
        "logistic",
        [0.0],
        OptimizerConfig(max_iterations=300, tolerance=1e-10),
        normalization=norm,
    )
    plain = train_glm(
        batch_s,
        "logistic",
        [0.0],
        OptimizerConfig(max_iterations=300, tolerance=1e-10),
    )
    np.testing.assert_allclose(
        entries[0].model.coefficients.means,
        plain[0].model.coefficients.means,
        rtol=2e-2,
        atol=2e-2,
    )


def test_select_best_model(rng):
    X, y, batch = _logistic_data(rng, n=500)
    Xv, yv, val_batch = _logistic_data(rng, n=300)
    lambdas = [100.0, 1.0, 0.01]
    entries = train_glm(batch, "logistic", lambdas, _l2_config())
    best, metric = select_best_model(entries, val_batch)
    assert best in entries
    assert 0.0 <= metric <= 1.0  # AUC for the logistic task
    # selection is argmax of the validation metric (AUC: larger is better)
    from photon_ml_tpu.evaluation import auc

    aucs = [
        float(auc(e.model.compute_score(val_batch), val_batch.labels,
                  val_batch.weights))
        for e in entries
    ]
    assert metric == pytest.approx(max(aucs))
    assert best is entries[int(np.argmax(aucs))]
    # RMSE selection direction (smaller is better) on the same entries
    best_rmse, val_rmse = select_best_model(entries, val_batch, metric="rmse")
    from photon_ml_tpu.evaluation import rmse as rmse_fn

    rmses = [
        float(rmse_fn(e.model.compute_score(val_batch), val_batch.labels,
                      val_batch.weights))
        for e in entries
    ]
    assert val_rmse == pytest.approx(min(rmses))


def test_owlqn_sweep_sparsity_increases_with_lambda(rng):
    X, y, batch = _logistic_data(rng)
    cfg = OptimizerConfig(
        regularization=RegularizationContext(RegularizationType.L1),
    )
    entries = train_glm(batch, "logistic", [5.0, 0.005], cfg)
    nnz_hi = int(np.sum(np.abs(np.asarray(entries[0].model.coefficients.means)) > 1e-8))
    nnz_lo = int(np.sum(np.abs(np.asarray(entries[1].model.coefficients.means)) > 1e-8))
    assert nnz_hi < nnz_lo


@pytest.mark.slow
def test_sweep_on_mesh_matches_single_device(rng):
    from photon_ml_tpu.parallel.mesh import make_mesh, shard_rows

    X, y, batch = _logistic_data(rng, n=256, d=10)
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    stacked = shard_rows(batch, 4)
    lambdas = [1.0, 0.1]
    dist = train_glm(stacked, "logistic", lambdas, _l2_config(), mesh=mesh)
    local = train_glm(batch, "logistic", lambdas, _l2_config())
    for d_e, l_e in zip(dist, local):
        np.testing.assert_allclose(
            d_e.model.coefficients.means,
            l_e.model.coefficients.means,
            rtol=1e-3,
            atol=1e-3,
        )


def test_game_fit_finish_event_carries_telemetry_snapshot(rng, tmp_path):
    """A toy GameEstimator.fit emits TrainingFinishEvent with the metrics
    snapshot attached — nonzero device_fetches, compile counters, and a
    JSONL span tree nesting fit > cd_iteration > coordinate:<name> that the
    Perfetto exporter converts without error (ISSUE 1 acceptance)."""
    import json

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.game import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
        RandomEffectConfig,
        build_game_dataset,
    )
    from photon_ml_tpu.utils.events import TrainingFinishEvent

    telemetry.reset()
    trace_out = tmp_path / "fit.trace.jsonl"
    telemetry.configure(trace_out=str(trace_out))
    try:
        X = rng.normal(size=(120, 5))
        users = rng.integers(0, 3, 120)
        y = (rng.random(120) < 0.5).astype(float)
        data = build_game_dataset(
            response=y,
            feature_shards={"f": SparseBatch.from_dense(X, y)},
            id_columns={"u": users},
        )
        est = GameEstimator(
            GameConfig(
                task="logistic",
                coordinates={
                    "fixed": FixedEffectConfig(shard_name="f"),
                    "perUser": RandomEffectConfig(shard_name="f", id_name="u"),
                },
            )
        )
        seen = []
        est.events.register(seen.append)
        est.fit(data)

        (finish,) = [e for e in seen if isinstance(e, TrainingFinishEvent)]
        snap = finish.metrics_snapshot
        assert snap is not None
        assert snap["counters"]["device_fetches"] > 0
        assert snap["counters"]["device_fetch_bytes"] > 0
        assert "jit_compiles" in snap["counters"]
        assert snap["histograms"]["re_solve_iterations"]["count"] > 0

        # per-coordinate span names, nested fit > cd_iteration > coordinate:*
        spans = telemetry.finished_spans()
        by_id = {s.span_id: s for s in spans}
        names = {s.name for s in spans}
        assert {"fit", "cd_iteration", "coordinate:fixed",
                "coordinate:perUser"} <= names
        for cname in ("coordinate:fixed", "coordinate:perUser"):
            (coord,) = [s for s in spans if s.name == cname]
            cd = by_id[coord.parent_id]
            assert cd.name == "cd_iteration"
            assert by_id[cd.parent_id].name == "fit"

        # the JSONL sink saw the same tree; the Perfetto export round-trips
        recorded = {
            json.loads(line)["name"]
            for line in trace_out.read_text().splitlines()
            if json.loads(line).get("type") == "span"
        }
        assert "coordinate:perUser" in recorded
        out = tmp_path / "fit.perfetto.json"
        assert telemetry.export_chrome_trace(str(trace_out), str(out)) > 0
        json.loads(out.read_text())
    finally:
        telemetry.reset()


def test_train_glm_emits_sweep_spans(rng):
    from photon_ml_tpu import telemetry

    telemetry.reset()
    try:
        X, y, batch = _logistic_data(rng, n=100, d=6)
        train_glm(batch, "logistic", [1.0, 0.1], _l2_config())
        (sweep,) = telemetry.finished_spans("train_glm")
        assert sweep.attrs["num_lambdas"] == 2
        solves = telemetry.finished_spans("lambda_solve")
        assert [s.attrs["reg_weight"] for s in solves] == [1.0, 0.1]
        assert all(s.parent_id == sweep.span_id for s in solves)
        assert telemetry.snapshot()["counters"]["glm_sweep_solves"] == 2
    finally:
        telemetry.reset()


def test_game_fit_with_nan_coordinate_completes_via_guard(rng):
    """ISSUE 2 acceptance: a fit with an injected NaN-producing coordinate
    completes — the bad coordinate rolls back (then freezes) instead of
    crashing the run, the divergence shows up in the telemetry snapshot,
    and the healthy coordinate still trains."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.game import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
        RandomEffectConfig,
        build_game_dataset,
    )
    from photon_ml_tpu.optim import GuardSpec

    n = 100
    Xf = rng.normal(size=(n, 4))
    Xg = rng.normal(size=(n, 4))
    Xg[3, 2] = np.nan  # one poisoned feature value -> NaN objective
    users = rng.integers(0, 3, n)
    y = (rng.random(n) < 0.5).astype(float)
    data = build_game_dataset(
        response=y,
        feature_shards={
            "f": SparseBatch.from_dense(Xf, y),
            "g": SparseBatch.from_dense(Xg, y),
        },
        id_columns={"u": users},
    )
    config = GameConfig(
        task="logistic",
        num_iterations=2,
        coordinates={
            "bad": FixedEffectConfig(shard_name="g"),
            "perUser": RandomEffectConfig(shard_name="f", id_name="u"),
        },
    )
    telemetry.reset()
    try:
        result = GameEstimator(config).fit(
            data, guard=GuardSpec(max_retries=1)
        )
        counters = telemetry.snapshot()["counters"]
        assert counters["solves.diverged"] >= 1
        assert counters["solves.retried"] >= 1
        assert counters["solves.rolled_back"] >= 1
        w_bad = np.asarray(result.model.models["bad"].coefficients)
        np.testing.assert_array_equal(w_bad, np.zeros_like(w_bad))
        # NaN scores were sanitized out of the residual: the healthy
        # coordinate trained to a finite non-zero model
        w_user = np.asarray(
            result.model.models["perUser"].buckets[0].coefficients
        )
        assert np.isfinite(w_user).all()
        assert np.any(np.abs(w_user) > 0)
    finally:
        telemetry.reset()


def test_variances_with_normalization_positive_and_scaled(rng):
    """The variance back-transform deviates from the reference deliberately:
    Var(c*X) = c^2 Var(X) — factor-squared scaling, no intercept shift term
    (the reference's means-transform on variances can go negative)."""
    X, y, _ = _logistic_data(rng, n=300, d=8)
    X = X.copy()
    X[:, 3] *= 50.0  # badly scaled column -> factor ~ 1/50
    batch = SparseBatch.from_dense(X, y)
    norm = build_normalization_context(
        NormalizationType.STANDARDIZATION, summarize(batch), intercept_index=0
    )
    e = train_glm(
        batch, "logistic", [1.0], _l2_config(), normalization=norm,
        compute_variances=True,
    )[0]
    v = np.asarray(e.model.coefficients.variances)
    assert np.all(v > 0)
    assert np.all(np.isfinite(v))
    # normalized-space variance is O(1) across columns; the factor^2 map
    # must shrink the scaled column's variance by ~50^2
    others = np.delete(v, [0, 3])
    assert v[3] < 0.05 * np.median(others)
