"""Avro codec + Photon wire formats: binary round-trips (both codecs),
TrainingExampleAvro -> GameDataset ingestion with shard merging and index
maps, LibSVM->Avro->train round-trip, model/score egress."""

import numpy as np
import pytest

from photon_ml_tpu.data.avro import (
    TRAINING_EXAMPLE_AVRO,
    build_index_map_from_avro,
    read_avro,
    read_bayesian_linear_model,
    read_game_dataset_from_avro,
    read_scoring_results,
    write_avro,
    write_bayesian_linear_model,
    write_scoring_results,
    write_training_examples,
)
from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.game import build_game_dataset
from photon_ml_tpu.ops.sparse import SparseBatch


def _example(i, features, user=None):
    rec = {
        "uid": str(i),
        "label": float(i % 2),
        "features": [
            {"name": n, "term": t, "value": float(v)} for n, t, v in features
        ],
        "metadataMap": {"userId": str(user)} if user is not None else None,
        "weight": 1.0 + 0.1 * i,
        "offset": 0.5 * i,
    }
    return rec


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_round_trip(tmp_path, codec):
    recs = [
        _example(i, [("f", str(j), (i + 1) * (j + 1)) for j in range(i % 4)],
                 user=i % 3)
        for i in range(257)  # crosses a block boundary with block_records=100
    ]
    p = str(tmp_path / "t.avro")
    n = write_avro(p, TRAINING_EXAMPLE_AVRO, recs, codec=codec,
                   block_records=100)
    assert n == 257
    back = list(read_avro(p))
    assert back == recs


def test_varint_edge_values(tmp_path):
    schema = {
        "name": "E",
        "type": "record",
        "fields": [
            {"name": "l", "type": "long"},
            {"name": "d", "type": "double"},
            {"name": "s", "type": "string"},
            {"name": "b", "type": "boolean"},
            {"name": "u", "type": ["null", "long"]},
        ],
    }
    vals = [0, -1, 1, 63, -64, 64, -65, 2**31, -(2**31), 2**62, -(2**62)]
    recs = [
        {"l": v, "d": v * 1.5, "s": f"v{v}", "b": v % 2 == 0,
         "u": None if v % 3 == 0 else v}
        for v in vals
    ]
    p = str(tmp_path / "e.avro")
    write_avro(p, schema, recs)
    assert list(read_avro(p)) == recs


def test_read_game_dataset_with_shard_merging(tmp_path):
    # two feature bags merged into one shard + a separate shard
    schema = dict(TRAINING_EXAMPLE_AVRO)
    schema = {
        **schema,
        "fields": schema["fields"]
        + [
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
        ],
    }
    recs = []
    for i in range(6):
        rec = _example(i, [("g", "a", i + 1), ("g", "b", 2 * i + 1)], user=i % 2)
        rec["userFeatures"] = [{"name": "u", "term": "x", "value": float(i)}]
        recs.append(rec)
    p = str(tmp_path / "m.avro")
    write_avro(p, schema, recs)

    data = read_game_dataset_from_avro(
        p,
        feature_shards={"global": ("features", "userFeatures"), "user": ("userFeatures",)},
        id_columns=["userId"],
    )
    assert data.num_rows == 6
    # global shard merged both bags: g|a, g|b, u|x + intercept = 4 features
    assert data.shard("global").num_features == 4
    assert data.shard("user").num_features == 2  # u|x + intercept
    np.testing.assert_allclose(data.offset, 0.5 * np.arange(6))
    np.testing.assert_allclose(data.weight, 1.0 + 0.1 * np.arange(6))
    assert data.id_columns["userId"].num_entities == 2
    # dense reconstruction of the user shard: value i in u|x + intercept 1
    ub = data.shard("user")
    vals = np.asarray(ub.values)
    assert vals[vals != 0].sum() == pytest.approx(sum(range(6)) + 6)


def test_unknown_features_dropped(tmp_path):
    p = str(tmp_path / "d.avro")
    write_avro(
        p,
        TRAINING_EXAMPLE_AVRO,
        [_example(i, [("known", "", 1.0), ("unknown", "", 9.0)]) for i in range(3)],
    )
    imap = IndexMap([feature_key("known", ""), INTERCEPT_KEY])
    data = read_game_dataset_from_avro(
        p, feature_shards={"f": ("features",)}, index_maps={"f": imap}
    )
    vals = np.asarray(data.shard("f").values)
    # per row: known=1.0 + intercept=1.0; the 9.0s are dropped
    assert vals.sum() == pytest.approx(6.0)


def test_libsvm_avro_round_trip_trains(rng, tmp_path):
    """LibSVM fixture -> GameDataset -> Avro -> GameDataset -> train; the
    re-read dataset must produce the same fit (dev-scripts
    libsvm_text_to_trainingexample_avro.py analog path)."""
    from photon_ml_tpu.data.libsvm import read_libsvm
    from photon_ml_tpu.training import train_glm
    from photon_ml_tpu.optim import OptimizerConfig

    # synthesize a small libsvm file
    lines = []
    n, d = 80, 10
    X = (rng.random((n, d)) < 0.4) * rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = np.sign(X @ w + 0.1 * rng.normal(size=n))
    for i in range(n):
        feats = " ".join(
            f"{j + 1}:{X[i, j]:.6f}" for j in np.nonzero(X[i])[0]
        )
        lines.append(f"{int(y[i])} {feats}")
    p = tmp_path / "a1a.libsvm"
    p.write_text("\n".join(lines) + "\n")

    lib = read_libsvm(str(p))
    batch = lib.to_batch(add_intercept=True)
    labels01 = (np.asarray(lib.labels) > 0).astype(float)
    data = build_game_dataset(
        response=labels01,
        feature_shards={"f": batch},
    )
    imap = IndexMap(
        [feature_key(str(j), "") for j in range(d)] + [INTERCEPT_KEY]
    )
    avro_path = str(tmp_path / "a1a.avro")
    n_written = write_training_examples(avro_path, data, "f", imap)
    assert n_written == n

    data2 = read_game_dataset_from_avro(
        avro_path, feature_shards={"f": ("features",)}, index_maps={"f": imap}
    )
    cfg = OptimizerConfig()
    e1 = train_glm(data.batch_for("f"), "logistic", [0.1], cfg)[0]
    e2 = train_glm(data2.batch_for("f"), "logistic", [0.1], cfg)[0]
    np.testing.assert_allclose(
        e1.model.coefficients.means, e2.model.coefficients.means,
        rtol=1e-4, atol=1e-4,
    )


def test_estimator_trains_from_avro_end_to_end(rng, tmp_path):
    from photon_ml_tpu.game import FixedEffectConfig, GameConfig, GameEstimator
    from photon_ml_tpu.optim import OptimizerConfig

    n, d = 100, 6
    X = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(float)
    recs = [
        _example(
            i,
            [(f"c{j}", "", X[i, j]) for j in range(d)],
            user=i % 5,
        )
        for i in range(n)
    ]
    for i, r in enumerate(recs):
        r["label"] = float(y[i])
        r["weight"] = None
        r["offset"] = None
    p = str(tmp_path / "train.avro")
    write_avro(p, TRAINING_EXAMPLE_AVRO, recs)

    data = read_game_dataset_from_avro(p, id_columns=["userId"])
    cfg = GameConfig(
        task="logistic",
        coordinates={"fixed": FixedEffectConfig(shard_name="features")},
    )
    result = GameEstimator(cfg).fit(data, output_dir=str(tmp_path / "model"))
    scores = np.asarray(result.model.score(data))[:n]
    acc = np.mean((scores > 0) == (y > 0.5))
    assert acc > 0.8


def test_model_export_import_avro(rng, tmp_path):
    imap = IndexMap.build(
        [feature_key("f", str(j)) for j in range(12)], add_intercept=True
    )
    means = rng.normal(size=len(imap))
    means[3] = 0.0  # sparse representation drops zeros
    variances = np.abs(rng.normal(size=len(imap))) + 0.1
    p = str(tmp_path / "model.avro")
    write_bayesian_linear_model(
        p, means, imap, model_id="m1", variances=variances,
        loss_function="logistic",
    )
    m2, v2, meta = read_bayesian_linear_model(p, imap)
    np.testing.assert_allclose(m2, means, rtol=1e-12)
    np.testing.assert_allclose(v2, variances, rtol=1e-12)
    assert meta["modelId"] == "m1"
    assert meta["lossFunction"] == "logistic"


def test_scoring_results_round_trip(tmp_path):
    scores = np.asarray([0.1, -2.5, 3.25])
    labels = np.asarray([1.0, 0.0, 1.0])
    p = str(tmp_path / "scores.avro")
    n = write_scoring_results(p, scores, model_id="best", labels=labels)
    assert n == 3
    recs = read_scoring_results(p)
    np.testing.assert_allclose([r["predictionScore"] for r in recs], scores)
    np.testing.assert_allclose([r["label"] for r in recs], labels)
    assert all(r["modelId"] == "best" for r in recs)


def test_build_index_map_from_avro(tmp_path):
    p = str(tmp_path / "x.avro")
    write_avro(
        p,
        TRAINING_EXAMPLE_AVRO,
        [_example(i, [("n", str(i % 3), 1.0)]) for i in range(9)],
    )
    imap = build_index_map_from_avro(p)
    assert len(imap) == 4  # 3 terms + intercept
    assert imap.get(INTERCEPT_KEY) >= 0


def test_weight_zero_and_null_id_fallback(tmp_path):
    """Explicit weight 0.0 survives; a null top-level id field falls back to
    the metadataMap value."""
    schema = dict(TRAINING_EXAMPLE_AVRO)
    schema = {
        **schema,
        "fields": schema["fields"]
        + [{"name": "userId", "type": ["null", "string"], "default": None}],
    }
    recs = [
        {**_example(0, [("f", "", 1.0)], user=7), "weight": 0.0, "userId": None},
        {**_example(1, [("f", "", 1.0)], user=8), "weight": 2.0, "userId": "9"},
    ]
    p = str(tmp_path / "w.avro")
    write_avro(p, schema, recs)
    data = read_game_dataset_from_avro(p, id_columns=["userId"])
    np.testing.assert_array_equal(data.weight, [0.0, 2.0])
    # record 0: top-level null -> metadataMap "7"; record 1: top-level "9"
    idc = data.id_columns["userId"]
    assert list(idc.vocab[idc.codes]) == ["7", "9"]


def test_feature_summary_round_trip(rng, tmp_path):
    from photon_ml_tpu.data.avro import read_feature_summary, write_feature_summary
    from photon_ml_tpu.data.stats import summarize

    n, d = 60, 5
    X = rng.normal(size=(n, d))
    batch = SparseBatch.from_dense(X, np.zeros(n))
    imap = IndexMap([feature_key("f", str(j)) for j in range(d)])
    p = str(tmp_path / "stats.avro")
    n_written = write_feature_summary(p, summarize(batch), imap)
    assert n_written == d
    stats = read_feature_summary(p)
    assert set(stats) == {feature_key("f", str(j)) for j in range(d)}
    k0 = feature_key("f", "0")
    assert stats[k0]["max"] == pytest.approx(X[:, 0].max(), rel=1e-5)
    assert stats[k0]["mean"] == pytest.approx(X[:, 0].mean(), rel=1e-4, abs=1e-5)
    assert stats[k0]["variance"] == pytest.approx(
        X[:, 0].var(ddof=1), rel=1e-4
    )


def test_native_reader_matches_python(tmp_path, rng):
    """The C++ fast path must be byte-identical to the pure-Python decoder:
    same COO, scalars, id values, and index maps — including union-null
    fields, terms, unknown-feature drops, and multi-file merges."""
    from photon_ml_tpu.data import avro as A
    from photon_ml_tpu.data.avro_native import read_game_arrays_native

    n = 300
    users = rng.integers(0, 9, size=n)

    def recs(lo, hi):
        for i in range(lo, hi):
            feats = [
                {"name": f"f{rng.integers(0, 40)}", "term": "t" if i % 3 else "",
                 "value": float(rng.normal())}
                for _ in range(int(rng.integers(1, 6)))
            ]
            yield {
                "uid": str(i) if i % 4 else None,
                "label": float(i % 2),
                "features": feats,
                "metadataMap": {"userId": str(users[i]), "junk": "x"},
                "weight": 2.0 if i % 5 == 0 else None,
                "offset": 0.25 if i % 7 == 0 else None,
            }

    p1 = str(tmp_path / "a.avro")
    p2 = str(tmp_path / "b.avro")
    write_avro(p1, TRAINING_EXAMPLE_AVRO, recs(0, 200))
    write_avro(p2, TRAINING_EXAMPLE_AVRO, recs(200, 300), codec="null")

    native = read_game_arrays_native(
        [p1, p2], {"features": ("features",)}, None, ("userId",)
    )
    if native is None:
        pytest.skip("native toolchain unavailable")

    ds_native = A.read_game_dataset_from_avro(
        [p1, p2], id_columns=("userId",)
    )
    # force the pure-Python path by making the program uncompilable is
    # invasive; instead call the internal python loop via a monkeypatch
    import photon_ml_tpu.data.avro_native as AN

    orig = AN.read_game_arrays_native
    AN.read_game_arrays_native = lambda *a, **k: None
    try:
        ds_python = A.read_game_dataset_from_avro(
            [p1, p2], id_columns=("userId",)
        )
    finally:
        AN.read_game_arrays_native = orig

    np.testing.assert_array_equal(ds_native.response, ds_python.response)
    np.testing.assert_array_equal(ds_native.offset, ds_python.offset)
    np.testing.assert_array_equal(ds_native.weight, ds_python.weight)
    in_ = ds_native.id_columns["userId"]
    ip = ds_python.id_columns["userId"]
    np.testing.assert_array_equal(in_.vocab[in_.codes], ip.vocab[ip.codes])
    dn = np.asarray(ds_native.shard("features").to_dense())
    dp = np.asarray(ds_python.shard("features").to_dense())
    np.testing.assert_allclose(dn, dp, rtol=0, atol=0)


def test_native_reader_missing_id_raises(tmp_path, rng):
    from photon_ml_tpu.data import avro as A

    path = str(tmp_path / "x.avro")
    write_avro(path, TRAINING_EXAMPLE_AVRO, [
        {"uid": "0", "label": 1.0,
         "features": [{"name": "a", "term": "", "value": 1.0}],
         "metadataMap": {}, "weight": None, "offset": None},
    ])
    with pytest.raises(KeyError, match="userId"):
        A.read_game_dataset_from_avro(path, id_columns=("userId",))


def test_env_toggle_hides_native_and_fallback_matches(
    tmp_path, rng, monkeypatch
):
    """PHOTON_NO_NATIVE=1 is the supported way to force the pure-Python
    reader: the native library must vanish immediately (no load-cache
    staleness) and read_game_dataset_from_avro must produce identical
    arrays through the fallback path."""
    from photon_ml_tpu.data import avro as A
    from photon_ml_tpu.data.native import load_native

    def recs():
        for i in range(120):
            yield {
                "uid": str(i),
                "label": float(i % 2),
                "features": [
                    {"name": f"f{rng.integers(0, 25)}", "term": "",
                     "value": float(rng.normal())}
                    for _ in range(3)
                ],
                "metadataMap": {"userId": str(i % 7)},
                "weight": None,
                "offset": 0.5 if i % 4 == 0 else None,
            }

    path = str(tmp_path / "toggle.avro")
    write_avro(path, TRAINING_EXAMPLE_AVRO, recs())
    if load_native() is None:
        pytest.skip("native toolchain unavailable")
    ds_native, maps = A.read_game_dataset_from_avro(
        path, id_columns=("userId",), return_index_maps=True
    )

    monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
    assert load_native() is None  # hidden immediately, not after a restart
    ds_py = A.read_game_dataset_from_avro(
        path, index_maps=maps, id_columns=("userId",)
    )
    np.testing.assert_array_equal(ds_py.response, ds_native.response)
    np.testing.assert_array_equal(ds_py.offset, ds_native.offset)
    np.testing.assert_array_equal(ds_py.weight, ds_native.weight)
    a = ds_py.shard("features").to_dense()
    b = ds_native.shard("features").to_dense()
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(
        ds_py.id_columns["userId"].codes, ds_native.id_columns["userId"].codes
    )

    monkeypatch.delenv("PHOTON_NO_NATIVE")
    assert load_native() is not None  # and back, same process
