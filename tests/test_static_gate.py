"""Tier-1 enforcement of the static-analysis gate.

``pytest tests/`` and ``python tools/check.py`` can no longer drift
apart: this test runs the real gate as a subprocess over the real tree
and fails on ANY non-baselined finding. A PR that introduces a hidden
device->host sync, an unregistered jit, an impure traced function, or an
unlocked cross-thread write now fails CI through the normal test run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECK = os.path.join(REPO, "tools", "check.py")


def test_static_gate_is_clean():
    proc = subprocess.run(
        [sys.executable, CHECK, "--json", "--no-external"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    doc = json.loads(proc.stdout)
    findings = "\n".join(
        f"{f['path']}:{f['line']}: {f['code']} {f['message']}"
        + (f"  [via {' -> '.join(f['chain'])}]" if f.get("chain") else "")
        for f in doc.get("findings", [])
    )
    assert proc.returncode == 0, f"static gate failed:\n{findings}"
    assert doc["findings"] == [], findings


def test_interprocedural_passes_cover_the_package():
    """The call-graph passes must really run over all of photon_ml_tpu/ —
    a silently empty graph (import bug, path change) would green-light
    everything L013-L015 exist to catch."""
    proc = subprocess.run(
        [sys.executable, CHECK, "--json", "--no-external"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    doc = json.loads(proc.stdout)
    # the package has ~87 modules / ~800 functions today; assert loose
    # floors so the test flags collapse, not growth
    assert doc["graph"]["modules"] >= 50, doc["graph"]
    assert doc["graph"]["functions"] >= 400, doc["graph"]
    assert doc["files"] >= 100, doc["files"]
