"""Tier-1 enforcement of the static-analysis gate.

``pytest tests/`` and ``python tools/check.py`` can no longer drift
apart: this test runs the real gate as a subprocess over the real tree
and fails on ANY non-baselined finding. A PR that introduces a hidden
device->host sync, an unregistered jit, an impure traced function, or an
unlocked cross-thread write now fails CI through the normal test run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECK = os.path.join(REPO, "tools", "check.py")

#: Wall-clock ceiling for one full gate run over the 140+-file tree on
#: the 2-core CI box. The gate runs as a tier-1 test AND as the
#: pre-commit loop's inner step: if the dataflow/lock passes ever make
#: it crawl, that is a regression to fix, not a timeout to raise.
GATE_BUDGET_S = 120.0


def test_static_gate_is_clean_within_budget():
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, CHECK, "--json", "--no-external"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    elapsed = time.monotonic() - t0
    doc = json.loads(proc.stdout)
    findings = "\n".join(
        f"{f['path']}:{f['line']}: {f['code']} {f['message']}"
        + (f"  [via {' -> '.join(f['chain'])}]" if f.get("chain") else "")
        for f in doc.get("findings", [])
    )
    assert proc.returncode == 0, f"static gate failed:\n{findings}"
    assert doc["findings"] == [], findings
    assert elapsed < GATE_BUDGET_S, (
        f"gate took {elapsed:.1f}s over {doc['files']} files — "
        f"budget {GATE_BUDGET_S:.0f}s"
    )


def test_interprocedural_passes_cover_the_package():
    """The call-graph passes must really run over all of photon_ml_tpu/ —
    a silently empty graph (import bug, path change) would green-light
    everything L013-L015 exist to catch."""
    proc = subprocess.run(
        [sys.executable, CHECK, "--json", "--no-external"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    doc = json.loads(proc.stdout)
    # the package has ~90 modules / ~1100 functions today; assert loose
    # floors so the test flags collapse, not growth
    assert doc["graph"]["modules"] >= 50, doc["graph"]
    assert doc["graph"]["functions"] >= 400, doc["graph"]
    assert doc["files"] >= 100, doc["files"]


def test_dataflow_and_lock_passes_really_ran():
    """The ISSUE 15 coverage contract: the ``--json`` document proves the
    taint engine walked the package (functions analyzed, taint edges
    propagated, jit callables seen — including the two donating
    writers) and the lock-order pass built a non-trivial graph. A
    silently-empty dataflow layer would green-light exactly the PR 10
    bug class it exists to catch."""
    proc = subprocess.run(
        [sys.executable, CHECK, "--json", "--no-external"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    doc = json.loads(proc.stdout)
    df = doc["graph"]["dataflow"]
    # ~1100 functions / ~1000 taint edges today; loose floors
    assert df["functions"] >= 400, df
    assert df["taint_edges"] >= 200, df
    assert df["jit_callables"] >= 10, df
    # the ingest assembler + streaming table chunk writers both donate
    assert df["donating_callables"] >= 2, df
    lk = doc["graph"]["locks"]
    # engine version lock, registry lock, nearline cv, fleet status
    # lock, batcher cv, heartbeat lock ... all acquired somewhere
    assert lk["nodes"] >= 5, lk
    # the shipped tree's lock-order graph must stay ACYCLIC; edges may
    # legitimately appear as the serving tier grows, cycles may not
    assert not any(
        f["code"] == "L018" for f in doc.get("findings", [])
    ), doc["findings"]
