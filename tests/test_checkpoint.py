"""Fault tolerance: checkpoint/resume for coordinate descent, crash
injection (truncated npz / deleted manifest -> fallback), guarded solves
(damped retry, rollback, freeze), and the graceful-preemption handshake."""

import dataclasses
import json
import os
import signal

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.game import (
    CheckpointError,
    CheckpointManager,
    CheckpointSpec,
    FixedEffectModel,
    GracefulStop,
    TrainingInterrupted,
    run_coordinate_descent,
)
from photon_ml_tpu.optim import GuardSpec


# ---------------------------------------------------------------------------
# toy coordinates: real Coordinate protocol, no optimizer work — so the
# checkpoint/guard machinery is exercised without compile cost
# ---------------------------------------------------------------------------


class _ToyCoordinate:
    """Deterministic coordinate: every update adds 1 to both coefficients.

    ``mode``: "ok" always converges; "nan_until_damped" produces NaNs until
    the guard applies extra L2; "nan" always produces NaNs.
    """

    def __init__(self, name, mode="ok", n_rows=6):
        self.name = name
        self.mode = mode
        self.n_rows = n_rows
        self.extra_l2 = 0.0
        self.updates = 0

    def initialize_model(self):
        return FixedEffectModel(
            coefficients=jnp.zeros((2,), jnp.float32), shard_name="f"
        )

    def update_model(self, model, residual_scores):
        self.updates += 1
        if self.mode == "nan" or (
            self.mode == "nan_until_damped" and not self.extra_l2
        ):
            return dataclasses.replace(
                model, coefficients=jnp.full((2,), jnp.nan, jnp.float32)
            )
        return dataclasses.replace(
            model, coefficients=model.coefficients + 1.0
        )

    def score(self, model):
        return jnp.broadcast_to(
            model.coefficients[0], (self.n_rows,)
        ).astype(jnp.float32)


def _run(coords, tmp_path=None, num_iterations=2, guard=None,
         should_stop=None, **spec_kw):
    checkpoint = None
    if tmp_path is not None:
        checkpoint = CheckpointManager(
            CheckpointSpec(directory=str(tmp_path), **spec_kw)
        )
    return run_coordinate_descent(
        coords,
        task="logistic",
        num_iterations=num_iterations,
        guard=guard,
        checkpoint=checkpoint,
        should_stop=should_stop,
    )


def _coef(result, name):
    return np.asarray(result.model.models[name].coefficients)


# ---------------------------------------------------------------------------
# checkpoint/resume
# ---------------------------------------------------------------------------


def test_checkpoint_saves_per_step_and_resume_skips_completed(tmp_path):
    coords = {"a": _ToyCoordinate("a"), "b": _ToyCoordinate("b")}
    reference = _run({"a": _ToyCoordinate("a"), "b": _ToyCoordinate("b")})

    stops = iter([False, False, True, True, True])
    with pytest.raises(TrainingInterrupted) as ei:
        _run(coords, tmp_path, should_stop=lambda: next(stops))
    # stopped after the 3rd step (global step 2); checkpoints 0..2 on disk
    assert ei.value.step == 2
    assert ei.value.checkpoint_path == str(tmp_path / "step-00000002")
    assert sorted(os.listdir(tmp_path)) == [
        "step-00000000", "step-00000001", "step-00000002"
    ]

    resumed_coords = {"a": _ToyCoordinate("a"), "b": _ToyCoordinate("b")}
    result = _run(resumed_coords, tmp_path)
    # only the single remaining step ran; models match the uninterrupted fit
    assert resumed_coords["a"].updates == 0
    assert resumed_coords["b"].updates == 1
    np.testing.assert_array_equal(_coef(result, "a"), _coef(reference, "a"))
    np.testing.assert_array_equal(_coef(result, "b"), _coef(reference, "b"))
    # the resumed history contains the restored steps plus the new one
    assert len(result.history) == 4


def test_resume_false_clears_stale_checkpoints(tmp_path):
    coords = {"a": _ToyCoordinate("a")}
    _run(coords, tmp_path, num_iterations=3, keep_last=10)
    fresh = {"a": _ToyCoordinate("a")}
    _run(fresh, tmp_path, num_iterations=1, resume=False, keep_last=10)
    assert fresh["a"].updates == 1  # trained from scratch
    # the stale run's higher-numbered steps are gone: only this run's
    # checkpoint remains, so a LATER --resume continues the right fit
    assert sorted(os.listdir(tmp_path)) == ["step-00000000"]


def test_frozen_coordinates_survive_resume(tmp_path):
    """A coordinate frozen before a preemption stays frozen after resume —
    the restart must not re-burn retries on a proven-divergent block."""
    guard = GuardSpec(max_retries=1, freeze_after=1)
    coords = {"bad": _ToyCoordinate("bad", mode="nan"),
              "ok": _ToyCoordinate("ok")}
    stops = iter([False, False, True, True])
    with pytest.raises(TrainingInterrupted):
        _run(coords, tmp_path, num_iterations=3, guard=guard,
             should_stop=lambda: next(stops))
    assert coords["bad"].updates == 2  # 1 attempt + 1 retry, then frozen

    resumed = {"bad": _ToyCoordinate("bad", mode="nan"),
               "ok": _ToyCoordinate("ok")}
    result = _run(resumed, tmp_path, num_iterations=3, guard=guard)
    assert resumed["bad"].updates == 0  # frozen state restored
    np.testing.assert_array_equal(_coef(result, "ok"), [3.0, 3.0])


def test_restore_falls_back_past_corrupt_checkpoints(tmp_path):
    telemetry.reset()
    try:
        coords = {"a": _ToyCoordinate("a")}
        _run(coords, tmp_path, num_iterations=3, keep_last=10)
        spec = CheckpointSpec(directory=str(tmp_path), keep_last=10)

        # newest checkpoint: truncate the coefficient npz mid-file
        npz = (tmp_path / "step-00000002" / "model" / "fixed-effect" / "a"
               / "coefficients.npz")
        npz.write_bytes(npz.read_bytes()[:20])
        state = CheckpointManager(spec).restore()
        assert state.step == 1

        # next: delete the manifest (simulates a crash before the rename)
        (tmp_path / "step-00000001" / "manifest.json").unlink()
        state = CheckpointManager(spec).restore()
        assert state.step == 0
        assert telemetry.snapshot()["counters"]["checkpoint.corrupt"] >= 2

        # all corrupt -> fresh start (restore returns None)
        with open(tmp_path / "step-00000000" / "manifest.json", "w") as f:
            f.write("{ not json")
        assert CheckpointManager(spec).restore() is None
    finally:
        telemetry.reset()


def test_restore_rejects_mismatched_coordinates(tmp_path):
    _run({"a": _ToyCoordinate("a")}, tmp_path, num_iterations=1)
    with pytest.raises(CheckpointError, match="coordinates"):
        _run({"other": _ToyCoordinate("other")}, tmp_path, num_iterations=1)


def test_retention_keeps_last_k_and_cleans_tmp(tmp_path):
    (tmp_path / ".tmp-step-00000099").mkdir()
    _run({"a": _ToyCoordinate("a")}, tmp_path, num_iterations=4, keep_last=2)
    assert sorted(os.listdir(tmp_path)) == ["step-00000002", "step-00000003"]


def test_checkpoint_every_n_steps(tmp_path):
    _run({"a": _ToyCoordinate("a")}, tmp_path, num_iterations=4, every=2,
         keep_last=10)
    assert sorted(os.listdir(tmp_path)) == ["step-00000001", "step-00000003"]


def test_manifest_is_json_safe_and_names_step(tmp_path):
    _run({"a": _ToyCoordinate("a")}, tmp_path, num_iterations=1)
    with open(tmp_path / "step-00000000" / "manifest.json") as f:
        manifest = json.load(f)
    assert manifest["step"] == 0
    assert manifest["coordinate_order"] == ["a"]
    assert manifest["history"][0]["coordinate"] == "a"


# ---------------------------------------------------------------------------
# guarded solves
# ---------------------------------------------------------------------------


def test_guard_damped_retry_recovers(tmp_path):
    telemetry.reset()
    try:
        coords = {"a": _ToyCoordinate("a", mode="nan_until_damped")}
        result = _run(coords, num_iterations=1, guard=GuardSpec(max_retries=2))
        np.testing.assert_array_equal(_coef(result, "a"), [1.0, 1.0])
        counters = telemetry.snapshot()["counters"]
        assert counters["solves.diverged"] == 1
        assert counters["solves.retried"] == 1
        assert "solves.rolled_back" not in counters
        assert result.history[0]["solve_retries"] == 1
    finally:
        telemetry.reset()


def test_guard_rollback_and_freeze(tmp_path):
    telemetry.reset()
    try:
        coords = {
            "bad": _ToyCoordinate("bad", mode="nan"),
            "ok": _ToyCoordinate("ok"),
        }
        result = _run(
            coords,
            num_iterations=3,
            guard=GuardSpec(max_retries=1, freeze_after=2),
        )
        # rolled back: the bad coordinate keeps its initial model, training
        # completed, and the healthy coordinate trained every iteration
        np.testing.assert_array_equal(_coef(result, "bad"), [0.0, 0.0])
        np.testing.assert_array_equal(_coef(result, "ok"), [3.0, 3.0])
        # frozen after 2 consecutive rollbacks -> no 3rd-iteration attempts
        assert coords["bad"].updates == 2 * 2  # 2 rollbacks x (1 + 1 retry)
        counters = telemetry.snapshot()["counters"]
        assert counters["solves.rolled_back"] == 2
        assert counters["solves.frozen"] == 1
        assert result.history[0]["rolled_back"] is True
    finally:
        telemetry.reset()


def test_guard_spec_validation():
    with pytest.raises(ValueError):
        GuardSpec(max_retries=-1)
    with pytest.raises(ValueError):
        GuardSpec(damping_factor=0.5)
    assert GuardSpec().damping_for(0) == 0.0
    assert GuardSpec(initial_damping=1.0, damping_factor=10.0).damping_for(2) \
        == 10.0


# ---------------------------------------------------------------------------
# graceful preemption
# ---------------------------------------------------------------------------


def test_graceful_stop_flag_on_sigterm():
    prev = signal.getsignal(signal.SIGTERM)
    try:
        stop = GracefulStop().install(signums=(signal.SIGTERM,))
        assert not stop()
        signal.raise_signal(signal.SIGTERM)
        assert stop()
        assert stop.signum == signal.SIGTERM
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_graceful_stop_second_signal_hard_exits_75(monkeypatch):
    """A REPEATED SIGTERM during the final-checkpoint write is the
    scheduler escalating: the process must hard-exit (75) immediately
    instead of blocking behind a slow save — via async-signal-safe calls
    only (a raw write(2) + ``os._exit``; logging could block on a lock a
    stuck thread holds). ``os._exit`` is intercepted — a real _exit
    would take the test runner with it; the crash matrix covers the
    true-exit shape via subprocesses."""
    exited = []
    monkeypatch.setattr(
        "photon_ml_tpu.game.checkpoint.os._exit",
        lambda code: exited.append(code),
    )
    prev = signal.getsignal(signal.SIGTERM)
    try:
        stop = GracefulStop().install(signums=(signal.SIGTERM,))
        signal.raise_signal(signal.SIGTERM)  # graceful request
        assert stop() and exited == []
        # ... the final checkpoint write is slow; the scheduler escalates
        signal.raise_signal(signal.SIGTERM)
        assert exited == [75]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_graceful_stop_hard_exit_code_is_configurable(monkeypatch):
    exited = []
    monkeypatch.setattr(
        "photon_ml_tpu.game.checkpoint.os._exit",
        lambda code: exited.append(code),
    )
    prev = signal.getsignal(signal.SIGINT)
    try:
        GracefulStop(hard_exit_code=99).install(signums=(signal.SIGINT,))
        signal.raise_signal(signal.SIGINT)
        signal.raise_signal(signal.SIGINT)
        assert exited == [99]
    finally:
        signal.signal(signal.SIGINT, prev)


def test_sigterm_mid_fit_writes_final_checkpoint(tmp_path):
    """The acceptance path in-process: a stop request arriving mid-fit ends
    the run with TrainingInterrupted AND a final checkpoint from which a
    restart reproduces the uninterrupted fit exactly."""
    prev = signal.getsignal(signal.SIGTERM)
    try:
        stop = GracefulStop().install(signums=(signal.SIGTERM,))
        coords = {"a": _ToyCoordinate("a"), "b": _ToyCoordinate("b")}
        fired = []

        def stop_after_first_step():
            if not fired:
                fired.append(True)
                signal.raise_signal(signal.SIGTERM)
            return stop()

        with pytest.raises(TrainingInterrupted):
            _run(coords, tmp_path, every=100,  # only the stop forces a save
                 should_stop=stop_after_first_step)
        assert sorted(os.listdir(tmp_path)) == ["step-00000000"]

        reference = _run({"a": _ToyCoordinate("a"), "b": _ToyCoordinate("b")})
        resumed = _run({"a": _ToyCoordinate("a"), "b": _ToyCoordinate("b")},
                       tmp_path, every=100)
        np.testing.assert_array_equal(_coef(resumed, "a"),
                                      _coef(reference, "a"))
        np.testing.assert_array_equal(_coef(resumed, "b"),
                                      _coef(reference, "b"))
    finally:
        signal.signal(signal.SIGTERM, prev)


# ---------------------------------------------------------------------------
# end-to-end: a real GAME fit interrupted and resumed
# ---------------------------------------------------------------------------


def _toy_game(rng):
    from photon_ml_tpu.game import (
        FixedEffectConfig,
        GameConfig,
        RandomEffectConfig,
        build_game_dataset,
    )
    from photon_ml_tpu.ops.sparse import SparseBatch

    # shapes deliberately distinct from test_training's toy fits: sharing
    # them would pre-warm the in-process jit cache and break that file's
    # jit_compiles counter assertion
    n = 130
    X = rng.normal(size=(n, 6))
    users = rng.integers(0, 4, n)
    y = (rng.random(n) < 0.5).astype(float)
    data = build_game_dataset(
        response=y,
        feature_shards={"f": SparseBatch.from_dense(X, y)},
        id_columns={"u": users},
    )
    config = GameConfig(
        task="logistic",
        num_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(shard_name="f"),
            "perUser": RandomEffectConfig(shard_name="f", id_name="u"),
        },
    )
    return data, config


def test_game_fit_interrupted_resume_reproduces_final_model(rng, tmp_path):
    from photon_ml_tpu.game import GameEstimator

    data, config = _toy_game(rng)
    reference = GameEstimator(config).fit(data)

    spec = CheckpointSpec(directory=str(tmp_path / "ckpt"))
    stops = iter([False, True, True, True])
    with pytest.raises(TrainingInterrupted):
        GameEstimator(config).fit(
            data, checkpoint_spec=spec, should_stop=lambda: next(stops)
        )

    resumed = GameEstimator(config).fit(data, checkpoint_spec=spec)
    for name in ("fixed",):
        np.testing.assert_allclose(
            np.asarray(resumed.model.models[name].coefficients),
            np.asarray(reference.model.models[name].coefficients),
            rtol=1e-6, atol=1e-7,
        )
    for ref_b, res_b in zip(
        reference.model.models["perUser"].buckets,
        resumed.model.models["perUser"].buckets,
    ):
        np.testing.assert_allclose(
            np.asarray(res_b.coefficients), np.asarray(ref_b.coefficients),
            rtol=1e-6, atol=1e-7,
        )


def test_cli_checkpoint_and_guard_config_parsing():
    from photon_ml_tpu.cli.train import (
        _parse_checkpoint_spec,
        _parse_guard_spec,
    )

    assert _parse_checkpoint_spec({}) is None
    spec = _parse_checkpoint_spec(
        {"checkpoint": {"dir": "/x", "every": 3, "resume": True}}
    )
    assert (spec.directory, spec.every, spec.resume) == ("/x", 3, True)
    # resume defaults TRUE: a scheduler restart with identical argv must
    # continue the preempted run, never wipe it
    assert _parse_checkpoint_spec({"checkpoint": {"dir": "/x"}}).resume
    assert not _parse_checkpoint_spec(
        {"checkpoint": {"dir": "/x", "resume": False}}
    ).resume
    with pytest.raises(ValueError, match="unknown checkpoint"):
        _parse_checkpoint_spec({"checkpoint": {"dir": "/x", "evry": 1}})
    with pytest.raises(ValueError, match="'dir'"):
        _parse_checkpoint_spec({"checkpoint": {"every": 2}})

    assert _parse_guard_spec({}) == GuardSpec()  # guarded by default
    assert _parse_guard_spec({"guard": False}) is None
    assert _parse_guard_spec({"guard": {"max_retries": 5}}).max_retries == 5
    with pytest.raises(ValueError, match="unknown guard"):
        _parse_guard_spec({"guard": {"retries": 5}})


# ---------------------------------------------------------------------------
# StreamingCheckpointManager (chunk-boundary checkpoints, ISSUE 9)
# ---------------------------------------------------------------------------


def test_streaming_manager_restore_falls_back_past_corrupt(tmp_path):
    import numpy as np

    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), keep_last=5)
    )
    for next_chunk in (1, 2, 3):
        mgr.save(
            StreamCheckpointState(
                next_chunk=next_chunk,
                coefficients=np.full((4, 3), float(next_chunk)),
            )
        )
    # corrupt the newest: truncate its manifest
    newest = tmp_path / "chunk-00000003" / "manifest.json"
    newest.write_text("{not json")
    state = mgr.restore()
    assert state is not None and state.next_chunk == 2
    np.testing.assert_array_equal(
        state.coefficients, np.full((4, 3), 2.0)
    )


def test_streaming_manager_retention_and_fresh_fit(tmp_path):
    import numpy as np

    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), keep_last=2)
    )
    for next_chunk in range(1, 6):
        mgr.save(
            StreamCheckpointState(
                next_chunk=next_chunk, coefficients=np.zeros((2, 2))
            )
        )
    kept = sorted(p.name for p in tmp_path.iterdir())
    assert kept == ["chunk-00000004", "chunk-00000005"]
    # resume=False clears the directory for a fresh fit
    fresh = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), resume=False)
    )
    assert fresh.restore() is None
    assert not any(p.name.startswith("chunk-") for p in tmp_path.iterdir())


def test_streaming_manager_rejects_shape_mismatch(tmp_path):
    import json

    import numpy as np

    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    mgr = StreamingCheckpointManager(CheckpointSpec(directory=str(tmp_path)))
    mgr.save(
        StreamCheckpointState(next_chunk=1, coefficients=np.zeros((4, 3)))
    )
    manifest = tmp_path / "chunk-00000001" / "manifest.json"
    doc = json.loads(manifest.read_text())
    doc["dim"] = 999  # lie about the shape
    manifest.write_text(json.dumps(doc))
    assert mgr.restore() is None  # skipped as corrupt, no newer fallback


# ---------------------------------------------------------------------------
# coordinated multi-process saves (quorum manifests, ISSUE 11)
# ---------------------------------------------------------------------------


def _patch_fleet(monkeypatch, pid, nproc):
    """Make this process claim fleet position (pid, nproc) — the
    coordinated-save protocol keys only on these two jax calls."""
    import jax

    monkeypatch.setattr(jax, "process_index", lambda: pid)
    monkeypatch.setattr(jax, "process_count", lambda: nproc)


def test_quorum_timeout_spec_validation(tmp_path):
    with pytest.raises(ValueError, match="quorum_timeout_s"):
        CheckpointSpec(directory=str(tmp_path), quorum_timeout_s=0.0)


def test_coordinated_save_abandons_uncertified_without_peer_quorum(
    tmp_path, monkeypatch
):
    """Process 0 with a dead peer: the quorum never forms, the save
    returns None after quorum_timeout_s, the directory is left
    UNCERTIFIED (no quorum manifest), restore refuses it, and the next
    successful save's retention sweeps the debris — a dead peer can
    neither hang the fleet nor poison the checkpoint chain."""
    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1,
                       quorum_timeout_s=0.3)
    )
    coeffs = np.arange(12, dtype=np.float32).reshape(4, 3)
    _patch_fleet(monkeypatch, pid=0, nproc=2)
    telemetry.reset()
    try:
        assert mgr.save(
            StreamCheckpointState(next_chunk=1, coefficients=coeffs)
        ) is None
        snap = telemetry.snapshot()["counters"]
        assert snap["checkpoint.quorum_timeouts"] == 1
        assert snap.get("checkpoint.saves") is None  # never certified
    finally:
        telemetry.reset()
    tmp_dirs = [n for n in os.listdir(tmp_path)
                if n.startswith(".tmp-chunk-")]
    assert tmp_dirs == [".tmp-chunk-00000001"]
    # process 0's OWN manifest landed; the quorum manifest did not
    contents = os.listdir(tmp_path / ".tmp-chunk-00000001")
    assert "manifest.proc-0000.json" in contents
    assert "manifest.json" not in contents
    assert mgr.restore() is None  # uncertified == invisible to restore
    # back to a healthy (single-process) fleet: saving works and sweeps
    _patch_fleet(monkeypatch, pid=0, nproc=1)
    path = mgr.save(
        StreamCheckpointState(next_chunk=2, coefficients=coeffs)
    )
    assert path is not None
    assert not [n for n in os.listdir(tmp_path)
                if n.startswith(".tmp-chunk-")]


def test_coordinated_save_certifies_quorum_after_all_peers_land(
    tmp_path, monkeypatch
):
    """The full rendezvous from process 0's seat, with a live peer
    simulated by a thread: rendezvous published, both per-process
    manifests land, the QUORUM manifest merges the shard lists sorted by
    row range and records the quorum size, the directory renames into
    place, and restore reassembles the full table."""
    import threading

    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1,
                       quorum_timeout_s=10.0)
    )
    tmp = tmp_path / ".tmp-chunk-00000003"
    # process 1 (simulated): joins the rendezvous, writes its half of the
    # entity axis [2, 4) and its per-process manifest (atomically last)
    peer_rows = np.full((2, 3), 7.0, np.float32)

    def peer():
        deadline = 10.0
        import time as _t
        t0 = _t.monotonic()
        while not os.path.exists(tmp / "rendezvous.json"):
            assert _t.monotonic() - t0 < deadline
            _t.sleep(0.01)
        rdv = json.load(open(tmp / "rendezvous.json"))
        assert rdv == {"num_processes": 2, "next_chunk": 3}
        np.save(tmp / "coefficients-p0001-0000.npy", peer_rows)
        with open(tmp / ".peer-manifest", "w") as fh:
            json.dump({
                "process_id": 1, "num_processes": 2, "next_chunk": 3,
                "shards": [{"file": "coefficients-p0001-0000.npy",
                            "row_start": 2, "rows": 2}],
                "variance_shards": None,
            }, fh)
        os.rename(tmp / ".peer-manifest", tmp / "manifest.proc-0001.json")

    t = threading.Thread(target=peer)
    t.start()
    # process 0 owns rows [0, 2): a 2-row local view whose manifest rows
    # say so (host arrays report row_start 0; the global row offsets in
    # a REAL fleet come from each process's addressable shard indices,
    # proven by the 2-process rows in tools/chaos.py --fleet)
    my_rows = np.full((2, 3), 3.0, np.float32)
    _patch_fleet(monkeypatch, pid=0, nproc=2)
    telemetry.reset()
    try:
        path = mgr.save(
            StreamCheckpointState(next_chunk=3, coefficients=my_rows)
        )
        t.join()
        assert path == str(tmp_path / "chunk-00000003")
        snap = telemetry.snapshot()["counters"]
        assert snap["checkpoint.saves"] == 1
        assert snap["checkpoint.peer_manifests"] == 1
        assert snap.get("checkpoint.quorum_timeouts") is None
    finally:
        telemetry.reset()
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    assert manifest["quorum"] == {"num_processes": 2}
    assert [
        (s["row_start"], s["rows"]) for s in manifest["shards"]
    ] == [(0, 2), (2, 2)]  # merged, sorted by row range
    # the per-process manifests ride along inside the certified dir
    assert {"manifest.proc-0000.json", "manifest.proc-0001.json"} <= set(
        os.listdir(path)
    )
    restored = mgr.restore()
    assert restored is not None and restored.next_chunk == 3
    got = np.asarray(restored.coefficients)
    np.testing.assert_array_equal(got[:2], my_rows)
    np.testing.assert_array_equal(got[2:], peer_rows)


def test_coordinated_save_abandons_on_cover_violation_or_missing_payload(
    tmp_path, monkeypatch
):
    """A peer manifest that breaks the entity-axis cover (overlap) or
    names a payload file not on disk (the stale-rendezvous race: the
    peer's shards died with a trashed tmp dir, its manifest landed in
    the fresh one) is NEVER certified — the save abandons with the
    distinct `checkpoint.quorum_cover_violations` counter, not a quorum
    timeout."""
    import threading

    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1,
                       quorum_timeout_s=10.0)
    )
    my_rows = np.zeros((2, 3), np.float32)

    def run_with_peer_manifest(next_chunk: int, peer_manifest: dict):
        tmp = tmp_path / f".tmp-chunk-{next_chunk:08d}"

        def peer():
            import time as _t
            t0 = _t.monotonic()
            while not os.path.exists(tmp / "rendezvous.json"):
                assert _t.monotonic() - t0 < 10.0
                _t.sleep(0.01)
            with open(tmp / ".peer-manifest", "w") as fh:
                json.dump(peer_manifest, fh)
            os.rename(
                tmp / ".peer-manifest", tmp / "manifest.proc-0001.json"
            )

        t = threading.Thread(target=peer)
        t.start()
        try:
            return mgr.save(StreamCheckpointState(
                next_chunk=next_chunk, coefficients=my_rows
            ))
        finally:
            t.join()

    _patch_fleet(monkeypatch, pid=0, nproc=2)
    telemetry.reset()
    try:
        # overlap: the peer claims rows [0, 2) that process 0 already owns
        assert run_with_peer_manifest(1, {
            "process_id": 1, "num_processes": 2, "next_chunk": 1,
            "shards": [{"file": "coefficients-p0001-0000.npy",
                        "row_start": 0, "rows": 2}],
            "variance_shards": None,
        }) is None
        # missing payload: contiguous cover, but the named file is absent
        assert run_with_peer_manifest(2, {
            "process_id": 1, "num_processes": 2, "next_chunk": 2,
            "shards": [{"file": "coefficients-p0001-0000.npy",
                        "row_start": 2, "rows": 2}],
            "variance_shards": None,
        }) is None
        snap = telemetry.snapshot()["counters"]
        assert snap["checkpoint.quorum_cover_violations"] == 2
        assert snap.get("checkpoint.quorum_timeouts") is None
        assert snap.get("checkpoint.saves") is None
    finally:
        telemetry.reset()
    assert mgr.restore() is None  # neither attempt is visible to restore


def test_coordinated_save_peer_ignores_stale_rendezvous(
    tmp_path, monkeypatch
):
    """A non-zero member finding a STALE rendezvous (wrong fleet size or
    wrong chunk — debris of an abandoned earlier save) keeps waiting
    instead of writing shards into a tmp dir process 0 is about to
    trash; with no fresh rendezvous it times out uncertified."""
    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )
    from photon_ml_tpu.utils.atomic import atomic_write_json

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1,
                       quorum_timeout_s=0.3)
    )
    tmp = tmp_path / ".tmp-chunk-00000001"
    os.makedirs(tmp)
    # stale: a 3-process fleet's rendezvous for the same chunk
    atomic_write_json(
        str(tmp / "rendezvous.json"),
        {"num_processes": 3, "next_chunk": 1},
    )
    _patch_fleet(monkeypatch, pid=1, nproc=2)
    telemetry.reset()
    try:
        assert mgr.save(StreamCheckpointState(
            next_chunk=1, coefficients=np.zeros((2, 3), np.float32)
        )) is None
        assert telemetry.snapshot()["counters"][
            "checkpoint.quorum_timeouts"] == 1
    finally:
        telemetry.reset()
    # the member never wrote shards into the stale dir
    assert sorted(os.listdir(tmp)) == ["rendezvous.json"]


def test_coordinated_save_peer_gives_up_without_process_zero(
    tmp_path, monkeypatch
):
    """A non-zero member whose process 0 died before the rendezvous:
    the bounded wait expires, the save returns None uncertified — the
    member carries on to the boundary stop instead of hanging."""
    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1,
                       quorum_timeout_s=0.3)
    )
    _patch_fleet(monkeypatch, pid=1, nproc=2)
    telemetry.reset()
    try:
        assert mgr.save(StreamCheckpointState(
            next_chunk=1,
            coefficients=np.zeros((4, 3), np.float32),
        )) is None
        assert telemetry.snapshot()["counters"][
            "checkpoint.quorum_timeouts"] == 1
    finally:
        telemetry.reset()
    assert mgr.restore() is None
