"""GLM objective tests: sparse vs dense parity, autodiff parity, normalization
algebra, Hessian products vs explicit Hessians."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch


def _random_problem(rng, n=50, d=12, density=0.4, loss="logistic"):
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < density)
    if loss == "poisson":
        y = rng.poisson(1.5, size=n).astype(np.float64)
    elif loss == "squared":
        y = rng.normal(size=n)
    else:
        y = (rng.random(n) > 0.5).astype(np.float64)
    offsets = rng.normal(size=n) * 0.1
    weights = rng.random(n) + 0.5
    batch = SparseBatch.from_dense(X, y, offsets=offsets, weights=weights)
    w = jnp.asarray(rng.normal(size=d) * 0.3, jnp.float32)
    return X, y, offsets, weights, batch, w


def _dense_value(loss_name, X, y, off, wt, w, l2=0.0, factors=None, shifts=None):
    loss = get_loss(loss_name)
    Xn = X if factors is None else (X - shifts) * factors
    z = Xn @ np.asarray(w, np.float64) + off
    l = np.asarray(loss.loss(jnp.asarray(z), jnp.asarray(y)), np.float64)
    return float(np.sum(wt * l) + 0.5 * l2 * np.dot(w, w))


@pytest.mark.parametrize("loss", ["logistic", "squared", "poisson", "smoothed_hinge"])
def test_value_and_grad_vs_dense(loss, rng):
    X, y, off, wt, batch, w = _random_problem(rng, loss=loss)
    obj = make_objective(loss, l2_weight=0.7)
    value, grad = obj.value_and_grad(w, batch)
    assert np.isclose(value, _dense_value(loss, X, y, off, wt, w, l2=0.7), rtol=1e-4)
    # autodiff through the sparse path must agree with the analytic gradient
    auto = jax.grad(lambda ww: obj.value(ww, batch))(w)
    np.testing.assert_allclose(grad, auto, rtol=2e-4, atol=2e-4)


def test_normalization_matches_explicit_transform(rng):
    X, y, off, wt, batch, w = _random_problem(rng)
    d = X.shape[1]
    factors = rng.random(d) + 0.5
    shifts = rng.normal(size=d) * 0.2
    obj = make_objective(
        "logistic",
        l2_weight=0.3,
        factors=jnp.asarray(factors, jnp.float32),
        shifts=jnp.asarray(shifts, jnp.float32),
    )
    value, grad = obj.value_and_grad(w, batch)
    # explicit: densify, transform, recompute
    expected = _dense_value(
        "logistic", X, y, off, wt, w, l2=0.3, factors=factors, shifts=shifts
    )
    assert np.isclose(float(value), expected, rtol=1e-4)
    # gradient vs autodiff of the explicitly transformed dense objective
    Xn = jnp.asarray((X - shifts) * factors, jnp.float32)

    def dense_obj(ww):
        z = Xn @ ww + jnp.asarray(off, jnp.float32)
        l = get_loss("logistic").loss(z, jnp.asarray(y, jnp.float32))
        return jnp.sum(jnp.asarray(wt, jnp.float32) * l) + 0.5 * 0.3 * jnp.dot(ww, ww)

    auto = jax.grad(dense_obj)(w)
    np.testing.assert_allclose(grad, auto, rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("use_norm", [False, True])
def test_hessian_vector_vs_autodiff(use_norm, rng):
    X, y, off, wt, batch, w = _random_problem(rng)
    d = X.shape[1]
    kwargs = {}
    if use_norm:
        kwargs = dict(
            factors=jnp.asarray(rng.random(d) + 0.5, jnp.float32),
            shifts=jnp.asarray(rng.normal(size=d) * 0.2, jnp.float32),
        )
    obj = make_objective("logistic", l2_weight=0.4, **kwargs)
    v = jnp.asarray(rng.normal(size=d), jnp.float32)
    hv = obj.hessian_vector(w, v, batch)
    _, auto_hv = jax.jvp(lambda ww: jax.grad(lambda u: obj.value(u, batch))(ww), (w,), (v,))
    np.testing.assert_allclose(hv, auto_hv, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("use_norm", [False, True])
def test_hessian_diagonal_vs_full_hessian(use_norm, rng):
    X, y, off, wt, batch, w = _random_problem(rng, n=30, d=8)
    d = X.shape[1]
    kwargs = {}
    if use_norm:
        kwargs = dict(
            factors=jnp.asarray(rng.random(d) + 0.5, jnp.float32),
            shifts=jnp.asarray(rng.normal(size=d) * 0.2, jnp.float32),
        )
    obj = make_objective("poisson", l2_weight=0.2, **kwargs)
    diag = obj.hessian_diagonal(w, batch)
    H = jax.hessian(lambda ww: obj.value(ww, batch))(w)
    np.testing.assert_allclose(diag, jnp.diagonal(H), rtol=2e-3, atol=2e-3)


def test_padding_is_inert(rng):
    X, y, off, wt, batch, w = _random_problem(rng)
    padded = batch.pad_rows_to(batch.num_rows + 13, batch.nnz + 29)
    obj = make_objective("logistic", l2_weight=0.5)
    v0, g0 = obj.value_and_grad(w, batch)
    v1, g1 = obj.value_and_grad(w, padded)
    np.testing.assert_allclose(v0, v1, rtol=1e-6)
    np.testing.assert_allclose(g0, g1, rtol=1e-6)


def test_jit_and_l2_donation(rng):
    _, _, _, _, batch, w = _random_problem(rng)
    obj = make_objective("logistic")
    f = jax.jit(lambda o, ww, b: o.value_and_grad(ww, b))
    v1, _ = f(obj, w, batch)
    # changing l2_weight must NOT retrigger compilation (same treedef)
    v2, _ = f(obj.with_l2(2.0), w, batch)
    assert f._cache_size() == 1
    assert float(v2) > float(v1)


def test_padded_rows_stay_sorted(rng):
    # segment_sum is promised sorted rows (indices_are_sorted=True); padding
    # must preserve that (pad entries point at the LAST row).
    X, y, off, wt, batch, w = _random_problem(rng)
    b = SparseBatch.from_coo(
        np.asarray(batch.values)[: batch.nnz],
        np.asarray(batch.rows),
        np.asarray(batch.cols),
        np.asarray(batch.labels),
        num_features=batch.num_features,
        row_pad_multiple=16,
        nnz_pad_multiple=128,
    )
    rows = np.asarray(b.rows)
    assert np.all(np.diff(rows) >= 0)
    padded = b.pad_rows_to(b.num_rows + 7, b.nnz + 31)
    assert np.all(np.diff(np.asarray(padded.rows)) >= 0)
