"""Factored random effects + matrix factorization: alternation improves the
objective, GLMix+MF beats FE+RE-only when the ground truth is low-rank,
save/load round-trips, random projection properties."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.projection import build_gaussian_projection_matrix
from photon_ml_tpu.game import (
    FactoredRandomEffectConfig,
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    MatrixFactorizationModel,
    RandomEffectConfig,
    build_game_dataset,
)
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)


def _low_rank_re_data(rng, n_users=40, rows_per_user=25, d=30, k_true=2,
                      noise=0.05):
    """Per-user linear responses whose user coefficient vectors live in a
    K-dim subspace: w_u = B^T z_u with shared B [k_true, d]. Exactly the
    structure factored RE models; independent per-user fits overfit it."""
    n = n_users * rows_per_user
    users = np.repeat(np.arange(n_users), rows_per_user)
    X = rng.normal(size=(n, d))
    B = rng.normal(size=(k_true, d)) / np.sqrt(d)
    Z = rng.normal(size=(n_users, k_true)) * 2.0
    W = Z @ B  # [n_users, d] true per-user coefficients
    y = np.einsum("nd,nd->n", X, W[users]) + noise * rng.normal(size=n)
    batch = SparseBatch.from_dense(X, y)
    data = build_game_dataset(
        response=y, feature_shards={"feats": batch}, id_columns={"userId": users}
    )
    return data, users, X, W


def _holdout(rng, W, n_users, d, rows=10, noise=0.05):
    n = n_users * rows
    users = np.repeat(np.arange(n_users), rows)
    X = rng.normal(size=(n, d))
    y = np.einsum("nd,nd->n", X, W[users]) + noise * rng.normal(size=n)
    return build_game_dataset(
        response=y,
        feature_shards={"feats": SparseBatch.from_dense(X, y)},
        id_columns={"userId": users},
    ), y


def _opt(lam=0.0, iters=100, tol=1e-9):
    reg = RegularizationContext(
        RegularizationType.L2 if lam > 0 else RegularizationType.NONE
    )
    return OptimizerConfig(
        regularization=reg, regularization_weight=lam, max_iterations=iters,
        tolerance=tol,
    )


def test_factored_re_alternation_reduces_training_loss(rng):
    data, users, X, W = _low_rank_re_data(rng)
    cfg = GameConfig(
        task="squared",
        coordinates={
            "mf": FactoredRandomEffectConfig(
                shard_name="feats",
                id_name="userId",
                latent_dim=2,
                mf_iterations=3,
                re_optimizer=_opt(lam=1e-3),
                latent_optimizer=_opt(lam=1e-3),
            )
        },
    )
    result = GameEstimator(cfg).fit(data)
    model = result.model.models["mf"]
    scores = np.asarray(result.model.score(data))[: data.num_rows]
    resid = data.response - scores
    # explains most of the variance of a low-rank ground truth
    assert np.var(resid) < 0.25 * np.var(data.response)
    assert model.latent_dim == 2
    assert model.projection.matrix.shape == (2, 30)


@pytest.mark.slow
def test_factored_beats_plain_re_on_holdout(rng):
    """The MF structure should generalize better than independent per-user
    fits when users have few rows and coefficients are truly low-rank."""
    data, users, X, W = _low_rank_re_data(
        rng, n_users=60, rows_per_user=15, d=40, k_true=2
    )
    val, y_val = _holdout(rng, W, n_users=60, d=40)

    mf_cfg = GameConfig(
        task="squared",
        coordinates={
            "re": FactoredRandomEffectConfig(
                shard_name="feats",
                id_name="userId",
                latent_dim=2,
                mf_iterations=10,
                re_optimizer=_opt(lam=1e-3),
                latent_optimizer=_opt(lam=1e-3),
            )
        },
    )
    re_cfg = GameConfig(
        task="squared",
        coordinates={
            "re": RandomEffectConfig(
                shard_name="feats", id_name="userId", optimizer=_opt(lam=1e-3)
            )
        },
    )
    mf_model = GameEstimator(mf_cfg).fit(data).model
    re_model = GameEstimator(re_cfg).fit(data).model

    def val_rmse(model):
        s = np.asarray(model.score(val))[: val.num_rows]
        return float(np.sqrt(np.mean((s - y_val) ** 2)))

    assert val_rmse(mf_model) < val_rmse(re_model)


@pytest.mark.slow
def test_factored_in_game_with_fixed_effect(rng):
    """FE + factored RE trained by coordinate descent: the combination must
    fit global + low-rank per-user structure better than FE alone."""
    data, users, X, W = _low_rank_re_data(rng, n_users=30, rows_per_user=20, d=20)
    w_global = rng.normal(size=20)
    y = np.asarray(data.response) + X @ w_global
    data = dataclasses.replace(data, response=y)

    both = GameConfig(
        task="squared",
        num_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(shard_name="feats", optimizer=_opt()),
            "mf": FactoredRandomEffectConfig(
                shard_name="feats",
                id_name="userId",
                latent_dim=2,
                mf_iterations=2,
                re_optimizer=_opt(lam=1e-3),
                latent_optimizer=_opt(lam=1e-3),
            ),
        },
    )
    fe_only = GameConfig(
        task="squared",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="feats", optimizer=_opt())
        },
    )
    r_both = GameEstimator(both).fit(data)
    r_fe = GameEstimator(fe_only).fit(data)

    def train_mse(r):
        s = np.asarray(r.model.score(data))[: data.num_rows]
        return float(np.mean((s - y) ** 2))

    assert train_mse(r_both) < 0.5 * train_mse(r_fe)


def test_factored_model_save_load_round_trip(rng, tmp_path):
    from photon_ml_tpu.data.model_store import load_game_model, save_game_model
    from photon_ml_tpu.game.models import GameModel

    data, *_ = _low_rank_re_data(rng, n_users=20, rows_per_user=10, d=15)
    cfg = GameConfig(
        task="squared",
        coordinates={
            "mf": FactoredRandomEffectConfig(
                shard_name="feats", id_name="userId", latent_dim=2,
                re_optimizer=_opt(lam=1e-3), latent_optimizer=_opt(lam=1e-3),
            )
        },
    )
    result = GameEstimator(cfg).fit(data)
    save_game_model(result.model, str(tmp_path / "m"))
    loaded = load_game_model(str(tmp_path / "m"))
    np.testing.assert_allclose(
        np.asarray(loaded.score(data)),
        np.asarray(result.model.score(data)),
        rtol=1e-6,
        atol=1e-6,
    )


def test_factored_scores_unseen_entities_zero(rng):
    data, *_ = _low_rank_re_data(rng, n_users=10, rows_per_user=10, d=12)
    cfg = GameConfig(
        task="squared",
        coordinates={
            "mf": FactoredRandomEffectConfig(
                shard_name="feats", id_name="userId", latent_dim=2,
                re_optimizer=_opt(lam=1e-3), latent_optimizer=_opt(lam=1e-3),
            )
        },
    )
    model = GameEstimator(cfg).fit(data).model
    # scoring data with entirely new user ids -> all scores 0
    n = 30
    X = rng.normal(size=(n, 12))
    new = build_game_dataset(
        response=np.zeros(n),
        feature_shards={"feats": SparseBatch.from_dense(X, np.zeros(n))},
        id_columns={"userId": np.arange(1000, 1000 + n)},
    )
    s = np.asarray(model.score(new))[:n]
    np.testing.assert_array_equal(s, 0.0)


def test_matrix_factorization_model_scoring_and_round_trip(rng, tmp_path):
    from photon_ml_tpu.data.model_store import load_game_model, save_game_model
    from photon_ml_tpu.game.models import GameModel

    n_users, n_items, k = 12, 9, 3
    RF = rng.normal(size=(n_users, k)).astype(np.float32)
    CF = rng.normal(size=(n_items, k)).astype(np.float32)
    mf = MatrixFactorizationModel(
        row_effect="userId",
        col_effect="itemId",
        row_factors=jnp.asarray(RF),
        col_factors=jnp.asarray(CF),
        row_vocab=np.arange(n_users),
        col_vocab=np.arange(n_items),
    )
    assert mf.num_latent_factors == k

    n = 50
    users = rng.integers(0, n_users, n)
    items = rng.integers(0, n_items, n)
    X = rng.normal(size=(n, 4))
    data = build_game_dataset(
        response=np.zeros(n),
        feature_shards={"feats": SparseBatch.from_dense(X, np.zeros(n))},
        id_columns={"userId": users, "itemId": items},
    )
    expected = np.einsum("nk,nk->n", RF[users], CF[items])
    np.testing.assert_allclose(
        np.asarray(mf.score(data))[:n], expected, rtol=1e-5, atol=1e-5
    )

    game = GameModel(task="squared", models={"mf": mf})
    save_game_model(game, str(tmp_path / "mf"))
    loaded = load_game_model(str(tmp_path / "mf"))
    np.testing.assert_allclose(
        np.asarray(loaded.score(data))[:n], expected, rtol=1e-5, atol=1e-5
    )
    # unseen ids score 0
    data2 = build_game_dataset(
        response=np.zeros(n),
        feature_shards={"feats": SparseBatch.from_dense(X, np.zeros(n))},
        id_columns={"userId": users + 500, "itemId": items},
    )
    np.testing.assert_array_equal(np.asarray(mf.score(data2))[:n], 0.0)


def test_gaussian_projection_matrix_properties(rng):
    pm = build_gaussian_projection_matrix(8, 100, seed=3)
    m = np.asarray(pm.matrix)
    assert m.shape == (8, 100)
    # entries N(0,1)/k clipped to [-1,1] (ProjectionMatrix.scala:95-124)
    assert np.all(np.abs(m) <= 1.0)
    assert np.std(m) == pytest.approx(1.0 / 8, rel=0.15)
    # intercept passthrough row
    pm2 = build_gaussian_projection_matrix(4, 10, intercept_index=10 - 1, seed=3)
    m2 = np.asarray(pm2.matrix)
    assert m2.shape == (5, 10)
    np.testing.assert_array_equal(m2[4, :9], 0.0)
    assert m2[4, 9] == 1.0
    # projection round trip on coefficients: A^T (A w) correlates with w
    w = rng.normal(size=100).astype(np.float32)
    back = np.asarray(pm.project_coefficients(pm.project_features(jnp.asarray(w))))
    assert back.shape == (100,)


@pytest.mark.slow
def test_factored_mesh_matches_single_device(rng):
    """Entity-sharded latent RE solves + data-parallel latent refit over an
    8-device mesh must reproduce the single-device factored fit."""
    import jax
    from jax.sharding import Mesh

    from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate
    from photon_ml_tpu.game.random_effect_data import build_random_effect_dataset

    data, *_ = _low_rank_re_data(rng, n_users=24, rows_per_user=12, d=16)
    red = build_random_effect_dataset(data, "userId", "feats")
    kw = dict(
        name="mf", data=data, re_data=red, loss_name="squared",
        re_config=_opt(lam=1e-3), latent_config=_opt(lam=1e-3),
        latent_dim=2, mf_iterations=3,
    )
    local = FactoredRandomEffectCoordinate(**kw)
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("entity",))
    sharded = FactoredRandomEffectCoordinate(**kw, mesh=mesh)

    m_local = local.update_model(local.initialize_model(), None)
    m_shard = sharded.update_model(sharded.initialize_model(), None)
    np.testing.assert_allclose(
        np.asarray(m_shard.projection.matrix),
        np.asarray(m_local.projection.matrix),
        rtol=5e-3, atol=5e-3,
    )
    np.testing.assert_allclose(
        np.asarray(sharded.score(m_shard)),
        np.asarray(local.score(m_local)),
        rtol=5e-3, atol=5e-3,
    )


def test_factored_coordinate_emits_tracker(rng):
    """The factored coordinate records per-MF-iteration telemetry pairs
    (FactoredRandomEffectOptimizationProblem tracker analog)."""
    from photon_ml_tpu.optim.trackers import (
        FactoredRandomEffectOptimizationTracker,
    )

    from photon_ml_tpu.game import build_random_effect_dataset
    from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate

    gds, *_ = _low_rank_re_data(rng, n_users=12, rows_per_user=15, d=10)
    red = build_random_effect_dataset(gds, "userId", "feats")
    coord = FactoredRandomEffectCoordinate(
        name="mf",
        data=gds,
        re_data=red,
        loss_name="squared",
        re_config=_opt(lam=0.1, iters=30),
        latent_config=_opt(lam=0.1, iters=30),
        latent_dim=2,
        mf_iterations=2,
    )
    coord.update_model(coord.initialize_model(), None)
    t = coord.last_tracker
    assert isinstance(t, FactoredRandomEffectOptimizationTracker)
    assert len(t.steps) == 2
    for re_t, fe_t in t.steps:
        assert len(re_t.iterations) > 0
        assert re_t.final_values is not None
        assert fe_t is not None and fe_t.iterations >= 1
    s = t.to_summary_string()
    assert "MF iteration 1" in s and "latent matrix" in s


def test_re_tracker_percentile_summary(rng):
    from photon_ml_tpu.optim.trackers import RandomEffectOptimizationTracker

    t = RandomEffectOptimizationTracker(
        iterations=np.arange(1, 101, dtype=np.int32),
        reasons=np.full(100, 3, np.int32),
        final_values=np.linspace(0.1, 1.0, 100).astype(np.float32),
    )
    p = t.percentile_summary()
    assert p["iterations"]["p50"] == pytest.approx(50.5)
    assert p["final_loss"]["p95"] == pytest.approx(
        float(np.percentile(np.linspace(0.1, 1.0, 100), 95)), rel=1e-5
    )
    assert "p95" in t.to_summary_string() or "final_loss" in t.to_summary_string()
