"""TiledBatch (pallas one-hot-matmul layout) parity vs SparseBatch.

The tiled kernels are the TPU fast path for the GLM hot loop
(ValueAndGradientAggregator.scala:132-153 analog); on CPU they run in
pallas interpret mode. Every quantity must match the padded-COO
segment-sum path to f32 tolerance.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.ops.tiled import TiledBatch
from photon_ml_tpu.optim import (
    LBFGSConfig,
    TRONConfig,
    glm_adapter,
    lbfgs_solve,
    tron_solve,
)


def _problem(rng, n=300, f=37, density=0.3, weights=True):
    X = rng.normal(size=(n, f)) * (rng.random((n, f)) < density)
    y = (rng.random(n) > 0.5).astype(np.float64)
    off = rng.normal(size=n) * 0.1
    wgt = rng.random(n) + 0.5 if weights else None
    sb = SparseBatch.from_dense(X, y, offsets=off, weights=wgt)
    tb = TiledBatch.from_dense(X, y, offsets=off, weights=wgt)
    return sb, tb


def _pad_to(x, n):
    return np.pad(np.asarray(x), (0, n - len(np.asarray(x))))


def test_margins_and_dot_rows_parity(rng):
    sb, tb = _problem(rng)
    w = jnp.asarray(rng.normal(size=37), jnp.float32)
    z_sb = np.asarray(sb.margins(w, shift=0.37))
    z_tb = np.asarray(tb.margins(w, shift=0.37))
    # padded rows differ (tb pads to 128-multiples); compare real rows
    np.testing.assert_allclose(z_tb[: len(z_sb)], z_sb, rtol=1e-4, atol=1e-4)

    u_sb = np.asarray(sb.dot_rows(w))
    u_tb = np.asarray(tb.dot_rows(w))
    np.testing.assert_allclose(u_tb[: len(u_sb)], u_sb, rtol=1e-4, atol=1e-4)


def test_margins_pair_matches_separate(rng):
    _, tb = _problem(rng)
    w = jnp.asarray(rng.normal(size=37), jnp.float32)
    p = jnp.asarray(rng.normal(size=37), jnp.float32)
    z, u = tb.margins_pair(w, 0.5, p, -0.25)
    np.testing.assert_allclose(
        np.asarray(z), np.asarray(tb.margins(w, 0.5)), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(u), np.asarray(tb.dot_rows(p)) - 0.25, rtol=1e-5, atol=1e-5)


def test_scatter_parity(rng):
    sb, tb = _problem(rng)
    per_row = rng.normal(size=sb.num_rows)
    g_sb = np.asarray(sb.scatter_features(jnp.asarray(per_row, jnp.float32)))
    g_tb = np.asarray(
        tb.scatter_features(jnp.asarray(_pad_to(per_row, tb.num_rows),
                                        jnp.float32)))
    np.testing.assert_allclose(g_tb, g_sb, rtol=1e-4, atol=1e-4)

    s_sb = np.asarray(sb.scatter_features_sq(jnp.asarray(per_row, jnp.float32)))
    s_tb = np.asarray(
        tb.scatter_features_sq(jnp.asarray(_pad_to(per_row, tb.num_rows),
                                           jnp.float32)))
    np.testing.assert_allclose(s_tb, s_sb, rtol=1e-4, atol=1e-4)


def test_feature_moment_sums_parity(rng):
    sb, tb = _problem(rng)
    for a, b in zip(tb.feature_moment_sums(), sb.feature_moment_sums()):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("loss", ["logistic", "squared", "poisson"])
def test_objective_value_and_grad_parity(rng, loss):
    sb, tb = _problem(rng)
    obj = make_objective(loss, l2_weight=0.7)
    w = jnp.asarray(rng.normal(size=37) * 0.1, jnp.float32)
    v_sb, g_sb = obj.value_and_grad(w, sb)
    v_tb, g_tb = obj.value_and_grad(w, tb)
    np.testing.assert_allclose(float(v_tb), float(v_sb), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(g_tb), np.asarray(g_sb), rtol=1e-3, atol=1e-4)


def test_objective_parity_with_normalization(rng):
    sb, tb = _problem(rng)
    factors = jnp.asarray(rng.random(37) + 0.5, jnp.float32)
    shifts = jnp.asarray(rng.normal(size=37) * 0.2, jnp.float32)
    obj = make_objective("logistic", l2_weight=0.3, factors=factors,
                         shifts=shifts)
    w = jnp.asarray(rng.normal(size=37) * 0.1, jnp.float32)
    v_sb, g_sb = obj.value_and_grad(w, sb)
    v_tb, g_tb = obj.value_and_grad(w, tb)
    np.testing.assert_allclose(float(v_tb), float(v_sb), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(g_tb), np.asarray(g_sb), rtol=1e-3, atol=1e-4)

    hv_sb = obj.hessian_vector(w, w, sb)
    hv_tb = obj.hessian_vector(w, w, tb)
    np.testing.assert_allclose(
        np.asarray(hv_tb), np.asarray(hv_sb), rtol=1e-3, atol=1e-4)

    hd_sb = obj.hessian_diagonal(w, sb)
    hd_tb = obj.hessian_diagonal(w, tb)
    np.testing.assert_allclose(
        np.asarray(hd_tb), np.asarray(hd_sb), rtol=1e-3, atol=1e-4)


def test_lbfgs_solve_matches_sparse_path(rng):
    sb, tb = _problem(rng, n=200, f=24)
    obj = make_objective("logistic", l2_weight=1.0)
    cfg = LBFGSConfig(max_iterations=30)
    w0 = jnp.zeros((24,), jnp.float32)
    res_sb = jax.jit(lambda w: lbfgs_solve(glm_adapter(obj, sb), w, cfg))(w0)
    res_tb = jax.jit(lambda w: lbfgs_solve(glm_adapter(obj, tb), w, cfg))(w0)
    np.testing.assert_allclose(float(res_tb.value), float(res_sb.value),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(res_tb.w), np.asarray(res_sb.w),
                               rtol=1e-2, atol=1e-3)


def test_tron_solve_matches_sparse_path(rng):
    sb, tb = _problem(rng, n=200, f=24)
    obj = make_objective("logistic", l2_weight=1.0)
    cfg = TRONConfig(max_iterations=10)
    w0 = jnp.zeros((24,), jnp.float32)
    res_sb = jax.jit(lambda w: tron_solve(glm_adapter(obj, sb), w, cfg))(w0)
    res_tb = jax.jit(lambda w: tron_solve(glm_adapter(obj, tb), w, cfg))(w0)
    np.testing.assert_allclose(float(res_tb.value), float(res_sb.value),
                               rtol=1e-4)


def test_from_batch_roundtrip(rng):
    sb, _ = _problem(rng, n=100, f=16)
    tb = TiledBatch.from_batch(sb)
    dense_sb = sb.to_dense()
    dense_tb = tb.to_dense()[: sb.num_rows]
    np.testing.assert_allclose(dense_tb, dense_sb, rtol=1e-6)


def test_bounds_validation():
    with pytest.raises(ValueError, match="feature indices"):
        TiledBatch.from_coo(
            values=np.ones(2), rows=np.array([0, 1]), cols=np.array([0, 9]),
            labels=np.zeros(2), num_features=5)
    with pytest.raises(ValueError, match="row indices"):
        TiledBatch.from_coo(
            values=np.ones(2), rows=np.array([0, 7]), cols=np.array([0, 1]),
            labels=np.zeros(2), num_features=5)


def test_with_offsets_flows_into_margins(rng):
    _, tb = _problem(rng, n=100, f=16)
    w = jnp.asarray(rng.normal(size=16), jnp.float32)
    new_off = jnp.asarray(rng.normal(size=tb.num_rows), jnp.float32)
    tb2 = tb.with_offsets(new_off)
    z1 = np.asarray(tb.dot_rows(w))
    z2 = np.asarray(tb2.margins(w))
    np.testing.assert_allclose(z2, z1 + np.asarray(new_off), rtol=1e-5,
                               atol=1e-5)
