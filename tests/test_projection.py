"""Pearson per-entity feature selection and the random-projection RE
projector (RE scaling tricks for the 1e8-entity regime)."""

import numpy as np
import pytest

from photon_ml_tpu.game import (
    GameConfig,
    GameEstimator,
    RandomEffectConfig,
    build_game_dataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)


def _re_data(rng, n_users=12, rows=20, d=30, informative=3):
    """Per-user data where only the first ``informative`` features predict
    the label; the rest are noise."""
    n = n_users * rows
    users = np.repeat(np.arange(n_users), rows)
    X = rng.normal(size=(n, d))
    w = np.zeros(d)
    w[:informative] = [3.0, -2.0, 1.5][:informative]
    y = X @ w + 0.01 * rng.normal(size=n)
    data = build_game_dataset(
        response=y,
        feature_shards={"f": SparseBatch.from_dense(X, y)},
        id_columns={"u": users},
    )
    return data, users, X, w, y


def _opt(lam=1e-3):
    return OptimizerConfig(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=lam,
        tolerance=1e-9,
    )


def test_pearson_selection_caps_feature_count(rng):
    data, users, X, w, y = _re_data(rng, rows=10, d=30)
    # ratio 0.5 -> each 10-row entity keeps ceil(5) features
    red = build_random_effect_dataset(
        data, "u", "f", features_to_samples_ratio=0.5
    )
    for b in red.buckets:
        proj = np.asarray(b.projection)
        per_entity_features = (proj < red.num_global_features).sum(axis=1)
        assert np.all(per_entity_features <= 5)


def test_pearson_selection_matches_per_entity_correlations(rng):
    """The kept set per entity is exactly the top-k features by that
    ENTITY's |Pearson(feature, label)| (computed independently here with
    np.corrcoef over the entity's own rows)."""
    data, users, X, w, y = _re_data(rng, rows=20, d=30, informative=3)
    red = build_random_effect_dataset(
        data, "u", "f", features_to_samples_ratio=0.25  # keep ceil(5) of 30
    )
    for b in red.buckets:
        proj = np.asarray(b.projection)
        codes = np.asarray(b.entity_codes)
        for e in range(b.num_entities):
            kept = set(proj[e][proj[e] < red.num_global_features].tolist())
            rows_e = users == codes[e]
            cors = np.abs(
                [np.corrcoef(X[rows_e, j], y[rows_e])[0, 1] for j in range(30)]
            )
            expected = set(np.argsort(-cors)[:5].tolist())
            assert kept == expected, (
                f"entity {codes[e]}: kept {sorted(kept)} vs top-5 "
                f"{sorted(expected)}"
            )


def test_pearson_selection_none_is_identity(rng):
    data, *_ = _re_data(rng)
    a = build_random_effect_dataset(data, "u", "f")
    b = build_random_effect_dataset(data, "u", "f", features_to_samples_ratio=None)
    for ba, bb in zip(a.buckets, b.buckets):
        np.testing.assert_array_equal(np.asarray(ba.values), np.asarray(bb.values))


def test_pearson_treats_constant_column_as_intercept(rng):
    n_users, rows, d = 6, 15, 10
    n = n_users * rows
    users = np.repeat(np.arange(n_users), rows)
    X = rng.normal(size=(n, d))
    X[:, 0] = 1.0  # constant intercept column
    y = 2.0 * X[:, 1] + 0.01 * rng.normal(size=n)
    data = build_game_dataset(
        response=y,
        feature_shards={"f": SparseBatch.from_dense(X, y)},
        id_columns={"u": users},
    )
    red = build_random_effect_dataset(
        data, "u", "f", features_to_samples_ratio=2 / 15  # keep 2 features
    )
    # intercept (score 1.0) + the informative column survive everywhere
    for b in red.buckets:
        proj = np.asarray(b.projection)
        for e in range(b.num_entities):
            kept = set(proj[e][proj[e] < red.num_global_features].tolist())
            assert kept == {0, 1}


def test_random_projection_re_trains_and_generalizes(rng):
    """projector='random': per-user solves in a shared Gaussian projected
    space; with truly low-rank structure it recovers most of the signal at
    a fraction of the per-entity dimension."""
    n_users, rows, d, k = 30, 40, 60, 8
    n = n_users * rows
    users = np.repeat(np.arange(n_users), rows)
    X = rng.normal(size=(n, d))
    B = rng.normal(size=(k, d)) / np.sqrt(d)
    Z = rng.normal(size=(n_users, k)) * 2
    y = np.einsum("nd,nd->n", X, (Z @ B)[users]) + 0.05 * rng.normal(size=n)
    data = build_game_dataset(
        response=y,
        feature_shards={"f": SparseBatch.from_dense(X, y)},
        id_columns={"u": users},
    )
    cfg = GameConfig(
        task="squared",
        coordinates={
            "re": RandomEffectConfig(
                shard_name="f",
                id_name="u",
                optimizer=_opt(),
                projector="random",
                projected_dim=24,
            )
        },
    )
    result = GameEstimator(cfg).fit(data)
    model = result.model.models["re"]
    # the model IS a fixed-projection factored model
    assert model.projection.matrix.shape == (24, d)
    s = np.asarray(result.model.score(data))[:n]
    # random projection to 24 of 60 dims keeps most of the fit
    assert np.var(y - s) < 0.5 * np.var(y)
    # scoring a dataset with unseen users gives 0
    new = build_game_dataset(
        response=np.zeros(10),
        feature_shards={"f": SparseBatch.from_dense(rng.normal(size=(10, d)),
                                                    np.zeros(10))},
        id_columns={"u": np.arange(900, 910)},
    )
    np.testing.assert_array_equal(np.asarray(model.score(new))[:10], 0.0)


def test_random_projection_config_validation():
    with pytest.raises(ValueError, match="projected_dim"):
        RandomEffectConfig(shard_name="f", id_name="u", projector="random")
    with pytest.raises(ValueError, match="unknown projector"):
        RandomEffectConfig(shard_name="f", id_name="u", projector="gauss")


def test_random_projection_with_intercept_passthrough(rng):
    from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate

    n_users, rows, d = 8, 25, 12
    n = n_users * rows
    users = np.repeat(np.arange(n_users), rows)
    X = rng.normal(size=(n, d))
    X[:, d - 1] = 1.0  # intercept column
    u_bias = rng.normal(size=n_users) * 3
    y = X[:, 0] + u_bias[users] + 0.05 * rng.normal(size=n)
    data = build_game_dataset(
        response=y,
        feature_shards={"f": SparseBatch.from_dense(X, y)},
        id_columns={"u": users},
    )
    red = build_random_effect_dataset(data, "u", "f")
    coord = FactoredRandomEffectCoordinate(
        name="re", data=data, re_data=red, loss_name="squared",
        re_config=_opt(), latent_config=_opt(), latent_dim=4,
        refit_projection=False, projection_intercept_index=d - 1,
    )
    model = coord.update_model(coord.initialize_model(), None)
    # A has 5 rows: 4 Gaussian + intercept passthrough
    assert model.projection.matrix.shape == (5, d)
    np.testing.assert_array_equal(
        np.asarray(model.projection.matrix)[4, : d - 1], 0.0
    )
    # per-user bias is recoverable through the passthrough row
    s = np.asarray(coord.score(model))[:n]
    assert np.var(y - s) < 0.3 * np.var(y)
    # the passthrough + refit combination is rejected
    with pytest.raises(ValueError, match="refit_projection"):
        FactoredRandomEffectCoordinate(
            name="re", data=data, re_data=red, loss_name="squared",
            re_config=_opt(), latent_config=_opt(), latent_dim=4,
            projection_intercept_index=d - 1,
        )
