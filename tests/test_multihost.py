"""Multi-host wiring tests (parallel/multihost.py).

The real multi-PROCESS parity run (2 processes x 4 virtual CPU devices,
jax.distributed + gloo collectives) lives in
``__graft_entry__._dryrun_multiprocess`` and is exercised here under the
``slow`` marker; the fast tests cover the pure-host helpers and the
single-process degenerate paths, which share all the code but the RPC.
"""

import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from photon_ml_tpu.parallel.multihost import (
    DistributedConfig,
    gather_to_host,
    global_mesh,
    host_local_array,
    process_slice,
    replicate_to_all,
)


def test_distributed_config_validation():
    DistributedConfig().validate()  # all-None is fine (single host / pod)
    with pytest.raises(ValueError, match="num_processes"):
        DistributedConfig(coordinator_address="h:1").validate()
    with pytest.raises(ValueError, match="out of range"):
        DistributedConfig(
            coordinator_address="h:1", num_processes=2, process_id=5
        ).validate()


def test_distributed_config_from_env(monkeypatch):
    monkeypatch.setenv("PHOTON_ML_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("PHOTON_ML_NUM_PROCESSES", "4")
    monkeypatch.setenv("PHOTON_ML_PROCESS_ID", "2")
    cfg = DistributedConfig.from_env()
    assert cfg.coordinator_address == "10.0.0.1:8476"
    assert cfg.num_processes == 4 and cfg.process_id == 2
    cfg.validate()


def test_process_slice_single_process_owns_everything():
    mesh = global_mesh({"entity": 8})
    assert process_slice(64, mesh, "entity") == (0, 64)
    with pytest.raises(ValueError, match="divide"):
        process_slice(63, mesh, "entity")


def test_host_local_array_and_gather_roundtrip():
    mesh = global_mesh({"data": 8})
    local = np.arange(32, dtype=np.float32).reshape(8, 4)
    arr = host_local_array(local, mesh, P("data"))
    assert arr.shape == (8, 4)
    np.testing.assert_array_equal(gather_to_host(arr), local)
    rep = replicate_to_all(np.float32(3.0), mesh)
    assert float(rep) == 3.0


def test_local_chunk_single_process_matches_dense():
    from photon_ml_tpu.game.streaming import (
        LocalChunk,
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )

    cfg = OptimizerConfig(
        optimizer_type=OptimizerType.LBFGS,
        max_iterations=10,
        tolerance=1e-9,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    rng = np.random.default_rng(0)
    n_ent, rows, k = 16, 5, 3
    batch = DenseBatch(
        x=rng.normal(size=(n_ent, rows, k)).astype(np.float32),
        labels=(rng.random((n_ent, rows)) > 0.5).astype(np.float32),
        offsets=np.zeros((n_ent, rows), np.float32),
        weights=np.ones((n_ent, rows), np.float32),
    )
    mesh = global_mesh({"entity": 8})

    def train(source):
        table = ShardedCoefficientTable(n_ent, k, mesh=mesh)
        StreamingRandomEffectTrainer("logistic", cfg, mesh=mesh).train(
            table, [(0, source)]
        )
        return table.to_numpy()

    w_plain = train(batch)
    w_local = train(LocalChunk(batch, global_size=n_ent))
    np.testing.assert_allclose(w_local, w_plain, atol=1e-6)


def test_table_bounds_checked():
    from photon_ml_tpu.game.streaming import ShardedCoefficientTable

    table = ShardedCoefficientTable(8, 3)
    with pytest.raises(ValueError, match="out of bounds"):
        table.read_chunk(4, 8)
    with pytest.raises(ValueError, match="out of bounds"):
        table.write_chunk(-1, np.zeros((2, 3), np.float32))
    with pytest.raises(ValueError, match="out of bounds"):
        table.write_chunk(7, np.zeros((2, 3), np.float32))
    # in-range write/read still fine
    table.write_chunk(6, np.ones((2, 3), np.float32))
    np.testing.assert_array_equal(
        np.asarray(table.read_chunk(6, 2)), np.ones((2, 3), np.float32)
    )


@pytest.mark.slow
def test_two_process_parity_dryrun():
    """2 OS processes x 2 devices each == one 4-device fleet; parity with
    the single-process 4-device run (full streaming + DP FE solve)."""
    import __graft_entry__ as ge

    ge._dryrun_multiprocess(4)


# ---------------------------------------------------------------------------
# fleet robustness: init retry, heartbeat liveness (PR 11)
# ---------------------------------------------------------------------------


def test_initialize_retries_transient_failures_with_backoff(monkeypatch):
    """A flaky rendezvous (gloo/grpc surfacing RuntimeError/OSError) is
    retried with exponential backoff and counted; the attempt that
    succeeds ends the loop."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.parallel import multihost

    sleeps: list[float] = []
    monkeypatch.setattr(multihost.time, "sleep", sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("connection refused")

    telemetry.reset()
    try:
        cfg = multihost.DistributedConfig(
            coordinator_address="10.0.0.9:8476", num_processes=2,
            process_id=0, init_retries=3, init_backoff_s=0.25,
        )
        multihost._init_attempts(cfg, flaky)
        assert calls["n"] == 3
        assert sleeps == [0.25, 0.5]  # exponential
        assert (
            telemetry.snapshot()["counters"]["multihost.init_retries"] == 2
        )
    finally:
        telemetry.reset()


def test_initialize_exhaustion_raises_fleet_init_error(monkeypatch):
    """Exhausted retries raise the typed FleetInitError NAMING the
    coordinator address — the operator learns which rendezvous died."""
    from photon_ml_tpu.parallel import multihost

    monkeypatch.setattr(multihost.time, "sleep", lambda s: None)

    def always_down():
        raise ConnectionError("no route to host")

    cfg = multihost.DistributedConfig(
        coordinator_address="10.1.2.3:9999", num_processes=2,
        process_id=1, init_retries=2,
    )
    with pytest.raises(multihost.FleetInitError, match="10.1.2.3:9999"):
        multihost._init_attempts(cfg, always_down)
    # attempts = 1 + init_retries, spelled out in the message
    try:
        multihost._init_attempts(cfg, always_down)
    except multihost.FleetInitError as e:
        assert "3 attempt(s)" in str(e)
        assert e.coordinator == "10.1.2.3:9999"


def test_initialize_injected_fault_seam_is_retryable(monkeypatch):
    """An armed `multihost.init` raise rule is absorbed by the bounded
    retry (InjectedFault is a RuntimeError) — the flaky-rendezvous shape
    the distributed matrix's exit rule escalates to a true kill."""
    from photon_ml_tpu import faults, telemetry
    from photon_ml_tpu.parallel import multihost

    monkeypatch.setattr(multihost.time, "sleep", lambda s: None)
    faults.install_plan(faults.FaultPlan(
        [faults.FaultRule("multihost.init", action="raise", nth=1)]
    ))
    telemetry.reset()
    try:
        cfg = multihost.DistributedConfig(
            coordinator_address="h:1", num_processes=2, process_id=0,
            init_retries=1,
        )
        done = {"n": 0}
        multihost._init_attempts(cfg, lambda: done.update(n=done["n"] + 1))
        assert done["n"] == 1  # first attempt died AT the seam, second ran
        assert (
            telemetry.snapshot()["counters"]["multihost.init_retries"] == 1
        )
    finally:
        faults.clear_plan()
        telemetry.reset()


def test_init_retries_config_from_env(monkeypatch):
    from photon_ml_tpu.parallel import multihost

    monkeypatch.setenv("PHOTON_ML_INIT_RETRIES", "7")
    assert multihost.DistributedConfig.from_env().init_retries == 7
    monkeypatch.delenv("PHOTON_ML_INIT_RETRIES")
    assert multihost.DistributedConfig.from_env().init_retries == 3


def test_heartbeat_writer_touches_and_dead_peers_detects_staleness(
    tmp_path,
):
    """The liveness protocol end-to-end on one filesystem: a started
    writer's file exists and refreshes; dead_peers flags only members
    whose file went STALE — never missing files (a member that has not
    joined yet is the exit-code watcher's job, not liveness')."""
    import os as _os
    import time as _time

    from photon_ml_tpu.parallel import multihost

    d = str(tmp_path)
    w = multihost.HeartbeatWriter(d, 0, interval_s=0.05).start()
    try:
        path = multihost.heartbeat_path(d, 0)
        assert _os.path.exists(path)
        m0 = _os.path.getmtime(path)
        deadline = _time.monotonic() + 5.0
        while _os.path.getmtime(path) <= m0:
            assert _time.monotonic() < deadline, "heartbeat never refreshed"
            _time.sleep(0.02)
    finally:
        w.stop()
    # staleness, evaluated with an injected clock (no sleeping): proc 0
    # beat "30s ago", proc 1 is fresh, proc 2 never joined
    now = _time.time()
    _os.utime(path, (now - 30.0, now - 30.0))
    fresh = multihost.HeartbeatWriter(d, 1, interval_s=1.0)
    _os.makedirs(d, exist_ok=True)
    fresh.beat()
    assert multihost.dead_peers(d, 3, deadline_s=5.0, now=now) == [0]
    assert multihost.dead_peers(d, 3, deadline_s=60.0, now=now) == []
    with pytest.raises(ValueError, match="interval_s"):
        multihost.HeartbeatWriter(d, 0, interval_s=0.0)


def test_fleet_any_single_process_is_the_local_flag():
    from photon_ml_tpu.parallel import multihost

    mesh = global_mesh({"entity": 8})
    assert multihost.fleet_any(True, mesh) is True
    assert multihost.fleet_any(False, mesh) is False
    assert multihost.fleet_any(True, None) is True
