"""Multi-host wiring tests (parallel/multihost.py).

The real multi-PROCESS parity run (2 processes x 4 virtual CPU devices,
jax.distributed + gloo collectives) lives in
``__graft_entry__._dryrun_multiprocess`` and is exercised here under the
``slow`` marker; the fast tests cover the pure-host helpers and the
single-process degenerate paths, which share all the code but the RPC.
"""

import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from photon_ml_tpu.parallel.multihost import (
    DistributedConfig,
    gather_to_host,
    global_mesh,
    host_local_array,
    process_slice,
    replicate_to_all,
)


def test_distributed_config_validation():
    DistributedConfig().validate()  # all-None is fine (single host / pod)
    with pytest.raises(ValueError, match="num_processes"):
        DistributedConfig(coordinator_address="h:1").validate()
    with pytest.raises(ValueError, match="out of range"):
        DistributedConfig(
            coordinator_address="h:1", num_processes=2, process_id=5
        ).validate()


def test_distributed_config_from_env(monkeypatch):
    monkeypatch.setenv("PHOTON_ML_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("PHOTON_ML_NUM_PROCESSES", "4")
    monkeypatch.setenv("PHOTON_ML_PROCESS_ID", "2")
    cfg = DistributedConfig.from_env()
    assert cfg.coordinator_address == "10.0.0.1:8476"
    assert cfg.num_processes == 4 and cfg.process_id == 2
    cfg.validate()


def test_process_slice_single_process_owns_everything():
    mesh = global_mesh({"entity": 8})
    assert process_slice(64, mesh, "entity") == (0, 64)
    with pytest.raises(ValueError, match="divide"):
        process_slice(63, mesh, "entity")


def test_host_local_array_and_gather_roundtrip():
    mesh = global_mesh({"data": 8})
    local = np.arange(32, dtype=np.float32).reshape(8, 4)
    arr = host_local_array(local, mesh, P("data"))
    assert arr.shape == (8, 4)
    np.testing.assert_array_equal(gather_to_host(arr), local)
    rep = replicate_to_all(np.float32(3.0), mesh)
    assert float(rep) == 3.0


def test_local_chunk_single_process_matches_dense():
    from photon_ml_tpu.game.streaming import (
        LocalChunk,
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )

    cfg = OptimizerConfig(
        optimizer_type=OptimizerType.LBFGS,
        max_iterations=10,
        tolerance=1e-9,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    rng = np.random.default_rng(0)
    n_ent, rows, k = 16, 5, 3
    batch = DenseBatch(
        x=rng.normal(size=(n_ent, rows, k)).astype(np.float32),
        labels=(rng.random((n_ent, rows)) > 0.5).astype(np.float32),
        offsets=np.zeros((n_ent, rows), np.float32),
        weights=np.ones((n_ent, rows), np.float32),
    )
    mesh = global_mesh({"entity": 8})

    def train(source):
        table = ShardedCoefficientTable(n_ent, k, mesh=mesh)
        StreamingRandomEffectTrainer("logistic", cfg, mesh=mesh).train(
            table, [(0, source)]
        )
        return table.to_numpy()

    w_plain = train(batch)
    w_local = train(LocalChunk(batch, global_size=n_ent))
    np.testing.assert_allclose(w_local, w_plain, atol=1e-6)


def test_table_bounds_checked():
    from photon_ml_tpu.game.streaming import ShardedCoefficientTable

    table = ShardedCoefficientTable(8, 3)
    with pytest.raises(ValueError, match="out of bounds"):
        table.read_chunk(4, 8)
    with pytest.raises(ValueError, match="out of bounds"):
        table.write_chunk(-1, np.zeros((2, 3), np.float32))
    with pytest.raises(ValueError, match="out of bounds"):
        table.write_chunk(7, np.zeros((2, 3), np.float32))
    # in-range write/read still fine
    table.write_chunk(6, np.ones((2, 3), np.float32))
    np.testing.assert_array_equal(
        np.asarray(table.read_chunk(6, 2)), np.ones((2, 3), np.float32)
    )


@pytest.mark.slow
def test_two_process_parity_dryrun():
    """2 OS processes x 2 devices each == one 4-device fleet; parity with
    the single-process 4-device run (full streaming + DP FE solve)."""
    import __graft_entry__ as ge

    ge._dryrun_multiprocess(4)
