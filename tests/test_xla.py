"""ISSUE 5 (hardware-level observability): the instrumented-jit executable
registry, recompile attribution, roofline peaks, collective estimates, the
run report's Device utilization section, heartbeat MFU fields, the bench
budget flush margin, and the `cli profile` capture path."""

import json
import logging
import os

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import xla
from photon_ml_tpu.telemetry.report import RunReport


FAKE_COST = {"flops": 1000.0, "bytes accessed": 640.0}
FAKE_MEM = {
    "temp_size_in_bytes": 128,
    "argument_size_in_bytes": 256,
    "output_size_in_bytes": 8,
    "generated_code_size_in_bytes": 4096,
}


@pytest.fixture
def fake_analysis():
    """Deterministic injected cost/memory analysis."""
    xla.set_analysis_provider(lambda compiled: (FAKE_COST, FAKE_MEM))
    yield
    xla.set_analysis_provider(None)


# -- registry round-trip ------------------------------------------------------


def test_registry_round_trip_with_injected_provider(fake_analysis):
    f = xla.instrumented_jit(lambda x: x * 2.0, name="double")
    x = np.ones((8,), np.float32)
    np.testing.assert_allclose(np.asarray(f(x)), 2.0)
    np.testing.assert_allclose(np.asarray(f(x)), 2.0)

    recs = xla.XLA_REGISTRY.executables("double")
    assert len(recs) == 1
    rec = recs[0]
    assert rec.calls == 2
    assert rec.flops == 1000.0
    assert rec.bytes_accessed == 640.0
    assert rec.temp_bytes == 128
    assert rec.argument_bytes == 256
    assert rec.output_bytes == 8
    assert rec.generated_code_bytes == 4096
    assert rec.compile_seconds >= 0
    assert rec.signature == ("f32[8]",)

    snap = telemetry.snapshot()["counters"]
    assert snap["xla.compiles"] == 1
    assert snap["xla.calls"] == 2
    assert snap["xla.flops_total"] == 2000.0
    assert snap["xla.bytes_total"] == 1280.0
    assert snap["xla.exec.double.calls"] == 2
    assert "xla.recompiles" not in snap

    # the registry snapshot is JSON-safe and ranked
    json.dumps(xla.XLA_REGISTRY.snapshot())


def test_unknown_degradation_when_analysis_unavailable():
    # a backend without cost/memory analysis: fields are None ("unknown"),
    # never zero, and nothing crashes
    xla.set_analysis_provider(lambda compiled: (None, None))
    f = xla.instrumented_jit(lambda x: x + 1.0, name="nocost")
    f(np.zeros((4,), np.float32))
    rec = xla.XLA_REGISTRY.executables("nocost")[0]
    assert rec.flops is None and rec.bytes_accessed is None
    assert rec.temp_bytes is None
    snap = telemetry.snapshot()["counters"]
    assert snap["xla.compiles"] == 1
    assert "xla.flops_total" not in snap  # unknown is not zero

    # a provider that RAISES degrades the same way
    def broken(compiled):
        raise RuntimeError("no analysis on this backend")

    xla.set_analysis_provider(broken)
    g = xla.instrumented_jit(lambda x: x - 1.0, name="nocost2")
    g(np.zeros((4,), np.float32))
    assert xla.XLA_REGISTRY.executables("nocost2")[0].flops is None


def test_real_cost_analysis_on_default_backend():
    # the CPU backend DOES publish cost analysis in this environment; the
    # real path must produce positive flops for a matmul
    f = xla.instrumented_jit(lambda a, b: a @ b, name="mm")
    f(np.ones((16, 8), np.float32), np.ones((8, 4), np.float32))
    rec = xla.XLA_REGISTRY.executables("mm")[0]
    assert rec.flops is None or rec.flops > 0  # None only if backend lacks it
    if rec.flops is not None:
        assert telemetry.snapshot()["counters"]["xla.flops_total"] > 0


# -- recompile attribution ----------------------------------------------------


def test_recompile_attributed_to_signature_delta(fake_analysis, caplog):
    f = xla.instrumented_jit(lambda x: x.sum(), name="sum_it")
    with telemetry.span("host"):
        f(np.zeros((4,), np.float32))
        f(np.zeros((4,), np.float32))  # same signature: no recompile
        f(np.zeros((9,), np.float32))  # shape change: recompile #1
    snap = telemetry.snapshot()["counters"]
    assert snap["xla.compiles"] == 2
    assert snap["xla.recompiles"] == 1
    assert snap["xla.exec.sum_it.recompiles"] == 1
    history = xla.XLA_REGISTRY.signature_history("sum_it")
    assert history == [("f32[4]",), ("f32[9]",)]
    # the span carries the recompile event with the exact delta
    span = telemetry.finished_spans("host")[0]
    ev = [e for e in span.events if e["name"] == "recompile"]
    assert len(ev) == 1
    assert "f32[4] -> f32[9]" in ev[0]["attrs"]["delta"]

    # a third distinct signature crosses RECOMPILE_WARN_THRESHOLD: one
    # structured warning naming the executable and the delta
    with caplog.at_level(
        logging.WARNING, logger="photon_ml_tpu.telemetry.xla"
    ):
        f(np.zeros((17,), np.float32))
    msgs = [r.message for r in caplog.records]
    assert any("recompile storm" in m and "sum_it" in m for m in msgs)
    assert any("f32[9] -> f32[17]" in m for m in msgs)
    # dtype changes attribute too
    f(np.zeros((17,), np.int32))
    history = xla.XLA_REGISTRY.signature_history("sum_it")
    assert history[-1] == ("i32[17]",)


def test_multi_shape_executables_are_not_recompile_storms(
    fake_analysis, caplog
):
    # the serving engine's batch buckets / per-bucket RE solvers compile a
    # signature SET by design: registered + accounted, never a storm
    f = xla.instrumented_jit(
        lambda x: x.sum(), name="bucketed", multi_shape=True
    )
    with caplog.at_level(
        logging.WARNING, logger="photon_ml_tpu.telemetry.xla"
    ):
        for n in (1, 2, 4, 8):
            f(np.zeros((n,), np.float32))
    snap = telemetry.snapshot()["counters"]
    assert snap["xla.exec.bucketed.compiles"] == 4
    assert "xla.recompiles" not in snap
    assert not any("recompile storm" in r.message for r in caplog.records)
    # every bucket's executable is still in the registry with its cost
    assert len(xla.XLA_REGISTRY.executables("bucketed")) == 4


def test_engine_warmup_counts_no_recompiles(fake_analysis):
    jnp = pytest.importorskip("jax.numpy")

    from photon_ml_tpu.game.models import FixedEffectModel, GameModel
    from photon_ml_tpu.serving.engine import ScoringEngine

    model = GameModel(
        task="logistic",
        models={
            "fixed": FixedEffectModel(
                coefficients=jnp.asarray([0.1, 0.2]), shard_name="global"
            )
        },
    )
    ScoringEngine(model, max_batch=8, version="v-w").warmup()
    # four buckets compiled, zero flagged as recompiles (the gate metric
    # must not fail a healthy warmup)
    counters = telemetry.snapshot()["counters"]
    assert "xla.recompiles" not in counters


def test_python_scalars_do_not_fragment_signatures(fake_analysis):
    # traced python scalars are typed, not valued, in the signature —
    # calling with different VALUES must not look like a recompile
    f = xla.instrumented_jit(lambda x, s: x * s, name="scale")
    f(np.ones((3,), np.float32), 2.0)
    f(np.ones((3,), np.float32), 7.0)
    assert telemetry.snapshot()["counters"]["xla.compiles"] == 1


def test_aot_failure_falls_back_to_plain_jit(fake_analysis):
    f = xla.instrumented_jit(lambda x: x * 3.0, name="fb")
    real_jit = f._jit

    class _LowerBoom:
        def lower(self, *a, **k):
            raise RuntimeError("AOT unsupported here")

        def __call__(self, *a, **k):
            return real_jit(*a, **k)

    f._jit = _LowerBoom()
    out = f(np.ones((2,), np.float32))
    np.testing.assert_allclose(np.asarray(out), 3.0)
    snap = telemetry.snapshot()["counters"]
    assert snap["xla.fallback_calls"] == 1
    assert snap["xla.compiles"] == 1  # still registered (cost unknown)
    assert xla.XLA_REGISTRY.executables("fb")[0].flops is None


# -- peaks / collectives ------------------------------------------------------


def test_device_peaks_injection_and_env(monkeypatch):
    assert xla.device_peaks() == (None, None)  # CPU: unknown
    monkeypatch.setenv("PHOTON_PEAK_FLOPS", "2e12")
    monkeypatch.setenv("PHOTON_PEAK_HBM_GBPS", "100")
    flops, bw = xla.device_peaks()
    assert flops == 2e12 and bw == 100e9
    g = telemetry.snapshot()["gauges"]
    assert g["device.peak_flops"] == 2e12
    assert g["device.peak_hbm_bytes_per_sec"] == 100e9
    # an explicit injection wins over env
    xla.set_peaks(1e12, 5e10)
    assert xla.device_peaks() == (1e12, 5e10)
    # malformed env overrides degrade to unknown, never crash
    xla.reset()
    monkeypatch.setenv("PHOTON_PEAK_FLOPS", "not-a-number")
    monkeypatch.setenv("PHOTON_PEAK_HBM_GBPS", "819GB")
    assert xla.device_peaks() == (None, None)


def test_collective_bytes_math():
    assert xla.collective_bytes("psum", 1, 1000) == 0  # elided
    assert xla.collective_bytes("psum", 4, 1000) == 1500  # 2*(3/4)
    assert xla.collective_bytes("all_gather", 4, 1000) == 750
    with pytest.raises(ValueError):
        xla.collective_bytes("all_to_all", 4, 1000)


def test_record_collective_gauges_and_span(fake_analysis):
    with telemetry.span("solve"):
        n = xla.record_collective("fe", "psum", 8, 4000, count=10)
    assert n == xla.collective_bytes("psum", 8, 4000) * 10
    snap = telemetry.snapshot()
    assert snap["counters"]["comms.bytes_total"] == n
    assert snap["counters"]["comms.fe.bytes"] == n
    # the per-call gauge is ONE collective's bytes, not the count total
    assert snap["gauges"]["comms.fe.bytes_per_call"] == xla.collective_bytes(
        "psum", 8, 4000
    )
    assert telemetry.finished_spans("solve")[0].attrs["comms_bytes"] == n
    # single-device: nothing recorded (no fake zeros)
    assert xla.record_collective("fe1", "psum", 1, 4000) == 0
    assert "comms.fe1.bytes" not in telemetry.snapshot()["counters"]


def test_distributed_solve_records_comms_estimate(rng):
    # the mesh-sharded FE solve publishes a comms.* estimate derived from
    # the mesh axis size and gradient payload
    import jax.numpy as jnp

    from photon_ml_tpu.ops.sparse import SparseBatch
    from photon_ml_tpu.optim.factory import OptimizerConfig
    from photon_ml_tpu.parallel.distributed import distributed_solve
    from photon_ml_tpu.parallel.mesh import make_mesh, shard_rows

    pytest.importorskip("jax")
    n, d = 64, 5
    vals = rng.normal(size=n * 3)
    rows = np.repeat(np.arange(n), 3)
    cols = rng.integers(0, d, n * 3)
    y = (rng.random(n) > 0.5).astype(float)
    batch = SparseBatch.from_coo(
        values=vals, rows=rows, cols=cols, labels=y, num_features=d
    )
    mesh = make_mesh()
    stacked = shard_rows(batch, int(mesh.devices.size))
    cfg = OptimizerConfig(max_iterations=3)
    try:
        distributed_solve(
            "logistic", stacked, cfg, jnp.zeros((d,), jnp.float32), mesh
        )
    except AttributeError:
        pass  # jax.shard_map missing on this jax (pre-existing seed skip)
    counters = telemetry.snapshot()["counters"]
    expected = xla.collective_bytes(
        "psum", int(mesh.devices.size), d * 4 + 4
    ) * 3
    assert counters["comms.distributed_solve.bytes"] == expected


# -- heartbeat fields ---------------------------------------------------------


def test_heartbeat_gains_mfu_and_comms_fraction(fake_analysis):
    from photon_ml_tpu.telemetry.progress import Heartbeat

    xla.set_peaks(1e9, None)
    hb = Heartbeat(interval=60.0)
    line = hb.beat()
    assert "mfu" not in line and "comms_fraction" not in line  # no work yet
    # probing must not REGISTER the counters: a zero in the snapshot
    # would read as "0 FLOPs" downstream instead of "unknown"
    assert "xla.flops_total" not in telemetry.snapshot()["counters"]
    assert "comms.bytes_total" not in telemetry.snapshot()["counters"]
    f = xla.instrumented_jit(lambda x: x + 1, name="hb_work")
    f(np.zeros((4,), np.float32))
    xla.record_collective("hb", "psum", 4, 1000)
    line = hb.beat()
    assert line["mfu"] > 0
    comms = xla.collective_bytes("psum", 4, 1000)
    assert line["comms_fraction"] == pytest.approx(
        comms / (comms + FAKE_COST["bytes accessed"])
    )
    # peaks unknown: the mfu field is OMITTED, not zero
    xla.reset()
    xla.set_analysis_provider(lambda compiled: (FAKE_COST, FAKE_MEM))
    g = xla.instrumented_jit(lambda x: x + 2, name="hb_work2")
    g(np.zeros((4,), np.float32))
    line = hb.beat()
    assert "mfu" not in line


# -- run report: Device utilization -------------------------------------------


def test_device_utilization_none_without_accounting():
    report = RunReport.from_live()
    assert report.device_utilization() is None
    assert "Device utilization" not in report.to_markdown()


def test_device_utilization_unknown_rendering(fake_analysis):
    # cost known but peaks unknown: MFU/BW render the explicit string
    # "unknown", phases still carry FLOPs
    f = xla.instrumented_jit(lambda x: x * 2, name="phase_work")
    with telemetry.span("fit"):
        f(np.ones((4,), np.float32))
    report = RunReport.from_live()
    du = report.device_utilization()
    assert du["mfu"] is None and du["flops_total"] == FAKE_COST["flops"]
    assert du["phases"][0]["phase"] == "fit"
    assert du["phases"][0]["flops"] == FAKE_COST["flops"]
    md = report.to_markdown()
    assert "## Device utilization" in md
    assert "- MFU: unknown" in md
    assert "device peak FLOP/s unknown" in md


def test_comms_fraction_unknown_without_hbm_bytes():
    # comms recorded but NO cost analysis (bytes unknown): the fraction
    # denominator is unknowable — "unknown", never a fabricated 100%
    xla.set_analysis_provider(lambda compiled: (None, None))
    f = xla.instrumented_jit(lambda x: x + 1, name="nk")
    with telemetry.span("fit"):
        f(np.zeros((2,), np.float32))
        xla.record_collective("s", "psum", 4, 1000)
    du = RunReport.from_live().device_utilization()
    assert du["comms_bytes_total"] > 0
    assert du["comms_fraction"] is None
    md = RunReport.from_live().to_markdown()
    assert "comms fraction unknown" in md


def test_device_utilization_full(fake_analysis):
    xla.set_peaks(1e12, 1e11)
    f = xla.instrumented_jit(lambda x: x * 2, name="work")
    with telemetry.span("fit"):
        with telemetry.span("coordinate:fixed"):
            f(np.ones((4,), np.float32))
            xla.record_collective("solve", "psum", 8, 4000)
    report = RunReport.from_live()
    du = report.device_utilization()
    assert du["mfu"] > 0 and du["bandwidth_utilization"] > 0
    assert du["comms_bytes_total"] == xla.collective_bytes("psum", 8, 4000)
    assert 0 < du["comms_fraction"] < 1
    assert du["compile_time_share"] is not None
    # the child phase rolls up into the parent's subtree numbers
    phases = {p["phase"]: p for p in du["phases"]}
    assert phases["fit"]["flops"] == FAKE_COST["flops"]
    assert phases["fit > coordinate:fixed"]["flops"] == FAKE_COST["flops"]
    top = du["top_executables"]
    assert top and top[0]["name"] == "work"
    md = report.to_markdown(deltas=None)
    assert "## Device utilization" in md
    assert "Top executables by cost" in md and "`work`" in md
    # key metrics expose mfu for the CI gate
    assert report.key_metrics()["mfu"] == pytest.approx(du["mfu"])
    # and the JSON document carries the whole structure
    doc = report.to_json()
    assert doc["device_utilization"]["mfu"] == pytest.approx(du["mfu"])


# -- serving per-bucket compile state -----------------------------------------


def test_engine_compile_summary_per_bucket(fake_analysis):
    jnp = pytest.importorskip("jax.numpy")

    from photon_ml_tpu.game.models import FixedEffectModel, GameModel
    from photon_ml_tpu.serving.engine import ScoringEngine

    model = GameModel(
        task="logistic",
        models={
            "fixed": FixedEffectModel(
                coefficients=jnp.asarray([0.5, -0.25, 0.1]),
                shard_name="global",
            )
        },
    )
    engine = ScoringEngine(model, max_batch=4, version="v-1").warmup()
    summary = engine.compile_summary()
    assert set(summary) == {"1", "2", "4"}
    for entry in summary.values():
        assert entry["compile_seconds"] >= 0
        assert entry["flops"] == FAKE_COST["flops"]
        assert entry["calls"] >= 1


# -- e2e acceptance: fit -> report with finite MFU -----------------------------


def test_e2e_fit_report_device_utilization(tmp_path):
    """ISSUE 5 acceptance: a default-backend fit + `cli report` run whose
    Device utilization section reports per-phase FLOPs, MFU, bandwidth
    utilization, compile-time share, and collective-bytes state (explicit
    "unknown" where the backend/peaks offer nothing)."""
    from photon_ml_tpu.cli.report import main as report_main
    from photon_ml_tpu.game.estimator import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
    )
    from photon_ml_tpu.optim.factory import OptimizerConfig
    from photon_ml_tpu.testing import generate_game_dataset

    # pin peaks so MFU is finite on the CPU test backend
    xla.set_peaks(1e12, 1e11)
    data, _ = generate_game_dataset(
        task="logistic", n_users=4, rows_per_user=8, fe_dim=4, re_dim=2
    )
    trace_out = tmp_path / "run.trace.jsonl"
    tele_out = tmp_path / "run.metrics.jsonl"
    telemetry.configure(trace_out=str(trace_out))
    estimator = GameEstimator(GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(
                shard_name="global",
                optimizer=OptimizerConfig(max_iterations=3),
            ),
        },
        num_iterations=1,
    ))
    estimator.fit(data)
    telemetry.flush_metrics(str(tele_out))

    live = RunReport.from_live()
    du = live.device_utilization()
    assert du is not None
    # the CPU backend publishes cost analysis here: finite MFU
    assert du["flops_total"] > 0
    assert np.isfinite(du["mfu"]) and du["mfu"] > 0
    assert np.isfinite(du["bandwidth_utilization"])
    assert du["compile_time_share"] is not None
    assert any("coordinate:fixed" in p["phase"] for p in du["phases"])

    md_path = tmp_path / "report.md"
    rc = report_main([
        "--trace", str(trace_out),
        "--telemetry", str(tele_out),
        "--out", str(md_path),
    ])
    assert rc == 0
    md = md_path.read_text()
    assert "## Device utilization" in md
    assert "- MFU: " in md and "- MFU: unknown" not in md
    assert "Top executables by cost" in md
    assert "`fe_solve`" in md


# -- cli profile --------------------------------------------------------------


def test_cli_profile_wraps_a_train_run(tmp_path):
    """`cli profile -- train ...` produces a profiler capture dir next to
    the span trace, mirrors spans as annotations, and returns the wrapped
    command's exit code."""
    from photon_ml_tpu.cli.__main__ import main as cli_main
    from photon_ml_tpu.telemetry import trace as trace_mod

    rng = np.random.default_rng(7)
    lib = tmp_path / "train.libsvm"
    lines = []
    for i in range(64):
        x = rng.normal(size=3)
        label = 1 if x.sum() + 0.1 * rng.normal() > 0 else 0
        feats = " ".join(f"{j + 1}:{x[j]:.4f}" for j in range(3))
        lines.append(f"{label} {feats}")
    lib.write_text("\n".join(lines) + "\n")
    config = {
        "task": "logistic",
        "input": {
            "format": "libsvm", "paths": [str(lib)],
            "shard_name": "features",
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect", "shard_name": "features",
                "optimizer": {"max_iterations": 3},
            }
        },
        "num_iterations": 1,
        "heartbeat": False,
    }
    cfg_path = tmp_path / "t.json"
    cfg_path.write_text(json.dumps(config))
    prof_dir = tmp_path / "prof"
    trace_out = tmp_path / "run.trace.jsonl"
    rc = cli_main([
        "profile", "--profile-dir", str(prof_dir), "--",
        "train", "--config", str(cfg_path), "--trace-out", str(trace_out),
    ])
    assert rc == 0
    # capture dir exists alongside the span trace
    assert prof_dir.is_dir()
    captured = [
        os.path.join(r, f)
        for r, _d, files in os.walk(prof_dir)
        for f in files
    ]
    assert captured, "profiler capture dir is empty"
    assert trace_out.exists()
    # the annotation mirror was torn down after the run
    assert trace_mod.TRACER._annotation_factory is None


def test_cli_profile_requires_wrapped_command(tmp_path):
    from photon_ml_tpu.cli.profile import main as profile_main

    with pytest.raises(SystemExit):
        profile_main(["--profile-dir", str(tmp_path / "p")])


# -- bench budget margin ------------------------------------------------------


def test_budget_deadline_reserves_flush_margin(monkeypatch):
    import time

    import bench_suite

    monkeypatch.setenv("PHOTON_BENCH_BUDGET_S", "100")
    now = time.monotonic()
    deadline = bench_suite.budget_deadline(now=now)
    # the flush-by deadline sits one margin BEFORE the budget wall, so
    # truncated lines + the run report flush before the outer timeout -k
    assert deadline == pytest.approx(
        now + 100 - bench_suite.DEFAULT_BUDGET_MARGIN_S
    )
    monkeypatch.setenv("PHOTON_BENCH_MARGIN_S", "10")
    assert bench_suite.budget_deadline(now=now) == pytest.approx(now + 90)
    # a budget at or below the margin keeps HALF the budget for work
    # (never a negative window, never an all-skipped run)
    monkeypatch.setenv("PHOTON_BENCH_MARGIN_S", "30")
    monkeypatch.setenv("PHOTON_BENCH_BUDGET_S", "5")
    assert bench_suite.budget_deadline(now=now) == pytest.approx(now + 2.5)
    # malformed env values degrade instead of killing the bench at start
    monkeypatch.setenv("PHOTON_BENCH_BUDGET_S", "100")
    monkeypatch.setenv("PHOTON_BENCH_MARGIN_S", "")
    assert bench_suite.budget_margin() == bench_suite.DEFAULT_BUDGET_MARGIN_S
    monkeypatch.setenv("PHOTON_BENCH_MARGIN_S", "30s")
    assert bench_suite.budget_margin() == bench_suite.DEFAULT_BUDGET_MARGIN_S
    monkeypatch.setenv("PHOTON_BENCH_BUDGET_S", "15 minutes")
    assert bench_suite.budget_deadline(now=now) is None


def test_bench_headline_truncates_when_budget_spent(capsys):
    import time

    import bench

    # deadline in the past: the headline never launches a subprocess but
    # still emits one valid truncated line per expected metric
    bench.run_headline(deadline=time.monotonic() - 1.0)
    lines = [
        json.loads(x)
        for x in capsys.readouterr().out.splitlines()
        if x.startswith("{")
    ]
    assert [x["metric"] for x in lines] == list(bench.HEADLINE_METRICS)
    assert all(x["truncated"] is True for x in lines)
