"""Data-plane tests: stats vs numpy, normalization contexts + model
back-transform, index maps (incl. mmap store), libsvm reader, validators."""


import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import (
    DataValidationError,
    IndexMap,
    MmapIndexMap,
    NormalizationType,
    ValidationMode,
    build_normalization_context,
    feature_key,
    read_libsvm,
    summarize,
    validate,
)
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import lbfgs_solve, glm_adapter


def test_summary_matches_numpy(rng):
    n, d = 80, 10
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.6)
    batch = SparseBatch.from_dense(X, np.zeros(n))
    s = summarize(batch)
    np.testing.assert_allclose(s.mean, X.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(s.variance, X.var(0, ddof=1), rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(s.max, X.max(0), rtol=1e-5)
    np.testing.assert_allclose(s.min, X.min(0), rtol=1e-5)
    np.testing.assert_allclose(s.num_nonzeros, (X != 0).sum(0), rtol=1e-6)
    np.testing.assert_allclose(s.norm_l1, np.abs(X).sum(0), rtol=1e-4)
    np.testing.assert_allclose(s.norm_l2, np.sqrt((X**2).sum(0)), rtol=1e-4)
    assert int(s.count) == n


def test_summary_ignores_padded_rows(rng):
    X = rng.normal(size=(30, 5))
    batch = SparseBatch.from_dense(X, np.zeros(30)).pad_rows_to(40, 200)
    s = summarize(batch)
    np.testing.assert_allclose(s.mean, X.mean(0), rtol=1e-4, atol=1e-5)
    assert int(s.count) == 30


def test_standardization_context_and_back_transform(rng):
    # train on standardized data, map coefficients back, scores must match
    n, d = 120, 8
    X = rng.normal(size=(n, d)) * 3 + 1.5
    X[:, -1] = 1.0  # intercept column
    y = (rng.random(n) < 0.5).astype(float)
    batch = SparseBatch.from_dense(X, y)
    s = summarize(batch)
    ctx = build_normalization_context(
        NormalizationType.STANDARDIZATION, s, intercept_index=d - 1
    )
    np.testing.assert_allclose(ctx.factors[-1], 1.0)
    np.testing.assert_allclose(ctx.shifts[-1], 0.0)

    obj_norm = make_objective(
        "logistic", l2_weight=0.1, factors=ctx.factors, shifts=ctx.shifts
    )
    res = lbfgs_solve(glm_adapter(obj_norm, batch), jnp.zeros(d, jnp.float32))
    w_orig = ctx.transform_model_coefficients(res.w)

    # margins with original-space coefficients on raw X == normalized-space
    # margins with trained coefficients
    z_norm = obj_norm.margins(res.w, batch)
    z_orig = batch.margins(w_orig, 0.0)
    np.testing.assert_allclose(z_orig, z_norm, rtol=1e-3, atol=1e-3)


def test_normalization_same_optimum_as_unnormalized(rng):
    # NormalizationTest.scala analog: optimizing with standardization then
    # back-transforming reaches the same solution as optimizing raw
    n, d = 150, 6
    X = np.hstack([rng.normal(size=(n, d - 1)) * np.asarray([1, 5, 0.2, 3, 0.7]),
                   np.ones((n, 1))])
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ rng.normal(size=d))))).astype(float)
    batch = SparseBatch.from_dense(X, y)
    raw = lbfgs_solve(
        glm_adapter(make_objective("logistic"), batch), jnp.zeros(d, jnp.float32)
    )
    ctx = build_normalization_context(
        NormalizationType.STANDARDIZATION, summarize(batch), intercept_index=d - 1
    )
    res = lbfgs_solve(
        glm_adapter(
            make_objective("logistic", factors=ctx.factors, shifts=ctx.shifts), batch
        ),
        jnp.zeros(d, jnp.float32),
    )
    w_back = ctx.transform_model_coefficients(res.w)
    np.testing.assert_allclose(w_back, raw.w, rtol=2e-2, atol=2e-2)


def test_scale_variants(rng):
    X = rng.normal(size=(50, 4)) * np.asarray([1.0, 10.0, 0.1, 5.0])
    batch = SparseBatch.from_dense(X, np.zeros(50))
    s = summarize(batch)
    c1 = build_normalization_context(NormalizationType.SCALE_WITH_MAX_MAGNITUDE, s)
    np.testing.assert_allclose(
        c1.factors, 1.0 / np.maximum(np.abs(X.max(0)), np.abs(X.min(0))), rtol=1e-4
    )
    c2 = build_normalization_context(
        NormalizationType.SCALE_WITH_STANDARD_DEVIATION, s
    )
    np.testing.assert_allclose(c2.factors, 1.0 / X.std(0, ddof=1), rtol=1e-3)
    with pytest.raises(ValueError, match="intercept"):
        build_normalization_context(NormalizationType.STANDARDIZATION, s)


def test_index_map_roundtrip(tmp_path):
    keys = [feature_key("age", ""), feature_key("country", "us"),
            feature_key("country", "de"), "plainfeature"]
    im = IndexMap.build(keys * 3, add_intercept=True)
    assert len(im) == 5
    # deterministic: sorted order
    assert im.names == sorted(im.names)
    d = str(tmp_path / "idx")
    im.save(d)
    im2 = IndexMap.load(d)
    assert im2.names == im.names
    mm = MmapIndexMap(d)
    assert len(mm) == len(im)
    for k in im:
        assert mm.get(k) == im[k]
        assert mm.name_of(im[k]) == k
    assert mm.get("missing-key") == -1
    got = mm.get_many(list(im.names) + ["nope"])
    np.testing.assert_array_equal(got[:-1], np.arange(len(im)))
    assert got[-1] == -1


def test_libsvm_reader(tmp_path):
    p = tmp_path / "small.libsvm"
    p.write_text("+1 1:0.5 3:2.0\n-1 2:1.0\n+1 1:1.5\n")
    data = read_libsvm(str(p))
    assert data.num_features == 3
    np.testing.assert_array_equal(data.labels, [1.0, 0.0, 1.0])
    batch = data.to_batch(add_intercept=True)
    assert batch.num_features == 4
    dense = batch.to_dense()[:3]
    np.testing.assert_allclose(
        dense,
        [[0.5, 0, 2.0, 1.0], [0, 1.0, 0, 1.0], [1.5, 0, 0, 1.0]],
    )


def test_validators(rng):
    X = rng.normal(size=(20, 4))
    ok = SparseBatch.from_dense(X, (rng.random(20) > 0.5).astype(float))
    validate(ok, "logistic_regression")

    bad_label = SparseBatch.from_dense(X, rng.normal(size=20) * 5)
    with pytest.raises(DataValidationError, match="binary"):
        validate(bad_label, "logistic_regression")
    validate(bad_label, "linear_regression")

    with pytest.raises(DataValidationError, match="non-negative"):
        validate(SparseBatch.from_dense(X, -np.ones(20)), "poisson_regression")

    nan_feat = X.copy()
    nan_feat[3, 2] = np.nan
    with pytest.raises(DataValidationError, match="feature"):
        validate(SparseBatch.from_dense(nan_feat, np.ones(20)), "linear_regression")

    # disabled mode swallows everything
    validate(bad_label, "logistic_regression", mode=ValidationMode.DISABLED)


def test_validators_collect_all_reports_every_failure(rng):
    """collect_all=True aggregates EVERY failed check into one error — the
    full damage report from one pass, not just the first failure."""
    X = rng.normal(size=(20, 4))
    X[3, 2] = np.nan  # non-finite features
    y = rng.normal(size=20) * 5  # non-binary labels for a logistic task
    weights = np.ones(20)
    weights[5] = -1.0  # negative weight
    batch = SparseBatch.from_dense(X, y, weights=weights)

    # fail-fast mode still stops at the first check
    with pytest.raises(DataValidationError, match="feature"):
        validate(batch, "logistic_regression")

    with pytest.raises(DataValidationError) as ei:
        validate(batch, "logistic_regression", collect_all=True)
    msg = str(ei.value)
    assert "3 validation check(s) failed" in msg
    assert "non-finite feature values" in msg
    assert "negative weights" in msg
    assert "binary task" in msg


def test_summary_maxmin_unaffected_by_nnz_padding():
    """Regression (ADVICE r1-a): when n == n_pad, padding nnz entries alias
    the real last row; their value-0 must not leak into feature 0's max/min."""
    vals = np.array([-2.0, -3.0, -1.0])
    rows = np.array([0, 1, 2])
    cols = np.array([0, 0, 0])
    b = SparseBatch.from_coo(
        vals, rows, cols, np.zeros(3), num_features=2, nnz_pad_multiple=16
    )
    s = summarize(b)
    assert float(s.max[0]) == -1.0
    assert float(s.min[0]) == -3.0
    # feature 1 is all implicit zeros
    assert float(s.max[1]) == 0.0 and float(s.min[1]) == 0.0


def test_from_coo_rejects_out_of_range_indices():
    """Regression (ADVICE r1-b): out-of-range col/row indices must raise,
    not be silently dropped by clamped gathers."""
    with pytest.raises(ValueError):
        SparseBatch.from_coo(
            np.ones(2), np.array([0, 1]), np.array([0, 5]),
            np.zeros(2), num_features=3,
        )
    with pytest.raises(ValueError):
        SparseBatch.from_coo(
            np.ones(2), np.array([0, 7]), np.array([0, 1]),
            np.zeros(2), num_features=3,
        )


def test_index_map_save_detects_hash_collision(tmp_path, monkeypatch):
    """Regression (ADVICE r1-c): a 64-bit hash collision between two keys
    must fail save() loudly — the mmap store resolves by hash alone."""
    from photon_ml_tpu.data import index_map as im_mod

    m = IndexMap(["featA", "featB"])
    monkeypatch.setattr(im_mod, "_hash64", lambda key: 42)
    with pytest.raises(ValueError, match="collision"):
        m.save(str(tmp_path / "idx"))


def test_testing_generators_smoke(rng):
    """Shared generator module (GameTestUtils analog): shapes, ground-truth
    recoverability, and task coverage."""
    from photon_ml_tpu.testing import (
        generate_game_dataset,
        generate_glm_problem,
        generate_low_rank_game_dataset,
    )
    from photon_ml_tpu.optim import OptimizerConfig, solve

    import jax.numpy as jnp

    for task in ("logistic", "squared", "poisson"):
        p = generate_glm_problem(task, n=300, d=8, seed=3)
        assert p.batch.num_features == 8
        res = solve(task, p.batch, OptimizerConfig(),
                    jnp.zeros(8, jnp.float32))
        corr = np.corrcoef(np.asarray(res.w), p.w_true)[0, 1]
        assert corr > 0.8, f"{task}: corr {corr}"

    data, truth = generate_game_dataset("squared", n_users=6, rows_per_user=10)
    assert data.num_rows == 60
    assert set(data.feature_shards) == {"global", "user"}
    assert data.id_columns["userId"].num_entities == 6

    data2, truth2 = generate_low_rank_game_dataset(n_users=8, rows_per_user=5)
    assert truth2["W"].shape == (8, 30)
    assert np.linalg.matrix_rank(truth2["W"]) == 2


def test_native_libsvm_parser_matches_python(rng, tmp_path):
    """The C++ parser (built on demand) must agree exactly with the python
    parser, including comments, blank lines, and {-1,1} label mapping."""
    import pytest as _pytest

    from photon_ml_tpu.data.libsvm import read_libsvm
    from photon_ml_tpu.data.native import load_native

    if load_native() is None:
        _pytest.skip("no native toolchain")

    lines = ["# header comment", ""]
    n, d = 200, 30
    X = (rng.random((n, d)) < 0.3) * rng.normal(size=(n, d))
    y = np.where(rng.random(n) < 0.5, -1, 1)
    for i in range(n):
        feats = " ".join(f"{j + 1}:{X[i, j]:.6f}" for j in np.nonzero(X[i])[0])
        suffix = " # trailing comment" if i % 7 == 0 else ""
        lines.append(f"{y[i]} {feats}{suffix}")
    p = tmp_path / "t.libsvm"
    p.write_text("\n".join(lines) + "\n")

    a = read_libsvm(str(p), engine="python")
    b = read_libsvm(str(p), engine="native")
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.rows, b.rows)
    np.testing.assert_array_equal(a.cols, b.cols)
    np.testing.assert_allclose(a.values, b.values, rtol=0, atol=0)
    assert a.num_features == b.num_features


def test_native_parser_rejects_malformed_input(tmp_path):
    """Malformed tokens must raise (never uninitialized-array garbage):
    the count/parse cross-check plus strict value-token validation."""
    import pytest as _pytest

    from photon_ml_tpu.data.libsvm import read_libsvm
    from photon_ml_tpu.data.native import load_native

    if load_native() is None:
        _pytest.skip("no native toolchain")

    bad_inputs = [
        "1 3: 5\n",  # space after colon: value token missing
        "1 3:\n-1 2:5\n",  # dangling colon would swallow the next label
        "1 3:abc\n",  # non-numeric value
        "x 3:1\n",  # non-numeric label
    ]
    for content in bad_inputs:
        p = tmp_path / "bad.libsvm"
        p.write_text(content)
        with _pytest.raises(ValueError):
            read_libsvm(str(p), engine="native")
        # the python engine rejects the same inputs
        with _pytest.raises(ValueError):
            read_libsvm(str(p), engine="python")


def test_native_parser_edge_semantics_match_python(tmp_path):
    """Divergence regressions: odd whitespace (\\v), labels-only files,
    attached '#', CR line endings — native and python must agree (both
    parse or both raise)."""
    import pytest as _pytest

    from photon_ml_tpu.data.libsvm import read_libsvm
    from photon_ml_tpu.data.native import load_native

    if load_native() is None:
        _pytest.skip("no native toolchain")

    def compare(content: str):
        p = tmp_path / "e.libsvm"
        p.write_text(content)
        try:
            a = read_libsvm(str(p), engine="python")
            py_err = None
        except ValueError as e:
            a, py_err = None, e
        try:
            b = read_libsvm(str(p), engine="native")
            nat_err = None
        except ValueError as e:
            b, nat_err = None, e
        assert (py_err is None) == (nat_err is None), (
            f"engines disagree on {content!r}: python={py_err} native={nat_err}"
        )
        if a is not None:
            np.testing.assert_array_equal(a.labels, b.labels)
            np.testing.assert_array_equal(a.cols, b.cols)
            np.testing.assert_allclose(a.values, b.values, atol=0)
            assert a.num_features == b.num_features

    compare("1 2:3\v4:5\n")       # \v separates tokens (no hang)
    compare("1\n0\n")             # labels-only file: num_features 0
    compare("")                   # empty file
    compare("# only a comment\n")
    compare("1 2:3#comment\n")    # attached '#': both must REJECT
    compare("1 2:3\r-1 4:5\r")    # CR-only line endings: two rows
    compare("+1 1:0.5 # ok\n")    # standalone trailing comment token
