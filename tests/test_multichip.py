"""Tier-1 multichip CI (ISSUE 6): sharded-vs-single-device parity on a
forced 8-device CPU mesh, in tests/ rather than only the MULTICHIP dryrun.

Acceptance pinned here:
  - the GSPMD FE solve (flat design committed P("batch"), one jit) and
    the entity-sharded GLMix CD/streaming loop reach the same final loss
    as the single-device run to 1e-6 (relative);
  - ``comms.*`` collective estimates are recorded for every multi-device
    solve;
  - repeated solves with refreshed per-row arrays do NOT grow the
    compiled-signature set (no recompile storms);
  - the game_10B capacity config computes its per-device table bytes and
    REFUSES to run unsharded with a clear headroom message;
  - ``bench_suite --gate`` skips (with a note) multichip metrics missing
    from an older baseline instead of erroring.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    solve,
)
from photon_ml_tpu.parallel import gspmd_solve, make_mesh, place_batch
from photon_ml_tpu.telemetry import metrics as telemetry_metrics
from photon_ml_tpu.telemetry import xla as telemetry_xla

_OPT = OptimizerConfig(
    optimizer_type=OptimizerType.LBFGS,
    max_iterations=80,
    tolerance=1e-10,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=0.7,
)


def _fe_problem(rng, n=480, d=24):
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.4)
    w_true = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w_true)))).astype(float)
    wt = rng.random(n) + 0.5
    return SparseBatch.from_dense(X, y, weights=wt)


@pytest.mark.multichip
def test_gspmd_fe_solve_single_device_parity(rng, multichip):
    batch = _fe_problem(rng)
    mesh = make_mesh({"batch": 8})
    placed = place_batch(batch, mesh)
    w0 = jnp.zeros(batch.num_features, jnp.float32)

    res_single = solve("logistic", batch, _OPT, w0)
    comms_before = telemetry_metrics.peek_counter("comms.bytes_total") or 0.0
    res_mesh = gspmd_solve("logistic", placed, _OPT, w0, mesh)

    v_s, v_m = float(res_single.value), float(res_mesh.value)
    # acceptance: same final loss to 1e-6 (relative)
    assert abs(v_m - v_s) <= 1e-6 * max(1.0, abs(v_s)), (v_m, v_s)
    np.testing.assert_allclose(res_mesh.w, res_single.w, rtol=5e-3, atol=5e-3)
    # the GSPMD outputs are pinned fully-replicated
    assert res_mesh.w.sharding.is_fully_replicated
    # comms recorded for the multi-device solve
    comms_after = telemetry_metrics.peek_counter("comms.bytes_total") or 0.0
    assert comms_after > comms_before
    assert (telemetry_metrics.peek_counter("comms.gspmd_solve.bytes") or 0) > 0


@pytest.mark.multichip
def test_gspmd_fe_solve_no_recompile_storm(rng, multichip):
    """Refreshed per-row arrays (the CD residual-update pattern) must hit
    the SAME compiled program — signature growth is the storm signal."""
    batch = _fe_problem(rng, n=320)
    mesh = make_mesh({"batch": 8})
    placed = place_batch(batch, mesh)
    w0 = jnp.zeros(batch.num_features, jnp.float32)
    gspmd_solve("logistic", placed, _OPT, w0, mesh)
    before = len(telemetry_xla.XLA_REGISTRY.signature_history("gspmd_solve"))
    import dataclasses

    from photon_ml_tpu.parallel.sharding import batch_sharding

    for salt in (1, 2, 3):
        offs = jax.device_put(
            jnp.full((placed.num_rows,), salt * 1e-3, jnp.float32),
            batch_sharding(mesh),
        )
        refreshed = dataclasses.replace(placed, offsets=offs)
        gspmd_solve("logistic", refreshed, _OPT, w0, mesh)
    after = len(telemetry_xla.XLA_REGISTRY.signature_history("gspmd_solve"))
    assert after == before, "per-update offsets changed the trace signature"


@pytest.mark.multichip
def test_streaming_cd_sharded_parity(rng, multichip):
    """Entity-sharded streaming CD loop == single-device loop: same final
    loss to 1e-6, same coefficients, comms recorded."""
    from photon_ml_tpu.game.streaming import (
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch

    n_ent, rows, k = 32, 6, 3
    Xe = rng.normal(size=(n_ent, rows, k)).astype(np.float32)
    We = rng.normal(size=(n_ent, k))
    ye = (
        rng.random((n_ent, rows))
        < 1 / (1 + np.exp(-np.einsum("erk,ek->er", Xe, We)))
    ).astype(np.float32)

    def run(mesh):
        table = ShardedCoefficientTable(n_ent, k, mesh=mesh)
        trainer = StreamingRandomEffectTrainer("logistic", _OPT, mesh=mesh)
        half = n_ent // 2

        def chunk(lo, hi):
            return DenseBatch(
                x=Xe[lo:hi], labels=ye[lo:hi],
                offsets=np.zeros((hi - lo, rows), np.float32),
                weights=np.ones((hi - lo, rows), np.float32),
            )

        stats = trainer.train(
            table, [(0, chunk(0, half)), (half, chunk(half, n_ent))]
        )
        return table, stats

    t_single, s_single = run(None)
    comms_before = telemetry_metrics.peek_counter("comms.bytes_total") or 0.0
    t_mesh, s_mesh = run(make_mesh({"model": 8}))

    assert t_mesh.sharding is not None
    # per-device residency: every device holds exactly 1/8 of the table
    shard_bytes = {
        s.data.nbytes for s in t_mesh.coefficients.addressable_shards
    }
    assert shard_bytes == {t_mesh.nbytes // 8}
    # acceptance: same final loss to 1e-6 (relative; sum over entities)
    lhs, rhs = s_mesh.total_final_value, s_single.total_final_value
    assert abs(lhs - rhs) <= 1e-6 * max(1.0, abs(rhs)), (lhs, rhs)
    np.testing.assert_allclose(
        np.asarray(t_mesh.coefficients),
        np.asarray(t_single.coefficients),
        rtol=2e-4, atol=2e-4,
    )
    comms_after = telemetry_metrics.peek_counter("comms.bytes_total") or 0.0
    assert comms_after > comms_before
    assert (
        telemetry_metrics.peek_counter("comms.streaming_chunk_solve.bytes")
        or 0
    ) > 0


@pytest.mark.multichip
@pytest.mark.slow
def test_estimator_2d_batch_model_mesh_parity(rng, multichip):
    """GameEstimator.fit over a named 2-D (batch, model) mesh reproduces
    the single-device GLMix fit — FE rows shard over 'batch', RE entity
    state over 'model', one physical mesh."""
    from photon_ml_tpu.game import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
        RandomEffectConfig,
        build_game_dataset,
    )

    n, n_users = 240, 11
    Xg = rng.normal(size=(n, 6)) * (rng.random((n, 6)) < 0.6)
    Xg[:, 0] = 1.0
    Xu = rng.normal(size=(n, 3))
    users = rng.integers(0, n_users, size=n)
    wg = rng.normal(size=6)
    wu = rng.normal(size=(n_users, 3))
    margin = Xg @ wg + np.einsum("ij,ij->i", Xu, wu[users])
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(float)
    gds = build_game_dataset(
        response=y,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y),
            "user": SparseBatch.from_dense(Xu, y),
        },
        id_columns={"userId": users},
    )
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="global", optimizer=_OPT),
            "per-user": RandomEffectConfig(
                shard_name="user", id_name="userId", optimizer=_OPT
            ),
        },
        num_iterations=2,
    )
    mesh = make_mesh({"batch": 4, "model": 2})
    r_mesh = GameEstimator(config).fit(gds, mesh=mesh)
    r_single = GameEstimator(config).fit(gds)
    np.testing.assert_allclose(
        r_mesh.model.models["fixed"].coefficients,
        r_single.model.models["fixed"].coefficients,
        rtol=5e-3, atol=5e-3,
    )
    for bm, bs in zip(
        r_mesh.model.models["per-user"].buckets,
        r_single.model.models["per-user"].buckets,
    ):
        np.testing.assert_allclose(
            bm.coefficients, bs.coefficients, rtol=5e-3, atol=5e-3
        )


@pytest.mark.multichip
def test_gspmd_solve_rejects_entity_only_mesh(rng, multichip):
    batch = _fe_problem(rng, n=64)
    mesh = make_mesh({"model": 8})
    with pytest.raises(ValueError, match="batch/data axis"):
        gspmd_solve(
            "logistic", batch, _OPT,
            jnp.zeros(batch.num_features, jnp.float32), mesh,
        )


@pytest.mark.multichip
def test_estimator_rejects_mesh_with_unknown_axes(rng, multichip):
    """A provisioned mesh whose axes nothing recognizes must fail loudly,
    not silently train single-device."""
    from photon_ml_tpu.game import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
        build_game_dataset,
    )

    X = rng.normal(size=(40, 4))
    y = (rng.random(40) > 0.5).astype(float)
    gds = build_game_dataset(
        response=y, feature_shards={"global": SparseBatch.from_dense(X, y)}
    )
    config = GameConfig(
        task="logistic",
        coordinates={"fixed": FixedEffectConfig(shard_name="global",
                                                optimizer=_OPT)},
        num_iterations=1,
    )
    mesh = make_mesh({"x": 4, "y": 2})
    with pytest.raises(ValueError, match="neither a batch/data"):
        GameEstimator(config).fit(gds, mesh=mesh)


# ---------------------------------------------------------------------------
# game_10B capacity config
# ---------------------------------------------------------------------------


def test_game_10b_refuses_unsharded(monkeypatch):
    import bench_multichip as mc

    monkeypatch.setenv("PHOTON_CHIP_HBM_GB", "16")
    plan = mc.game_10b_plan(8)
    assert plan["total_coefficients"] == 10_240_000_000
    assert not plan["fits_unsharded"]
    assert plan["per_device_gb"] < 16
    with pytest.raises(RuntimeError, match="refuses to run on 1 device"):
        mc.check_game_10b_headroom(1)
    # the message carries the memory math and the fix
    try:
        mc.check_game_10b_headroom(1)
    except RuntimeError as e:
        msg = str(e)
        assert "GB per device" in msg and "shard the entity axis" in msg
    # sharded over >= min_devices it passes the headroom check
    mc.check_game_10b_headroom(plan["min_devices"])
    mc.check_game_10b_headroom(8)


def test_game_10b_bench_line_shape(monkeypatch):
    import bench_multichip as mc

    monkeypatch.setenv("PHOTON_CHIP_HBM_GB", "16")
    line = mc.bench_game_10b(8, simulated=True)
    assert line["metric"] == "multichip_game10B_per_device_gb"
    detail = line["detail"]
    assert detail["unsharded_refused"] is True
    assert "refuses to run" in detail["refusal"]
    assert detail["sharded_plan_fits"] is True
    assert detail["simulated"] is True
    json.dumps(line)  # bench contract: every line is valid JSON


# ---------------------------------------------------------------------------
# gate tolerance for baselines predating the multichip metrics
# ---------------------------------------------------------------------------


def test_gate_skips_multichip_metrics_missing_from_baseline(capsys):
    import bench_suite

    results = {
        "linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0,
        "multichip_glm_rows_per_sec": 500.0,
        "multichip_glmix_cd_coeffs_per_sec": None,  # budget-truncated
    }
    baseline = {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 90.0}
    rc = bench_suite.run_gate(results, baseline, threshold=0.2)
    err = capsys.readouterr().err
    assert rc == 0
    assert "multichip_glm_rows_per_sec: new metric" in err
    assert "skipped" in err
    assert "truncated, not gated" in err


def test_gate_fleet_observability_metrics_lower_is_better(capsys):
    """The fleet_* observability metrics regress UPWARD (more waiting,
    wider MFU spread = worse) and skip-with-note against baselines that
    predate them — the established new-metric gate path."""
    import bench_multichip
    import bench_suite

    assert "fleet_collective_wait_fraction" in bench_multichip.MULTICHIP_METRICS
    assert "fleet_mfu_spread" in bench_multichip.MULTICHIP_METRICS
    baseline = {
        "fleet_collective_wait_fraction": 0.1,
        "fleet_mfu_spread": 0.05,
    }
    # a RISE is the regression
    rc = bench_suite.run_gate(
        {"fleet_collective_wait_fraction": 0.5, "fleet_mfu_spread": 0.05},
        baseline, threshold=0.2,
    )
    assert rc == bench_suite.GATE_EXIT_CODE
    capsys.readouterr()
    # a drop (less waiting) passes
    rc = bench_suite.run_gate(
        {"fleet_collective_wait_fraction": 0.05, "fleet_mfu_spread": 0.01},
        baseline, threshold=0.2,
    )
    assert rc == 0
    capsys.readouterr()
    # baselines predating the fleet metrics: skipped with a note
    rc = bench_suite.run_gate(
        {
            "fleet_collective_wait_fraction": 0.5,
            "linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0,
        },
        {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0},
        threshold=0.2,
    )
    err = capsys.readouterr().err
    assert rc == 0
    assert "fleet_collective_wait_fraction: new metric" in err


# ---------------------------------------------------------------------------
# ISSUE 16: per-device HBM high-watermarks across a multichip fleet, and
# the gated per-kernel utilization metrics
# ---------------------------------------------------------------------------


class _StatsDevice:
    def __init__(self, did, in_use, limit=16 * 2**30):
        self.id = did
        self.stats = {"bytes_in_use": in_use, "bytes_limit": limit}

    def memory_stats(self):
        return self.stats


def test_watermark_spread_across_eight_devices():
    """Per-device HBM peaks are max-tracked independently per device and
    per phase; the spread (max-min of current usage) exposes the skewed
    member — exactly the imbalance a fleet report needs to attribute."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry import memory as tmem

    devices = [_StatsDevice(i, (i + 1) * 2**20) for i in range(8)]
    tmem.record_device_watermarks(devices, phase="fit")
    # device 3 spikes during scoring, everyone else dips
    for d in devices:
        d.stats["bytes_in_use"] = 2**20
    devices[3].stats["bytes_in_use"] = 12 * 2**20
    tmem.record_device_watermarks(devices, phase="score")

    g = telemetry.snapshot()["gauges"]
    # global per-device peaks hold the max across BOTH phases
    assert g["memory.device.3.peak_bytes"] == 12 * 2**20
    assert g["memory.device.7.peak_bytes"] == 8 * 2**20
    # per-phase peaks stay attributed to their phase
    assert g["memory.phase.fit.device.3.peak_bytes"] == 4 * 2**20
    assert g["memory.phase.score.device.3.peak_bytes"] == 12 * 2**20
    assert g["memory.phase.score.device.0.peak_bytes"] == 2**20
    # the live spread names the imbalance: 12 MiB vs 1 MiB
    assert tmem.device_spread_bytes() == 11 * 2**20


def test_gate_kernel_utilization_metrics(capsys):
    """The per-kernel utilization metrics ride bench_suite's gate:
    an MFU drop regresses (higher is better), and baselines predating
    the profiler skip-with-note."""
    import bench_suite

    assert "glm_value_grad_mfu" in bench_suite.SUITE_METRICS
    assert "hot_dispatch_fraction" in bench_suite.SUITE_METRICS
    baseline = {"glm_value_grad_mfu": 0.5, "hot_dispatch_fraction": 0.8}
    rc = bench_suite.run_gate(
        {"glm_value_grad_mfu": 0.1, "hot_dispatch_fraction": 0.8},
        baseline, threshold=0.2,
    )
    assert rc == bench_suite.GATE_EXIT_CODE  # MFU collapsed: regression
    capsys.readouterr()
    rc = bench_suite.run_gate(
        {"glm_value_grad_mfu": 0.55, "hot_dispatch_fraction": 0.9},
        baseline, threshold=0.2,
    )
    assert rc == 0  # better utilization passes
    capsys.readouterr()
    # an old baseline without the profiler metrics: skipped with a note
    rc = bench_suite.run_gate(
        {
            "glm_value_grad_mfu": 0.1,
            "linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0,
        },
        {"linreg_tron_1Mx10K_rows_per_sec_per_chip": 100.0},
        threshold=0.2,
    )
    err = capsys.readouterr().err
    assert rc == 0
    assert "glm_value_grad_mfu: new metric" in err and "skipped" in err
