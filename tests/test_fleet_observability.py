"""ISSUE 13 acceptance: fleet observability end-to-end on a REAL
2-process gloo fleet (the tools/fleet.py supervisor over the dryrun fit
shape).

One supervised run must prove the whole chain at once:

- each member writes per-member SUFFIXED trace + telemetry artifacts with
  its process identity recorded in the trace header (the
  ``telemetry.identity`` naming contract applied via PHOTON_*_OUT);
- ``cli report --fleet`` over the artifact dir renders ONE merged report
  whose per-member rows, collective-wait attribution, and straggler
  callout round-trip through JSON;
- the supervisor's live status snapshot DURING the run shows both
  members alive with fresh heartbeat fields (polled from the atomic
  ``--status-file`` while the fit runs).

Member 1 carries a per-boundary sleep (``chunk_sleep_proc=1``) so it
arrives LAST at every ``fleet_any`` barrier — the deterministic
straggler: its collective wait is near zero while member 0 stands by.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from tools import fleet


@pytest.fixture(scope="module")
def fleet_obs_run(tmp_path_factory):
    """One supervised 2-process gloo fleet with telemetry + live status;
    shared by every assertion below (the run is the expensive part)."""
    workdir = str(tmp_path_factory.mktemp("fleet_obs"))
    status_file = os.path.join(workdir, "status.json")
    snapshots: list[dict] = []
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            try:
                with open(status_file, encoding="utf-8") as fh:
                    snapshots.append(json.load(fh))
            except (OSError, ValueError):
                pass  # not written yet / atomic swap in flight elsewhere
            time.sleep(0.15)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    try:
        report = fleet.run_fleet(fleet.FleetSpec(
            workdir=workdir,
            num_processes=2,
            devices_per_process=2,
            # member 1 = the deterministic straggler: it sleeps BEFORE
            # every fleet_any barrier, so member 0 stands waiting. The
            # sleep must dwarf 2-core scheduling noise (supervisor +
            # status + pytest threads contend with the workers): ~5 s of
            # injected asymmetry across the 4 boundaries vs sub-second
            # jitter per barrier
            chunk_sleep_s=1.25,
            chunk_sleep_proc=1,
            progress_heartbeat_every_s=0.4,
            status_file=status_file,
            status_port=0,
            status_interval_s=0.25,
            timeout_s=300.0,
        ))
    finally:
        stop.set()
        poller.join(timeout=5.0)
    assert report.get("ok"), json.dumps(report, default=str)[:2000]
    return {"report": report, "snapshots": snapshots,
            "status_file": status_file}


def test_per_member_suffixed_artifacts_with_identity(fleet_obs_run):
    tdir = fleet_obs_run["report"]["telemetry_dir"]
    names = set(os.listdir(tdir))
    assert {
        "trace.proc-0.jsonl", "trace.proc-1.jsonl",
        "telemetry.proc-0.jsonl", "telemetry.proc-1.jsonl",
    } <= names
    # no unsuffixed clobber target exists
    assert "trace.jsonl" not in names and "telemetry.jsonl" not in names
    for proc in (0, 1):
        with open(os.path.join(tdir, f"trace.proc-{proc}.jsonl")) as fh:
            header = json.loads(fh.readline())
        assert header["type"] == "trace_header"
        assert header["process_index"] == proc
        assert header["num_processes"] == 2
        assert isinstance(header["anchor_unix_s"], float)
        assert isinstance(header["hostname"], str)
        # final metrics snapshot carries the same identity
        metrics_lines = [
            json.loads(line)
            for line in open(
                os.path.join(tdir, f"telemetry.proc-{proc}.jsonl")
            )
            if line.strip()
        ]
        finals = [r for r in metrics_lines if r.get("type") == "metrics"]
        assert finals and finals[-1]["process_index"] == proc
        beats = [r for r in metrics_lines if r.get("type") == "heartbeat"]
        assert beats and all(b["proc"] == proc for b in beats)


def test_live_status_showed_both_members_alive(fleet_obs_run):
    snapshots = fleet_obs_run["snapshots"]
    assert snapshots, "the status file was never readable during the run"
    both_alive = [
        s for s in snapshots if s.get("alive_members") == [0, 1]
    ]
    assert both_alive, [s.get("alive_members") for s in snapshots[-5:]]
    # fresh per-member heartbeat fields, correctly attributed
    with_fields = [
        s for s in both_alive
        if all(
            s["members"][str(p)].get("last_heartbeat", {}).get("proc") == p
            for p in (0, 1)
        )
    ]
    assert with_fields
    member0 = with_fields[-1]["members"]["0"]
    assert member0["heartbeat_age_s"] < 5.0
    assert member0["last_heartbeat"]["seq"] >= 1
    # the final snapshot records the completed outcome
    final = json.loads(open(fleet_obs_run["status_file"]).read())
    assert final["outcome"] == "complete"
    assert final["deaths"] == []


def test_cli_report_fleet_merges_run_with_straggler(fleet_obs_run, tmp_path):
    from photon_ml_tpu.cli.report import main as report_main

    tdir = fleet_obs_run["report"]["telemetry_dir"]
    out_md = tmp_path / "fleet.md"
    out_json = tmp_path / "fleet.json"
    rc = report_main([
        "--fleet", tdir, "--out", str(out_md), "--json", str(out_json),
    ])
    assert rc == 0
    doc = json.loads(out_json.read_text())
    assert doc["type"] == "fleet_report"
    assert doc["lost_members"] == []
    rows = {r["process_index"]: r for r in doc["members"]}
    assert set(rows) == {0, 1}
    for proc, row in rows.items():
        assert row["status"] == "ok"
        # collective-wait attribution recorded per member (fleet_any
        # barriers + chunk-solve dispatch under jax.process_count()==2)
        assert row["collective_wait_s"] is not None
        assert row["collective_wait_calls"] >= 1
        assert row["heartbeats"] >= 1
        assert row["chunks_done"] == fleet.N_CHUNKS
    # the slept member arrives last at every barrier => waits least =>
    # is named the straggler; the prompt member accumulated real wait
    straggler = doc["straggler"]
    assert straggler is not None
    assert straggler["process_index"] == 1
    assert rows[0]["collective_wait_s"] > rows[1]["collective_wait_s"]
    km = doc["key_metrics"]
    assert km["fleet_collective_wait_s"] > 0
    assert 0 < km["fleet_collective_wait_fraction"] <= 1
    assert km["fleet_lost_members"] == 0
    md = out_md.read_text()
    assert "Straggler: member 1" in md

    # the aggregated metrics gate: self-compare green, degraded baseline
    # (much lower wait fraction) exits 3 under --fail-on-regress
    assert report_main([
        "--fleet", tdir, "--compare", str(out_json), "--fail-on-regress",
    ]) == 0
    worse = dict(km)
    worse["fleet_collective_wait_fraction"] = (
        km["fleet_collective_wait_fraction"] / 10.0
    )
    base = tmp_path / "strict_baseline.json"
    base.write_text(json.dumps({"key_metrics": worse}))
    assert report_main([
        "--fleet", tdir, "--compare", str(base), "--fail-on-regress",
    ]) == 3
