"""Live supervisor status (photon_ml_tpu/parallel/fleet_status.py):
snapshot semantics, atomic writes, the ``fleet.status_write`` fault seam,
and the HTTP arm. The seam's failure contract is the load-bearing part:
status is observability, never control — an unwritable status file must
not take the supervisor down with it."""

from __future__ import annotations

import json
import os
import time
import urllib.request

import pytest

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.parallel import multihost
from photon_ml_tpu.parallel.fleet_status import FleetStatusWriter


def _touch_heartbeat(fleet_dir: str, pid: int) -> None:
    os.makedirs(fleet_dir, exist_ok=True)
    path = multihost.heartbeat_path(fleet_dir, pid)
    with open(path, "a"):
        os.utime(path, None)


def test_snapshot_liveness_from_heartbeat_mtimes(tmp_path):
    fleet_dir = str(tmp_path / "fleet")
    _touch_heartbeat(fleet_dir, 0)  # fresh
    _touch_heartbeat(fleet_dir, 1)
    # stale member 1: beat "30s ago"
    past = time.time() - 30.0
    os.utime(multihost.heartbeat_path(fleet_dir, 1), (past, past))
    writer = FleetStatusWriter(
        fleet_dir=fleet_dir, num_processes=3, heartbeat_deadline_s=5.0,
    )
    snap = writer.snapshot()
    members = snap["members"]
    assert members["0"]["alive"] is True
    assert members["0"]["heartbeat_age_s"] < 5.0
    assert members["1"]["alive"] is False  # stale beyond deadline
    assert members["1"]["heartbeat_age_s"] >= 29.0
    assert members["2"]["alive"] is False  # never beat
    assert members["2"]["heartbeat_age_s"] is None
    assert snap["alive_members"] == [0]
    assert snap["type"] == "fleet_status"


def test_snapshot_exited_member_not_alive_and_update_merges(tmp_path):
    fleet_dir = str(tmp_path / "fleet")
    _touch_heartbeat(fleet_dir, 0)
    writer = FleetStatusWriter(
        fleet_dir=fleet_dir, num_processes=1, heartbeat_deadline_s=5.0,
    )
    writer.update(rcs={0: 113}, deaths=[0], generation=1, relaunches=1,
                  death_history=[{"generation": 0, "process_id": 0}])
    snap = writer.snapshot()
    # a fresh heartbeat file does NOT make an exited member alive
    assert snap["members"]["0"]["alive"] is False
    assert snap["members"]["0"]["rc"] == 113
    assert snap["members"]["0"]["lost"] is True
    assert snap["generation"] == 1 and snap["relaunches"] == 1
    assert snap["deaths_total"] == 1
    # the cumulative record survives a per-generation deaths=[] reset
    # (run_fleet pushes it; a recovered run's FINAL snapshot must still
    # say a member was lost along the way)
    writer.update(deaths=[], generation=2)
    snap = writer.snapshot()
    assert snap["deaths"] == []
    assert snap["death_history"] == [{"generation": 0, "process_id": 0}]
    assert snap["deaths_total"] == 1


def test_snapshot_includes_member_heartbeat_fields(tmp_path):
    fleet_dir = str(tmp_path / "fleet")
    _touch_heartbeat(fleet_dir, 0)
    telemetry_out = str(tmp_path / "telemetry.jsonl")
    with open(str(tmp_path / "telemetry.proc-0.jsonl"), "w") as fh:
        fh.write(json.dumps(
            {"type": "heartbeat", "seq": 7, "proc": 0, "rows_per_s": 9.0}
        ) + "\n")
    writer = FleetStatusWriter(
        fleet_dir=fleet_dir, num_processes=1, heartbeat_deadline_s=5.0,
        telemetry_out=telemetry_out,
    )
    snap = writer.snapshot()
    hb = snap["members"]["0"]["last_heartbeat"]
    assert hb["seq"] == 7 and hb["rows_per_s"] == 9.0


def test_write_once_is_atomic_json(tmp_path):
    fleet_dir = str(tmp_path / "fleet")
    _touch_heartbeat(fleet_dir, 0)
    status_file = str(tmp_path / "status.json")
    writer = FleetStatusWriter(
        fleet_dir=fleet_dir, num_processes=1, heartbeat_deadline_s=5.0,
        status_file=status_file,
    )
    snap = writer.write_once()
    assert snap is not None
    on_disk = json.loads(open(status_file).read())
    assert on_disk["alive_members"] == [0]
    # atomic-write discipline: no tmp debris next to the snapshot
    assert not os.path.exists(status_file + ".tmp")
    assert telemetry.snapshot()["counters"]["fleet.status_writes"] == 1


def test_status_write_fault_seam_io_is_absorbed(tmp_path):
    """An `io` rule at fleet.status_write (disk flaking on the status
    file) is absorbed: write_once returns None, counts the error, and
    the NEXT write succeeds — status failures never stop supervision."""
    fleet_dir = str(tmp_path / "fleet")
    _touch_heartbeat(fleet_dir, 0)
    status_file = str(tmp_path / "status.json")
    writer = FleetStatusWriter(
        fleet_dir=fleet_dir, num_processes=1, heartbeat_deadline_s=5.0,
        status_file=status_file,
    )
    faults.install_plan(faults.FaultPlan(
        [faults.FaultRule("fleet.status_write", action="io", nth=1)]
    ))
    try:
        assert writer.write_once() is None  # injected write failure
        assert not os.path.exists(status_file)
        snap = telemetry.snapshot()["counters"]
        assert snap["fleet.status_write_errors"] == 1
        assert snap["faults.injected.fleet.status_write"] == 1
        assert writer.write_once() is not None  # next cadence recovers
        assert json.loads(open(status_file).read())["alive_members"] == [0]
    finally:
        faults.clear_plan()


def test_status_writer_thread_and_http_server(tmp_path):
    fleet_dir = str(tmp_path / "fleet")
    _touch_heartbeat(fleet_dir, 0)
    status_file = str(tmp_path / "status.json")
    writer = FleetStatusWriter(
        fleet_dir=fleet_dir, num_processes=1, heartbeat_deadline_s=5.0,
        status_file=status_file, port=0, interval_s=0.05,
    )
    with writer:
        assert writer.port  # ephemeral port bound
        deadline = time.monotonic() + 5.0
        while not os.path.exists(status_file):
            assert time.monotonic() < deadline
            time.sleep(0.02)
        url = f"http://127.0.0.1:{writer.port}/statusz"
        with urllib.request.urlopen(url, timeout=5) as resp:
            doc = json.loads(resp.read())
        assert doc["type"] == "fleet_status"
        assert doc["alive_members"] == [0]
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{writer.port}/nope", timeout=5
            )
        writer.update(outcome="complete")
    # stop() writes the final state
    assert json.loads(open(status_file).read())["outcome"] == "complete"


def test_status_writer_rejects_bad_interval(tmp_path):
    with pytest.raises(ValueError, match="interval_s"):
        FleetStatusWriter(
            fleet_dir=str(tmp_path), num_processes=1,
            heartbeat_deadline_s=5.0, interval_s=0.0,
        )
