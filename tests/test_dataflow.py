"""Fixture-driven coverage for the interprocedural dataflow gate
(ISSUE 15): L017 donation safety, L018 lock-order cycles, L019
unsanctioned host transfer, the chain-dedupe, and ``--changed``.

Every rule gets planted-defect positives (asserted through the REAL
``tools/check.py`` CLI where the acceptance criteria demand it) and
sanctioned-idiom negatives; the taint engine's interprocedural
propagation (arguments/returns one call level deep) gets direct units;
and a donated-mmap defect planted in a COPY of the real
``photon_ml_tpu/ingest/assemble.py`` flips the CLI to exit 1 naming the
flow chain — the PR 10 bug class can no longer land silently.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analysis import core, dataflow, driver, locks
from tools.analysis.callgraph import build_graph

CHECK = os.path.join(REPO, "tools", "check.py")


def write_tree(tmp_path, files: dict):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    return tmp_path


def analyze(tmp_path, files: dict, **kw):
    write_tree(tmp_path, files)
    kw.setdefault("require_seeds", False)
    return driver.analyze(str(tmp_path), **kw)


def graph_of(tmp_path, files: dict):
    write_tree(tmp_path, files)
    srcs = []
    for rel in files:
        if rel.startswith("photon_ml_tpu/") and rel.endswith(".py"):
            srcs.append(core.load_source(rel, str(tmp_path / rel)))
    return build_graph(srcs)


def codes(findings):
    return sorted(f.code for f in findings)


def run_cli(root):
    proc = subprocess.run(
        [sys.executable, CHECK, "--root", str(root), "--json"],
        capture_output=True, text=True, timeout=180,
    )
    return proc, json.loads(proc.stdout)


# the instrumented_jit shim every fixture resolves through (mirrors the
# real re-export surface: telemetry/__init__ re-exports xla's wrapper)
_XLA_SHIM = {
    "photon_ml_tpu/__init__.py": "",
    "photon_ml_tpu/telemetry/__init__.py": (
        "from photon_ml_tpu.telemetry.xla import instrumented_jit\n"
    ),
    "photon_ml_tpu/telemetry/xla.py": (
        "def instrumented_jit(fn=None, name=None, multi_shape=False,"
        " **kw):\n"
        "    return fn\n"
    ),
}


# ---------------------------------------------------------------------------
# L017 donation safety
# ---------------------------------------------------------------------------


def _donation_tree(
    source_stmt: str, arg: str, extra_imports: str = ""
) -> dict:
    """The ingest-assembler idiom: a factory returning a donating
    executable, called with ``arg`` in the donated slot."""
    files = dict(_XLA_SHIM)
    files["photon_ml_tpu/ingest/__init__.py"] = ""
    files["photon_ml_tpu/ingest/spill.py"] = (
        "import numpy as np\n"
        f"{extra_imports}"
        "from photon_ml_tpu import telemetry\n\n"
        "SPILL_DTYPE = np.float32\n\n\n"
        "def _writer(donate):\n"
        "    def write(buf, v, off):\n"
        "        return buf\n"
        "    return telemetry.instrumented_jit(\n"
        "        write, name='spill_write',\n"
        "        donate_argnums=(0,) if donate else (),\n"
        "    )\n\n\n"
        "def resume(path, v, off):\n"
        f"    {source_stmt}\n"
        f"    return _writer(True)({arg}, v, off)\n"
    )
    return files


class TestDonationSafetyL017:
    def test_mmap_load_into_donated_slot_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            _donation_tree("buf = np.load(path, mmap_mode='r')", "buf"),
        )
        assert codes(res.findings) == ["L017"]
        f = res.findings[0]
        assert f.path == "photon_ml_tpu/ingest/spill.py"
        assert "np.load(mmap_mode=...)" in f.message
        assert "donated argument 0" in f.message
        assert "spill_write" in f.message
        # the flow chain names the binding hop
        assert "`buf`" in f.message

    def test_frombuffer_into_direct_jax_jit_donation(self, tmp_path):
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/m.py"] = """
            import jax
            import numpy as np


            def write(buf):
                return buf


            def push(raw):
                view = np.frombuffer(raw, np.uint8)
                fn = jax.jit(write, donate_argnums=(0,))
                return fn(view)
        """
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L017"]
        assert "np.frombuffer" in res.findings[0].message

    def test_view_of_parameter_donated_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            _donation_tree("buf = v[:128]", "buf"),
        )
        assert codes(res.findings) == ["L017"]
        assert "view/slice of parameter `v`" in res.findings[0].message

    def test_interprocedural_borrow_through_callee_donation(self, tmp_path):
        """The caller holds the mmap; the DONATION happens one call away
        inside a helper — the finding stitches the two."""
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/io2.py"] = """
            import numpy as np

            from photon_ml_tpu import telemetry


            def _writer():
                def write(buf):
                    return buf
                return telemetry.instrumented_jit(
                    write, name='w', donate_argnums=(0,)
                )


            def commit(table):
                return _writer()(table)


            def restore(path):
                base = np.load(path, mmap_mode='r')
                return commit(base)
        """
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L017"]
        f = res.findings[0]
        assert f.chain == ("io2.restore", "io2.commit")
        assert "donates it" in f.message

    def test_sanctioned_copy_launders(self, tmp_path):
        for launder, imports in (
            ("buf = jnp.array(np.load(path, mmap_mode='r'), copy=True)",
             "import jax.numpy as jnp\n"),
            ("buf = np.load(path, mmap_mode='r').copy()", ""),
            ("buf = np.array(np.load(path, mmap_mode='r'))", ""),
        ):
            files = _donation_tree(launder, "buf", extra_imports=imports)
            res = analyze(tmp_path, files)
            assert res.findings == [], (launder, codes(res.findings))

    def test_owned_buffer_donation_clean(self, tmp_path):
        # the real assembler donates buffers IT allocated — no taint
        files = _donation_tree(
            "buf = jnp.zeros(128)", "buf",
            extra_imports="import jax.numpy as jnp\n",
        )
        res = analyze(tmp_path, files)
        assert res.findings == []

    def test_non_donated_slot_clean(self, tmp_path):
        # borrowed memory in a NON-donated argument is fine (the
        # executable reads it; nothing frees it)
        res = analyze(
            tmp_path,
            _donation_tree("v = np.load(path, mmap_mode='r')", "off"),
        )
        assert res.findings == []

    def test_noqa_suppresses_l017(self, tmp_path):
        files = _donation_tree("buf = np.load(path, mmap_mode='r')", "buf")
        files["photon_ml_tpu/ingest/spill.py"] = files[
            "photon_ml_tpu/ingest/spill.py"
        ].replace(
            "return _writer(True)(buf, v, off)",
            "return _writer(True)(buf, v, off)  # photon: noqa[L017]",
        )
        res = analyze(tmp_path, files)
        assert res.findings == []

    def test_planted_defect_fails_real_cli_with_chain(self, tmp_path):
        write_tree(
            tmp_path,
            _donation_tree("buf = np.load(path, mmap_mode='r')", "buf"),
        )
        proc, doc = run_cli(tmp_path)
        assert proc.returncode == 1
        (finding,) = doc["findings"]
        assert finding["code"] == "L017"
        assert finding["chain"] == ["ingest.spill.resume"]
        assert "np.load(mmap_mode=...)" in finding["message"]
        assert "`buf`" in finding["message"]  # the complete flow chain


# ---------------------------------------------------------------------------
# L018 lock-order cycles
# ---------------------------------------------------------------------------


def _lock_tree(publish_body: str) -> dict:
    """The serving topology in miniature: the engine's version lock vs
    the registry's lock; ``publish_body`` decides whether the registry
    calls back into the engine WHILE holding its own lock (a cycle) or
    after releasing it (a consistent order)."""
    return {
        "photon_ml_tpu/__init__.py": "",
        "photon_ml_tpu/serving/__init__.py": "",
        "photon_ml_tpu/serving/engine.py": """
            import threading

            from photon_ml_tpu.serving.registry import ModelRegistry


            class ScoringEngine:
                def __init__(self):
                    self._version_lock = threading.Lock()
                    self._registry = ModelRegistry()

                def swap(self):
                    with self._version_lock:
                        self._registry.refresh()

                def bump_seq(self):
                    with self._version_lock:
                        pass
        """,
        "photon_ml_tpu/serving/registry.py": (
            """
            import threading


            def _engine_of(source) -> "ScoringEngine":
                from photon_ml_tpu.serving.engine import ScoringEngine
                return source


            class ModelRegistry:
                def __init__(self):
                    self._lock = threading.Lock()

                def refresh(self):
                    with self._lock:
                        pass

"""
            + publish_body
        ),
    }


_CYCLE_PUBLISH = """\
                def publish(self, source):
                    with self._lock:
                        engine = _engine_of(source)
                        engine.bump_seq()
"""

_ORDERED_PUBLISH = """\
                def publish(self, source):
                    with self._lock:
                        pass
                    engine = _engine_of(source)
                    engine.bump_seq()
"""

_CYCLE_TREE = _lock_tree(_CYCLE_PUBLISH)


class TestLockOrderL018:
    def test_opposite_order_cycle_flagged(self, tmp_path):
        res = analyze(tmp_path, _CYCLE_TREE)
        assert codes(res.findings) == ["L018"]
        msg = res.findings[0].message
        assert "lock-order cycle" in msg
        assert "ScoringEngine._version_lock" in msg
        assert "ModelRegistry._lock" in msg
        # both acquisition legs are named with their call chains
        assert "ScoringEngine.swap -> " in msg
        assert "ModelRegistry.publish -> " in msg

    def test_consistent_order_clean(self, tmp_path):
        # the registry releases its lock BEFORE calling back into the
        # engine: same locks, no cycle
        res = analyze(tmp_path, _lock_tree(_ORDERED_PUBLISH))
        assert res.findings == []

    def test_self_reacquire_through_helper_flagged(self, tmp_path):
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/h.py"] = """
            import threading


            class Holder:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self._inner()

                def _inner(self):
                    with self._lock:
                        pass
        """
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L018"]
        assert "re-acquired while held" in res.findings[0].message

    def test_lexical_nesting_is_an_order_not_a_cycle(self, tmp_path):
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/n.py"] = """
            import threading


            class TwoLocks:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def both(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def both_again(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
        """
        res = analyze(tmp_path, files)
        assert res.findings == []  # same order everywhere: no cycle

    def test_call_in_with_item_is_a_held_call(self, tmp_path):
        """`with self._lock, helper():` runs ``helper`` while the first
        item's lock is held — its acquisitions are order edges too
        (code-review regression)."""
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/w.py"] = """
            import threading


            class A:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b = B(self)

                def fwd(self):
                    with self._a_lock, self._b.use():
                        pass

                def poke(self):
                    with self._a_lock:
                        pass


            class B:
                def __init__(self, a):
                    self._b_lock = threading.Lock()
                    self._a = a

                def use(self):
                    with self._b_lock:
                        pass
                    return open("/dev/null")

                def back(self, a: "A"):
                    with self._b_lock:
                        a.poke()
        """
        res = analyze(tmp_path, files)
        assert "L018" in codes(res.findings)

    def test_lexical_opposite_orders_cycle(self, tmp_path):
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/n.py"] = """
            import threading


            class TwoLocks:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def ab(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def ba(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
        """
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L018"]

    def test_cycle_fails_real_cli(self, tmp_path):
        write_tree(tmp_path, _CYCLE_TREE)
        proc, doc = run_cli(tmp_path)
        assert proc.returncode == 1
        l018 = [f for f in doc["findings"] if f["code"] == "L018"]
        assert len(l018) == 1
        assert "_version_lock" in l018[0]["message"]
        assert "_lock" in l018[0]["message"]

    def test_real_serving_lock_graph_is_acyclic(self):
        """The REAL serving/nearline/registry/fleet lock topology: locks
        exist (nodes), and the order graph has no cycles — the shipped
        tree passes with the rule armed."""
        rels = [
            os.path.join("photon_ml_tpu", "serving", "engine.py"),
            os.path.join("photon_ml_tpu", "serving", "registry.py"),
            os.path.join("photon_ml_tpu", "serving", "nearline.py"),
            os.path.join("photon_ml_tpu", "serving", "batcher.py"),
            os.path.join("photon_ml_tpu", "serving", "server.py"),
            os.path.join("photon_ml_tpu", "parallel", "fleet_status.py"),
            os.path.join("photon_ml_tpu", "telemetry", "progress.py"),
        ]
        srcs = [core.load_source(rel, os.path.join(REPO, rel))
                for rel in rels]
        g = build_graph(srcs)
        stats: dict = {}
        findings = locks.run_lock_order(g, stats)
        assert stats["nodes"] >= 4  # engine/registry/nearline/fleet locks
        assert findings == []


# ---------------------------------------------------------------------------
# L019 unsanctioned host transfer
# ---------------------------------------------------------------------------


def _transfer_tree(body: str, extra_imports: str = "") -> dict:
    files = dict(_XLA_SHIM)
    files["photon_ml_tpu/score.py"] = (
        f"{extra_imports}"
        "from photon_ml_tpu import telemetry\n\n\n"
        "def _scorer():\n"
        "    def run(x):\n"
        "        return x\n"
        "    return telemetry.instrumented_jit(run, name='score')\n\n\n"
        "def evaluate(batch):\n"
        f"    {body}\n"
    )
    return files


class TestHostTransferL019:
    def test_float_of_jitted_result_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            _transfer_tree(
                "scores = _scorer()(batch)\n    return float(scores)"
            ),
        )
        assert codes(res.findings) == ["L019"]
        f = res.findings[0]
        assert "float()" in f.message
        assert "result of jitted `score`" in f.message

    def test_asarray_and_tolist_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            _transfer_tree(
                "scores = _scorer()(batch)\n"
                "    a = np.asarray(scores)\n"
                "    return scores.tolist(), a",
                extra_imports="import numpy as np\n",
            ),
        )
        assert codes(res.findings) == ["L019", "L019"]
        msgs = " | ".join(f.message for f in res.findings)
        assert "np.asarray" in msgs and ".tolist()" in msgs

    def test_comparison_in_branch_flagged(self, tmp_path):
        res = analyze(
            tmp_path,
            _transfer_tree(
                "scores = _scorer()(batch)\n"
                "    if scores > 0:\n"
                "        return 1\n"
                "    return 0"
            ),
        )
        assert codes(res.findings) == ["L019"]
        assert "comparison in a branch condition" in res.findings[0].message

    def test_shape_comparison_and_is_none_clean(self, tmp_path):
        # array METADATA and identity checks are host-side bookkeeping,
        # not transfers — the false positives the audit flushed out
        res = analyze(
            tmp_path,
            _transfer_tree(
                "scores = _scorer()(batch)\n"
                "    if scores.shape[0] > 4:\n"
                "        return 1\n"
                "    if scores is not None:\n"
                "        return 2\n"
                "    return scores"
            ),
        )
        assert res.findings == []

    def test_sync_fetch_pass_through_clean(self, tmp_path):
        files = _transfer_tree(
            "scores = _scorer()(batch)\n"
            "    host = sync_fetch(scores, label='scores')\n"
            "    return float(host)",
            extra_imports=(
                "from photon_ml_tpu.telemetry.device import sync_fetch\n"
            ),
        )
        files["photon_ml_tpu/telemetry/device.py"] = (
            "import numpy as np\n\n\n"
            "def sync_fetch(x, label=None):\n"
            "    return np.asarray(x)\n"
        )
        res = analyze(tmp_path, files)
        assert res.findings == []

    def test_interprocedural_device_result_through_helper(self, tmp_path):
        """The jitted call is hidden in a helper; its RETURN carries the
        device taint into the caller's float()."""
        res = analyze(
            tmp_path,
            _transfer_tree(
                "return float(_solve(batch))\n\n\n"
                "def _solve(batch):\n"
                "    return _scorer()(batch)"
            ),
        )
        assert codes(res.findings) == ["L019"]
        assert "via `_solve`" in res.findings[0].message

    def test_param_sink_inside_callee_flagged_at_caller(self, tmp_path):
        """The SINK is inside the callee (it floats its parameter); the
        caller hands it a jitted result — flagged with both sides."""
        res = analyze(
            tmp_path,
            _transfer_tree(
                "scores = _scorer()(batch)\n"
                "    return _log_scalar(scores)\n\n\n"
                "def _log_scalar(v):\n"
                "    return float(v)"
            ),
        )
        assert codes(res.findings) == ["L019"]
        f = res.findings[0]
        assert "inside `_log_scalar`" in f.message
        assert f.chain == ("score.evaluate", "score._log_scalar")

    def test_plain_float_without_device_source_clean(self, tmp_path):
        res = analyze(
            tmp_path,
            _transfer_tree("return float(len(batch))"),
        )
        assert res.findings == []

    def test_planted_transfer_fails_real_cli(self, tmp_path):
        write_tree(
            tmp_path,
            _transfer_tree(
                "scores = _scorer()(batch)\n    return float(scores)"
            ),
        )
        proc, doc = run_cli(tmp_path)
        assert proc.returncode == 1
        (finding,) = doc["findings"]
        assert finding["code"] == "L019"
        assert finding["chain"] == ["score.evaluate"]


# ---------------------------------------------------------------------------
# Taint-propagation units (the engine itself)
# ---------------------------------------------------------------------------


class TestTaintPropagation:
    def _summaries(self, tmp_path, files):
        g = graph_of(tmp_path, files)
        summaries = {}
        for qname, fn in sorted(g.functions.items()):
            flow = dataflow._FunctionFlow(g, fn, {}, dataflow.Stats())
            summaries[qname] = flow.run()
        for qname, fn in sorted(g.functions.items()):
            flow = dataflow._FunctionFlow(
                g, fn, summaries, dataflow.Stats()
            )
            summaries[qname] = flow.run()
        return g, summaries

    def test_returns_borrowed_summary(self, tmp_path):
        g, summaries = self._summaries(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/m.py": (
                    "import numpy as np\n\n\n"
                    "def open_base(path):\n"
                    "    return np.load(path, mmap_mode='r')\n"
                ),
            },
        )
        ret = summaries["photon_ml_tpu.m.open_base"].returns
        assert any(t.kind == dataflow.BORROWED for t in ret)

    def test_returns_view_of_param_summary(self, tmp_path):
        g, summaries = self._summaries(
            tmp_path,
            {
                "photon_ml_tpu/__init__.py": "",
                "photon_ml_tpu/m.py": (
                    "def head(a, n):\n"
                    "    return a[:n]\n"
                ),
            },
        )
        ret = summaries["photon_ml_tpu.m.head"].returns
        borrowed = [t for t in ret if t.kind == dataflow.BORROWED]
        assert borrowed and borrowed[0].param == 0

    def test_param_donation_summary(self, tmp_path):
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/m.py"] = """
            from photon_ml_tpu import telemetry


            def _w():
                def write(buf):
                    return buf
                return telemetry.instrumented_jit(
                    write, name='w', donate_argnums=(0,)
                )


            def commit(table):
                return _w()(table)
        """
        g, summaries = self._summaries(tmp_path, files)
        dons = summaries["photon_ml_tpu.m.commit"].param_donations
        assert 0 in dons

    def test_branch_join_keeps_both_taints(self, tmp_path):
        # `x` is borrowed on ONE branch: the join must keep the taint
        files = _donation_tree(
            "buf = v\n"
            "    if off:\n"
            "        buf = np.load(path, mmap_mode='r')",
            "buf",
        )
        import tools.analysis.driver as drv

        write_tree(tmp_path, files)
        res = drv.analyze(str(tmp_path), require_seeds=False)
        assert codes(res.findings) == ["L017"]

    def test_sanitizer_kills_taint_on_reassignment(self, tmp_path):
        files = _donation_tree(
            "buf = np.load(path, mmap_mode='r')\n"
            "    buf = buf.copy()",
            "buf",
        )
        res = analyze(tmp_path, files)
        assert res.findings == []

    def test_tuple_unpacking_distributes_taint(self, tmp_path):
        files = _donation_tree(
            "buf, other = np.load(path, mmap_mode='r'), 1",
            "buf",
        )
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L017"]

    def test_copy_false_is_not_a_sanitizer(self, tmp_path):
        # np.array(x, copy=False) ALIASES — the taint must flow through
        # (code-review regression)
        files = _donation_tree(
            "buf = np.array(np.load(path, mmap_mode='r'), copy=False)",
            "buf",
        )
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L017"]

    def test_element_write_does_not_disown_the_array(self, tmp_path):
        # `buf[0] = 0` mutates without disowning: the frombuffer taint
        # survives to the donation (code-review regression)
        files = _donation_tree(
            "buf = np.frombuffer(path, np.uint8)\n"
            "    buf[0] = 0",
            "buf",
        )
        res = analyze(tmp_path, files)
        assert codes(res.findings) == ["L017"]

    def test_while_condition_sees_loop_carried_device_taint(self, tmp_path):
        # the canonical convergence loop: `while err > tol:` where err
        # is re-bound to a jitted result INSIDE the body (code-review
        # regression — the test re-executes every iteration)
        res = analyze(
            tmp_path,
            _transfer_tree(
                "err = 1.0\n"
                "    while err > 0.5:\n"
                "        err = _scorer()(batch)\n"
                "    return err"
            ),
        )
        assert "L019" in codes(res.findings)


# ---------------------------------------------------------------------------
# Chain dedupe (driver satellite)
# ---------------------------------------------------------------------------


class TestChainDedupe:
    def test_multiple_chains_report_once_with_shortest(self, tmp_path):
        """One impure traced helper reached from TWO jit registrations:
        one finding, shortest chain, alternates counted."""
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/s.py"] = """
            import time

            import jax


            def _tick(x):
                return x * time.time()


            def direct():
                return jax.jit(_tick)


            def nested():
                def run(x):
                    return _tick(x) + 1
                return jax.jit(run)
        """
        res = analyze(tmp_path, files)
        l014 = [f for f in res.findings if f.code == "L014"]
        assert len(l014) == 1
        f = l014[0]
        assert f.chain == ("s._tick",)  # the shortest of the two
        assert f.alternates >= 1
        assert "alternate call chain" in f.render()

    def test_distinct_sites_not_merged(self, tmp_path):
        files = dict(_XLA_SHIM)
        files["photon_ml_tpu/s.py"] = """
            import time

            import jax


            def _tick(x):
                print("x"); return x * time.time()


            def direct():
                return jax.jit(_tick)
        """
        res = analyze(tmp_path, files)
        l014 = [f for f in res.findings if f.code == "L014"]
        # wall clock + print: two DIFFERENT sites on one line stay apart
        assert len(l014) == 2


# ---------------------------------------------------------------------------
# --changed (fast pre-commit scope)
# ---------------------------------------------------------------------------


@pytest.fixture
def git_tree(tmp_path):
    files = {
        "photon_ml_tpu/__init__.py": "",
        "photon_ml_tpu/util.py": "def helper(x):\n    return x\n",
        "photon_ml_tpu/caller.py": (
            "from photon_ml_tpu.util import helper\n\n\n"
            "def use(x):\n    return helper(x)\n"
        ),
        "photon_ml_tpu/standalone.py": (
            "import os\n"  # an L001 in an UNRELATED file
        ),
    }
    write_tree(tmp_path, files)
    env = dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
    for cmd in (
        ["git", "init", "-q"],
        ["git", "add", "-A"],
        ["git", "commit", "-qm", "seed"],
    ):
        subprocess.run(cmd, cwd=tmp_path, env=env, check=True,
                       capture_output=True)
    return tmp_path


class TestChangedScope:
    def test_changed_file_and_dependents_in_scope(self, git_tree):
        # introduce a finding in util.py (changed) and leave the
        # unrelated standalone.py finding untouched (pre-existing)
        (git_tree / "photon_ml_tpu" / "util.py").write_text(
            "import json\n\n\ndef helper(x):\n    return x\n"
        )
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(git_tree),
             "--changed", "HEAD", "--json"],
            capture_output=True, text=True, timeout=120,
        )
        doc = json.loads(proc.stdout)
        assert proc.returncode == 1
        scope = set(doc["changed_scope"])
        assert "photon_ml_tpu/util.py" in scope
        # caller.py calls into the changed file: a DEPENDENT, in scope
        assert "photon_ml_tpu/caller.py" in scope
        assert "photon_ml_tpu/standalone.py" not in scope
        assert [f["path"] for f in doc["findings"]] == [
            "photon_ml_tpu/util.py"
        ]

    def test_unchanged_tree_is_clean_and_fast_scope_is_empty(self, git_tree):
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(git_tree),
             "--changed", "HEAD", "--json"],
            capture_output=True, text=True, timeout=120,
        )
        doc = json.loads(proc.stdout)
        assert proc.returncode == 0
        assert doc["changed_scope"] == []
        assert doc["findings"] == []  # standalone.py L001 out of scope

    def test_w002_survives_changed_scope(self, git_tree):
        """Renaming a registered sanitizer must fail even the scoped
        pre-commit run: W002 is pass-config health, never scoped out
        (code-review regression)."""
        write_tree(git_tree, {"photon_ml_tpu/__init__.py": ""})
        res = driver.analyze(
            str(git_tree), require_seeds=True,
            changed={"photon_ml_tpu/util.py"},
        )
        assert "W002" in codes(res.findings)

    def test_write_baseline_with_changed_is_rejected(self, git_tree):
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(git_tree),
             "--changed", "HEAD", "--write-baseline",
             str(git_tree / "b.json")],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode != 0
        assert "full tree" in proc.stderr
        assert not (git_tree / "b.json").exists()

    def test_full_tree_behavior_unchanged(self, git_tree):
        # without --changed the pre-existing L001 still fails the gate
        proc = subprocess.run(
            [sys.executable, CHECK, "--root", str(git_tree), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        doc = json.loads(proc.stdout)
        assert proc.returncode == 1
        assert "changed_scope" not in doc
        assert [f["path"] for f in doc["findings"]] == [
            "photon_ml_tpu/standalone.py"
        ]


# ---------------------------------------------------------------------------
# W002: the sanitizer/ring-source tables must keep resolving
# ---------------------------------------------------------------------------


class TestDataflowSeedGuard:
    def test_missing_sanitizer_is_w002_on_real_trees(self, tmp_path):
        """With ``require_seeds=True`` (the real repo), a tree where the
        registered L017 sanitizers do not resolve fails with W002 — a
        rename of `_owned_copy` must not silently launder nothing."""
        write_tree(tmp_path, {"photon_ml_tpu/__init__.py": ""})
        res = driver.analyze(str(tmp_path), require_seeds=True)
        msgs = [f.message for f in res.findings if f.code == "W002"]
        assert any("COPY_SANITIZERS" in m for m in msgs), msgs
        assert any("RING_SOURCES" in m for m in msgs), msgs

    def test_real_tree_sanitizers_resolve(self):
        srcs = []
        for rel in (
            os.path.join("photon_ml_tpu", "parallel", "sharding.py"),
            os.path.join("photon_ml_tpu", "ingest", "buffers.py"),
        ):
            srcs.append(core.load_source(rel, os.path.join(REPO, rel)))
        g = build_graph(srcs)
        for qname in sorted(
            dataflow.COPY_SANITIZERS | dataflow.RING_SOURCES
        ):
            assert qname in g.functions, qname


# ---------------------------------------------------------------------------
# Acceptance: a donated-mmap defect planted in the REAL ingest module
# ---------------------------------------------------------------------------


class TestPlantedRealTreeDefect:
    def test_donated_mmap_in_real_assembler_fails_gate(self, tmp_path):
        """Copy the real package, plant the PR 10 bug class in
        ``ingest/assemble.py`` (an mmap'd spill resume donated into the
        real ``_chunk_writer``), and prove the REAL CLI exits 1 naming
        the complete flow chain."""
        shutil.copytree(
            os.path.join(REPO, "photon_ml_tpu"),
            tmp_path / "photon_ml_tpu",
            ignore=shutil.ignore_patterns("__pycache__"),
        )
        target = tmp_path / "photon_ml_tpu" / "ingest" / "assemble.py"
        target.write_text(
            target.read_text()
            + textwrap.dedent(
                """

                def _resume_from_spill(spill_path, asm):
                    vals = np.load(spill_path, mmap_mode="r")
                    writer = _chunk_writer(True)
                    asm._v, asm._r, asm._c = writer(
                        vals, asm._r, asm._c, vals, asm._r, asm._c,
                        jnp.int32(0), jnp.int32(0),
                    )
                """
            )
        )
        proc, doc = run_cli(tmp_path)
        assert proc.returncode == 1, proc.stdout
        l017 = [f for f in doc["findings"] if f["code"] == "L017"]
        assert l017, doc["findings"]
        # the donated slots also carry fields of the caller-owned `asm`
        # parameter (borrowed too) — the mmap flow is the one we assert
        mmap = [f for f in l017 if "np.load(mmap_mode=...)" in f["message"]]
        assert mmap, l017
        f = mmap[0]
        assert f["path"] == "photon_ml_tpu/ingest/assemble.py"
        assert "ingest_assemble_write" in f["message"]
        assert "`vals`" in f["message"]  # the flow hop
        assert f["chain"] == ["ingest.assemble._resume_from_spill"]

    def test_unmodified_real_package_copy_is_clean(self, tmp_path):
        """The control: the same copy WITHOUT the plant passes — the
        shipped tree is clean under all three new rules."""
        shutil.copytree(
            os.path.join(REPO, "photon_ml_tpu"),
            tmp_path / "photon_ml_tpu",
            ignore=shutil.ignore_patterns("__pycache__"),
        )
        proc, doc = run_cli(tmp_path)
        assert proc.returncode == 0, json.dumps(
            doc.get("findings"), indent=2
        )


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
