"""The crash matrix + elastic sharded checkpoints, CI-enforced.

Three acceptance properties of ISSUE 10 live here:

1. **Crash matrix**: for every registered write-path fault point, a
   subprocess fit hard-killed (``os._exit``) at that point resumes to a
   final model EXACTLY matching the uninterrupted fit (tools/chaos.py).
   Budget-aware: ``PHOTON_CHAOS_BUDGET_S`` bounds the tier-1 slice;
   points that don't fit are reported, never silently dropped.
2. **Elastic resume**: a checkpoint written on an 8-way entity-sharded
   mesh restores onto a 4-way mesh and onto a single device, and the
   resumed fit matches the uninterrupted final loss to 1e-6.
3. **No host gather**: a sharded save fetches one shard at a time —
   ``checkpoint.max_shard_fetch_bytes`` stays at table_bytes / n_shards
   (the telemetry check standing in for a host-OOM at the 40 GB scale).
"""

from __future__ import annotations

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.game.checkpoint import (
    CheckpointSpec,
    StreamingCheckpointManager,
)
from photon_ml_tpu.game.streaming import (
    ShardedCoefficientTable,
    StreamingRandomEffectTrainer,
)
from photon_ml_tpu.ops.dense import DenseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)

_CFG = OptimizerConfig(
    max_iterations=60,
    tolerance=1e-9,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=0.3,
)


# ---------------------------------------------------------------------------
# 1. the crash matrix (subprocess kills via tools/chaos.py)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow
def test_crash_matrix_every_write_path_point_recovers(tmp_path):
    """Subprocess fits killed with true-crash semantics at each phase of
    the atomic checkpoint protocol — before the tmp dir, between payload
    and manifest, between manifest and rename, after rename — all resume
    to the uninterrupted fit's exact bits."""
    from tools import chaos

    import photon_ml_tpu.game.checkpoint  # noqa: F401 (registers points)

    # the enumeration itself is part of the contract: a new write-path
    # seam must be added HERE (and thereby to the matrix) to land
    assert faults.write_path_points() == [
        "checkpoint.save.after_rename",
        "checkpoint.save.before_manifest",
        "checkpoint.save.before_rename",
        "checkpoint.save.before_tmp",
    ]
    budget = float(os.environ.get("PHOTON_CHAOS_BUDGET_S", "300"))
    report = chaos.run_matrix(str(tmp_path), budget_s=budget)
    assert report["ok"], json.dumps(report, indent=2)
    covered = [
        p for p, e in report["results"].items() if e.get("exact")
    ]
    assert covered, (
        "the chaos budget covered no point at all — raise "
        "PHOTON_CHAOS_BUDGET_S"
    )
    for entry in report["results"].values():
        assert entry["armed_rc"] == faults.DEFAULT_EXIT_CODE
        assert entry["max_abs_delta"] == 0.0
    if report["skipped"]:
        warnings.warn(
            "chaos budget truncated the matrix; uncovered this run: "
            f"{report['skipped']} (full matrix: python -m tools.chaos)",
            stacklevel=1,
        )


# ---------------------------------------------------------------------------
# 2 + 3. elastic sharded checkpoints (in-process, 8-device CPU mesh)
# ---------------------------------------------------------------------------


def _entity_problem(rng, n_ent, rows, k):
    X = rng.normal(size=(n_ent, rows, k))
    W = rng.normal(size=(n_ent, k))
    z = np.einsum("erk,ek->er", X, W)
    y = (rng.random((n_ent, rows)) < 1 / (1 + np.exp(-z))).astype(float)
    return X, y


def _chunks(X, y, n_chunks):
    n_ent, rows, _ = X.shape
    per = n_ent // n_chunks

    def chunk(lo, hi):
        return DenseBatch(
            x=X[lo:hi].astype(np.float32),
            labels=y[lo:hi].astype(np.float32),
            offsets=np.zeros((hi - lo, rows), np.float32),
            weights=np.ones((hi - lo, rows), np.float32),
        )

    return [(i * per, chunk(i * per, (i + 1) * per))
            for i in range(n_chunks)]


def _final_loss(table_np, X, y):
    """Total per-entity objective at the final coefficients — the scalar
    the 1e-6 elastic-resume acceptance is stated over."""
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optim import glm_adapter

    obj = make_objective("logistic", l2_weight=0.3)
    total = 0.0
    for e in range(X.shape[0]):
        adapter = glm_adapter(obj, DenseBatch.from_arrays(X[e], y[e]))
        total += float(adapter.value_and_grad(jnp.asarray(table_np[e]))[0])
    return total


@pytest.mark.slow
def test_elastic_restore_shrinks_mesh_and_matches_reference(
    rng, tmp_path, multichip
):
    """Save on an entity=8 mesh, restore onto entity=4 AND onto a single
    device; both resumed fits match the uninterrupted final loss to 1e-6.
    The sharded save itself never assembles the table on the host
    (max shard fetch == table_bytes / 8)."""
    from photon_ml_tpu.parallel import make_mesh

    mesh8 = make_mesh({"entity": 8})
    n_ent, rows, k = 32, 8, 5
    X, y = _entity_problem(rng, n_ent, rows, k)
    chunks = _chunks(X, y, n_chunks=4)

    # uninterrupted reference on the full mesh
    ref = ShardedCoefficientTable(n_ent, k, mesh=mesh8)
    StreamingRandomEffectTrainer("logistic", _CFG, mesh=mesh8).train(
        ref, chunks
    )
    expected = ref.to_numpy()
    expected_loss = _final_loss(expected, X, y)

    # interrupted run: two chunks on the 8-mesh, checkpoint each boundary
    telemetry.reset()
    try:
        mgr = StreamingCheckpointManager(
            CheckpointSpec(directory=str(tmp_path / "ckpt"), every=1)
        )
        table8 = ShardedCoefficientTable(n_ent, k, mesh=mesh8)
        StreamingRandomEffectTrainer("logistic", _CFG, mesh=mesh8).train(
            table8, chunks[:2], checkpointer=mgr
        )
        mid = table8.to_numpy()
        snap = telemetry.snapshot()
        # 3 saves ran (2 boundaries + terminal), each writing 8 shard
        # files; the largest single host fetch was ONE shard, not the
        # table — the no-full-gather property
        assert snap["counters"]["checkpoint.shard_saves"] == 3 * 8
        assert (
            snap["gauges"]["checkpoint.max_shard_fetch_bytes"]
            == table8.nbytes // 8
        )
    finally:
        telemetry.reset()

    # -- restore onto a 4-device mesh (device loss -> mesh-shrunken) -----
    telemetry.reset()
    try:
        mesh4 = make_mesh({"entity": 4}, devices=jax.devices()[:4])
        restored = mgr.restore_placed(mesh=mesh4)
        assert restored is not None and restored.elastic
        assert restored.next_chunk == 2
        assert restored.saved_sharding["mesh_axes"] == {"entity": 8}
        np.testing.assert_array_equal(
            np.asarray(restored.coefficients), mid
        )
        shard_rows = {
            (s.index[0].start or 0, s.index[0].stop)
            for s in restored.coefficients.addressable_shards
        }
        assert len(shard_rows) == 4  # genuinely re-placed 4 ways
        assert (
            telemetry.snapshot()["counters"]["recovery.elastic_resumes"]
            == 1
        )
        table4 = ShardedCoefficientTable.from_coefficients(
            restored.coefficients, mesh=mesh4
        )
        StreamingRandomEffectTrainer("logistic", _CFG, mesh=mesh4).train(
            table4, chunks, start_chunk=restored.next_chunk
        )
        got4 = table4.to_numpy()
        # the acceptance metric: final LOSS to 1e-6 (at the optimum, loss
        # deltas are second-order in the cross-mesh fp noise that keeps
        # raw coefficients only to ~1e-3, same as the mesh-parity tests)
        assert abs(_final_loss(got4, X, y) - expected_loss) < 1e-6
        np.testing.assert_allclose(got4, expected, rtol=5e-3, atol=5e-4)
    finally:
        telemetry.reset()

    # -- restore onto ONE device (the single-host debug/degraded shape) --
    restored1 = mgr.restore_placed(mesh=None)
    assert restored1 is not None and restored1.elastic
    np.testing.assert_array_equal(np.asarray(restored1.coefficients), mid)
    table1 = ShardedCoefficientTable.from_coefficients(
        restored1.coefficients
    )
    StreamingRandomEffectTrainer("logistic", _CFG).train(
        table1, chunks, start_chunk=restored1.next_chunk
    )
    got1 = table1.to_numpy()
    assert abs(_final_loss(got1, X, y) - expected_loss) < 1e-6
    np.testing.assert_allclose(got1, expected, rtol=5e-3, atol=5e-4)


def test_sharded_save_writes_one_file_per_shard(rng, tmp_path, multichip):
    """Manifest anatomy of a sharded save: 8 contiguous shard
    descriptors covering [0, N), the writing mesh + spec + environment
    recorded for the restore-side delta report."""
    from photon_ml_tpu.game.checkpoint import StreamCheckpointState
    from photon_ml_tpu.parallel import make_mesh

    mesh = make_mesh({"entity": 8})
    table = ShardedCoefficientTable(16, 3, mesh=mesh)
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1)
    )
    path = mgr.save(
        StreamCheckpointState(next_chunk=1,
                              coefficients=table.coefficients)
    )
    manifest = json.loads(
        open(os.path.join(path, "manifest.json")).read()
    )
    assert manifest["format_version"] == 2
    shards = manifest["shards"]
    assert len(shards) == 8
    assert [s["row_start"] for s in shards] == list(range(0, 16, 2))
    assert all(s["rows"] == 2 for s in shards)
    assert manifest["sharding"]["mesh_axes"] == {"entity": 8}
    assert manifest["env"]["device_count"] == jax.device_count()
    for s in shards:
        arr = np.load(os.path.join(path, s["file"]))
        assert arr.shape == (2, 3)


def test_restore_under_different_environment_than_saved(
    rng, tmp_path, multichip, monkeypatch, caplog
):
    """A sharded checkpoint written under one decode/topology environment
    (``PHOTON_NO_NATIVE=1``, 8 devices) restores cleanly under another
    (native decoder back on, single device): the manifest recorded BOTH
    sides' facts, the restore logs the delta instead of failing
    mysteriously, and the shard payloads — plain .npy files — come back
    bit-identical. The device-count delta is simulated by rewriting the
    recorded env (an in-process jax cannot change its device count)."""
    import logging as _logging

    from photon_ml_tpu.game.checkpoint import StreamCheckpointState
    from photon_ml_tpu.parallel import make_mesh

    mesh = make_mesh({"entity": 8})
    monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
    table = ShardedCoefficientTable(16, 3, mesh=mesh)
    table.write_chunk(
        0, jnp.asarray(rng.normal(size=(16, 3)), jnp.float32)
    )
    saved = table.to_numpy()
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1)
    )
    path = mgr.save(
        StreamCheckpointState(next_chunk=3,
                              coefficients=table.coefficients)
    )
    manifest_path = os.path.join(path, "manifest.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    # the writing run's environment is on record
    assert manifest["env"]["no_native"] is True
    assert manifest["env"]["device_count"] == jax.device_count()

    # restore side: native decoder back on, and (simulated) fewer devices
    monkeypatch.delenv("PHOTON_NO_NATIVE")
    manifest["env"]["device_count"] = 64
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    with caplog.at_level(_logging.WARNING,
                         logger="photon_ml_tpu.game.checkpoint"):
        restored = mgr.restore_placed(mesh=None)
    assert restored is not None and restored.next_chunk == 3
    np.testing.assert_array_equal(np.asarray(restored.coefficients), saved)
    assert restored.elastic  # 8 shards -> 1 device
    assert restored.saved_env["no_native"] is True
    delta_logs = [
        r.message for r in caplog.records
        if "environment differs" in r.message
    ]
    assert delta_logs and "no_native" in delta_logs[0]
    assert "device_count" in delta_logs[0]


def test_indivisible_target_mesh_raises_instead_of_skipping(
    rng, tmp_path, multichip
):
    """A target mesh the entity count cannot divide over is a
    CONFIGURATION error, not corruption: restore_placed must raise the
    typed ElasticPlacementError — silently skipping every (valid)
    checkpoint would restart training from scratch."""
    from photon_ml_tpu.game.checkpoint import StreamCheckpointState
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.sharding import ElasticPlacementError

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1)
    )
    coeffs = rng.normal(size=(16, 3)).astype(np.float32)
    mgr.save(StreamCheckpointState(next_chunk=1, coefficients=coeffs))
    mesh3 = make_mesh({"entity": 3}, devices=jax.devices()[:3])
    telemetry.reset()
    try:
        with pytest.raises(ElasticPlacementError, match="must divide"):
            mgr.restore_placed(mesh=mesh3)  # 16 % 3 != 0
        # the checkpoint was NOT branded corrupt
        assert telemetry.snapshot()["counters"].get(
            "checkpoint.corrupt") is None
    finally:
        telemetry.reset()
    # and it stays restorable on a workable topology
    restored = mgr.restore_placed(mesh=None)
    np.testing.assert_array_equal(np.asarray(restored.coefficients), coeffs)


def test_restore_placed_falls_back_past_corrupt_newest(rng, tmp_path):
    """The elastic restore path inherits newest-valid fallback: a
    truncated shard file in the newest checkpoint falls back to the one
    before it (checkpoint.corrupt counted)."""
    from photon_ml_tpu.game.checkpoint import StreamCheckpointState

    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1, keep_last=5)
    )
    good = np.arange(12, dtype=np.float32).reshape(4, 3)
    mgr.save(StreamCheckpointState(next_chunk=1, coefficients=good))
    bad_path = mgr.save(
        StreamCheckpointState(next_chunk=2, coefficients=good + 1)
    )
    with open(os.path.join(bad_path, "coefficients-0000.npy"), "wb") as f:
        f.write(b"\x00" * 7)  # truncated payload, valid manifest
    telemetry.reset()
    try:
        restored = mgr.restore_placed(mesh=None)
        assert restored is not None and restored.next_chunk == 1
        np.testing.assert_array_equal(
            np.asarray(restored.coefficients), good
        )
        assert telemetry.snapshot()["counters"]["checkpoint.corrupt"] == 1
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# 4. elastic restore, mesh-GROW direction (recovered capacity)
# ---------------------------------------------------------------------------


def test_elastic_restore_grows_mesh_one_to_four(rng, tmp_path, multichip):
    """Elasticity works in BOTH directions: a checkpoint written on a
    single device (the degraded survivor shape) restores onto a 4-device
    mesh — capacity recovered after an incident — genuinely re-sliced
    4 ways and bit-identical."""
    from photon_ml_tpu.game.checkpoint import StreamCheckpointState
    from photon_ml_tpu.parallel import make_mesh

    coeffs = rng.normal(size=(16, 3)).astype(np.float32)
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1)
    )
    mgr.save(StreamCheckpointState(next_chunk=2, coefficients=coeffs))
    mesh4 = make_mesh({"entity": 4}, devices=jax.devices()[:4])
    restored = mgr.restore_placed(mesh=mesh4)
    assert restored is not None and restored.elastic
    assert restored.next_chunk == 2
    np.testing.assert_array_equal(np.asarray(restored.coefficients), coeffs)
    shard_rows = {
        (s.index[0].start or 0, s.index[0].stop)
        for s in restored.coefficients.addressable_shards
    }
    assert len(shard_rows) == 4  # 1 shard on disk -> 4 on the mesh


def test_elastic_grow_indivisible_names_the_valid_sizes(
    rng, tmp_path, multichip
):
    """Growing onto a mesh the entity count cannot divide over raises the
    typed error AND lists the legal target axis sizes — the operator
    picking a survivor/recovery fleet size reads them off the message
    instead of factorizing entity counts by hand."""
    from photon_ml_tpu.game.checkpoint import StreamCheckpointState
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.sharding import ElasticPlacementError

    coeffs = rng.normal(size=(6, 3)).astype(np.float32)  # 6 % 4 != 0
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path), every=1)
    )
    mgr.save(StreamCheckpointState(next_chunk=1, coefficients=coeffs))
    mesh4 = make_mesh({"entity": 4}, devices=jax.devices()[:4])
    with pytest.raises(ElasticPlacementError) as exc:
        mgr.restore_placed(mesh=mesh4)
    msg = str(exc.value)
    assert "valid target axis sizes" in msg
    assert "[1, 2, 3, 6]" in msg  # divisors of 6 within device reach
    # the checkpoint stays restorable on any of the named sizes
    mesh2 = make_mesh({"entity": 2}, devices=jax.devices()[:2])
    restored = mgr.restore_placed(mesh=mesh2)
    np.testing.assert_array_equal(np.asarray(restored.coefficients), coeffs)


# ---------------------------------------------------------------------------
# 5. the DISTRIBUTED crash matrix (2-process gloo fleets, tools/fleet.py)
# ---------------------------------------------------------------------------


def test_distributed_points_enumeration_is_stable():
    """The fleet-seam set the distributed matrix (tools/chaos.py --fleet)
    runs over is part of the contract: a new distributed seam must be
    added HERE to land (and thereby to the matrix and lint L016)."""
    import photon_ml_tpu.game.checkpoint  # noqa: F401
    import photon_ml_tpu.parallel.distributed  # noqa: F401
    import photon_ml_tpu.parallel.multihost  # noqa: F401
    import photon_ml_tpu.serving.router  # noqa: F401
    import photon_ml_tpu.serving.shard  # noqa: F401

    assert faults.distributed_points() == [
        "checkpoint.peer_manifest",
        "fleet.heartbeat",
        "multihost.init",
        "parallel.collective.entry",
        # serving-fleet seams: registered distributed, matrixed by
        # tools/chaos.py --serving-fleet (they fire in serving
        # processes, never in a training fleet worker)
        "serving.member_load",
        "serving.resize_swap",
        "serving.route_fanout",
    ]


@pytest.mark.chaos_distributed
def test_distributed_matrix_tier1_row(tmp_path):
    """Budget-capped tier-1 slice of the DISTRIBUTED matrix: one
    2-process gloo fleet with one member hard-killed at the
    checkpoint.peer_manifest seam (the quorum seam — a certified
    coordinated checkpoint sits behind the kill, so this row proves
    survivor resume FROM a coordinated checkpoint, the protocol's whole
    point). The full 4-seam matrix runs under --slow /
    `python -m tools.chaos --fleet`."""
    from tools import chaos

    budget = float(os.environ.get("PHOTON_CHAOS_BUDGET_S", "300"))
    report = chaos.run_fleet_matrix(
        str(tmp_path),
        points=["checkpoint.peer_manifest"],
        budget_s=budget,
    )
    if report["skipped"]:
        warnings.warn(
            "chaos budget truncated the distributed matrix; uncovered "
            f"this run: {report['skipped']} (full matrix: python -m "
            "tools.chaos --fleet)",
            stacklevel=1,
        )
        return
    assert report["ok"], json.dumps(report, indent=2, default=str)
    entry = report["results"]["checkpoint.peer_manifest"]
    assert entry["victim_rc"] == faults.DEFAULT_EXIT_CODE
    assert entry["relaunches"] == 1  # resumed on the survivor
    assert entry["loss_delta"] < 1e-6
    assert entry["partial_certified"] == []  # zero partial checkpoints
    # fleet-observability degradation over the row's REAL leftover
    # artifact dirs (ISSUE 13), one per generation: in gen0 the
    # hard-killed victim (proc 1, os._exit at the seam — no atexit
    # metrics flush) AND the survivor that noticed the broken fleet
    # (os._exit 76) both render `lost` — their runs genuinely never
    # completed; the relaunched gen1 fleet's member renders `ok`. Never
    # a crash, never silently complete.
    from photon_ml_tpu.telemetry.fleet_report import FleetReport

    telemetry_dir = os.path.join(
        str(tmp_path), "checkpoint_peer_manifest", "telemetry"
    )
    gen0 = FleetReport.load(os.path.join(telemetry_dir, "gen0"))
    assert 1 in gen0.lost_members()
    rows = {r["process_index"]: r for r in gen0.rows()}
    assert rows[1]["status"] == "lost"
    json.dumps(gen0.to_json(), default=str)  # JSON-safe partial
    gen1 = FleetReport.load(os.path.join(telemetry_dir, "gen1"))
    assert gen1.lost_members() == []
    assert [r["status"] for r in gen1.rows()] == ["ok"]


@pytest.mark.chaos_distributed
@pytest.mark.slow
def test_distributed_matrix_every_fleet_seam_recovers(tmp_path):
    """The full distributed matrix: for EVERY registered distributed
    seam, a 2-process fleet with one member hard-killed at the seam
    resumes on the survivor, matches the uninterrupted fleet reference's
    final loss to 1e-6, and never certifies a partial checkpoint."""
    from tools import chaos

    budget = float(os.environ.get("PHOTON_CHAOS_BUDGET_S", "600"))
    report = chaos.run_fleet_matrix(str(tmp_path), budget_s=budget)
    assert report["ok"], json.dumps(report, indent=2, default=str)
    covered = [
        p for p, e in report["results"].items() if e.get("passed")
    ]
    assert covered, "the budget covered no distributed point at all"
    for entry in report["results"].values():
        assert entry["victim_rc"] == faults.DEFAULT_EXIT_CODE
        assert entry["partial_certified"] == []
        assert entry["loss_delta"] < 1e-6
    if report["skipped"]:
        warnings.warn(
            "chaos budget truncated the distributed matrix; uncovered "
            f"this run: {report['skipped']}",
            stacklevel=1,
        )


@pytest.mark.chaos_distributed
def test_sigterm_to_one_member_boundary_stops_the_whole_fleet(tmp_path):
    """GracefulStop across a fleet: SIGTERM delivered to ONE member of a
    2-process gloo fit propagates through the fleet_any boundary
    agreement — EVERY member stops at the SAME chunk boundary, writes
    the coordinated final checkpoint, and exits 75. No member is left
    spinning in a collective (nobody needed SIGKILL escalation), and the
    final checkpoint is quorum-certified by both processes."""
    from tools import fleet

    report = fleet.run_fleet(fleet.FleetSpec(
        workdir=str(tmp_path),
        num_processes=2,
        devices_per_process=2,
        sigterm_after_s=1.5,
        sigterm_process=0,
        chunk_sleep_s=0.3,
        quorum_timeout_s=5.0,
        grace_s=20.0,
        timeout_s=240.0,
    ))
    assert report["interrupted"] is True, json.dumps(report, default=str)
    gen0 = report["generations"][0]
    assert gen0["outcome"] == "interrupted"
    # BOTH members exited through the graceful boundary stop — the
    # unsignaled member agreed via the fleet_any collective
    assert gen0["rcs"] == {0: fleet.GRACEFUL_EXIT_CODE,
                           1: fleet.GRACEFUL_EXIT_CODE}
    assert gen0["escalated"] == []  # clean boundary stop, no SIGKILL
    assert not report["generations"][1:]  # interrupted, not relaunched
    # the final coordinated checkpoint is certified with a 2-process
    # quorum and fully readable
    ckpt_dir = os.path.join(str(tmp_path), "ckpt")
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=ckpt_dir, every=1)
    )
    assert fleet.verify_certified_checkpoints(
        ckpt_dir, fleet.N_ENTITIES, fleet.DIM
    ) == []
    restored = mgr.restore()
    assert restored is not None
    manifest = json.loads(open(os.path.join(
        mgr._chunk_dirs()[-1][1], "manifest.json")).read())
    assert manifest["quorum"] == {"num_processes": 2}
