"""Telemetry subsystem: span trees, metrics registry, device accounting,
the timed()/Timer integration, event-bus hardening, and tracker telemetry.
"""

import json
import os
import sys
import threading
import types

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import trace as ttrace


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


# -- spans -------------------------------------------------------------------


def test_span_tree_nesting_and_attrs():
    with telemetry.span("outer", phase="x") as outer:
        with telemetry.span("inner") as inner:
            inner.set_attr(k=1)
        assert inner.parent_id == outer.span_id
    spans = {s.name: s for s in telemetry.finished_spans()}
    assert spans["outer"].parent_id is None
    assert spans["outer"].dur is not None and spans["outer"].dur >= 0
    assert spans["inner"].attrs == {"k": 1}
    assert spans["outer"].attrs == {"phase": "x"}
    # children close before parents
    assert spans["inner"].ts >= spans["outer"].ts


def test_span_events_attach_to_current_span():
    telemetry.add_event("orphan")  # no open span: must be a silent no-op
    with telemetry.span("s"):
        telemetry.add_event("marker", code=7)
    (s,) = telemetry.finished_spans("s")
    assert [e["name"] for e in s.events] == ["marker"]
    assert s.events[0]["attrs"] == {"code": 7}


def test_spans_are_per_thread_roots():
    done = threading.Event()

    def worker():
        with telemetry.span("worker_root"):
            pass
        done.set()

    with telemetry.span("main_root"):
        t = threading.Thread(target=worker, name="w0")
        t.start()
        t.join()
    assert done.wait(1)
    (w,) = telemetry.finished_spans("worker_root")
    # a span opened on another thread is NOT parented under main's span
    assert w.parent_id is None
    assert w.thread == "w0"


def test_jsonl_sink_and_chrome_export(tmp_path):
    out = tmp_path / "trace.jsonl"
    telemetry.configure(trace_out=str(out))
    with telemetry.span("fit"):
        with telemetry.span("step"):
            telemetry.add_event("device_fetch", bytes=4)
    lines = [json.loads(x) for x in out.read_text().splitlines()]
    assert lines[0]["type"] == "trace_header"
    spans = [x for x in lines if x["type"] == "span"]
    assert [s["name"] for s in spans] == ["step", "fit"]  # close order
    assert spans[0]["parent"] == spans[1]["id"]

    perfetto = tmp_path / "trace.json"
    n = telemetry.export_chrome_trace(str(out), str(perfetto))
    doc = json.loads(perfetto.read_text())
    events = doc["traceEvents"]
    assert n == len(events)
    complete = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert {e["name"] for e in complete} == {"fit", "step"}
    assert instants[0]["name"] == "device_fetch"
    # microsecond timebase, monotone non-negative
    assert all(e["ts"] >= 0 for e in events if "ts" in e)


def test_configure_truncates_stale_trace_file(tmp_path):
    out = tmp_path / "trace.jsonl"
    out.write_text('{"type": "span", "name": "stale_run"}\n')
    telemetry.configure(trace_out=str(out))
    with telemetry.span("fresh"):
        pass
    lines = [json.loads(x) for x in out.read_text().splitlines()]
    # one session per file: the stale run is gone, header leads
    assert lines[0]["type"] == "trace_header"
    assert [x["name"] for x in lines if x["type"] == "span"] == ["fresh"]


def test_reset_clears_other_threads_open_spans():
    leaked = threading.Event()
    release = threading.Event()

    def worker():
        cm = ttrace.TRACER.span("leaked_parent")
        cm.__enter__()
        leaked.set()
        release.wait(5)
        with ttrace.TRACER.span("post_reset"):
            pass

    t = threading.Thread(target=worker, name="leaky")
    t.start()
    assert leaked.wait(5)
    telemetry.reset()  # must clear the WORKER's open stack too
    release.set()
    t.join()
    (post,) = telemetry.finished_spans("post_reset")
    assert post.parent_id is None  # not parented under the stale span


def test_tracer_survives_out_of_order_exit():
    tr = ttrace.Tracer()
    outer_cm = tr.span("outer")
    outer_cm.__enter__()
    inner_cm = tr.span("inner")
    inner_cm.__enter__()
    # exit OUTER first (a leaked inner span); tracer must not corrupt
    outer_cm.__exit__(None, None, None)
    assert tr.current() is None
    with tr.span("next"):
        pass
    assert {s.name for s in tr.finished_spans()} >= {"outer", "next"}


def test_tracer_counts_dropped_spans_on_buffer_overflow():
    """Satellite: buffer overflow must not be silent — drops are counted
    in `trace.dropped_spans` and surfaced through snapshot()."""
    ttrace.TRACER.configure(buffer_limit=5)
    for i in range(12):
        with telemetry.span(f"s{i}"):
            pass
    assert len(telemetry.finished_spans()) == 5
    assert ttrace.TRACER.dropped_spans == 7
    assert telemetry.snapshot()["counters"]["trace.dropped_spans"] == 7
    # reset restores the default buffer limit AND clears drop accounting
    telemetry.reset()
    assert ttrace.TRACER._buffer_limit == ttrace.DEFAULT_BUFFER_LIMIT
    assert ttrace.TRACER.dropped_spans == 0


def test_active_span_path_visible_from_other_thread():
    seen = {}
    ready = threading.Event()
    release = threading.Event()

    def watcher():
        ready.wait(5)
        seen["path"] = telemetry.active_span_path()
        release.set()

    t = threading.Thread(target=watcher, name="watcher")
    t.start()
    with telemetry.span("fit"):
        with telemetry.span("coordinate:x"):
            ready.set()
            assert release.wait(5)
    t.join()
    assert seen["path"] == "fit > coordinate:x"
    assert telemetry.active_span_path() == ""  # nothing open now


def test_to_chrome_trace_multi_thread_spans():
    """Satellite: spans finishing on multiple threads export with one
    thread lane (tid + thread_name metadata) per thread."""
    barrier = threading.Barrier(3)

    def worker():
        barrier.wait(5)
        with telemetry.span("work"):
            telemetry.add_event("tick")

    threads = [
        threading.Thread(target=worker, name=f"w{i}") for i in range(2)
    ]
    for t in threads:
        t.start()
    with telemetry.span("main_work"):
        barrier.wait(5)
    for t in threads:
        t.join()
    records = [s.to_dict() for s in telemetry.finished_spans()]
    doc = telemetry.to_chrome_trace(records)
    events = doc["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    lanes = {e["args"]["name"]: e["tid"] for e in meta}
    assert {"w0", "w1", "MainThread"} <= set(lanes)
    assert len(set(lanes.values())) == len(lanes)  # distinct tids
    # each worker span rides ITS thread's tid, instants included
    by_name = {}
    for e in events:
        if e["ph"] in ("X", "i"):
            by_name.setdefault(e["name"], set()).add(e["tid"])
    assert by_name["work"] == {lanes["w0"], lanes["w1"]}
    assert by_name["tick"] == {lanes["w0"], lanes["w1"]}
    assert by_name["main_work"] == {lanes["MainThread"]}


# -- metrics -----------------------------------------------------------------


def test_counters_gauges_histograms_snapshot():
    telemetry.counter("c").inc()
    telemetry.counter("c").inc(2.5)
    telemetry.gauge("g").set(7)
    h = telemetry.histogram("h")
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    snap = telemetry.snapshot()
    assert snap["counters"]["c"] == pytest.approx(3.5)
    assert snap["gauges"]["g"] == 7.0
    hs = snap["histograms"]["h"]
    assert hs["count"] == 4
    assert hs["sum"] == pytest.approx(10.0)
    assert hs["min"] == 1.0 and hs["max"] == 4.0
    assert hs["p50"] in (2.0, 3.0)
    # snapshot is JSON-safe
    json.dumps(snap)


def test_histogram_reservoir_bounded_and_percentiles_sane():
    h = telemetry.histogram("big")
    h.observe_many(float(i) for i in range(100_000))
    s = h.summary()
    assert s["count"] == 100_000
    assert s["sum"] == pytest.approx(sum(range(100_000)))
    assert len(h._sample) <= 4096
    # uniform reservoir over 0..1e5: p50 within a loose band
    assert 30_000 < s["p50"] < 70_000
    assert s["min"] == 0.0 and s["max"] == 99_999.0
    # the vectorized bulk path and the scalar path agree on exact stats
    h2 = telemetry.histogram("big_np")
    h2.observe_many(np.arange(100_000, dtype=np.int32))  # array input
    for k in ("count", "sum", "min", "max"):
        assert h2.summary()[k] == s[k]


def test_histogram_summary_empty_and_single_value():
    h = telemetry.histogram("edge")
    assert h.summary() == {"count": 0}  # empty: count only, no percentiles
    h.observe_many([])  # empty bulk observe: a no-op, not an error
    h.observe_many(iter(()))  # empty ITERATOR (no __len__) too
    assert h.summary() == {"count": 0}
    h.observe_many([2.5])  # single value: every stat collapses onto it
    s = h.summary()
    assert s["count"] == 1
    assert s["sum"] == s["min"] == s["max"] == s["mean"] == 2.5
    assert all(s[f"p{p}"] == 2.5 for p in (5, 25, 50, 75, 95, 99))


def test_histogram_observe_many_reservoir_cap_overflow():
    """Bulk observes that CROSS the reservoir cap keep exact aggregate
    stats, a bounded sample, and in-range percentiles."""
    h = telemetry.histogram("cap_cross")
    h.observe_many(np.arange(4000, dtype=np.float64))  # under cap (4096)
    assert len(h._sample) == 4000
    h.observe_many(np.arange(4000, 50_000, dtype=np.float64))  # crosses it
    s = h.summary()
    assert s["count"] == 50_000
    assert s["sum"] == pytest.approx(sum(range(50_000)))
    assert s["min"] == 0.0 and s["max"] == 49_999.0
    assert len(h._sample) == 4096  # cap held after the crossing
    assert all(0.0 <= v <= 49_999.0 for v in h._sample)
    # another bulk round entirely IN the replacement regime
    h.observe_many(np.full(10_000, -7.0))
    assert h.summary()["count"] == 60_000
    assert h.summary()["min"] == -7.0
    assert len(h._sample) == 4096
    # a scalar observe after bulk stays consistent too
    h.observe(123.0)
    assert h.summary()["count"] == 60_001


def test_metrics_flush_jsonl(tmp_path):
    telemetry.counter("x").inc(3)
    path = tmp_path / "metrics.jsonl"
    snap = telemetry.flush_metrics(str(path))
    telemetry.counter("x").inc()
    telemetry.flush_metrics(str(path))  # appends
    lines = [json.loads(x) for x in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["type"] == "metrics"
    assert lines[0]["snapshot"]["counters"]["x"] == 3
    assert lines[1]["snapshot"]["counters"]["x"] == 4
    assert snap["counters"]["x"] == 3


def test_metrics_thread_safety():
    c = telemetry.counter("threaded")

    def spin():
        for _ in range(10_000):
            c.inc()

    threads = [threading.Thread(target=spin) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40_000


# -- device accounting -------------------------------------------------------


def test_sync_fetch_counts_fetches_bytes_and_span_event():
    import jax.numpy as jnp

    x = jnp.arange(8, dtype=jnp.float32)
    with telemetry.span("host"):
        out = telemetry.sync_fetch(x, label="t")
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, np.arange(8, dtype=np.float32))
    snap = telemetry.snapshot()
    assert snap["counters"]["device_fetches"] == 1
    assert snap["counters"]["device_fetch_bytes"] == 32
    assert snap["counters"]["device_fetch_seconds"] >= 0
    (s,) = telemetry.finished_spans("host")
    assert s.events and s.events[0]["name"] == "device_fetch"
    assert s.events[0]["attrs"]["bytes"] == 32


def test_compile_hook_counts_jit_compiles():
    import jax
    import jax.numpy as jnp

    assert telemetry.install_compile_hooks()
    before = telemetry.snapshot()["counters"].get("jit_compiles", 0)
    # a fresh closure + unusual shape forces a fresh XLA compile
    salt = len(telemetry.finished_spans()) + 17.5

    @jax.jit
    def fresh(v):
        return v * salt + jnp.tanh(v)

    with telemetry.span("compile_here"):
        fresh(jnp.ones((3, 5, 7)))
    after = telemetry.snapshot()["counters"].get("jit_compiles", 0)
    assert after >= before + 1
    assert telemetry.snapshot()["histograms"]["jit_compile_seconds"]["count"] >= 1
    (s,) = telemetry.finished_spans("compile_here")
    assert any(e["name"] == "compile" for e in s.events)


# -- timing integration ------------------------------------------------------


def test_timer_uses_monotonic_clock(monkeypatch):
    import time as _time

    from photon_ml_tpu.utils.timing import Timer

    t = Timer().start()
    # a wall-clock step must NOT affect the measured duration
    monkeypatch.setattr(
        _time, "time", lambda: _time.monotonic() + 3600.0
    )
    assert t.stop() < 60.0


def test_timed_opens_a_span_and_logs(caplog):
    import logging

    from photon_ml_tpu.utils.timing import timed

    with caplog.at_level(logging.INFO, logger="photon_ml_tpu"):
        with timed("phase_x") as t:
            pass
    assert t.seconds >= 0.0
    assert any("phase_x" in r.message for r in caplog.records)
    (s,) = telemetry.finished_spans("phase_x")
    assert s.dur is not None


def test_setup_logging_file_handler_uses_abspath(tmp_path, monkeypatch):
    import logging

    from photon_ml_tpu.utils.timing import setup_logging

    root = logging.getLogger("photon_ml_tpu")
    old = list(root.handlers)
    root.handlers = []
    try:
        monkeypatch.chdir(tmp_path)
        setup_logging(log_file="rel.log")
        (h,) = [x for x in root.handlers if isinstance(x, logging.FileHandler)]
        assert os.path.isabs(h.baseFilename)
        assert h.baseFilename == str(tmp_path / "rel.log")
        # dedup agrees with the handler path: re-adding is a no-op
        setup_logging(log_file=str(tmp_path / "rel.log"))
        assert (
            len([x for x in root.handlers
                 if isinstance(x, logging.FileHandler)]) == 1
        )
        h.close()
    finally:
        root.handlers = old


# -- event bus ---------------------------------------------------------------


def test_emitter_register_idempotent_and_unregister():
    from photon_ml_tpu.utils.events import EventEmitter, TrainingStartEvent

    seen = []
    em = EventEmitter()
    em.register(seen.append)
    em.register(seen.append)  # duplicate: must NOT double-fire
    em.send(TrainingStartEvent(num_rows=1))
    assert len(seen) == 1
    em.unregister(seen.append)
    em.unregister(seen.append)  # unknown: no-op
    em.send(TrainingStartEvent(num_rows=2))
    assert len(seen) == 1


def test_emitter_send_counts_per_event_type():
    from photon_ml_tpu.utils.events import (
        EventEmitter,
        TrainingFinishEvent,
        TrainingStartEvent,
    )

    em = EventEmitter()
    em.send(TrainingStartEvent(num_rows=1))
    em.send(TrainingStartEvent(num_rows=2))
    em.send(TrainingFinishEvent(best_metric=None, seconds=0.0))
    c = telemetry.snapshot()["counters"]
    assert c["events.TrainingStartEvent"] == 2
    assert c["events.TrainingFinishEvent"] == 1


def test_load_listener_error_paths():
    from photon_ml_tpu.utils.events import load_listener

    # importable fixture module with the three shapes under test
    mod = types.ModuleType("_telemetry_listener_fixture")

    class Listener:
        def __init__(self):
            self.events = []

        def __call__(self, event):
            self.events.append(event)

    class Needy:
        def __init__(self, required):
            pass

    mod.Listener = Listener
    mod.Needy = Needy
    mod.NOT_CALLABLE = 42
    sys.modules["_telemetry_listener_fixture"] = mod
    try:
        # classes are instantiated (newInstance() analog)
        fn = load_listener("_telemetry_listener_fixture:Listener")
        fn("evt")
        assert fn.events == ["evt"]
        # bad spec: no dots at all
        with pytest.raises(ValueError, match="dotted path"):
            load_listener("nodots")
        # resolves but is not callable
        with pytest.raises(ValueError, match="not callable"):
            load_listener("_telemetry_listener_fixture:NOT_CALLABLE")
        # class whose zero-arg instantiation fails
        with pytest.raises(ValueError, match="cannot load"):
            load_listener("_telemetry_listener_fixture:Needy")
        # missing module / missing attribute
        with pytest.raises(ValueError, match="cannot load"):
            load_listener("no.such.module:thing")
        with pytest.raises(ValueError, match="cannot load"):
            load_listener("_telemetry_listener_fixture:missing")
    finally:
        del sys.modules["_telemetry_listener_fixture"]


# -- tracker telemetry -------------------------------------------------------


def test_re_tracker_from_device_parts_empty():
    from photon_ml_tpu.optim.trackers import RandomEffectOptimizationTracker

    t = RandomEffectOptimizationTracker.from_device_parts([], [], [])
    assert len(t.iterations) == 0 and len(t.reasons) == 0
    assert t.final_values is not None and len(t.final_values) == 0
    assert t.iteration_stats()["count"] == 0
    assert t.count_convergence_reasons() == {}
    pcts = t.percentile_summary()
    assert pcts["iterations"] == {f"p{p}": 0.0 for p in (5, 25, 50, 75, 95)}
    assert t.to_summary_string().startswith("entities=0")


def test_re_tracker_from_device_parts_single_entity_round_trip():
    import jax.numpy as jnp

    from photon_ml_tpu.optim.trackers import RandomEffectOptimizationTracker

    t = RandomEffectOptimizationTracker.from_device_parts(
        [jnp.asarray([5], jnp.int32)],
        [jnp.asarray([1], jnp.int32)],
        [jnp.asarray([0.125], jnp.float32)],
    )
    np.testing.assert_array_equal(t.iterations, [5])
    np.testing.assert_array_equal(t.reasons, [1])
    # the f32 terminal value must survive the i32 bitcast ride exactly
    np.testing.assert_array_equal(t.final_values, np.float32([0.125]))
    pcts = t.percentile_summary()
    assert all(v == 5.0 for v in pcts["iterations"].values())
    assert all(v == pytest.approx(0.125) for v in pcts["final_loss"].values())
    # the packed crossing is accounted as ONE device fetch
    snap = telemetry.snapshot()
    assert snap["counters"]["device_fetches"] == 1
    assert snap["counters"]["re_solved_entities"] == 1
    assert snap["histograms"]["re_solve_iterations"]["count"] == 1


def test_fe_tracker_feeds_histogram():
    from photon_ml_tpu.optim.trackers import FixedEffectOptimizationTracker

    class _Res:
        iterations = 7
        reason = 0
        value = 0.5
        grad_norms = np.zeros(8)

    t = FixedEffectOptimizationTracker.from_result(_Res())
    assert t.iterations == 7
    snap = telemetry.snapshot()
    assert snap["counters"]["fe_solves"] == 1
    assert snap["histograms"]["fe_solve_iterations"]["count"] == 1


# -- lint gate ---------------------------------------------------------------


def test_check_lint_rejects_fake_timing_in_library_code(tmp_path):
    # the _Lint monolith moved into the tools.analysis package (ISSUE 7);
    # the per-file rules live in LocalLint and emit structured findings
    import ast

    from tools.analysis.local import LocalLint

    src = (
        "import time\n"
        "import jax\n"
        "def f(x):\n"
        "    t0 = time.time()\n"
        "    jax.block_until_ready(x)\n"
        "    return time.monotonic() - t0\n"
    )
    # from-import forms must not evade the rules
    evasive = (
        "from time import time as now\n"
        "from jax import block_until_ready\n"
        "def f(x):\n"
        "    t0 = now()\n"
        "    block_until_ready(x)\n"
        "    return t0\n"
    )
    ev = LocalLint("photon_ml_tpu/z.py", ast.parse(evasive), library=True)
    ev_codes = [f.code for f in ev.findings]
    assert "L006" in ev_codes and "L007" in ev_codes
    tree = ast.parse(src)
    lib = LocalLint("photon_ml_tpu/x.py", tree, library=True)
    codes = [f.code for f in lib.findings]
    assert "L006" in codes and "L007" in codes
    # benches/tests keep their freedom
    bench = LocalLint("bench.py", ast.parse(src), library=False)
    assert not any(f.code in ("L006", "L007") for f in bench.findings)
    # a USED result is not flagged (only bare statements are timing syncs)
    used = ast.parse("import jax\ndef g(x):\n    return jax.block_until_ready(x)\n")
    lib2 = LocalLint("photon_ml_tpu/y.py", used, library=True)
    assert not any(f.code == "L007" for f in lib2.findings)


def test_check_lint_rejects_bare_print_in_library_code():
    """L009 satellite: bare print() is rejected in library code, allowed
    in CLI modules (stdout is their interface) and in benches/tests."""
    import ast

    from tools.analysis.local import LocalLint

    src = 'def f():\n    print("hi")\n'
    lib = LocalLint("photon_ml_tpu/game/x.py", ast.parse(src), library=True)
    assert any(f.code == "L009" for f in lib.findings)
    cli = LocalLint(
        "photon_ml_tpu/cli/train.py", ast.parse(src), library=True
    )
    assert not any(f.code == "L009" for f in cli.findings)
    bench = LocalLint("bench.py", ast.parse(src), library=False)
    assert not any(f.code == "L009" for f in bench.findings)
    # method calls named print (e.g. logger-ish objects) are not flagged
    method = LocalLint(
        "photon_ml_tpu/game/y.py",
        ast.parse("def f(doc):\n    doc.print()\n"),
        library=True,
    )
    assert not any(f.code == "L009" for f in method.findings)


# -- reset / env configuration ------------------------------------------------


def test_reset_restores_configure_from_env_state(tmp_path, monkeypatch):
    """Satellite: reset() must fully restore defaults — the env-registered
    atexit flush and env-pointed trace sink must not leak across tests."""
    import atexit

    metrics_out = tmp_path / "env.metrics.jsonl"
    trace_out = tmp_path / "env.trace.jsonl"
    monkeypatch.setenv("PHOTON_TELEMETRY_OUT", str(metrics_out))
    monkeypatch.setenv("PHOTON_TRACE_OUT", str(trace_out))
    telemetry.configure_from_env()
    flush = telemetry._env_state["atexit_flush"]
    assert flush is not None
    assert ttrace.TRACER._sink_path == str(trace_out)
    # calling again replaces (not stacks) the atexit registration
    telemetry.configure_from_env()
    assert telemetry._env_state["atexit_flush"] is not flush

    telemetry.reset()
    assert telemetry._env_state["atexit_flush"] is None
    assert ttrace.TRACER._sink_path is None
    # the unregistered flush must NOT fire at exit: registering the stale
    # handle again would be the leak; simulate by checking unregister took
    atexit.unregister(flush)  # no-op either way; just must not raise

    # stats-provider injection is also restored by reset()
    from photon_ml_tpu.telemetry import memory

    memory.set_stats_provider(lambda: {"bytes_in_use": 1, "bytes_limit": 2})
    assert memory.hbm_stats() == {"bytes_in_use": 1, "bytes_limit": 2}
    telemetry.reset()
    assert memory._stats_provider is None
