"""GAME layer tests: bucketing/projection correctness, vmapped RE solves vs
per-entity references, coordinate descent on synthetic GLMix data."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
    ValidationSpec,
    build_game_dataset,
    build_random_effect_dataset,
    run_coordinate_descent,
)
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    glm_adapter,
    lbfgs_solve,
)


def _glmix_data(rng, n=600, d_global=12, n_users=25, d_user=6, noise=0.3):
    """response = sigmoid(X_g w_g + X_u w_u[user]) — FE + per-user RE."""
    Xg = rng.normal(size=(n, d_global)) * (rng.random((n, d_global)) < 0.5)
    Xu = rng.normal(size=(n, d_user)) * (rng.random((n, d_user)) < 0.7)
    users = rng.integers(0, n_users, size=n)
    wg = rng.normal(size=d_global)
    wu = rng.normal(size=(n_users, d_user)) * 1.5
    margin = Xg @ wg + np.einsum("ij,ij->i", Xu, wu[users])
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(float)

    gds = build_game_dataset(
        response=y,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y),
            "user": SparseBatch.from_dense(Xu, y),
        },
        id_columns={"userId": [f"u{u:03d}" for u in users]},
    )
    return gds, Xg, Xu, users, wg, wu


_CFG = OptimizerConfig(
    optimizer_type=OptimizerType.LBFGS,
    max_iterations=50,
    tolerance=1e-7,
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=1.0,
)


def test_bucketing_roundtrip(rng):
    gds, Xg, Xu, users, *_ = _glmix_data(rng, n=200, n_users=10)
    red = build_random_effect_dataset(gds, "userId", "user")
    # every example row appears exactly once across buckets
    seen = []
    for b in red.buckets:
        idx = np.asarray(b.row_index).reshape(-1)
        seen.extend(idx[idx >= 0].tolist())
    assert sorted(seen) == list(range(200))
    # projection reconstructs the original features
    for b in red.buckets:
        E = b.num_entities
        for e in range(min(E, 3)):
            proj = np.asarray(b.projection[e])
            vals = np.asarray(b.values[e])
            lrows = np.asarray(b.rows[e])
            lcols = np.asarray(b.cols[e])
            ridx = np.asarray(b.row_index[e])
            for v, lr, lc in zip(vals, lrows, lcols):
                if v == 0:
                    continue
                grow = ridx[lr]
                gcol = proj[lc]
                assert np.isclose(Xu[grow, gcol], v, atol=1e-5)


@pytest.mark.slow
def test_re_coordinate_matches_per_entity_solves(rng):
    gds, Xg, Xu, users, *_ = _glmix_data(rng, n=300, n_users=8)
    red = build_random_effect_dataset(gds, "userId", "user")
    coord = RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG)
    model = coord.update_model(coord.initialize_model(), None)

    # reference: solve each entity independently with the same optimizer
    obj = make_objective("logistic", l2_weight=1.0)
    vocab = gds.id_columns["userId"].vocab
    for code in range(min(len(vocab), 5)):
        rows = np.where(gds.id_columns["userId"].codes == code)[0]
        sub = Xu[rows]
        support = np.where(np.any(sub != 0, axis=0))[0]
        ref_batch = SparseBatch.from_dense(
            sub[:, support], gds.response[rows], weights=gds.weight[rows]
        )
        ref = lbfgs_solve(
            glm_adapter(obj, ref_batch), jnp.zeros(len(support), jnp.float32)
        )
        b_idx, pos = red.entity_bucket[code], red.entity_pos[code]
        bm = model.buckets[b_idx]
        proj = np.asarray(bm.projection[pos])
        w_game = np.asarray(bm.coefficients[pos])[np.searchsorted(proj, support)]
        np.testing.assert_allclose(w_game, np.asarray(ref.w), rtol=2e-2, atol=2e-2)


@pytest.mark.slow
def test_re_scores_match_dense_computation(rng):
    gds, Xg, Xu, users, *_ = _glmix_data(rng, n=250, n_users=7)
    red = build_random_effect_dataset(gds, "userId", "user")
    coord = RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG)
    model = coord.update_model(coord.initialize_model(), None)

    scores_fast = np.asarray(coord.score(model))[:250]
    scores_model = np.asarray(model.score(gds))[:250]
    np.testing.assert_allclose(scores_fast, scores_model, rtol=1e-3, atol=1e-3)

    # dense check: scores = Xu . w_user
    codes = gds.id_columns["userId"].codes
    for i in list(range(0, 250, 37)):
        code = codes[i]
        b_idx, pos = red.entity_bucket[code], red.entity_pos[code]
        bm = model.buckets[b_idx]
        proj = np.asarray(bm.projection[pos])
        w_dense = np.zeros(Xu.shape[1])
        valid = proj < Xu.shape[1]
        w_dense[proj[valid]] = np.asarray(bm.coefficients[pos])[valid]
        np.testing.assert_allclose(
            scores_fast[i], Xu[i] @ w_dense, rtol=1e-3, atol=1e-3
        )


@pytest.mark.slow
def test_coordinate_descent_glmix_beats_fe_only(rng):
    gds, Xg, Xu, users, wg, wu = _glmix_data(rng, n=600, n_users=20)
    red = build_random_effect_dataset(gds, "userId", "user")
    val = ValidationSpec(data=gds, evaluators=["auc", "logistic_loss"])

    fe_only = run_coordinate_descent(
        {"fixed": FixedEffectCoordinate("fixed", gds, "global", "logistic", _CFG)},
        task="logistic",
        num_iterations=1,
        validation=val,
    )
    full = run_coordinate_descent(
        {
            "fixed": FixedEffectCoordinate("fixed", gds, "global", "logistic", _CFG),
            "per-user": RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG),
        },
        task="logistic",
        num_iterations=2,
        validation=val,
    )
    assert full.best_metric > fe_only.best_metric + 0.02, (
        f"GLMix {full.best_metric} should beat FE-only {fe_only.best_metric}"
    )
    # residual trick: history has metrics for every (iter, coordinate)
    assert len(full.history) == 4
    assert full.history[-1]["metrics"]["auc"] == pytest.approx(
        max(h["metrics"]["auc"] for h in full.history), abs=0.05
    )


@pytest.mark.slow
def test_best_model_tracking(rng):
    gds, *_ = _glmix_data(rng, n=200, n_users=6)
    red = build_random_effect_dataset(gds, "userId", "user")
    val = ValidationSpec(data=gds, evaluators=["logistic_loss"])  # minimize
    res = run_coordinate_descent(
        {
            "fixed": FixedEffectCoordinate("fixed", gds, "global", "logistic", _CFG),
            "per-user": RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG),
        },
        task="logistic",
        num_iterations=2,
        validation=val,
    )
    losses = [h["metrics"]["logistic_loss"] for h in res.history]
    assert res.best_metric == pytest.approx(min(losses))


def test_active_data_cap_and_passive_scoring(rng):
    gds, Xg, Xu, users, *_ = _glmix_data(rng, n=400, n_users=5)
    red = build_random_effect_dataset(
        gds, "userId", "user", active_rows_per_entity=32, seed=3
    )
    assert len(red.passive_rows) > 0
    active_count = sum(
        int((np.asarray(b.weights) > 0).sum()) for b in red.buckets
    )
    assert active_count + len(red.passive_rows) == 400
    # capped rows carry rescaled weights (sum of active weights ~ total)
    total_active_w = sum(float(np.asarray(b.weights).sum()) for b in red.buckets)
    assert total_active_w == pytest.approx(400, rel=0.01)

    coord = RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG)
    model = coord.update_model(coord.initialize_model(), None)
    scores = np.asarray(coord.score(model))
    # passive rows scored (non-zero for rows with features)
    pr = red.passive_rows[:20]
    model_scores = np.asarray(model.score(gds))
    np.testing.assert_allclose(scores[pr], model_scores[pr], rtol=1e-4, atol=1e-4)


def test_unseen_entity_scores_zero(rng):
    gds, Xg, Xu, users, *_ = _glmix_data(rng, n=150, n_users=5)
    red = build_random_effect_dataset(gds, "userId", "user")
    coord = RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG)
    model = coord.update_model(coord.initialize_model(), None)

    # scoring data with brand-new users must get zero RE scores
    gds2 = build_game_dataset(
        response=gds.response[:50],
        feature_shards={"user": SparseBatch.from_dense(Xu[:50], gds.response[:50])},
        id_columns={"userId": [f"new{u}" for u in range(50)]},
    )
    s = np.asarray(model.score(gds2))
    np.testing.assert_allclose(s[:50], 0.0, atol=1e-6)


@pytest.mark.slow
def test_fe_down_sampling_resamples_per_update(rng):
    """Regression (ADVICE r1-d): the FE coordinate must draw a FRESH negative
    down-sample on every update_model call (runWithSampling parity), not
    freeze one sample at construction."""
    from photon_ml_tpu.game.coordinates import FixedEffectCoordinate
    from photon_ml_tpu.optim import OptimizerConfig

    n = 200
    X = rng.normal(size=(n, 5))
    y = (rng.random(n) > 0.7).astype(float)
    gds = build_game_dataset(
        response=y, feature_shards={"g": SparseBatch.from_dense(X, y)})
    coord = FixedEffectCoordinate(
        name="fe", data=gds, shard_name="g", loss_name="logistic",
        config=OptimizerConfig(max_iterations=3, down_sampling_rate=0.5),
    )
    b0 = coord._maybe_downsample(coord._base_batch, 0)
    b1 = coord._maybe_downsample(coord._base_batch, 1)
    w0 = np.asarray(b0.weights)
    w1 = np.asarray(b1.weights)
    assert not np.array_equal(w0, w1)  # different draws
    # positives always kept at weight 1; kept negatives reweighted by 1/rate
    pos = np.asarray(coord._base_batch.labels) > 0.5
    real = np.asarray(coord._base_batch.weights) > 0
    np.testing.assert_allclose(w0[pos & real], 1.0)
    kept_neg = (~pos) & real & (w0 > 0)
    np.testing.assert_allclose(w0[kept_neg], 2.0)
    # update_model advances the sample index
    m = coord.initialize_model()
    m = coord.update_model(m, None)
    assert coord._update_count == 1


def test_random_effect_newton_matches_lbfgs(rng):
    """The batched-Newton RE fast path reaches the same per-entity optima
    as vmapped LBFGS."""
    import dataclasses as _dc

    from photon_ml_tpu.game import (
        GameConfig, GameEstimator, RandomEffectConfig, build_game_dataset,
    )
    from photon_ml_tpu.optim import (
        OptimizerConfig, OptimizerType, RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.ops.sparse import SparseBatch

    n_users, rows, d = 12, 20, 6
    n = n_users * rows
    users = np.repeat(np.arange(n_users), rows)
    X = rng.normal(size=(n, d))
    w_u = rng.normal(size=(n_users, d))
    y = np.einsum("nd,nd->n", X, w_u[users]) + 0.05 * rng.normal(size=n)
    data = build_game_dataset(
        response=y,
        feature_shards={"f": SparseBatch.from_dense(X, y)},
        id_columns={"u": users},
    )
    base = OptimizerConfig(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.1,
        tolerance=1e-9,
    )

    def fit(opt_type):
        cfg = GameConfig(
            task="squared",
            coordinates={
                "re": RandomEffectConfig(
                    shard_name="f", id_name="u",
                    optimizer=_dc.replace(base, optimizer_type=opt_type),
                )
            },
        )
        return GameEstimator(cfg).fit(data).model

    m_newton = fit(OptimizerType.NEWTON)
    m_lbfgs = fit(OptimizerType.LBFGS)
    s_n = np.asarray(m_newton.score(data))[:n]
    s_l = np.asarray(m_lbfgs.score(data))[:n]
    np.testing.assert_allclose(s_n, s_l, rtol=5e-3, atol=5e-3)


def test_re_variances_match_hessian_diag(rng):
    """computeVariances parity (SingleNodeOptimizationProblem.scala:57-88):
    RE bucket models carry 1/(diag H(w*) + eps) per entity when configured."""
    import dataclasses as _dc

    import jax

    gds, Xg, Xu, users, *_ = _glmix_data(rng, n=300, n_users=8)
    red = build_random_effect_dataset(gds, "userId", "user")
    coord = RandomEffectCoordinate(
        "per-user", gds, red, "logistic", _CFG, compute_variances=True
    )
    model = coord.update_model(coord.initialize_model(), None)

    obj = make_objective("logistic", l2_weight=1.0)
    checked = 0
    for code in range(len(gds.id_columns["userId"].vocab)):
        b_idx, pos = int(red.entity_bucket[code]), int(red.entity_pos[code])
        if b_idx < 0:
            continue
        bm = model.buckets[b_idx]
        assert bm.variances is not None
        one = jax.tree.map(lambda x: x[pos], red.buckets[b_idx].entity_batch())
        hdiag = np.asarray(obj.hessian_diagonal(bm.coefficients[pos], one))
        np.testing.assert_allclose(
            np.asarray(bm.variances[pos]), 1.0 / (hdiag + 1e-12), rtol=1e-4
        )
        checked += 1
        if checked >= 3:
            break
    assert checked == 3

    # unconfigured fits carry no variances
    plain = RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG)
    m2 = plain.update_model(plain.initialize_model(), None)
    assert all(b.variances is None for b in m2.buckets)


@pytest.mark.slow
def test_re_box_constraints_respected_and_match_reference(rng):
    """Per-entity solves honor GLOBAL-space box constraints through the
    index-map projection (SingleNodeOptimizationProblem.scala:124-139)."""
    import dataclasses as _dc

    from photon_ml_tpu.optim import solve

    gds, Xg, Xu, users, *_ = _glmix_data(rng, n=400, n_users=6)
    red = build_random_effect_dataset(gds, "userId", "user")
    bounds = ((0, -0.05, 0.05), (2, 0.0, float("inf")))
    cfg = _dc.replace(_CFG, box_constraints=bounds)
    coord = RandomEffectCoordinate("per-user", gds, red, "logistic", cfg)
    model = coord.update_model(coord.initialize_model(), None)

    # every entity's coefficient at a bounded global feature is in its box
    for bm in model.buckets:
        proj = np.asarray(bm.projection)
        w = np.asarray(bm.coefficients)
        assert np.all(w[proj == 0] >= -0.05 - 1e-6)
        assert np.all(w[proj == 0] <= 0.05 + 1e-6)
        assert np.all(w[proj == 2] >= -1e-6)

    # parity with an independent constrained solve on one entity
    codes = gds.id_columns["userId"].codes
    code = int(codes[0])
    rows = np.where(codes == code)[0]
    sub = Xu[rows]
    support = np.where(np.any(sub != 0, axis=0))[0]
    local_bounds = tuple(
        (int(np.searchsorted(support, g)), lo, hi)
        for g, lo, hi in bounds
        if g in support
    )
    ref_batch = SparseBatch.from_dense(
        sub[:, support], gds.response[rows], weights=gds.weight[rows]
    )
    ref = solve(
        "logistic",
        ref_batch,
        _dc.replace(cfg, box_constraints=local_bounds),
        jnp.zeros(len(support), jnp.float32),
    )
    b_idx, pos = red.entity_bucket[code], red.entity_pos[code]
    bm = model.buckets[b_idx]
    proj = np.asarray(bm.projection[pos])
    w_game = np.asarray(bm.coefficients[pos])[np.searchsorted(proj, support)]
    np.testing.assert_allclose(w_game, np.asarray(ref.w), rtol=2e-2, atol=2e-2)


def _trained_re_model(rng, n=250, n_users=7):
    """(dataset, model, Xu) for the RE scoring-kernel tests below."""
    gds, _Xg, Xu, _users, _wg, _wu = _glmix_data(rng, n=n, n_users=n_users)
    red = build_random_effect_dataset(gds, "userId", "user")
    coord = RandomEffectCoordinate("per-user", gds, red, "logistic", _CFG)
    model = coord.update_model(coord.initialize_model(), None)
    return gds, model, Xu


def _pad_local_dim(model, num_global, new_k):
    """The same RE model with every bucket's local dim padded to ``new_k``
    (sentinel projections, zero coefficients) — semantically identical,
    but scored through the K>64 searchsorted kernel when new_k > 64."""
    import dataclasses

    buckets = []
    for bm in model.buckets:
        num_e, k = bm.projection.shape
        proj = np.full((num_e, new_k), num_global, np.int32)
        proj[:, :k] = np.asarray(bm.projection)
        coef = np.zeros((num_e, new_k), np.float32)
        coef[:, :k] = np.asarray(bm.coefficients)
        buckets.append(
            dataclasses.replace(
                bm,
                projection=jnp.asarray(proj),
                coefficients=jnp.asarray(coef),
                variances=None,
            )
        )
    return dataclasses.replace(model, buckets=tuple(buckets))


def test_re_score_kernel_parity_compare_scan_vs_searchsorted(rng):
    """K<=64 (transposed compare-scan) and K>64 (vmapped searchsorted)
    paths must agree on the same data: pad the projection past the kernel
    switchover with sentinels and assert identical scores."""
    gds, model, Xu = _trained_re_model(rng)
    small_k = np.asarray(model.score(gds))[: gds.num_rows]
    assert model.buckets[0].projection.shape[1] <= 64  # compare-scan path
    padded = _pad_local_dim(model, num_global=Xu.shape[1], new_k=65)
    assert padded.buckets[0].projection.shape[1] > 64  # searchsorted path
    large_k = np.asarray(padded.score(gds))[: gds.num_rows]
    np.testing.assert_allclose(small_k, large_k, rtol=1e-6, atol=1e-6)


def test_re_score_chunk_boundary(rng, monkeypatch):
    """Scores must not depend on the nnz chunking: shrink SCORE_CHUNK so
    every bucket crosses the boundary several times and compare against
    the unchunked result."""
    from photon_ml_tpu.game import models as models_mod

    gds, model, _Xu = _trained_re_model(rng)
    unchunked = np.asarray(model.score(gds))[: gds.num_rows]
    nnz = int(np.sum(np.asarray(gds.shard("user").values) != 0))
    assert nnz > 7  # the patched chunk really splits the work
    monkeypatch.setattr(models_mod, "SCORE_CHUNK", 7)
    chunked = np.asarray(model.score(gds))[: gds.num_rows]
    np.testing.assert_allclose(chunked, unchunked, rtol=1e-6, atol=1e-6)


def test_re_grouping_memoized_per_model_and_dataset(rng):
    """Repeated scoring of one dataset must not redo the host-side
    vocabulary join / bucket grouping (validation every CD iteration);
    a DIFFERENT model on the same dataset must not reuse stale arrays."""
    from photon_ml_tpu import telemetry

    gds, model, Xu = _trained_re_model(rng)
    counters = lambda: telemetry.snapshot()["counters"]  # noqa: E731
    model.score(gds)
    assert counters().get("scoring.code_cache.misses", 0) == 1
    first = model._codes_for(gds)
    second = model._codes_for(gds)
    assert first is second  # cached object, not a recomputed copy
    model.score(gds)
    assert counters().get("scoring.code_cache.misses", 0) == 1
    assert counters().get("scoring.code_cache.hits", 0) >= 3
    # a different model (its own vocab/placement identities) recomputes
    other = _pad_local_dim(model, num_global=Xu.shape[1], new_k=65)
    other = other.__class__(
        id_name=other.id_name,
        shard_name=other.shard_name,
        buckets=other.buckets,
        entity_bucket=other.entity_bucket.copy(),
        entity_pos=other.entity_pos.copy(),
        vocab=other.vocab.copy(),
    )
    other.score(gds)
    assert counters().get("scoring.code_cache.misses", 0) == 2
