"""Continuous-freshness loop: incremental warm-start retrains (ISSUE 14).

The acceptance spine: full fit → checkpoint → 5% delta → warm-start
refresh produces a model whose untouched RE lanes are BIT-IDENTICAL to
the base, whose validation metric matches a from-scratch fit on the
combined data within tolerance, and whose solve-count/lane-skip
telemetry proves the structural speedup (re-solved lanes ≈ the touched
fraction, zero-touched bucket solves skipped entirely). Plus the
satellites: streaming-checkpoint warm starts with vocabulary growth
(new rows zero-init, existing rows bit-identical, indivisible-axis
errors typed), registry lineage on /healthz and in `cli report`, the
incremental fault seams ("incremental.warm_restore",
"incremental.delta_scan", "incremental.publish" — L016 coverage), and
the crash row: a hard kill at incremental.publish leaves the base
checkpoint and the registry intact.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu import incremental, telemetry
from photon_ml_tpu.faults import (
    FaultPlan,
    FaultRule,
    InjectedFault,
    clear_plan,
    install_plan,
)
from photon_ml_tpu.game import (
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    RandomEffectConfig,
    build_game_dataset,
)
from photon_ml_tpu.game.checkpoint import CheckpointSpec
from photon_ml_tpu.game.coordinate_descent import ValidationSpec, _evaluate
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_D = 8
_N_USERS = 40
_TOUCHED = (3, 17)  # base users the delta touches; plus one NEW user


def _build(Xm, us, ys):
    r, c = np.nonzero(Xm)
    b = SparseBatch.from_coo(
        values=Xm[r, c], rows=r, cols=c, labels=ys, num_features=_D
    )
    return build_game_dataset(
        response=ys,
        feature_shards={"g": b},
        id_columns={"userId": np.array([f"u{u:03d}" for u in us])},
    )


def _opt(**kw):
    base = dict(
        max_iterations=50,
        tolerance=1e-8,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    base.update(kw)
    return OptimizerConfig(**base)


def _config(**kw):
    return GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="g", optimizer=_opt()),
            "perUser": RandomEffectConfig(
                shard_name="g", id_name="userId", optimizer=_opt()
            ),
        },
        num_iterations=2,
        evaluators=["auc"],
        **kw,
    )


@pytest.fixture(scope="module")
def glmix(tmp_path_factory):
    """Base fit + checkpoint, delta, combined, incremental refresh, and
    the from-scratch reference — the whole acceptance spine, built once."""
    rng = np.random.default_rng(7)
    tmp = tmp_path_factory.mktemp("incremental")
    n_base = 2000
    X = rng.normal(size=(n_base, _D))
    users = rng.integers(0, _N_USERS, n_base)
    w = rng.normal(size=_D)
    u_eff = rng.normal(size=_N_USERS + 1) * 0.8

    def make_rows(Xm, us):
        logits = Xm @ w + u_eff[us]
        return (rng.random(len(us)) < 1 / (1 + np.exp(-logits))).astype(
            float
        )

    y_base = make_rows(X, users)
    base_data = _build(X, users, y_base)
    # ~5% delta: 2 touched existing users + 1 genuinely NEW user
    du = np.array(list(_TOUCHED) * 15 + [_N_USERS] * 10)
    Xd = rng.normal(size=(len(du), _D))
    yd = make_rows(Xd, du)
    comb_data = _build(
        np.vstack([X, Xd]),
        np.concatenate([users, du]),
        np.concatenate([y_base, yd]),
    )
    delta_data = _build(Xd, du, yd)
    Xv = rng.normal(size=(800, _D))
    uv = rng.integers(0, _N_USERS, 800)
    val_data = _build(Xv, uv, make_rows(Xv, uv))

    ckpt = str(tmp / "base-ckpt")
    config = _config()
    est = GameEstimator(config)
    base_fit = est.fit(
        base_data,
        validation_data=val_data,
        checkpoint_spec=CheckpointSpec(directory=ckpt, resume=False),
    )
    telemetry.reset()
    ws = incremental.load_warm_start(ckpt)
    scan = incremental.scan_delta(
        delta_data, {"userId": ws.model.models["perUser"].vocab}
    )
    res = GameEstimator(config).fit_incremental(
        comb_data, ws, delta=scan, validation_data=val_data
    )
    # telemetry is reset after every test (conftest isolation), so the
    # counters/spans of the incremental fit — and the report built from
    # them — must be captured NOW, inside the fixture
    snap = telemetry.snapshot()
    from photon_ml_tpu.telemetry.report import RunReport

    report = RunReport.from_live()
    ref = GameEstimator(config).fit(comb_data, validation_data=val_data)
    return dict(
        tmp=tmp, ckpt=ckpt, config=config, base_fit=base_fit, ws=ws,
        scan=scan, res=res, ref=ref, comb_data=comb_data,
        delta_data=delta_data, val_data=val_data, snap=snap,
        report=report,
    )


def _entity_coeffs(model, coord="perUser"):
    """entity value -> {global feature id: coefficient} (geometry-free;
    untouched entities keep identical geometry base-vs-refreshed, so
    dict equality IS bitwise row equality)."""
    re = model.models[coord]
    out = {}
    for bm in re.buckets:
        P = np.asarray(bm.projection)
        W = np.asarray(bm.coefficients)
        codes = np.asarray(bm.entity_codes)
        for e in range(len(codes)):
            val = re.vocab[codes[e]]
            out[val] = {
                int(g): float(W[e, k]) for k, g in enumerate(P[e])
            }
    return out


# ---------------------------------------------------------------------------
# warm-start loading + lineage
# ---------------------------------------------------------------------------


def test_load_warm_start_step_kind_records_lineage(glmix):
    ws = glmix["ws"]
    assert ws.lineage.kind == "step"
    assert ws.lineage.step == 3  # 2 iterations x 2 coordinates - 1
    assert ws.lineage.digest and len(ws.lineage.digest) == 64
    assert ws.model is not None and "perUser" in ws.model.models
    doc = ws.lineage.to_json()
    assert doc["kind"] == "step" and doc["checkpoint_dir"] == os.path.abspath(
        glmix["ckpt"]
    )


def test_load_warm_start_model_dir_kind(glmix, tmp_path):
    from photon_ml_tpu.data.model_store import save_game_model

    save_game_model(glmix["base_fit"].model, str(tmp_path / "m"))
    ws = incremental.load_warm_start(str(tmp_path / "m"))
    assert ws.lineage.kind == "model"
    assert ws.model.models.keys() == glmix["base_fit"].model.models.keys()


def test_load_warm_start_bad_dirs_are_typed(tmp_path):
    with pytest.raises(incremental.WarmStartError, match="does not exist"):
        incremental.load_warm_start(str(tmp_path / "nope"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(incremental.WarmStartError, match="nothing to"):
        incremental.load_warm_start(str(empty))


# ---------------------------------------------------------------------------
# the acceptance spine
# ---------------------------------------------------------------------------


def test_untouched_lanes_bit_identical_to_base(glmix):
    base_map = _entity_coeffs(glmix["base_fit"].model)
    inc_map = _entity_coeffs(glmix["res"].model)
    touched_vals = {f"u{u:03d}" for u in _TOUCHED}
    checked = 0
    for val, coeffs in base_map.items():
        if val in touched_vals:
            continue
        checked += 1
        for g, wv in coeffs.items():
            # exact float equality: the untouched lane was transplanted
            # by element take and never re-solved
            assert inc_map[val][g] == wv, (val, g)
    assert checked >= _N_USERS - len(_TOUCHED) - 2


def test_touched_and_new_lanes_did_resolve(glmix):
    base_map = _entity_coeffs(glmix["base_fit"].model)
    inc_map = _entity_coeffs(glmix["res"].model)
    for u in _TOUCHED:
        val = f"u{u:03d}"
        assert any(
            inc_map[val][g] != wv for g, wv in base_map[val].items()
        ), f"touched entity {val} kept its base coefficients"
    # the NEW user exists only in the refreshed model, with a real solve
    new_val = f"u{_N_USERS:03d}"
    assert new_val not in base_map
    assert any(abs(v) > 1e-8 for v in inc_map[new_val].values())
    assert glmix["res"].new_entities >= 1


def test_quality_matches_from_scratch_fit(glmix):
    spec = ValidationSpec(data=glmix["val_data"], evaluators=["auc"])
    m_inc = _evaluate(glmix["res"].model, spec)["auc"]
    m_ref = _evaluate(glmix["ref"].model, spec)["auc"]
    assert abs(m_inc - m_ref) < 0.02, (m_inc, m_ref)


def test_structural_speedup_lane_telemetry(glmix):
    res = glmix["res"]
    # 3 touched entities (2 existing + 1 new) out of 41 active: the
    # re-solved lane set must be the touched set, nothing more — the
    # structural form of the >=10x time-to-fresh claim
    assert res.lanes_solved >= 3
    assert res.lanes_skipped > 10 * res.lanes_solved / 2  # >~5x lanes kept
    total = res.lanes_solved + res.lanes_skipped
    assert res.lanes_solved / total < 0.2
    assert res.buckets_skipped >= 1  # some bucket held zero touched
    assert res.bucket_solves >= 1
    snap = glmix["snap"]["counters"]
    assert snap.get("incremental.lanes_solved", 0) >= res.lanes_solved
    assert snap.get("incremental.buckets_skipped", 0) >= res.buckets_skipped


def test_freshness_report_round_trip(glmix):
    report = glmix["report"]
    fresh = report.freshness_summary()
    assert fresh is not None
    assert fresh["lanes_solved"] >= 3
    assert fresh["lanes_skipped"] > 0
    assert 0 < fresh["lanes_solved_fraction"] < 0.5
    assert fresh["touched_fraction"] == pytest.approx(3 / 41, abs=0.05)
    md = report.to_markdown()
    assert "## Freshness" in md
    assert "kept bit-identical" in md
    doc = report.to_json()
    assert doc["freshness"]["lanes_solved"] == fresh["lanes_solved"]
    assert "time_to_fresh_s" in report.key_metrics()


def test_incremental_refuses_checkpointing_into_its_base(glmix):
    with pytest.raises(incremental.WarmStartError, match="base"):
        GameEstimator(glmix["config"]).fit_incremental(
            glmix["comb_data"],
            glmix["ws"],
            delta=glmix["scan"],
            checkpoint_spec=CheckpointSpec(directory=glmix["ckpt"]),
        )


def test_local_lambda_sweep_selects_with_policies(glmix):
    factors = incremental.local_lambda_factors(points=3, span=4.0)
    assert factors == [4.0, 1.0, 0.25]
    res = GameEstimator(glmix["config"]).fit_incremental(
        glmix["comb_data"],
        glmix["ws"],
        delta=glmix["scan"],
        validation_data=glmix["val_data"],
        lambda_factors=factors,
        policy="parsimonious",
        rel_tol=0.05,
    )
    sel = res.selection
    assert sel is not None and sel.policy == "parsimonious"
    assert len(sel.metrics) == 3 and np.isfinite(sel.metrics).all()
    assert sel.metric == "auc"
    # parsimonious ties toward the MORE regularized (lower index) lane
    best = int(np.nanargmax(sel.metrics))
    assert sel.index <= best
    # untouched lanes stay bit-identical through the whole sweep
    base_map = _entity_coeffs(glmix["base_fit"].model)
    inc_map = _entity_coeffs(res.model)
    untouched = f"u{(set(range(_N_USERS)) - set(_TOUCHED)).pop():03d}"
    assert inc_map[untouched] == base_map[untouched]


def test_entity_absent_from_base_and_delta_still_resolves(tmp_path):
    """A shifted base window can admit entities through the COMBINED
    data that neither the base model nor the delta shards name. Their
    transplant rows are zero-init, so the masked solve must treat them
    as touched — skipping them would publish an all-zero random effect."""
    rng = np.random.default_rng(21)
    n = 400
    X = rng.normal(size=(n, _D))
    users = rng.integers(0, 3, n)  # users u000..u002
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ rng.normal(size=_D))))
         ).astype(float)
    base_sel = users != 2  # the base window never saw u002
    base_data = _build(X[base_sel], users[base_sel], y[base_sel])
    comb_data = _build(X, users, y)
    delta_sel = users == 1  # the delta only touches u001
    delta_data = _build(X[delta_sel][:20], users[delta_sel][:20],
                        y[delta_sel][:20])

    config = _config()
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(config).fit(
        base_data,
        checkpoint_spec=CheckpointSpec(directory=ckpt, resume=False),
    )
    ws = incremental.load_warm_start(ckpt)
    scan = incremental.scan_delta(
        delta_data, {"userId": ws.model.models["perUser"].vocab}
    )
    res = GameEstimator(config).fit_incremental(comb_data, ws, delta=scan)
    inc_map = _entity_coeffs(res.model)
    # u002 was in neither the base vocab nor the delta's touched set,
    # yet its lane re-solved to real coefficients
    assert any(abs(v) > 1e-8 for v in inc_map["u002"].values())
    assert res.new_entities >= 1
    # u000 (untouched, transplanted) stayed bit-identical to the base
    base_map = _entity_coeffs(
        incremental.load_warm_start(ckpt).model
    )
    assert inc_map["u000"] == base_map["u000"]


def test_lambda_sweep_without_validation_is_typed(glmix):
    with pytest.raises(ValueError, match="validation"):
        GameEstimator(glmix["config"]).fit_incremental(
            glmix["comb_data"], glmix["ws"], delta=glmix["scan"],
            lambda_factors=[4.0, 1.0],
        )


# ---------------------------------------------------------------------------
# streaming warm starts + vocabulary growth
# ---------------------------------------------------------------------------


def test_streaming_warm_start_restores_table(tmp_path):
    import jax.numpy as jnp

    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )

    table = np.arange(48, dtype=np.float32).reshape(16, 3)
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path / "s"), resume=False)
    )
    mgr.save(StreamCheckpointState(next_chunk=5, coefficients=jnp.asarray(table)))
    ws = incremental.load_warm_start(str(tmp_path / "s"))
    assert ws.lineage.kind == "streaming"
    assert ws.lineage.next_chunk == 5 and ws.next_chunk == 5
    assert ws.model is None and ws.table is not None
    np.testing.assert_array_equal(np.asarray(ws.table.coefficients), table)
    # a bare table cannot seed the estimator path — typed refusal
    with pytest.raises(incremental.WarmStartError, match="bare"):
        GameEstimator(_config()).fit_incremental(
            _build(np.zeros((4, _D)), [0, 1, 2, 3],
                   np.array([0.0, 1, 0, 1])),
            ws,
        )


def test_grow_entity_rows_zero_init_and_bit_identical(tmp_path):
    import jax.numpy as jnp

    table = np.arange(30, dtype=np.float32).reshape(10, 3)
    grown = incremental.grow_entity_rows(jnp.asarray(table), 14)
    assert grown.shape == (14, 3)
    np.testing.assert_array_equal(np.asarray(grown)[:10], table)
    assert not np.asarray(grown)[10:].any()
    with pytest.raises(incremental.WarmStartError, match="shrink"):
        incremental.grow_entity_rows(jnp.asarray(table), 8)


def test_grow_entity_rows_sharded_elastic(tmp_path, multichip):
    """Checkpoint holding FEWER entities than the current index map,
    restored + grown onto a mesh: new rows zero-init, existing rows
    bit-identical, indivisible axis still the typed error."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from photon_ml_tpu.game.checkpoint import (
        StreamCheckpointState,
        StreamingCheckpointManager,
    )
    from photon_ml_tpu.parallel.sharding import ElasticPlacementError

    mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
    rng = np.random.default_rng(3)
    table = rng.normal(size=(12, 4)).astype(np.float32)
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=str(tmp_path / "s"), resume=False)
    )
    mgr.save(StreamCheckpointState(next_chunk=1,
                                   coefficients=jnp.asarray(table)))
    ws = incremental.load_warm_start(str(tmp_path / "s"), mesh=mesh)
    assert ws.table.mesh is mesh
    grown = incremental.grow_entity_rows(
        ws.table.coefficients, 16, mesh=mesh
    )
    host = np.asarray(grown)
    np.testing.assert_array_equal(host[:12], table)  # bit-identical
    assert not host[12:].any()  # zero-init growth
    # wrap the grown table without re-placing (the warm-start contract)
    from photon_ml_tpu.game.streaming import ShardedCoefficientTable

    wrapped = ShardedCoefficientTable.from_coefficients(grown, mesh=mesh)
    assert wrapped.num_entities == 16
    with pytest.raises(ElasticPlacementError, match="valid"):
        incremental.grow_entity_rows(ws.table.coefficients, 13, mesh=mesh)


# ---------------------------------------------------------------------------
# delta scans: in-core and out-of-core agree
# ---------------------------------------------------------------------------


def test_delta_scan_stream_agrees_with_in_core(tmp_path):
    from photon_ml_tpu.data.avro import (
        TRAINING_EXAMPLE_AVRO,
        build_index_maps_from_avro,
        read_game_dataset_from_avro,
        write_avro,
    )
    from photon_ml_tpu.ingest import IngestSpec

    rng = np.random.default_rng(11)

    def recs(n, users):
        for i in range(n):
            yield {
                "uid": str(i),
                "label": float(i % 2),
                "features": [
                    {"name": f"f{rng.integers(0, 10)}", "term": "",
                     "value": float(rng.normal())}
                    for _ in range(4)
                ],
                "metadataMap": {"userId": str(users[i % len(users)])},
                "weight": None,
                "offset": None,
            }

    delta_path = str(tmp_path / "delta.avro")
    write_avro(delta_path, TRAINING_EXAMPLE_AVRO,
               recs(300, [5, 9, 23, 77]), block_records=64)
    # base vocabularies are sorted-unique by construction (IdColumn /
    # RandomEffectModel.vocab); 77 is the new entity
    base_vocabs = {
        "userId": np.sort(np.array([str(u) for u in range(30)]))
    }
    imaps = build_index_maps_from_avro(
        [delta_path], feature_shards={"g": ("features",)}
    )
    data, _ = read_game_dataset_from_avro(
        [delta_path], feature_shards={"g": ("features",)},
        id_columns=("userId",), index_maps=imaps, return_index_maps=True,
    )
    in_core = incremental.scan_delta(data, base_vocabs,
                                     paths=[delta_path])
    streamed = incremental.scan_delta_stream(
        [delta_path], base_vocabs, index_maps=imaps,
        feature_shards={"g": ("features",)},
        spec=IngestSpec(chunk_rows=64, workers=2),
    )
    # the digest is content-aware: a rewrite with the SAME basename and
    # byte size (different dir, one flipped byte) must change it
    with open(delta_path, "rb") as fh:
        raw = bytearray(fh.read())
    raw[16] ^= 0xFF
    (tmp_path / "rewrite").mkdir()
    rewritten = str(tmp_path / "rewrite" / "delta.avro")
    with open(rewritten, "wb") as fh:
        fh.write(raw)
    assert (incremental.delta_digest([rewritten])
            != incremental.delta_digest([delta_path]))
    a, b = in_core.for_id("userId"), streamed.for_id("userId")
    np.testing.assert_array_equal(a.touched_values, b.touched_values)
    np.testing.assert_array_equal(a.new_values, b.new_values)
    assert a.new_values.tolist() == ["77"]
    assert in_core.digest == streamed.digest
    assert streamed.delta_rows == 300
    snap = telemetry.snapshot()
    assert snap["counters"].get("incremental.touched_entities", 0) >= 8
    assert 0 < snap["gauges"]["incremental.touched_fraction"] <= 1


# ---------------------------------------------------------------------------
# the streamed loop end-to-end: ChunkStream base fit -> streamed scan ->
# streamed combined re-read -> masked refresh
# ---------------------------------------------------------------------------


def test_streamed_incremental_end_to_end(tmp_path):
    """The WHOLE incremental loop out-of-core: base data assembled
    through the ChunkStream reader (multi-chunk, parallel decode), delta
    scanned with scan_delta_stream, the combined window re-read streamed
    with the SAME pinned index maps, and a warm-started masked refresh —
    untouched lanes still bit-identical to the base fit."""
    from photon_ml_tpu.data.avro import (
        TRAINING_EXAMPLE_AVRO,
        build_index_maps_from_avro,
        write_avro,
    )
    from photon_ml_tpu.ingest import IngestSpec
    from photon_ml_tpu.ingest.assemble import read_game_dataset_streamed

    rng = np.random.default_rng(17)
    d, n_users, n_base, n_delta = _D, 8, 600, 45
    X = rng.normal(size=(n_base + n_delta, d))
    users = np.concatenate([
        rng.integers(0, n_users, n_base),
        np.array([1, 4, n_users] * (n_delta // 3)),  # u1, u4 + NEW u8
    ])
    w = rng.normal(size=d)
    u_eff = rng.normal(size=n_users + 1)
    logits = X @ w + u_eff[users]
    y = (rng.random(len(users)) < 1 / (1 + np.exp(-logits))).astype(float)

    def recs(lo, hi):
        for i in range(lo, hi):
            yield {
                "uid": str(i),
                "label": float(y[i]),
                "features": [
                    {"name": f"c{j}", "term": "", "value": float(X[i, j])}
                    for j in range(d)
                ],
                "metadataMap": {"userId": f"u{users[i]:03d}"},
                "weight": None,
                "offset": None,
            }

    train_path = str(tmp_path / "base.avro")
    delta_path = str(tmp_path / "delta.avro")
    write_avro(train_path, TRAINING_EXAMPLE_AVRO, recs(0, n_base),
               block_records=64)
    write_avro(delta_path, TRAINING_EXAMPLE_AVRO,
               recs(n_base, n_base + n_delta), block_records=64)
    shards = {"g": ("features",)}
    spec = IngestSpec(chunk_rows=128, workers=2)
    # index maps pinned over base ∪ delta: the base and combined reads
    # must agree on feature geometry for the transplant to line up
    imaps = build_index_maps_from_avro([train_path, delta_path], shards)
    base_data = read_game_dataset_streamed(
        [train_path], feature_shards=shards, index_maps=imaps,
        id_columns=("userId",), spec=spec,
    )
    config = _config()
    ckpt = str(tmp_path / "ckpt")
    base_fit = GameEstimator(config).fit(
        base_data,
        checkpoint_spec=CheckpointSpec(directory=ckpt, resume=False),
    )
    ws = incremental.load_warm_start(ckpt)
    scan = incremental.scan_delta_stream(
        [delta_path], {"userId": ws.model.models["perUser"].vocab},
        index_maps=imaps, feature_shards=shards, spec=spec,
    )
    comb_data = read_game_dataset_streamed(
        [train_path, delta_path], feature_shards=shards, index_maps=imaps,
        id_columns=("userId",), spec=spec,
    )
    res = GameEstimator(config).fit_incremental(comb_data, ws, delta=scan)

    base_map = _entity_coeffs(base_fit.model)
    inc_map = _entity_coeffs(res.model)
    touched = {"u001", "u004"}
    checked = 0
    for val, coeffs in base_map.items():
        if val in touched:
            continue
        checked += 1
        assert inc_map[val] == coeffs, val  # bit-identical through i/o
    assert checked >= n_users - len(touched) - 1
    for val in touched:
        assert any(
            inc_map[val][g] != wv for g, wv in base_map[val].items()
        ), f"touched entity {val} kept its base coefficients"
    new_val = f"u{n_users:03d}"
    assert new_val not in base_map
    assert any(abs(v) > 1e-8 for v in inc_map[new_val].values())
    assert res.lanes_solved >= 3 and res.lanes_skipped >= 1
    assert scan.digest == incremental.delta_digest([delta_path])


# ---------------------------------------------------------------------------
# masked solves for FACTORED coordinates (frozen projection)
# ---------------------------------------------------------------------------


def _latent_rows(model, coord="perUser"):
    """entity value -> latent row (host copy) for a factored coordinate."""
    m = model.models[coord]
    lat = np.asarray(m.latent)
    flat = np.asarray(m.entity_flat)
    return {
        m.vocab[c]: lat[flat[c]]
        for c in range(len(m.vocab)) if flat[c] >= 0
    }


def test_masked_factored_coordinate_parity(tmp_path):
    """Factored (projected) coordinates get the same masked treatment:
    untouched latent rows EXACT from the transplant, touched + new rows
    matching a full unmasked re-solve under the same frozen projection
    (the seeded Gaussian A is identical across all three fits)."""
    rng = np.random.default_rng(23)
    d, k, n_users, n_base, n_delta = _D, 3, 10, 900, 60
    X = rng.normal(size=(n_base + n_delta, d))
    users = np.concatenate([
        rng.integers(0, n_users, n_base),
        np.array([2, 7, n_users] * (n_delta // 3)),  # u2, u7 + NEW u10
    ])
    w = rng.normal(size=d)
    u_eff = rng.normal(size=n_users + 1)
    logits = X @ w + u_eff[users]
    y = (rng.random(len(users)) < 1 / (1 + np.exp(-logits))).astype(float)
    base_data = _build(X[:n_base], users[:n_base], y[:n_base])
    comb_data = _build(X, users, y)
    delta_data = _build(X[n_base:], users[n_base:], y[n_base:])

    # a SINGLE factored coordinate: per-entity latent solves are convex
    # and independent, so the masked re-solve and the full re-solve land
    # on the same optimum for every touched entity
    config = GameConfig(
        task="logistic",
        coordinates={
            "perUser": RandomEffectConfig(
                shard_name="g", id_name="userId", optimizer=_opt(),
                projector="random", projected_dim=k,
            ),
        },
        num_iterations=1,
    )
    ckpt = str(tmp_path / "ckpt")
    base_fit = GameEstimator(config).fit(
        base_data,
        checkpoint_spec=CheckpointSpec(directory=ckpt, resume=False),
    )
    ws = incremental.load_warm_start(ckpt)
    scan = incremental.scan_delta(
        delta_data, {"userId": ws.model.models["perUser"].vocab}
    )
    res = GameEstimator(config).fit_incremental(comb_data, ws, delta=scan)
    ref = GameEstimator(config).fit(comb_data)

    base_rows = _latent_rows(base_fit.model)
    inc_rows = _latent_rows(res.model)
    ref_rows = _latent_rows(ref.model)
    touched = {"u002", "u007", f"u{n_users:03d}"}
    checked = 0
    for val, row in base_rows.items():
        if val in touched:
            continue
        checked += 1
        # untouched latent rows are the TRANSPLANT: exact float equality
        np.testing.assert_array_equal(inc_rows[val], row, err_msg=val)
    assert checked >= n_users - 2
    for val in touched:
        np.testing.assert_allclose(
            inc_rows[val], ref_rows[val], atol=1e-3, rtol=1e-3,
            err_msg=f"masked re-solve of {val} off the full re-solve",
        )
        if val in base_rows:
            assert not np.array_equal(inc_rows[val], base_rows[val]), val
    # the structural evidence flows through the same lane counters
    assert res.lanes_solved >= 3
    assert res.lanes_skipped >= n_users - 3
    assert res.bucket_solves >= 1


def test_transplant_factored_dim_mismatch_is_typed(tmp_path):
    """A base latent table of a DIFFERENT latent_dim cannot seed the new
    coordinate — typed WarmStartError, not a silent shape blowup."""
    rng = np.random.default_rng(29)
    n = 300
    X = rng.normal(size=(n, _D))
    users = rng.integers(0, 4, n)
    y = (rng.random(n) < 0.5).astype(float)
    data = _build(X, users, y)

    def cfg(k):
        return GameConfig(
            task="logistic",
            coordinates={
                "perUser": RandomEffectConfig(
                    shard_name="g", id_name="userId", optimizer=_opt(),
                    projector="random", projected_dim=k,
                ),
            },
            num_iterations=1,
        )

    ckpt = str(tmp_path / "ckpt")
    GameEstimator(cfg(3)).fit(
        data, checkpoint_spec=CheckpointSpec(directory=ckpt, resume=False)
    )
    ws = incremental.load_warm_start(ckpt)
    with pytest.raises(incremental.WarmStartError, match="latent"):
        GameEstimator(cfg(4)).fit_incremental(data, ws)


# ---------------------------------------------------------------------------
# stale-delta refusal (publish gate + cli refresh --force)
# ---------------------------------------------------------------------------


def test_check_delta_freshness_refuses_matching_digest(glmix, tmp_path):
    reg = str(tmp_path / "registry")
    res = glmix["res"]
    incremental.publish_incremental(
        reg, res.model, {"g": [f"c{j}" for j in range(_D)]},
        res.lineage, delta=res.delta,
    )
    # unchanged delta: typed refusal naming the version that already
    # trained on it (a stuck cron must not publish no-op versions)
    with pytest.raises(incremental.StaleDeltaError, match="v-00000001"):
        incremental.check_delta_freshness(reg, res.delta.digest)
    # --force and a genuinely new digest both pass
    incremental.check_delta_freshness(reg, res.delta.digest, force=True)
    incremental.check_delta_freshness(reg, "0" * 64)
    # a missing or empty registry never refuses (first publish must work)
    incremental.check_delta_freshness(
        str(tmp_path / "nope"), res.delta.digest
    )


# ---------------------------------------------------------------------------
# fault seams (L016 coverage: incremental.warm_restore,
# incremental.delta_scan, incremental.publish)
# ---------------------------------------------------------------------------


def test_incremental_fault_seams_fire_typed(glmix, tmp_path):
    install_plan(FaultPlan([FaultRule("incremental.warm_restore",
                                      action="raise")]))
    try:
        with pytest.raises(InjectedFault):
            incremental.load_warm_start(glmix["ckpt"])
    finally:
        clear_plan()

    install_plan(FaultPlan([FaultRule("incremental.delta_scan",
                                      action="raise")]))
    try:
        with pytest.raises(InjectedFault):
            incremental.scan_delta(
                glmix["delta_data"],
                {"userId": glmix["ws"].model.models["perUser"].vocab},
            )
    finally:
        clear_plan()

    install_plan(FaultPlan([FaultRule("incremental.publish",
                                      action="raise")]))
    try:
        with pytest.raises(InjectedFault):
            incremental.publish_incremental(
                str(tmp_path / "reg"),
                glmix["res"].model,
                {"g": [f"c{j}" for j in range(_D)]},
                glmix["res"].lineage,
            )
    finally:
        clear_plan()
    # an aborted publish left no version behind
    assert not os.path.isdir(tmp_path / "reg") or not any(
        n.startswith("v-") for n in os.listdir(tmp_path / "reg")
    )


# ---------------------------------------------------------------------------
# registry lineage: publish -> engine -> /healthz
# ---------------------------------------------------------------------------


def test_publish_lineage_roundtrip_and_healthz(glmix, tmp_path):
    from photon_ml_tpu.serving.engine import ScoringEngine
    from photon_ml_tpu.serving.server import ScoringService

    reg = str(tmp_path / "registry")
    res = glmix["res"]
    path = incremental.publish_incremental(
        reg,
        res.model,
        {"g": [f"c{j}" for j in range(_D)]},
        res.lineage,
        delta=res.delta,
        base_version="v-00000007",
    )
    with open(os.path.join(path, "model-metadata.json")) as fh:
        meta = json.load(fh)
    lineage = meta["extra"]["lineage"]
    assert lineage["base_version"] == "v-00000007"
    assert lineage["warm_start_checkpoint"] == res.lineage.checkpoint_dir
    assert lineage["base_kind"] == "step"
    assert lineage["delta_digest"] == res.delta.digest
    assert lineage["touched_fraction"] == pytest.approx(3 / 40, abs=0.01)

    engine = ScoringEngine.load(path, max_batch=4)
    assert engine.lineage == lineage
    health = ScoringService(engine).health()
    assert health["lineage"]["warm_start_checkpoint"] == (
        res.lineage.checkpoint_dir
    )
    assert health["lineage"]["delta_digest"] == res.delta.digest


# ---------------------------------------------------------------------------
# CLI end-to-end + the crash row
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cli_base(tmp_path_factory):
    """One CLI base train with a checkpoint dir + delta shard, shared by
    the e2e refresh test and the crash row."""
    from photon_ml_tpu.data.avro import TRAINING_EXAMPLE_AVRO, write_avro

    rng = np.random.default_rng(99)
    tmp = tmp_path_factory.mktemp("cli_incremental")
    n, d, n_users = 240, _D, 6
    X = rng.normal(size=(n + 60, d))
    users = np.concatenate([
        rng.integers(0, n_users, n),
        np.array([1, 2, n_users] * 20),  # delta touches u1, u2 + NEW u6
    ])
    w = rng.normal(size=d)
    u_eff = rng.normal(size=n_users + 1)
    logits = X @ w + u_eff[users]
    y = (rng.random(len(users)) < 1 / (1 + np.exp(-logits))).astype(float)

    def recs(lo, hi):
        for i in range(lo, hi):
            yield {
                "uid": str(i),
                "label": float(y[i]),
                "features": [
                    {"name": f"c{j}", "term": "", "value": float(X[i, j])}
                    for j in range(d)
                ],
                "metadataMap": {"userId": str(users[i])},
                "weight": None,
                "offset": None,
            }

    train_path = str(tmp / "train.avro")
    delta_path = str(tmp / "delta.avro")
    write_avro(train_path, TRAINING_EXAMPLE_AVRO, recs(0, n))
    write_avro(delta_path, TRAINING_EXAMPLE_AVRO, recs(n, n + 60))
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 0.1},
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "global",
                "id_name": "userId",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 1.0},
            },
        },
        "num_iterations": 1,
        "output_dir": str(tmp / "base-model"),
        "checkpoint": {"dir": str(tmp / "base-ckpt"), "resume": False},
    }
    cfg_path = tmp / "train.json"
    cfg_path.write_text(json.dumps(config))
    _run_cli(["train", "--config", str(cfg_path)], cwd=tmp)
    return dict(tmp=tmp, config=config, cfg_path=cfg_path,
                delta_path=delta_path)


def _run_cli(args, cwd, env_extra=None, expect_rc=0):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli", *args],
        capture_output=True, text=True, cwd=str(cwd), env=env, timeout=600,
    )
    assert proc.returncode == expect_rc, (
        proc.returncode, proc.stderr[-3000:]
    )
    if expect_rc:
        return None
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _tree_digest(root):
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as fh:
                h.update(fh.read())
    return h.hexdigest()


def test_cli_refresh_end_to_end(cli_base):
    from photon_ml_tpu.data.model_store import load_game_model

    tmp = cli_base["tmp"]
    ckpt = cli_base["config"]["checkpoint"]["dir"]
    reg = str(tmp / "registry")
    report = str(tmp / "refresh-report.md")
    summary = _run_cli(
        [
            "refresh",
            "--config", str(cli_base["cfg_path"]),
            "--warm-start", ckpt,
            "--delta", cli_base["delta_path"],
            "--registry-dir", reg,
            "--output-dir", str(tmp / "fresh-model"),
            "--report-out", report,
        ],
        cwd=tmp,
    )
    fresh = summary["freshness"]
    assert fresh["base"]["kind"] == "step"
    assert fresh["lanes_solved"] >= 3
    assert fresh["lanes_skipped"] >= 1
    assert fresh["delta"]["coordinates"]["userId"]["new_entities"] == 1
    assert fresh["time_to_fresh_s"] > 0
    assert fresh["published_version"].endswith("v-00000001")

    # untouched RE lanes bit-identical between base and refreshed models
    base_model = load_game_model(str(tmp / "base-model" / "final"))
    fresh_model = load_game_model(str(tmp / "fresh-model" / "final"))
    base_map = _entity_coeffs(base_model)
    fresh_map = _entity_coeffs(fresh_model)
    untouched = [v for v in base_map if v not in ("1", "2")]
    assert untouched
    for val in untouched:
        assert fresh_map[val] == base_map[val], val

    # a refreshed model dir carries the same feature artifacts a trained
    # one does: index maps AND the per-shard feature statistics
    assert os.path.isdir(
        tmp / "fresh-model" / "final" / "feature-indexes" / "global"
    )
    assert os.path.exists(
        tmp / "fresh-model" / "feature-stats" / "global.avro"
    )

    # the registry version carries lineage; loads into a serving engine
    with open(os.path.join(reg, "v-00000001",
                           "model-metadata.json")) as fh:
        meta = json.load(fh)
    assert meta["extra"]["lineage"]["base_kind"] == "step"
    assert meta["extra"]["lineage"]["delta_digest"]

    # the run report rendered the Freshness section
    with open(report) as fh:
        md = fh.read()
    assert "## Freshness" in md and "kept bit-identical" in md


def test_crash_at_publish_preserves_base_and_registry(cli_base):
    """The incremental crash row: a hard kill (os._exit 113) at the
    incremental.publish seam mid-refresh leaves the BASE checkpoint
    byte-identical and the registry without any partial version; the
    unarmed rerun publishes cleanly."""
    tmp = cli_base["tmp"]
    ckpt = cli_base["config"]["checkpoint"]["dir"]
    reg = str(tmp / "crash-registry")
    before = _tree_digest(ckpt)
    plan = json.dumps({
        "rules": [{"point": "incremental.publish", "action": "exit",
                   "exit_code": 113}]
    })
    _run_cli(
        [
            "refresh",
            "--config", str(cli_base["cfg_path"]),
            "--warm-start", ckpt,
            "--delta", cli_base["delta_path"],
            "--registry-dir", reg,
            "--output-dir", str(tmp / "crash-model"),
        ],
        cwd=tmp,
        env_extra={"PHOTON_FAULT_PLAN": plan},
        expect_rc=113,
    )
    # the base checkpoint is byte-identical — the refresh never writes it
    assert _tree_digest(ckpt) == before
    # no partial registry version (tmp debris is ignored by scans)
    assert not os.path.isdir(reg) or not any(
        n.startswith("v-") for n in os.listdir(reg)
    )
    # unarmed rerun succeeds and publishes v1
    summary = _run_cli(
        [
            "refresh",
            "--config", str(cli_base["cfg_path"]),
            "--warm-start", ckpt,
            "--delta", cli_base["delta_path"],
            "--registry-dir", reg,
            "--output-dir", str(tmp / "crash-model-2"),
        ],
        cwd=tmp,
    )
    assert summary["freshness"]["published_version"].endswith("v-00000001")
    assert _tree_digest(ckpt) == before


def test_cli_refresh_stale_delta_refusal_and_force(cli_base):
    """`cli refresh` refuses (typed, rc != 0) a delta whose digest the
    newest registry version already recorded — the stuck-cron guard —
    and publishes nothing; --force deliberately republishes."""
    tmp = cli_base["tmp"]
    ckpt = cli_base["config"]["checkpoint"]["dir"]
    reg = str(tmp / "stale-registry")

    def args(out_name, *extra):
        return [
            "refresh",
            "--config", str(cli_base["cfg_path"]),
            "--warm-start", ckpt,
            "--delta", cli_base["delta_path"],
            "--registry-dir", reg,
            "--output-dir", str(tmp / out_name),
            *extra,
        ]

    _run_cli(args("stale-model-1"), cwd=tmp)  # publishes v-00000001

    # the SAME delta again: typed refusal, nothing published
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli",
         *args("stale-model-2")],
        capture_output=True, text=True, cwd=str(tmp), env=env, timeout=600,
    )
    assert proc.returncode != 0
    assert "StaleDeltaError" in proc.stderr
    assert "--force" in proc.stderr  # the override is named in the error
    assert sorted(
        n for n in os.listdir(reg) if n.startswith("v-")
    ) == ["v-00000001"]

    # --force: the deliberate republish goes through
    summary = _run_cli(args("stale-model-3", "--force"), cwd=tmp)
    assert summary["freshness"]["published_version"].endswith("v-00000002")


# ---------------------------------------------------------------------------
# bench wiring
# ---------------------------------------------------------------------------


def test_bench_freshness_budget_truncation(capsys):
    import bench_freshness

    out = bench_freshness.run_freshness(deadline=-1.0)
    # BOTH freshness metrics are reported None with truncated lines —
    # the suite gate must see every declared metric, never a silent gap
    assert out == {
        "freshness_speedup": None,
        "event_to_served_staleness_p99_s": None,
    }
    lines = [
        json.loads(ln)
        for ln in capsys.readouterr().out.strip().splitlines()
        if ln.startswith("{")
    ]
    truncated = {
        ln["metric"] for ln in lines if ln.get("truncated") is True
    }
    assert truncated == set(bench_freshness.FRESHNESS_METRICS)
