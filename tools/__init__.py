"""Repo tooling: the static-analysis gate lives in tools/analysis."""
