#!/usr/bin/env python
"""Static-analysis gate: `python tools/check.py`.

Reference analog: the scalastyle + Apache RAT gates of the reference build
(scalastyle-config.xml, build-scripts/rat.gradle) — a zero-setup check that
every source file parses and passes lint before code lands.

Runs, in order:
  1. syntax: compile every .py under photon_ml_tpu/ tests/ tools/ (py_compile)
  2. stdlib AST lint (dependency-free, so the gate works in hermetic
     images with no linters installed):
       - unused imports (module scope)
       - bare `except:` clauses
       - mutable default arguments (list/dict/set literals)
       - `== None` / `!= None` comparisons
       - f-strings with no placeholders
  3. ruff + mypy, IF installed (configs live in pyproject.toml)

Exit code 0 = clean. Any finding prints `path:line: code message` and the
run exits 1.
"""

from __future__ import annotations

import ast
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = ("photon_ml_tpu", "tests", "tools", "__graft_entry__.py")


def source_files() -> list[str]:
    import glob as _glob

    # every bench script is gated (a literal list silently missed new ones)
    out = sorted(_glob.glob(os.path.join(REPO, "bench*.py")))
    for t in TARGETS:
        path = os.path.join(REPO, t)
        if os.path.isfile(path):
            out.append(path)
            continue
        for root, _dirs, files in os.walk(path):
            out.extend(
                os.path.join(root, f) for f in files if f.endswith(".py")
            )
    return sorted(out)


def check_syntax(files: list[str]) -> list[str]:
    errs = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            try:
                compile(fh.read(), f, "exec")
            except SyntaxError as e:
                errs.append(f"{f}:{e.lineno}: SYNTAX {e.msg}")
    return errs


class _Lint(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.findings: list[str] = []
        self.imported: dict[str, int] = {}  # name -> lineno (module scope)
        self.used: set[str] = set()
        self._collect(tree)

    def _report(self, node: ast.AST, code: str, msg: str) -> None:
        self.findings.append(f"{self.path}:{node.lineno}: {code} {msg}")

    def _collect(self, tree: ast.Module) -> None:
        for node in tree.body:  # module scope only: re-export surfaces stay
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    self.imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__" or any(
                    a.name == "*" for a in node.names
                ):
                    continue
                for a in node.names:
                    self.imported[a.asname or a.name] = node.lineno
        self.visit(tree)

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._report(node, "L002", "bare `except:` (catch something)")
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for d in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self._report(
                    d, "L003", "mutable default argument (use None sentinel)"
                )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                isinstance(comp, ast.Constant) and comp.value is None
            ):
                self._report(node, "L004", "use `is None` / `is not None`")
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self._report(node, "L005", "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # format specs parse as nested JoinedStrs of constants (e.g. ':.3g');
        # visiting them would false-positive L005 on every formatted field
        self.visit(node.value)

    def unused_imports(self, tree: ast.Module) -> None:
        exported = set()
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                exported |= {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant)
                }
        for name, lineno in sorted(self.imported.items(), key=lambda kv: kv[1]):
            if name not in self.used and name not in exported:
                self.findings.append(
                    f"{self.path}:{lineno}: L001 unused import `{name}`"
                )


def check_lint(files: list[str]) -> list[str]:
    findings = []
    for f in files:
        if os.path.basename(f) == "__init__.py":
            continue  # re-export surfaces import without using
        with open(f, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=f)
            except SyntaxError:
                continue  # reported by the syntax phase
        lint = _Lint(os.path.relpath(f, REPO), tree)
        lint.unused_imports(tree)
        findings.extend(lint.findings)
    return findings


def run_external() -> list[str]:
    errs = []
    for tool, args in (
        ("ruff", ["check", "photon_ml_tpu", "tests", "tools"]),
        ("mypy", ["photon_ml_tpu"]),
    ):
        exe = shutil.which(tool)
        if exe is None:
            print(f"  - {tool}: not installed, skipped (stdlib gate still ran)")
            continue
        proc = subprocess.run(
            [exe, *args], cwd=REPO, capture_output=True, text=True
        )
        if proc.returncode != 0:
            errs.append(f"{tool} failed:\n{proc.stdout}\n{proc.stderr}")
        else:
            print(f"  - {tool}: clean")
    return errs


def main() -> int:
    files = source_files()
    print(f"checking {len(files)} files")
    findings = check_syntax(files)
    findings += check_lint(files)
    print("external tools:")
    findings += run_external()
    if findings:
        print("\n".join(findings))
        print(f"\n{len(findings)} finding(s)")
        return 1
    print("clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
