#!/usr/bin/env python
"""Static-analysis gate: `python tools/check.py`.

Reference analog: the scalastyle + Apache RAT gates of the reference build
(scalastyle-config.xml, build-scripts/rat.gradle) — a zero-setup check that
every source file parses and passes lint before code lands.

The analysis itself lives in the tools/analysis package (see its module
docstrings for the pass-by-pass story):

  1. single parse of every .py under photon_ml_tpu/ tests/ tools/ bench*.py
     (syntax errors are findings of that one parse — no separate
     py_compile phase)
  2. per-file stdlib AST lint, rules L001-L012 (tools/analysis/local.py)
  3. whole-package interprocedural passes over the import-resolved call
     graph (tools/analysis/callgraph.py):
       L013  hot-path propagation — the L010/L011 path lists are seeds;
             syncs/bare jits reachable from ScoringEngine.score_rows or
             the solver loops are flagged WITH the call chain
       L014  jit-purity — functions traced by instrumented_jit/jax.jit/
             lax.while_loop/lax.scan must not touch host state (telemetry,
             logs, wall clock, files, module globals): trace-time effects
             run once and silently never again
       L015  lock discipline — thread-spawning classes (MicroBatcher,
             ModelRegistry, Heartbeat) must guard attributes written from
             both the thread target and public methods with
             `with self._lock/_cv:`
  4. interprocedural DATAFLOW over the same graph (tools/analysis/
     dataflow.py + locks.py): these track VALUES, not names —
       L017  donation safety — borrowed host memory (mmap'd np.load,
             np.frombuffer, staging-ring slots, views of parameters)
             must not reach a donate_argnums slot of instrumented_jit/
             jax.jit without a sanctioned laundering copy
       L018  lock-order cycles — `with self._lock:` acquisition orders
             (incl. calls into other lock-holding methods) must form an
             acyclic cross-class graph
       L019  unsanctioned host transfer — jitted-function results must
             not flow into float()/int()/np.asarray/.tolist()/json.dump/
             branch comparisons outside telemetry.device.sync_fetch
  5. ruff + mypy, IF installed (configs live in pyproject.toml)

Inline suppression: `# photon: noqa[L013]` on the reported line (stale
suppressions are themselves findings, W001). `--baseline accepted.json`
grandfathers existing findings so only NEW ones fail CI;
`--write-baseline` emits that file. `--json` prints the machine-readable
findings document (the schema tests/test_static_gate.py pins).
`--changed GIT_REF` is the fast pre-commit scope: only files touched vs
the ref (plus their call-graph dependents) are linted/reported, while
the interprocedural passes still see the whole graph.

Exit code 0 = clean (no new findings). Otherwise every finding prints as
`path:line: code message [via call -> chain]` and the run exits 1.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analysis import core, driver  # noqa: E402 (path bootstrap above)


def changed_files(root: str, ref: str) -> set:
    """Repo-relative .py paths touched vs ``ref``: committed/staged/
    worktree diffs plus untracked files — everything a pre-commit run
    must re-judge."""
    out: set = set()
    for cmd in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, cwd=root, capture_output=True, text=True
        )
        if proc.returncode != 0:
            raise SystemExit(
                f"--changed: `{' '.join(cmd)}` failed in {root}:\n"
                f"{proc.stderr.strip()}"
            )
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(line.replace("/", os.sep))
    return out


def run_external(quiet: bool) -> list[core.Finding]:
    errs = []
    for tool, args in (
        ("ruff", ["check", "photon_ml_tpu", "tests", "tools"]),
        ("mypy", ["photon_ml_tpu"]),
    ):
        exe = shutil.which(tool)
        if exe is None:
            if not quiet:
                print(
                    f"  - {tool}: not installed, skipped "
                    f"(stdlib gate still ran)"
                )
            continue
        proc = subprocess.run(
            [exe, *args], cwd=REPO, capture_output=True, text=True
        )
        if proc.returncode != 0:
            errs.append(
                core.Finding(
                    path=tool,
                    line=0,
                    code="EXT",
                    message=f"{tool} failed:\n{proc.stdout}\n{proc.stderr}",
                )
            )
        elif not quiet:
            print(f"  - {tool}: clean")
    return errs


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--json",
        action="store_true",
        help="print the findings document as JSON (stdout carries ONLY "
        "the JSON)",
    )
    ap.add_argument(
        "--baseline",
        metavar="PATH",
        help="accepted-findings JSON: matching findings are grandfathered "
        "and only NEW findings fail the gate",
    )
    ap.add_argument(
        "--write-baseline",
        metavar="PATH",
        help="write the current findings as a baseline file and exit 0",
    )
    ap.add_argument(
        "--root",
        default=REPO,
        help="tree to analyze (default: this repo; tests point it at "
        "fixture trees)",
    )
    ap.add_argument(
        "--no-external",
        action="store_true",
        help="skip ruff/mypy even when installed",
    )
    ap.add_argument(
        "--changed",
        metavar="GIT_REF",
        help="fast pre-commit scope: lint/report only files touched vs "
        "GIT_REF (plus their call-graph dependents); the whole tree is "
        "still parsed and the interprocedural passes still see the full "
        "graph. External tools are skipped (they have no changed-scope "
        "mode). Full-tree behavior without this flag is unchanged.",
    )
    args = ap.parse_args(argv)

    baseline = None
    if args.baseline:
        baseline = core.load_baseline(args.baseline)

    root = os.path.abspath(args.root)
    changed = None
    if args.changed and args.write_baseline:
        # a scope-filtered result would write a PARTIAL baseline,
        # silently dropping every out-of-scope accepted entry — the next
        # full-tree run would then fail on all of them
        ap.error("--write-baseline needs the full tree; drop --changed")
    if args.changed:
        changed = changed_files(root, args.changed)
        if not args.json:
            print(
                f"--changed {args.changed}: {len(changed)} touched "
                f"python file(s)"
            )
    # fixture trees are not this repo: their seed classes are whatever the
    # test planted, so the missing-seed config check (W002) stays repo-only
    result = driver.analyze(
        root, baseline=baseline, require_seeds=(root == REPO),
        changed=changed,
    )

    if args.write_baseline:
        # include currently-grandfathered findings: refreshing a baseline
        # with --baseline also on the command line must not silently drop
        # every previously-accepted entry
        accepted = result.findings + result.grandfathered
        doc = {
            "version": 1,
            "findings": [f.to_json() for f in accepted],
        }
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
        print(
            f"wrote {len(accepted)} finding(s) to {args.write_baseline}"
        )
        return 0

    if not args.json:
        print(f"checking {len(result.files)} files")

    external: list[core.Finding] = []
    if not args.no_external and root == REPO and changed is None:
        if not args.json:
            print("external tools:")
        external = run_external(quiet=args.json)
    result.findings.extend(external)

    if args.json:
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    if result.grandfathered:
        print(
            f"({len(result.grandfathered)} baselined finding(s) "
            f"grandfathered)"
        )
    for key in result.stale_baseline:
        print(f"note: stale baseline entry (fixed — delete it): {key}")
    if result.findings:
        print(f"\n{len(result.findings)} finding(s)")
        return 1
    print("clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
