#!/usr/bin/env python
"""Static-analysis gate: `python tools/check.py`.

Reference analog: the scalastyle + Apache RAT gates of the reference build
(scalastyle-config.xml, build-scripts/rat.gradle) — a zero-setup check that
every source file parses and passes lint before code lands.

Runs, in order:
  1. syntax: compile every .py under photon_ml_tpu/ tests/ tools/ (py_compile)
  2. stdlib AST lint (dependency-free, so the gate works in hermetic
     images with no linters installed):
       - unused imports (module scope)
       - bare `except:` clauses
       - mutable default arguments (list/dict/set literals)
       - `== None` / `!= None` comparisons
       - f-strings with no placeholders
       - library-only (photon_ml_tpu/) fake-timing rules from PERF_NOTES.md:
         `time.time()` (wall-clock steps corrupt durations — use
         time.monotonic()/utils.timing.Timer) and bare
         `block_until_ready()` statements (a NO-OP sync through the
         tunnel — use telemetry.sync_fetch, the accounted fetch point)
       - library-only non-atomic persistence (L008): `np.savez*` /
         `json.dump`-to-final-path writes outside the blessed atomic
         writers (utils/atomic.py and the model/checkpoint stores built on
         it) — a crash mid-write must never leave a truncated file a later
         load half-reads
       - library-only bare `print()` (L009): stdout belongs to drivers;
         library code routes output through loggers/telemetry so fits are
         greppable and machine-readable. CLI modules (photon_ml_tpu/cli/)
         are exempt — stdout IS their interface.
       - serving hot-path device->host syncs (L010): `jax.device_get`,
         `np.asarray(...)`, and `float(...)`-on-non-constants inside the
         serving hot-path modules (photon_ml_tpu/serving/{engine,batcher}.py)
         — every request would pay a full tunnel round trip per call; the
         one sanctioned crossing is telemetry.sync_fetch.
       - bare `jax.jit` in hot-path library modules (L011: parallel/,
         game/, ops/, training.py, serving/engine.py) — jits must go
         through telemetry.xla.instrumented_jit so compiles land in the
         executable registry with cost analysis and recompile
         attribution; cold paths opt out via L011_COLD_ALLOWLIST.
       - sharding discipline (L012: parallel/, the game/ mesh modules,
         serving/): `jax.device_put` calls must pass an explicit
         Sharding/device (a bare put lands on the default device and
         silently replicates at the next jit boundary), and `pmap` is
         rejected outright — GSPMD via NamedSharding + jit is the one
         parallelism API (parallel/sharding.py).
  3. ruff + mypy, IF installed (configs live in pyproject.toml)

Exit code 0 = clean. Any finding prints `path:line: code message` and the
run exits 1.
"""

from __future__ import annotations

import ast
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = ("photon_ml_tpu", "tests", "tools", "__graft_entry__.py")


def source_files() -> list[str]:
    import glob as _glob

    # every bench script is gated (a literal list silently missed new ones)
    out = sorted(_glob.glob(os.path.join(REPO, "bench*.py")))
    for t in TARGETS:
        path = os.path.join(REPO, t)
        if os.path.isfile(path):
            out.append(path)
            continue
        for root, _dirs, files in os.walk(path):
            out.extend(
                os.path.join(root, f) for f in files if f.endswith(".py")
            )
    return sorted(out)


def check_syntax(files: list[str]) -> list[str]:
    errs = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            try:
                compile(fh.read(), f, "exec")
            except SyntaxError as e:
                errs.append(f"{f}:{e.lineno}: SYNTAX {e.msg}")
    return errs


# Files allowed to call np.savez/json.dump directly: the atomic-write
# primitives and the persistence layers built immediately on top of them.
L008_BLESSED = {
    os.path.join("photon_ml_tpu", "utils", "atomic.py"),
    os.path.join("photon_ml_tpu", "data", "model_store.py"),
    os.path.join("photon_ml_tpu", "game", "checkpoint.py"),
}

# Serving hot-path modules: every score request flows through these, so a
# stray device->host sync (jax.device_get, float() on an array, np.asarray
# on a jax array) costs the full tunnel round trip PER REQUEST. The one
# sanctioned crossing is telemetry.sync_fetch (device.py accounts it).
L010_HOT_PATH = {
    os.path.join("photon_ml_tpu", "serving", "engine.py"),
    os.path.join("photon_ml_tpu", "serving", "batcher.py"),
}

# Hot-path library modules where every jit-compiled program must go
# through telemetry.xla.instrumented_jit (L011): a bare jax.jit hides its
# compile time, cost analysis, and recompile attribution from the
# executable registry — exactly the blind spot that made BENCH_r05
# unexplainable. Cold paths (one-off summaries, diagnostics) may stay on
# bare jax.jit via the allowlist.
L011_HOT_DIRS = (
    os.path.join("photon_ml_tpu", "parallel") + os.sep,
    os.path.join("photon_ml_tpu", "game") + os.sep,
    os.path.join("photon_ml_tpu", "ops") + os.sep,
)
L011_HOT_FILES = {
    os.path.join("photon_ml_tpu", "serving", "engine.py"),
    "photon_ml_tpu/training.py".replace("/", os.sep),
}
L011_COLD_ALLOWLIST = {
    # gather_to_host: a once-per-summary replicating identity, not a
    # training/serving hot path
    os.path.join("photon_ml_tpu", "parallel", "multihost.py"),
}

# Sharding-discipline modules (L012): in these hot paths every
# `jax.device_put` must name an explicit placement (a Sharding/
# NamedSharding/device second argument or device=/... keyword) — a bare
# `device_put(x)` lands on the default device and is then silently
# replicated/resharded at the next jit boundary, exactly the bug class
# the GSPMD scale-out removed. Bare `pmap` is rejected outright (the
# legacy per-device API; use NamedSharding + jit, parallel/sharding.py).
L012_HOT_DIRS = (
    os.path.join("photon_ml_tpu", "parallel") + os.sep,
)
L012_HOT_FILES = {
    os.path.join("photon_ml_tpu", "game", "coordinates.py"),
    os.path.join("photon_ml_tpu", "game", "streaming.py"),
    os.path.join("photon_ml_tpu", "game", "factored.py"),
    os.path.join("photon_ml_tpu", "serving", "engine.py"),
    os.path.join("photon_ml_tpu", "serving", "registry.py"),
}


class _Lint(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module, library: bool = False):
        self.path = path
        # library code (photon_ml_tpu/) additionally gets the fake-timing
        # rules L006/L007; benches and tests may time however they like
        self.library = library
        self._l008_exempt = path in L008_BLESSED
        self._l010_hot = path in L010_HOT_PATH
        self._l011_hot = (
            path in L011_HOT_FILES or path.startswith(L011_HOT_DIRS)
        ) and path not in L011_COLD_ALLOWLIST
        self._l012_hot = (
            path in L012_HOT_FILES or path.startswith(L012_HOT_DIRS)
        )
        # CLI modules own stdout: bare print() is their user interface
        self._l009_exempt = path.startswith(
            os.path.join("photon_ml_tpu", "cli") + os.sep
        )
        self.findings: list[str] = []
        self.imported: dict[str, int] = {}  # name -> lineno (module scope)
        self.used: set[str] = set()
        # names bound to the wall clock by `from time import time [as x]`
        self._time_aliases: set[str] = set()
        # names bound to the jit transform by `from jax import jit [as x]`
        self._jit_aliases: set[str] = set()
        self._collect(tree)

    def _report(self, node: ast.AST, code: str, msg: str) -> None:
        self.findings.append(f"{self.path}:{node.lineno}: {code} {msg}")

    def _collect(self, tree: ast.Module) -> None:
        for node in tree.body:  # module scope only: re-export surfaces stay
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    self.imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__" or any(
                    a.name == "*" for a in node.names
                ):
                    continue
                for a in node.names:
                    self.imported[a.asname or a.name] = node.lineno
                    if node.module == "time" and a.name == "time":
                        self._time_aliases.add(a.asname or a.name)
                    if node.module == "jax" and a.name == "jit":
                        self._jit_aliases.add(a.asname or a.name)
        self.visit(tree)

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._report(node, "L002", "bare `except:` (catch something)")
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for d in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self._report(
                    d, "L003", "mutable default argument (use None sentinel)"
                )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_defaults(node)
        if self._l011_hot:
            # `@jax.jit` decorators without a call are Attribute/Name
            # nodes, invisible to visit_Call
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call) and self._is_bare_jit(dec):
                    self._report_l011(dec)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                isinstance(comp, ast.Constant) and comp.value is None
            ):
                self._report(node, "L004", "use `is None` / `is not None`")
        self.generic_visit(node)

    def _is_wall_clock_call(self, node: ast.Call) -> bool:
        # `time.time()` or a bare `time()` bound by `from time import time`
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "time"
            and isinstance(f.value, ast.Name)
            and f.value.id == "time"
        ):
            return True
        return isinstance(f, ast.Name) and f.id in self._time_aliases

    def _is_non_atomic_persist_call(self, node: ast.Call) -> bool:
        # `<anything>.savez(...)` / `<anything>.savez_compressed(...)` and
        # `json.dump(...)` (json.dumps returns a string and is fine)
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in (
            "savez", "savez_compressed",
        ):
            return True
        return (
            isinstance(f, ast.Attribute)
            and f.attr == "dump"
            and isinstance(f.value, ast.Name)
            and f.value.id == "json"
        )

    def _is_bare_jit(self, node: ast.AST) -> bool:
        # `jax.jit(...)` / `@jax.jit` / from-imported `jit(...)`
        f = node.func if isinstance(node, ast.Call) else node
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "jit"
            and isinstance(f.value, ast.Name)
            and f.value.id == "jax"
        ):
            return True
        return isinstance(f, ast.Name) and f.id in self._jit_aliases

    def _report_l011(self, node: ast.AST) -> None:
        self._report(
            node,
            "L011",
            "bare jax.jit in a hot-path library module — compiles escape "
            "the executable registry (no cost analysis, no recompile "
            "attribution); use telemetry.xla.instrumented_jit(fn, "
            "name=...), or add a cold path to L011_COLD_ALLOWLIST",
        )

    def _is_serving_sync_call(self, node: ast.Call) -> bool:
        # device->host crossings in serving hot paths: `jax.device_get`
        # (any spelling), `np.asarray`/`numpy.asarray` (a jax-array arg
        # forces a fetch), and `float(x)` on anything but a literal
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "device_get":
            return True
        if isinstance(f, ast.Name) and f.id == "device_get":
            return True
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "asarray"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy")
        ):
            return True
        return (
            isinstance(f, ast.Name)
            and f.id == "float"
            and not all(isinstance(a, ast.Constant) for a in node.args)
        )

    def _check_l012(self, node: ast.Call) -> None:
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if attr == "pmap":
            self._report(
                node,
                "L012",
                "bare pmap in a sharding-discipline module — the legacy "
                "per-device API replicates state and bypasses GSPMD; use "
                "NamedSharding + jit (parallel/sharding.py)",
            )
        if attr == "device_put":
            explicit = len(node.args) >= 2 or any(
                k.arg in ("device", "sharding")
                for k in node.keywords
                if k.arg is not None
            )
            if not explicit:
                self._report(
                    node,
                    "L012",
                    "jax.device_put without an explicit Sharding — an "
                    "unsharded upload lands on the default device and "
                    "silently replicates/reshards at the next jit "
                    "boundary; pass a NamedSharding (parallel/sharding.py "
                    "placement helpers)",
                )

    def visit_Call(self, node: ast.Call) -> None:
        if self._l012_hot:
            self._check_l012(node)
        if self.library and self._is_wall_clock_call(node):
            self._report(
                node,
                "L006",
                "time.time() in library code — wall-clock steps corrupt "
                "phase durations; use time.monotonic() / utils.timing.Timer",
            )
        if (
            self.library
            and not self._l008_exempt
            and self._is_non_atomic_persist_call(node)
        ):
            self._report(
                node,
                "L008",
                "non-atomic persistence (np.savez/json.dump to a final "
                "path) in library code — a crash mid-write leaves a "
                "truncated file; route through utils.atomic / the "
                "model_store//checkpoint writers",
            )
        if self._l011_hot and self._is_bare_jit(node):
            self._report_l011(node)
        if self._l010_hot and self._is_serving_sync_call(node):
            self._report(
                node,
                "L010",
                "device->host sync in a serving hot-path module — every "
                "request pays the tunnel round trip; fetch results through "
                "telemetry.sync_fetch only",
            )
        if (
            self.library
            and not self._l009_exempt
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            self._report(
                node,
                "L009",
                "bare print() in library code — stdout belongs to CLI "
                "drivers; route output through logging or telemetry",
            )
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        # a bare `x.block_until_ready()` / `jax.block_until_ready(x)` /
        # from-imported `block_until_ready(x)` STATEMENT is a timing sync —
        # which is a no-op through the tunnel (PERF_NOTES.md); uses whose
        # result feeds real code are fine
        call = node.value
        if (
            self.library
            and isinstance(call, ast.Call)
            and (
                (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr == "block_until_ready"
                )
                or (
                    isinstance(call.func, ast.Name)
                    and call.func.id == "block_until_ready"
                )
            )
        ):
            self._report(
                node,
                "L007",
                "bare block_until_ready() for timing is a no-op sync on the "
                "tunnel TPU; fetch via telemetry.sync_fetch instead",
            )
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self._report(node, "L005", "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # format specs parse as nested JoinedStrs of constants (e.g. ':.3g');
        # visiting them would false-positive L005 on every formatted field
        self.visit(node.value)

    def unused_imports(self, tree: ast.Module) -> None:
        exported = set()
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                exported |= {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant)
                }
        for name, lineno in sorted(self.imported.items(), key=lambda kv: kv[1]):
            if name not in self.used and name not in exported:
                self.findings.append(
                    f"{self.path}:{lineno}: L001 unused import `{name}`"
                )


def check_lint(files: list[str]) -> list[str]:
    findings = []
    for f in files:
        if os.path.basename(f) == "__init__.py":
            continue  # re-export surfaces import without using
        with open(f, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=f)
            except SyntaxError:
                continue  # reported by the syntax phase
        rel = os.path.relpath(f, REPO)
        lint = _Lint(
            rel, tree, library=rel.startswith("photon_ml_tpu" + os.sep)
        )
        lint.unused_imports(tree)
        findings.extend(lint.findings)
    return findings


def run_external() -> list[str]:
    errs = []
    for tool, args in (
        ("ruff", ["check", "photon_ml_tpu", "tests", "tools"]),
        ("mypy", ["photon_ml_tpu"]),
    ):
        exe = shutil.which(tool)
        if exe is None:
            print(f"  - {tool}: not installed, skipped (stdlib gate still ran)")
            continue
        proc = subprocess.run(
            [exe, *args], cwd=REPO, capture_output=True, text=True
        )
        if proc.returncode != 0:
            errs.append(f"{tool} failed:\n{proc.stdout}\n{proc.stderr}")
        else:
            print(f"  - {tool}: clean")
    return errs


def main() -> int:
    files = source_files()
    print(f"checking {len(files)} files")
    findings = check_syntax(files)
    findings += check_lint(files)
    print("external tools:")
    findings += run_external()
    if findings:
        print("\n".join(findings))
        print(f"\n{len(findings)} finding(s)")
        return 1
    print("clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
